"""Headline benchmark: flagship training throughput on real hardware.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Metric: GPT-2-small causal-LM training throughput (tokens/sec) at batch 8 ×
seq 512 — driver config #1 ("GPT-2-small on WikiText-103, single job, 1
device", BASELINE.md). The reference publishes no in-tree numbers
(SURVEY.md §6), so the baseline is self-measured: the first recorded run's
value per platform is stored in ``bench_baseline.json`` and later runs report
``vs_baseline = value / baseline`` (>1 is faster).

Round-1 hardening: the TPU backend can fail to init transiently
(``UNAVAILABLE`` through the tunnel — BENCH_r01.json rc=1). The backend is
now probed in a bounded-time subprocess with retries before the in-process
run; on persistent failure the benchmark falls back to CPU so a parsed
number always exists, with the degradation recorded in the JSON line.

Round-4 hardening: the round-3 fallback never landed a record
(BENCH_r03.json rc=124) because the probe burned ~380s of the driver's
budget and the CPU fallback then attempted the FULL b8x512 workload —
minutes of compile plus ~25s/step on the 1-core host. The probe budget is
now ~160s worst case, and the degraded path measures a deliberately
reduced shape (b2x256, 3 timed steps) tagged with its own shape fields and
baseline key — a health signal that always parses, not a perf claim.
``SATURN_BENCH_FORCE_DEGRADED=1`` skips the probe for testing.

The probe outcome is persisted in a TTL'd sentinel file (tmpdir, keyed on
boot id) so back-to-back runs don't re-burn the probe timeout before every
CPU fallback; ``SATURN_BENCH_PROBE_CACHE=0`` disables it. Round-10: a probe
timeout also short-circuits the in-run retry loop (BENCH_r05 still paid
2 x 75 s because the sentinel only helped the *next* run) — see
``_probe_backend`` — and the degraded run disables XLA:CPU's thunk runtime
(probed for flag support first), whose per-op dispatch overhead was
throttling the 1-core host ~5x — see ``_degraded_cpu_flag``.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import timeit

# bf16 peak TFLOP/s per chip, by device_kind substring (public specs).
_PEAK_TFLOPS = {
    "v2": 45.0,
    "v3": 123.0,
    "v4": 275.0,
    "v5 lite": 197.0,
    "v5e": 197.0,
    "v5p": 459.0,
    "v6": 918.0,
    "cpu": 0.0,  # no meaningful MFU on host
}


_PROBE_TTL_S = 900.0  # re-probe after 15 min: tunnels do recover


def _boot_key() -> str:
    """Identity of this boot/session — a cached probe from before a reboot
    (new tunnel, new driver state) must not be trusted."""
    try:
        with open("/proc/sys/kernel/random/boot_id") as f:
            return f.read().strip()
    except OSError:
        return "no-boot-id"


def _probe_sentinel_path() -> str:
    import tempfile

    return os.path.join(tempfile.gettempdir(), "saturn_bench_probe.json")


def _cached_probe():
    """(platform-or-None,) from the TTL'd sentinel, or None on miss.

    Back-to-back bench runs otherwise re-burn the full probe budget
    (2 x 75 s of timeouts when the TPU tunnel is wedged — BENCH_r05) before
    every CPU fallback. Disable with SATURN_BENCH_PROBE_CACHE=0.
    """
    if os.environ.get("SATURN_BENCH_PROBE_CACHE", "1").lower() in ("0", "false", "off"):
        return None
    try:
        with open(_probe_sentinel_path()) as f:
            rec = json.load(f)
        if rec.get("boot") != _boot_key():
            return None
        age = time.time() - float(rec["ts"])
        ttl = float(os.environ.get("SATURN_BENCH_PROBE_TTL", _PROBE_TTL_S))
        if age < 0 or age > ttl:
            return None
        return (rec.get("platform"),)
    except (OSError, ValueError, KeyError, TypeError):
        return None


def _store_probe(platform) -> None:
    rec = {"boot": _boot_key(), "ts": time.time(), "platform": platform}
    path = _probe_sentinel_path()
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(rec, f)
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass


def _probe_backend(timeout_s: float = 75.0, retries: int = 1, delay_s: float = 5.0):
    """Probe default-backend availability in a subprocess (bounded time).

    Returns the platform string on success, None on failure. A subprocess
    keeps a wedged TPU tunnel from hanging or poisoning the parent's
    backend cache.

    A probe that burns its FULL timeout is a wedged tunnel, not a flaky
    init: retrying has never been observed to recover it, and BENCH_r05
    paid 2 x 75 s per run doing so — the TTL sentinel only short-circuited
    the NEXT run, not the retry loop inside this one. So a timeout now
    records the failure in the sentinel immediately and returns; the retry
    budget applies only to fast failures (rc != 0), which genuinely are
    transient (``UNAVAILABLE`` through the tunnel, BENCH_r01).
    """
    code = "import jax; d = jax.devices(); print('PLATFORM=' + d[0].platform)"
    for attempt in range(retries + 1):
        try:
            r = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True,
                text=True,
                timeout=timeout_s,
            )
            for line in r.stdout.splitlines():
                if line.startswith("PLATFORM="):
                    return line.split("=", 1)[1]
            diag = (r.stderr or r.stdout).strip().splitlines()
            print(
                f"bench: backend probe attempt {attempt + 1} failed "
                f"(rc={r.returncode}): {diag[-1] if diag else '<no output>'}",
                file=sys.stderr,
            )
        except subprocess.TimeoutExpired:
            print(
                f"bench: backend probe attempt {attempt + 1} timed out "
                f"after {timeout_s}s — wedged tunnel, not retrying",
                file=sys.stderr,
            )
            _store_probe(None)
            return None
        if attempt < retries:
            time.sleep(delay_s)
    return None


def _degraded_cpu_flag() -> str:
    """XLA flag for the degraded CPU run: disable the thunk runtime.

    On the 1-core CI host the thunk runtime's per-op dispatch overhead
    dominates the b2x256 step (round 10 measured ~33 tokens/s thunk vs ~165
    legacy — same HLO, same numerics, 5x wall clock), the in-process analog
    of the per-step Python dispatch overhead the fused-scan pipeline
    removes. XLA FATALLY aborts on unknown flags at backend init
    (``parse_flags_from_env.cc``), so probe support in a subprocess first —
    the same pattern as tests/conftest.py — and cache the verdict keyed on
    the jaxlib version (the probe costs a ~5s jax import).

    Returns the flag string, or "" when unsupported/unprobeable.
    """
    import tempfile

    flag = "--xla_cpu_use_thunk_runtime=false"
    try:
        import jaxlib.version

        ver = jaxlib.version.__version__
    except Exception:
        return ""
    sentinel = os.path.join(tempfile.gettempdir(), "saturn_bench_cpu_flag.json")
    try:
        with open(sentinel) as f:
            rec = json.load(f)
        if rec.get("jaxlib") == ver:
            return flag if rec["supported"] else ""
    except (OSError, ValueError, KeyError):
        pass
    env = dict(os.environ)
    env["XLA_FLAGS"] = flag
    env["JAX_PLATFORMS"] = "cpu"
    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            capture_output=True, env=env, timeout=120,
        )
        ok = r.returncode == 0
    except subprocess.TimeoutExpired:
        return ""  # don't cache a timeout: says nothing about the flag
    try:
        tmp = f"{sentinel}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"jaxlib": ver, "supported": ok}, f)
        os.replace(tmp, sentinel)
    except OSError:
        pass
    return flag if ok else ""


def _flops_per_step(cfg, batch_size: int, seq_len: int, n_params: int) -> float:
    """Training FLOPs per step: 6N per token + attention score/value terms
    (12·L·S·D per token), the standard MFU accounting."""
    tokens = batch_size * seq_len
    return tokens * (6.0 * n_params + 12.0 * cfg.n_layers * seq_len * cfg.d_model)


def _peak_tflops(device) -> float:
    kind = getattr(device, "device_kind", device.platform).lower()
    for key, peak in _PEAK_TFLOPS.items():
        if key in kind:
            return peak
    return 0.0


def main() -> None:
    probe_cached = False
    if os.environ.get("SATURN_BENCH_FORCE_DEGRADED"):
        platform = None
    else:
        hit = _cached_probe()
        if hit is not None:
            (platform,) = hit
            probe_cached = True
            print(
                f"bench: using cached backend probe ({platform or 'unavailable'})"
                f" from {_probe_sentinel_path()}",
                file=sys.stderr,
            )
        else:
            platform = _probe_backend()
            _store_probe(platform)
    # Degraded = no accelerator: either the probe exhausted retries (wedged
    # tunnel) or it succeeded but the default backend IS the host CPU (no
    # TPU runtime present) — both must take the reduced workload, or the
    # full b8x512 config times out the driver on the 1-core host.
    degraded = platform is None or platform == "cpu"
    if degraded:
        os.environ["JAX_PLATFORMS"] = "cpu"
        cpu_flag = _degraded_cpu_flag()
        if cpu_flag and cpu_flag not in os.environ.get("XLA_FLAGS", ""):
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "") + " " + cpu_flag
            ).strip()
        reason = ("unavailable after retries" if platform is None
                  else "absent (probe returned cpu)")
        print(f"bench: TPU backend {reason}; reduced CPU workload",
              file=sys.stderr)

    import jax

    if degraded:
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import optax

    from saturn_tpu.data.lm_dataset import make_lm_dataset
    from saturn_tpu.models.gpt2 import build_gpt2
    from saturn_tpu.models.loss import pretraining_loss

    # Degraded mode runs a reduced shape and step count: the full b8x512
    # config is minutes of compile plus ~25s/step on the 1-core CI host —
    # the reason BENCH_r03.json timed out instead of recording anything.
    batch_size, seq_len = (2, 256) if degraded else (8, 512)
    n_warmup, n_timed = (1, 3) if degraded else (3, 20)
    spec = build_gpt2("gpt2-small", seq_len=seq_len)
    ds = make_lm_dataset(
        context_length=seq_len,
        batch_size=batch_size,
        vocab_size=spec.config.vocab_size,
        n_tokens=seq_len * batch_size * 16,
    )
    tx = optax.adamw(3e-4)

    def init_state():
        params = spec.init_fn(jax.random.PRNGKey(0))
        return {"params": params, "opt_state": tx.init(params)}

    # Fused head+loss when the model provides it (ops/ce.py) — the same
    # path the executors select for pretraining_loss tasks.
    loss_of_params = spec.fused_loss_fn or (
        lambda p, b: pretraining_loss(spec.apply_fn(p, b), b)
    )

    def train_step(state, batch):
        loss, grads = jax.value_and_grad(loss_of_params)(state["params"], batch)
        updates, new_opt = tx.update(grads, state["opt_state"], state["params"])
        new_params = optax.apply_updates(state["params"], updates)
        return {"params": new_params, "opt_state": new_opt}, loss

    step = jax.jit(train_step, donate_argnums=(0,))
    state = jax.jit(init_state)()
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(state["params"]))
    batches = [jnp.asarray(ds.batch(i)) for i in range(8)]

    # compile + warmup (excluded from timing; SURVEY.md §7 "honest profiling").
    # Sync via host read of the loss: block_until_ready on the tunneled TPU
    # platform can return before queued steps drain (see utils/timing.py).
    for _ in range(n_warmup):
        state, loss = step(state, batches[0])
    float(jax.device_get(loss))

    t0 = timeit.default_timer()
    for i in range(n_timed):
        state, loss = step(state, batches[i % len(batches)])
    float(jax.device_get(loss))
    dt = (timeit.default_timer() - t0) / n_timed

    tokens_per_sec = batch_size * seq_len / dt

    dev = jax.devices()[0]
    peak = _peak_tflops(dev)
    mfu = None
    if peak > 0:
        achieved = _flops_per_step(spec.config, batch_size, seq_len, n_params) / dt
        mfu = achieved / (peak * 1e12)

    base_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "bench_baseline.json"
    )
    key = f"gpt2s_train_tokens_per_sec_{dev.platform}"
    if degraded:
        # Degraded shapes get their own baseline key: a b2x256 CPU number
        # must never update or compare against the b8x512 series.
        key += f"_b{batch_size}x{seq_len}"
    baseline = None
    if os.path.exists(base_path):
        with open(base_path) as f:
            baseline = json.load(f).get(key)
    if baseline is None:
        baseline = tokens_per_sec  # first run on this platform defines the baseline
        try:
            data = {}
            if os.path.exists(base_path):
                with open(base_path) as f:
                    data = json.load(f)
            data[key] = tokens_per_sec
            with open(base_path, "w") as f:
                json.dump(data, f, indent=1)
        except OSError:
            pass

    out = {
        "metric": "gpt2s_train_tokens_per_sec",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(tokens_per_sec / baseline, 4),
        "platform": dev.platform,
    }
    if mfu is not None:
        out["mfu"] = round(mfu, 4)
    if degraded:
        out["degraded"] = ("tpu_unavailable_cpu_fallback" if platform is None
                           else "no_tpu_backend_cpu")
        out["batch_size"] = batch_size
        out["seq_len"] = seq_len
    if probe_cached:
        out["probe_cached"] = True
    if os.environ.get("SATURN_TPU_TSAN", "") == "1":
        # Stamp instrumented runs: traced locks/queues perturb the hot path,
        # so bench_guard refuses to gate on (or record) such a row.
        out["tsan"] = True
    print(json.dumps(out))


if __name__ == "__main__":
    main()
