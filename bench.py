"""Headline benchmark: flagship training throughput on real hardware.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: GPT-2-small causal-LM training throughput (tokens/sec) at batch 8 ×
seq 512 — driver config #1 ("GPT-2-small on WikiText-103, single job, 1
device", BASELINE.md). The reference publishes no in-tree numbers
(SURVEY.md §6), so the baseline is self-measured: the first recorded run's
value is stored in ``bench_baseline.json`` and later runs report
``vs_baseline = value / baseline`` (>1 is faster).
"""

from __future__ import annotations

import json
import os
import timeit


def main() -> None:
    import jax
    import jax.numpy as jnp
    import optax

    from saturn_tpu.data.lm_dataset import make_lm_dataset
    from saturn_tpu.models.gpt2 import build_gpt2
    from saturn_tpu.models.loss import pretraining_loss

    batch_size, seq_len = 8, 512
    spec = build_gpt2("gpt2-small", seq_len=seq_len)
    ds = make_lm_dataset(
        context_length=seq_len,
        batch_size=batch_size,
        vocab_size=spec.config.vocab_size,
        n_tokens=seq_len * batch_size * 16,
    )
    tx = optax.adamw(3e-4)

    def init_state():
        params = spec.init_fn(jax.random.PRNGKey(0))
        return {"params": params, "opt_state": tx.init(params)}

    def train_step(state, batch):
        def loss_of(p):
            return pretraining_loss(spec.apply_fn(p, batch), batch)

        loss, grads = jax.value_and_grad(loss_of)(state["params"])
        updates, new_opt = tx.update(grads, state["opt_state"], state["params"])
        new_params = optax.apply_updates(state["params"], updates)
        return {"params": new_params, "opt_state": new_opt}, loss

    step = jax.jit(train_step, donate_argnums=(0,))
    state = jax.jit(init_state)()
    batches = [jnp.asarray(ds.batch(i)) for i in range(8)]

    # compile + warmup (excluded from timing; SURVEY.md §7 "honest profiling").
    # Sync via host read of the loss: block_until_ready on the tunneled TPU
    # platform can return before queued steps drain (see utils/timing.py).
    for _ in range(3):
        state, loss = step(state, batches[0])
    float(jax.device_get(loss))

    n_timed = 20
    t0 = timeit.default_timer()
    for i in range(n_timed):
        state, loss = step(state, batches[i % len(batches)])
    float(jax.device_get(loss))
    dt = (timeit.default_timer() - t0) / n_timed

    tokens_per_sec = batch_size * seq_len / dt

    base_path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "bench_baseline.json")
    platform = jax.devices()[0].platform
    key = f"gpt2s_train_tokens_per_sec_{platform}"
    baseline = None
    if os.path.exists(base_path):
        with open(base_path) as f:
            baseline = json.load(f).get(key)
    if baseline is None:
        baseline = tokens_per_sec  # first run defines the baseline
        try:
            data = {}
            if os.path.exists(base_path):
                with open(base_path) as f:
                    data = json.load(f)
            data[key] = tokens_per_sec
            with open(base_path, "w") as f:
                json.dump(data, f, indent=1)
        except OSError:
            pass

    print(
        json.dumps(
            {
                "metric": "gpt2s_train_tokens_per_sec",
                "value": round(tokens_per_sec, 1),
                "unit": "tokens/s",
                "vs_baseline": round(tokens_per_sec / baseline, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
