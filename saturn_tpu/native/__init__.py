"""Native (C++) runtime components, ctypes-bound.

The reference's native capability was all external (Gurobi/CBC, Ray's C++
core — SURVEY.md §2.2); here the in-tree native layer is built from source
on first use with the system toolchain and loaded via ctypes (pybind11 is
not in-image). Every native entry point has a pure-Python fallback, so the
framework works — slower — if no compiler is available.

Components:
- ``libspase``   — SPASE list-scheduler + local search (``spase.cpp``)
- ``libtokenize`` — corpus tokenizer/chunker (``tokenize.cpp``)
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

log = logging.getLogger("saturn_tpu")

_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_DIR, "_build")
_LOCK = threading.Lock()
_CACHE: dict = {}


def _ensure_built(name: str) -> Optional[str]:
    """Compile ``<name>.cpp`` → ``_build/lib<name>.so`` if missing/stale."""
    src = os.path.join(_DIR, f"{name}.cpp")
    out = os.path.join(_BUILD_DIR, f"lib{name}.so")
    if not os.path.exists(src):
        # source-less install (only prebuilt artifacts shipped): use the .so
        # if present, else fall back to Python.
        return out if os.path.exists(out) else None
    if os.path.exists(out) and os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    os.makedirs(_BUILD_DIR, exist_ok=True)
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", out, src]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (OSError, subprocess.SubprocessError) as e:
        log.warning("native build of %s failed (%r); using Python fallback", name, e)
        return None
    return out


def load(name: str) -> Optional[ctypes.CDLL]:
    """Build-if-needed and dlopen ``lib<name>.so``; None if unavailable."""
    with _LOCK:
        if name in _CACHE:
            return _CACHE[name]
        path = _ensure_built(name)
        lib = None
        if path is not None:
            try:
                lib = ctypes.CDLL(path)
            except OSError as e:
                log.warning("dlopen(%s) failed: %r", path, e)
        _CACHE[name] = lib
        return lib
