// Native corpus tokenizer: word-level vocabulary build + encode in one pass.
//
// The reference's data pipeline tokenized WikiText with torchtext's native
// tokenizer/vocab machinery and cached the id stream
// (examples/wikitext103/dataloaders/dataloaders.py:70-84). This is the
// in-tree native equivalent: lowercase word/punctuation split, frequency-
// ranked vocabulary capped at max_vocab (id 0 = pad, 1 = <unk>), greedy
// encode of every token to int32 ids. The Python side caches the result as
// .npz, so this runs once per corpus.
//
// Protocol (ctypes-friendly): call with out_ids == NULL to get the required
// token count; allocate; call again to fill. Negative returns are errors.

#include <algorithm>
#include <cctype>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

bool read_file(const char* path, std::string& out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return false;
  std::fseek(f, 0, SEEK_END);
  const long n = std::ftell(f);
  if (n < 0) {
    std::fclose(f);
    return false;
  }
  std::fseek(f, 0, SEEK_SET);
  out.resize(static_cast<size_t>(n));
  const size_t got = n ? std::fread(&out[0], 1, static_cast<size_t>(n), f) : 0;
  std::fclose(f);
  return got == static_cast<size_t>(n);
}

// ASCII-only classifiers: the std::ctype functions are locale-dependent (a
// non-C LC_CTYPE classifies bytes >= 0x80 as alnum), which would diverge
// from the Python fallback's ASCII regex and poison the .npz cache. These
// match `[a-z0-9]` / `\s` after ASCII lowercasing exactly, per byte.
inline bool ascii_alnum_lower(unsigned char c) {
  return (c >= 'a' && c <= 'z') || (c >= '0' && c <= '9');
}
inline unsigned char ascii_lower(unsigned char c) {
  return (c >= 'A' && c <= 'Z') ? static_cast<unsigned char>(c + 32) : c;
}
inline bool ascii_space(unsigned char c) {
  return c == ' ' || c == '\t' || c == '\n' || c == '\r' || c == '\v' ||
         c == '\f';
}

// Lowercased word (alnum run) / single punctuation-char tokens.
void split_tokens(const std::string& text, std::vector<std::string>& toks) {
  std::string cur;
  for (unsigned char raw : text) {
    const unsigned char c = ascii_lower(raw);
    if (ascii_alnum_lower(c)) {
      cur.push_back(static_cast<char>(c));
    } else {
      if (!cur.empty()) {
        toks.push_back(cur);
        cur.clear();
      }
      if (!ascii_space(c)) toks.emplace_back(1, static_cast<char>(c));
    }
  }
  if (!cur.empty()) toks.push_back(cur);
}

}  // namespace

extern "C" {

// Returns the corpus token count (>= 0) or a negative error code
// (-1 unreadable file, -2 bad args). When out_ids is non-NULL it must hold
// out_capacity entries; encoding stops short if the capacity is too small.
long word_tokenize_file(const char* path, int max_vocab,
                        const char* vocab_out_path, int32_t* out_ids,
                        long out_capacity, int* out_vocab_size) {
  if (!path || max_vocab < 3) return -2;
  std::string text;
  if (!read_file(path, text)) return -1;

  std::vector<std::string> toks;
  split_tokens(text, toks);
  const long n = static_cast<long>(toks.size());
  if (!out_ids) return n;

  // Frequency count, ranked descending (ties: first occurrence wins so the
  // mapping is deterministic across runs).
  std::unordered_map<std::string, std::pair<long, long>> freq;  // count, first
  freq.reserve(toks.size() / 4 + 16);
  for (long i = 0; i < n; ++i) {
    auto it = freq.find(toks[i]);
    if (it == freq.end())
      freq.emplace(toks[i], std::make_pair(1L, i));
    else
      ++it->second.first;
  }
  std::vector<const std::pair<const std::string, std::pair<long, long>>*> ranked;
  ranked.reserve(freq.size());
  for (const auto& kv : freq) ranked.push_back(&kv);
  std::sort(ranked.begin(), ranked.end(), [](const auto* a, const auto* b) {
    if (a->second.first != b->second.first)
      return a->second.first > b->second.first;
    return a->second.second < b->second.second;
  });

  const size_t keep =
      std::min(ranked.size(), static_cast<size_t>(max_vocab - 2));
  std::unordered_map<std::string, int32_t> vocab;
  vocab.reserve(keep * 2);
  for (size_t r = 0; r < keep; ++r)
    vocab.emplace(ranked[r]->first, static_cast<int32_t>(r + 2));
  if (out_vocab_size) *out_vocab_size = static_cast<int>(keep + 2);

  if (vocab_out_path && vocab_out_path[0]) {
    FILE* vf = std::fopen(vocab_out_path, "wb");
    if (vf) {
      std::fputs("<pad>\n<unk>\n", vf);
      for (size_t r = 0; r < keep; ++r)
        std::fprintf(vf, "%s\n", ranked[r]->first.c_str());
      std::fclose(vf);
    }
  }

  const long m = std::min(n, out_capacity);
  for (long i = 0; i < m; ++i) {
    auto it = vocab.find(toks[i]);
    out_ids[i] = (it == vocab.end()) ? 1 : it->second;
  }
  return n;
}

}  // extern "C"
