// Native corpus tokenizer: word-level vocabulary build + encode in one pass.
//
// The reference's data pipeline tokenized WikiText with torchtext's native
// tokenizer/vocab machinery and cached the id stream
// (examples/wikitext103/dataloaders/dataloaders.py:70-84). This is the
// in-tree native equivalent: lowercase word/punctuation split, frequency-
// ranked vocabulary capped at max_vocab (id 0 = pad, 1 = <unk>), greedy
// encode of every token to int32 ids. The Python side caches the result as
// .npz, so this runs once per corpus.
//
// Protocol (ctypes-friendly): call with out_ids == NULL to get the required
// token count; allocate; call again to fill. Negative returns are errors.

#include <algorithm>
#include <cctype>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <utility>
#include <string>
#include <unordered_map>
#include <vector>

#include <sys/stat.h>

namespace {

bool read_file(const char* path, std::string& out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return false;
  std::fseek(f, 0, SEEK_END);
  const long n = std::ftell(f);
  if (n < 0) {
    std::fclose(f);
    return false;
  }
  std::fseek(f, 0, SEEK_SET);
  out.resize(static_cast<size_t>(n));
  const size_t got = n ? std::fread(&out[0], 1, static_cast<size_t>(n), f) : 0;
  std::fclose(f);
  return got == static_cast<size_t>(n);
}

// ASCII-only classifiers: the std::ctype functions are locale-dependent (a
// non-C LC_CTYPE classifies bytes >= 0x80 as alnum), which would diverge
// from the Python fallback's ASCII regex and poison the .npz cache. These
// match `[a-z0-9]` / `\s` after ASCII lowercasing exactly, per byte.
inline bool ascii_alnum_lower(unsigned char c) {
  return (c >= 'a' && c <= 'z') || (c >= '0' && c <= '9');
}
inline unsigned char ascii_lower(unsigned char c) {
  return (c >= 'A' && c <= 'Z') ? static_cast<unsigned char>(c + 32) : c;
}
inline bool ascii_space(unsigned char c) {
  return c == ' ' || c == '\t' || c == '\n' || c == '\r' || c == '\v' ||
         c == '\f';
}

// One fully-encoded corpus, cached between the count call and the fill
// call of the two-call ctypes protocol — pre-round-4 both calls redid the
// whole split/count/rank (and the token stream was a vector of 100M+
// std::strings, ~3 GB of allocator traffic at WikiText-103 scale; the
// interned stream below is 4 bytes/token).
struct Encoded {
  std::string path;
  int max_vocab = 0;
  long file_size = -1;
  double file_mtime = -1.0;
  std::vector<int32_t> ids;          // final vocab ids, ready to copy out
  std::vector<std::string> words;    // ranked vocab (ids 2..keep+1)
  int vocab_size = 0;
  bool valid = false;
};

// (size, mtime) of a file — the cache staleness key; (-1, -1) if stat fails.
void stat_file(const char* path, long* size, double* mtime) {
  *size = -1;
  *mtime = -1.0;
  struct stat st;
  if (::stat(path, &st) == 0) {
    *size = static_cast<long>(st.st_size);
    *mtime = static_cast<double>(st.st_mtim.tv_sec) +
             1e-9 * static_cast<double>(st.st_mtim.tv_nsec);
  }
}

// Single pass: intern tokens to dense first-occurrence ids, count, rank,
// then remap the dense stream to vocab ids. Tie-break parity with the
// Python fallback: intern order IS first-occurrence order.
bool build_encoded(const char* path, int max_vocab, Encoded& out) {
  std::string text;
  if (!read_file(path, text)) return false;

  std::unordered_map<std::string, int32_t> intern;
  intern.reserve(1 << 16);
  std::vector<long> counts;
  std::vector<const std::string*> words;  // dense id -> token text
  std::vector<int32_t> dense;
  dense.reserve(text.size() / 5 + 16);

  std::string cur;
  auto emit = [&](const std::string& tok) {
    auto it = intern.find(tok);
    int32_t id;
    if (it == intern.end()) {
      id = static_cast<int32_t>(intern.size());
      auto ins = intern.emplace(tok, id);
      counts.push_back(0);
      words.push_back(&ins.first->first);
    } else {
      id = it->second;
    }
    ++counts[id];
    dense.push_back(id);
  };
  std::string punct(1, '\0');
  for (unsigned char raw : text) {
    const unsigned char c = ascii_lower(raw);
    if (ascii_alnum_lower(c)) {
      cur.push_back(static_cast<char>(c));
    } else {
      if (!cur.empty()) {
        emit(cur);
        cur.clear();
      }
      if (!ascii_space(c)) {
        punct[0] = static_cast<char>(c);
        emit(punct);
      }
    }
  }
  if (!cur.empty()) emit(cur);

  // Rank by (count desc, first occurrence asc == dense id asc).
  const size_t u = counts.size();
  std::vector<int32_t> order(u);
  for (size_t i = 0; i < u; ++i) order[i] = static_cast<int32_t>(i);
  std::sort(order.begin(), order.end(), [&](int32_t a, int32_t b) {
    if (counts[a] != counts[b]) return counts[a] > counts[b];
    return a < b;
  });
  const size_t keep = std::min(u, static_cast<size_t>(max_vocab - 2));
  std::vector<int32_t> remap(u, 1);  // default <unk>
  out.words.clear();
  out.words.reserve(keep);
  for (size_t r = 0; r < keep; ++r) {
    remap[order[r]] = static_cast<int32_t>(r + 2);
    out.words.push_back(*words[order[r]]);
  }

  out.ids.resize(dense.size());
  for (size_t i = 0; i < dense.size(); ++i) out.ids[i] = remap[dense[i]];
  out.path = path;
  out.max_vocab = max_vocab;
  out.file_size = static_cast<long>(text.size());
  out.vocab_size = static_cast<int>(keep + 2);
  out.valid = true;
  return true;
}

std::mutex g_cache_mu;
// Cache keyed per (path, max_vocab): interleaved count/fill call pairs for
// different corpora (or vocab caps) must not invalidate each other — the
// single-slot version silently reverted to two full builds per encode in
// exactly that pattern. Entries are erased on fill, so only unpaired count
// calls linger; the size cap bounds worst-case resident id streams
// (~4 B/token each) if a caller counts many corpora and never fills.
constexpr size_t kCacheCap = 4;
std::map<std::pair<std::string, int>, Encoded> g_cache;

}  // namespace

extern "C" {

// Returns the corpus token count (>= 0) or a negative error code
// (-1 unreadable file, -2 bad args). When out_ids is non-NULL it must hold
// out_capacity entries; encoding stops short if the capacity is too small.
long word_tokenize_file(const char* path, int max_vocab,
                        const char* vocab_out_path, int32_t* out_ids,
                        long out_capacity, int* out_vocab_size) {
  if (!path || max_vocab < 3) return -2;
  if (out_ids && out_capacity < 0) return -2;  // memcpy below must not
  //                                              underflow to a huge size_t
  // The Python wrapper calls count (out_ids == NULL) then fill; the cache
  // makes the pair cost ONE build. Freshness is checked on (file size,
  // mtime) so a corpus rewritten between an unpaired count call and a
  // later call re-builds — size alone misses same-length rewrites.
  long cur_size;
  double cur_mtime;
  stat_file(path, &cur_size, &cur_mtime);
  const std::pair<std::string, int> key{path, max_vocab};

  Encoded local;
  Encoded* enc = nullptr;
  {
    std::lock_guard<std::mutex> lock(g_cache_mu);
    auto it = g_cache.find(key);
    if (it != g_cache.end()) {
      if (it->second.valid && it->second.file_size == cur_size &&
          it->second.file_mtime == cur_mtime) {
        if (!out_ids) return static_cast<long>(it->second.ids.size());
        // Fill call: take ownership so the entry frees on return and the
        // build below never runs.
        local = std::move(it->second);
        g_cache.erase(it);
        enc = &local;
      } else {
        // Stale (corpus rewritten since the count call): free the old
        // ~4 B/token stream now, not at process exit.
        g_cache.erase(it);
      }
    }
  }
  if (!enc) {
    // Build OUTSIDE the lock: concurrent encodes of unrelated corpora must
    // not serialize behind each other's multi-second builds. Two threads
    // racing on the SAME key both build; the insert below keeps one.
    if (!build_encoded(path, max_vocab, local)) return -1;
    local.file_size = cur_size;
    local.file_mtime = cur_mtime;
    enc = &local;
    if (!out_ids) {
      const long n = static_cast<long>(local.ids.size());
      std::lock_guard<std::mutex> lock(g_cache_mu);
      if (g_cache.size() >= kCacheCap) g_cache.erase(g_cache.begin());
      g_cache[key] = std::move(local);
      return n;
    }
  }
  const long n = static_cast<long>(enc->ids.size());
  if (!out_ids) return n;

  if (out_vocab_size) *out_vocab_size = enc->vocab_size;
  if (vocab_out_path && vocab_out_path[0]) {
    FILE* vf = std::fopen(vocab_out_path, "wb");
    if (vf) {
      std::fputs("<pad>\n<unk>\n", vf);
      for (const auto& w : enc->words)
        std::fprintf(vf, "%s\n", w.c_str());
      std::fclose(vf);
    }
  }
  const long m = std::min(n, out_capacity);
  std::memcpy(out_ids, enc->ids.data(), sizeof(int32_t) * m);
  return n;  // `local` frees the ~4B/token stream on return
}

}  // extern "C"
