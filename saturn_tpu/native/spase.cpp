// Native SPASE scheduler core: joint (strategy, block, start-time) assignment.
//
// The reference delegated all native scheduling work to external C++ —
// Gurobi/CBC branch-and-bound behind PuLP (saturn/solver/milp.py:322-327) and
// Ray's C++ raylet for placement (saturn/executor/executor.py:59-62). This is
// the in-tree native equivalent for the TPU rebuild: a list-scheduling
// constructor plus time-bounded stochastic local search over task orderings.
// It consumes the same inputs as the Python MILP (per-task options of
// (block offset, block size, runtime) over a ring of `capacity` devices) and
// produces the same outputs (chosen option, start time per task, makespan).
//
// Used as the fast path for large batches and as the fallback when the MILP
// hits its time limit; the Python side validates the plan (no overlap on any
// device) before trusting it.

#include <algorithm>
#include <chrono>
#include <cstdint>
#include <random>
#include <utility>
#include <vector>

namespace {

struct Option {
  int offset;
  int size;
  double runtime;
};

struct Instance {
  int n_tasks = 0;
  int capacity = 0;
  double slack = 0.0;
  std::vector<std::vector<Option>> opts;
};

// Place tasks one by one in `order`; each task takes the (option, earliest
// aligned slot) pair minimizing its own finish time given what is already
// placed — unless `forced[t] >= 0` pins its option (the local-search move
// that escapes the myopic per-task choice, e.g. everyone-grabs-the-big-block
// schedules that a narrower option would parallelize). Occupied windows are
// extended by `slack` so consecutive tasks on a shared device keep the same
// separation the MILP's ordering constraints enforce. Returns the makespan
// (finish times exclude the slack pad).
double evaluate(const Instance& inst, const std::vector<int>& order,
                const std::vector<int>& forced, std::vector<int>& chosen,
                std::vector<double>& starts) {
  std::vector<std::vector<std::pair<double, double>>> busy(inst.capacity);
  double makespan = 0.0;
  chosen.assign(inst.n_tasks, -1);
  starts.assign(inst.n_tasks, 0.0);
  std::vector<std::pair<double, double>> merged;

  for (int t : order) {
    double best_fin = 1e300, best_start = 0.0;
    int best_opt = -1;
    const auto& topts = inst.opts[t];
    for (int oi = 0; oi < static_cast<int>(topts.size()); ++oi) {
      if (forced[t] >= 0 && forced[t] != oi) continue;
      const Option& o = topts[oi];
      merged.clear();
      for (int d = o.offset; d < o.offset + o.size; ++d)
        merged.insert(merged.end(), busy[d].begin(), busy[d].end());
      std::sort(merged.begin(), merged.end());
      const double dur = o.runtime + inst.slack;
      double t0 = 0.0;
      for (const auto& iv : merged) {
        if (t0 + dur <= iv.first) break;
        t0 = std::max(t0, iv.second);
      }
      const double fin = t0 + o.runtime;
      if (fin < best_fin) {
        best_fin = fin;
        best_start = t0;
        best_opt = oi;
      }
    }
    const Option& o = topts[best_opt];
    for (int d = o.offset; d < o.offset + o.size; ++d)
      busy[d].emplace_back(best_start, best_start + o.runtime + inst.slack);
    chosen[t] = best_opt;
    starts[t] = best_start;
    makespan = std::max(makespan, best_fin);
  }
  return makespan;
}

}  // namespace

extern "C" {

// Inputs are flattened: task t's options live at indices
// [opt_starts[t], opt_starts[t] + opt_counts[t]) of the *_flat arrays.
// warm_opt (nullable) warm-starts the search from a previous plan: warm_opt[t]
// is the option index to pin task t to in a second constructor pass (-1 = no
// pin); the local search then starts from whichever constructor won. This is
// the native analog of the reference's Gurobi warmStart seeding
// (saturn/solver/milp.py:103-104,151-155,323).
// Returns 0 on success, nonzero on malformed input.
// (v2: the warm_opt parameter was inserted in round 2 — the symbol is
// versioned so a stale prebuilt .so fails symbol lookup and the caller
// falls back gracefully instead of writing through a misplaced pointer.)
int spase_solve_v2(int n_tasks, const int* opt_counts, const int* opt_offset_flat,
                const int* opt_size_flat, const double* opt_runtime_flat,
                int capacity, double time_limit_s, double ordering_slack,
                uint64_t seed, const int* warm_opt, int* chosen_out,
                double* start_out, double* makespan_out) {
  if (n_tasks <= 0 || capacity <= 0) return 1;

  Instance inst;
  inst.n_tasks = n_tasks;
  inst.capacity = capacity;
  inst.slack = ordering_slack;
  inst.opts.resize(n_tasks);
  int flat = 0;
  for (int t = 0; t < n_tasks; ++t) {
    if (opt_counts[t] <= 0) return 2;  // task with no feasible option
    for (int i = 0; i < opt_counts[t]; ++i, ++flat) {
      Option o{opt_offset_flat[flat], opt_size_flat[flat],
               opt_runtime_flat[flat]};
      if (o.offset < 0 || o.size <= 0 || o.offset + o.size > capacity ||
          o.runtime < 0.0)
        return 3;
      inst.opts[t].push_back(o);
    }
  }

  // Constructor: longest-minimum-runtime first (the classic LPT rule, and
  // the same order the Python greedy uses).
  std::vector<int> order(n_tasks);
  for (int t = 0; t < n_tasks; ++t) order[t] = t;
  std::vector<double> min_rt(n_tasks);
  for (int t = 0; t < n_tasks; ++t) {
    double m = 1e300;
    for (const auto& o : inst.opts[t]) m = std::min(m, o.runtime);
    min_rt[t] = m;
  }
  std::stable_sort(order.begin(), order.end(),
                   [&](int a, int b) { return min_rt[a] > min_rt[b]; });

  std::vector<int> chosen, best_chosen;
  std::vector<double> starts, best_starts;
  std::vector<int> forced(n_tasks, -1);
  double best = evaluate(inst, order, forced, best_chosen, best_starts);

  // Warm constructor: pin each task to its previous plan's option and
  // re-evaluate; adopt if it beats (or ties) the LPT constructor so the
  // local search walks out from the incumbent schedule.
  if (warm_opt != nullptr) {
    std::vector<int> wforced(n_tasks, -1);
    bool any = false;
    for (int t = 0; t < n_tasks; ++t) {
      if (warm_opt[t] >= 0 && warm_opt[t] < static_cast<int>(inst.opts[t].size())) {
        wforced[t] = warm_opt[t];
        any = true;
      }
    }
    if (any) {
      std::vector<int> wchosen;
      std::vector<double> wstarts;
      const double wm = evaluate(inst, order, wforced, wchosen, wstarts);
      if (wm <= best) {
        best = wm;
        best_chosen = wchosen;
        best_starts = wstarts;
        forced = wforced;
      }
    }
  }

  // Local search: random order swap / reinsertion / option-pinning moves,
  // deterministic seed. Pinning a task's option (forced) is what escapes the
  // constructor's myopic min-finish choice — but a single pin usually lands
  // on a plateau (same makespan), so acceptance is "not worse": the walk
  // drifts sideways and coordinated multi-pin improvements can accumulate.
  // The strictly-best schedule is tracked separately and is what's returned.
  std::vector<int> cur_order = order, cur_forced = forced;
  double cur = best;
  const auto t_begin = std::chrono::steady_clock::now();
  const auto deadline =
      t_begin + std::chrono::duration<double>(std::max(0.0, time_limit_s));
  std::mt19937_64 rng(seed);
  std::uniform_int_distribution<int> pick(0, n_tasks - 1);

  int stale = 0;
  const int max_stale = 20000;
  while (n_tasks > 0 && stale < max_stale &&
         std::chrono::steady_clock::now() < deadline) {
    order = cur_order;
    forced = cur_forced;
    const uint64_t move = rng() % 3;
    if (move == 0 && n_tasks > 1) {
      const int a = pick(rng);
      int b = pick(rng);
      while (b == a) b = pick(rng);
      std::swap(order[a], order[b]);
    } else if (move == 1 && n_tasks > 1) {
      const int a = pick(rng);
      int b = pick(rng);
      while (b == a) b = pick(rng);
      const int v = order[a];
      order.erase(order.begin() + a);
      order.insert(order.begin() + b, v);
    } else {
      const int t = pick(rng);
      const int nopt = static_cast<int>(inst.opts[t].size());
      // pin a random option, or release an existing pin.
      if (forced[t] >= 0 && (rng() & 1))
        forced[t] = -1;
      else
        forced[t] = static_cast<int>(rng() % nopt);
    }
    const double m = evaluate(inst, order, forced, chosen, starts);
    if (m <= cur + 1e-12) {  // accept sideways: plateau random walk
      cur = m;
      cur_order = order;
      cur_forced = forced;
    }
    if (m < best - 1e-12) {
      best = m;

      best_chosen = chosen;
      best_starts = starts;
      stale = 0;
    } else {
      ++stale;
    }
  }

  for (int t = 0; t < n_tasks; ++t) {
    chosen_out[t] = best_chosen[t];
    start_out[t] = best_starts[t];
  }
  *makespan_out = best;
  return 0;
}

}  // extern "C"
