"""Device-side batch prefetch: overlap host staging with device compute.

The per-step hot loop (``parallel/spmd_base.py::execute``) used to alternate
host work (numpy batch slicing + ``device_put``) with device work one step at
a time, so the accelerator idled through every transfer — the host/device
bubble MPMD-pipelining systems close by overlapping transfer with compute.
:class:`DevicePrefetcher` closes it with the smallest mechanism that works:
a background daemon thread stages unit i+1 onto the device (under the
bundle's batch sharding) while the main thread runs unit i, double-buffered
through a bounded queue so at most ``depth`` staged units are alive at once.

JAX dispatch is thread-safe: ``device_put`` from the producer thread and the
jitted step from the consumer thread enqueue onto the same device stream
without coordination beyond the queue hand-off.
"""

from __future__ import annotations

import logging
import queue
import sys
import threading
from typing import Any, Callable

from saturn_tpu.analysis import concurrency as tsan

log = logging.getLogger("saturn_tpu")

_POLL_S = 0.1
#: How long ``close()`` waits for the producer thread before declaring it
#: wedged and abandoning it (it is a daemon; a hung ``stage`` callback must
#: not hang the interval's unwind path too).
_CLOSE_JOIN_S = 5.0

#: Sentinel returned by :meth:`DevicePrefetcher.try_next` while the producer
#: is still staging the next unit — distinct from any staged value and from
#: exhaustion (which raises StopIteration like the iterator protocol does).
NOT_READY = object()


class ShapeContractError(ValueError):
    """A staged value violates the prefetcher's declared shape contract.

    For fused groups the contract is the stacked ``(N, batch, seq)`` /
    ``(K, N, batch, seq)`` window (``ops/stacking.py``); the per-MEMBER
    mismatch inside a stack is already attributed by
    ``stacking.stack_member_batches`` (it raises ``MemberShapeError`` naming
    the exact task id), so reaching here means the stack as a whole — or a
    solo batch — came out the wrong shape for the compiled program.
    """

    def __init__(self, unit: int, got, expect, member_names=None):
        self.unit = unit
        self.got = got
        self.expect = expect
        self.member_names = list(member_names) if member_names else None
        who = (
            f" (fused group of {len(self.member_names)}: "
            f"{self.member_names})" if self.member_names else ""
        )
        super().__init__(
            f"staged unit {unit} has shape {got}, expected one of "
            f"{list(expect)}{who} — the staging callback and the compiled "
            f"program disagree on the batch layout"
        )


class DevicePrefetcher:
    """Iterate device-staged values produced by a background thread.

    ``stage(i)`` is called for ``i in range(n)`` on the producer thread and
    must return the device-resident value for unit ``i`` (host slicing +
    ``device_put``). Iteration yields those values in order.

    Exceptions from ``stage`` — **including** ``BaseException`` subclasses
    like the crash harness's ``SimulatedKill``, which ``except Exception``
    would miss — are captured and re-raised in the consumer at the position
    they occurred, so a kill barrier inside batch staging still unwinds the
    interval exactly like the synchronous path did.

    ``close()`` must run even on abnormal exits (use ``try/finally``): it
    unblocks a producer parked on a full queue and joins the thread, so a
    killed interval never leaks a producer that keeps slicing batches from a
    task the harness is rolling back. Consuming every item closes
    implicitly.

    **Staged-shape contract.** A staged value's leading dims are whatever
    the compiled program was lowered for: ``(batch, seq)`` per-step,
    ``(K, batch, seq)`` for a solo fused window, and for a FUSED GROUP the
    stacked forms ``(N, batch, seq)`` / ``(K, N, batch, seq)`` with the
    member axis explicit. Pass ``expect_shapes`` (the allowed shapes) and
    ``member_names`` (stack order) and the producer validates every staged
    value BEFORE hand-off, raising :class:`ShapeContractError` that names
    the offending member instead of the opaque XLA arity/shape error the
    consumer's compiled call would produce.
    """

    def __init__(
        self,
        n: int,
        stage: Callable[[int], Any],
        depth: int = 2,
        expect_shapes: Any = None,
        member_names: Any = None,
    ):
        self.n = int(n)
        self._stage = stage
        self._expect = (
            tuple(tuple(int(d) for d in s) for s in expect_shapes)
            if expect_shapes else None
        )
        self._member_names = list(member_names) if member_names else None
        self._q: "queue.Queue" = tsan.make_queue(
            "prefetch.q", maxsize=max(1, int(depth))
        )
        self._closed = threading.Event()
        self._taken = 0
        self._thread = threading.Thread(
            target=self._produce, name="saturn-prefetch", daemon=True
        )
        self._thread.start()

    def _check_shape(self, i: int, item: Any) -> None:
        """Enforce the staged-shape contract (no-op when ``expect_shapes``
        was not given). Runs on the producer thread so the attributable
        error crosses to the consumer through the normal error channel."""
        if self._expect is None:
            return
        shape = tuple(getattr(item, "shape", ()) or ())
        if shape in self._expect:
            return
        raise ShapeContractError(i, shape, self._expect, self._member_names)

    def _produce(self) -> None:
        try:
            for i in range(self.n):
                if self._closed.is_set():
                    return
                item = self._stage(i)
                self._check_shape(i, item)
                if not self._offer(("ok", item)):
                    return
        except BaseException as e:  # SimulatedKill must cross the thread
            self._offer(("err", e))

    def _offer(self, msg) -> bool:
        """Bounded put that gives up once the consumer closed us — a plain
        blocking put would park this thread forever after an early close."""
        while not self._closed.is_set():
            try:
                self._q.put(msg, timeout=_POLL_S)
                return True
            except queue.Full:
                continue
        return False

    def __iter__(self) -> "DevicePrefetcher":
        return self

    def __next__(self):
        if self._taken >= self.n:
            self.close()
            raise StopIteration
        while True:
            try:
                tag, val = self._q.get(timeout=_POLL_S)
                break
            except queue.Empty:
                if self._closed.is_set():
                    raise StopIteration
                if not self._thread.is_alive():
                    # always posts ("err", e) before dying, so an empty queue
                    # with a dead producer is a bug worth failing loudly on
                    raise RuntimeError(
                        "prefetch producer thread died without posting a "
                        "result or an error"
                    )
        if tag == "err":
            self._taken = self.n
            raise val
        self._taken += 1
        return val

    def try_next(self):
        """Non-blocking ``__next__``: the staged value when the producer has
        it ready, :data:`NOT_READY` while staging is still in flight, and
        StopIteration on exhaustion (same protocol as iteration). The
        co-schedule shared launcher uses this so one member's slow host
        staging never parks the launcher while another member has device
        windows ready to dispatch — the interleave win depends on it."""
        if self._taken >= self.n:
            self.close()
            raise StopIteration
        try:
            tag, val = self._q.get_nowait()
        except queue.Empty:
            if self._closed.is_set():
                raise StopIteration
            if not self._thread.is_alive():
                raise RuntimeError(
                    "prefetch producer thread died without posting a "
                    "result or an error"
                )
            return NOT_READY
        if tag == "err":
            self._taken = self.n
            raise val
        self._taken += 1
        return val

    def close(self) -> None:
        """Stop the producer, join it with a timeout, and re-raise a pending
        producer error the consumer never got to see (idempotent).

        The timed join means a WEDGED producer (a ``stage`` callback stuck
        in I/O) can never hang the interval's unwind path: past the timeout
        the daemon thread is abandoned with a warning — the hung-dispatch
        watchdog owns escalation. A pending ``("err", e)`` drained here used
        to be swallowed; now it re-raises, but only when this close is NOT
        already unwinding another exception (masking the in-flight error
        from a ``finally``/``GeneratorExit`` would trade a real traceback
        for a stale one) and the consumer hasn't already consumed an error
        for this run.
        """
        self._closed.set()
        pending = self._drain()  # a producer blocked on put() can now observe close
        self._thread.join(timeout=_CLOSE_JOIN_S)
        if self._thread.is_alive():
            log.warning(
                "prefetch producer wedged: not joinable after %.1fs — "
                "abandoning the daemon thread", _CLOSE_JOIN_S,
            )
        # The producer may have slipped one last item in between the drain
        # and observing the close flag; drain again now that it is dead so
        # post-close iteration deterministically sees an empty queue.
        pending = self._drain() or pending
        if (
            pending is not None
            and self._taken < self.n          # consumer never saw an error
            and sys.exc_info()[1] is None     # not unwinding something else
        ):
            self._taken = self.n
            raise pending

    def _drain(self):
        """Empty the queue; returns the first pending producer exception
        encountered (or None) instead of silently discarding it."""
        pending = None
        while True:
            try:
                tag, val = self._q.get_nowait()
            except queue.Empty:
                return pending
            if tag == "err" and pending is None:
                pending = val

    def __enter__(self) -> "DevicePrefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
