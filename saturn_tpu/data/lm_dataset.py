"""Language-model datasets: one token stream, chunked into fixed-length batches.

Parity target: ``examples/wikitext103/dataloaders/dataloaders.py:22-84`` —
tokenize a corpus into a single token stream, cache it, slice into
``context_length`` chunks, and serve (input, label) pairs where label == input
(the loss does the shift). TPU-native deltas:

- Batches are dense numpy int32 arrays with **static shapes** (XLA requirement).
- ``batch(i)`` is O(1) random access, fixing the reference's O(position)
  iterator fast-forward on resume (``Task.py:138-139``).
- With no network access, the default corpus is a deterministic synthetic
  Zipf-distributed token stream; a local text file can be supplied and is
  byte-tokenized and cached as ``.npz`` exactly like the reference's cache
  (``dataloaders.py:70-84``).
"""

from __future__ import annotations

import hashlib
import os
from typing import Optional

import numpy as np


class TokenDataset:
    """Fixed-shape LM batches over one token stream."""

    def __init__(
        self,
        tokens: np.ndarray,
        context_length: int = 512,
        batch_size: int = 8,
    ):
        tokens = np.asarray(tokens, dtype=np.int32)
        self.context_length = context_length
        self.batch_size = batch_size
        n_chunks = len(tokens) // context_length
        if n_chunks < batch_size:
            raise ValueError(
                f"corpus too small: {n_chunks} chunks < batch_size {batch_size}"
            )
        self._chunks = tokens[: n_chunks * context_length].reshape(
            n_chunks, context_length
        )
        self._n_batches = n_chunks // batch_size

    def __len__(self) -> int:
        """Batches per epoch (reference ``Task.py:127`` epoch_length)."""
        return self._n_batches

    def batch(self, i: int) -> np.ndarray:
        """(batch_size, context_length) int32 tokens for batch index ``i``."""
        i = i % self._n_batches
        return self._chunks[i * self.batch_size : (i + 1) * self.batch_size]

    def example_batch(self) -> np.ndarray:
        return np.zeros((self.batch_size, self.context_length), dtype=np.int32)


def synthetic_tokens(
    n_tokens: int, vocab_size: int, seed: int = 0, zipf_a: float = 1.2
) -> np.ndarray:
    """Deterministic Zipf-ish token stream — realistic rank-frequency shape so
    embedding-gather and softmax behave like natural text."""
    rng = np.random.default_rng(seed)
    ranks = rng.zipf(zipf_a, size=n_tokens)
    return (ranks % vocab_size).astype(np.int32)


def byte_tokenize_file(path: str, cache_dir: str = ".saturn_data_cache") -> np.ndarray:
    """Byte-level tokenization of a local text file, cached as .npz
    (cache scheme parity with ``dataloaders.py:70-84``)."""
    os.makedirs(cache_dir, exist_ok=True)
    key = hashlib.sha1(os.path.abspath(path).encode()).hexdigest()[:16]
    cache = os.path.join(cache_dir, f"bytes_{key}.npz")
    if os.path.exists(cache):
        with np.load(cache) as z:
            return z["tokens"]
    with open(path, "rb") as f:
        tokens = np.frombuffer(f.read(), dtype=np.uint8).astype(np.int32)
    np.savez(cache, tokens=tokens)
    return tokens


def _word_tokenize_python(data: bytes, max_vocab: int):
    """Pure-Python fallback for the native tokenizer — byte-identical
    semantics to ``tokenize.cpp``: operates on raw bytes, ASCII-only
    lowercasing, ASCII-alnum runs are words, each non-space non-alnum byte is
    its own token, frequency-ranked vocab, 0=pad 1=<unk>. (Multi-byte UTF-8
    chars split into byte tokens on both paths, so native and fallback yield
    the same id stream for any corpus.)"""
    import re
    from collections import Counter

    toks = [
        m.decode("latin-1")
        for m in re.findall(rb"[a-z0-9]+|[^\sa-z0-9]", data.lower())
    ]
    counts = Counter(toks)
    first = {}
    for i, t in enumerate(toks):
        first.setdefault(t, i)
    ranked = sorted(counts, key=lambda t: (-counts[t], first[t]))[: max_vocab - 2]
    vocab = {t: i + 2 for i, t in enumerate(ranked)}
    ids = np.fromiter((vocab.get(t, 1) for t in toks), dtype=np.int32, count=len(toks))
    return ids, len(vocab) + 2


def word_tokenize_file(
    path: str,
    max_vocab: int = 32768,
    cache_dir: str = ".saturn_data_cache",
) -> tuple:
    """Word-level tokenization of a local text file → (ids, vocab_size).

    Native fast path: ``native/tokenize.cpp`` (the in-tree analog of the
    reference's torchtext tokenizer+vocab pipeline, ``dataloaders.py:70-84``);
    pure-Python fallback when no compiler is available. Results are cached as
    ``.npz`` keyed on (path, max_vocab), exactly like the reference's cache.
    """
    import ctypes

    from saturn_tpu import native

    os.makedirs(cache_dir, exist_ok=True)
    key = hashlib.sha1(
        f"{os.path.abspath(path)}:{max_vocab}".encode()
    ).hexdigest()[:16]
    cache = os.path.join(cache_dir, f"words_{key}.npz")
    if os.path.exists(cache):
        with np.load(cache) as z:
            return z["tokens"], int(z["vocab_size"])

    lib = native.load("tokenize")
    if lib is not None:
        fn = lib.word_tokenize_file
        fn.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_long,
            ctypes.POINTER(ctypes.c_int),
        ]
        fn.restype = ctypes.c_long
        p = path.encode()
        n = fn(p, max_vocab, None, None, 0, None)
        if n >= 0:
            ids = np.empty(n, dtype=np.int32)
            vs = ctypes.c_int()
            vocab_path = os.path.join(cache_dir, f"vocab_{key}.txt")
            got = fn(
                p, max_vocab, vocab_path.encode(),
                ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                n, ctypes.byref(vs),
            )
            if got == n:
                np.savez(cache, tokens=ids, vocab_size=vs.value)
                return ids, int(vs.value)

    with open(path, "rb") as f:
        ids, vocab_size = _word_tokenize_python(f.read(), max_vocab)
    np.savez(cache, tokens=ids, vocab_size=vocab_size)
    return ids, vocab_size


def make_lm_dataset(
    context_length: int = 512,
    batch_size: int = 8,
    vocab_size: int = 50304,
    n_tokens: Optional[int] = None,
    corpus_path: Optional[str] = None,
    seed: int = 0,
    tokenizer: str = "byte",
    reserved_ids: int = 0,
) -> TokenDataset:
    """Dataloader factory for ``Task(get_dataloader=...)``.

    Uses ``corpus_path`` if given and present — ``tokenizer="byte"`` (ids are
    raw bytes; vocab must be >= 256) or ``tokenizer="word"`` (native
    frequency-ranked word vocab capped at ``vocab_size``) — else a synthetic
    stream of ``n_tokens`` tokens (default: enough for 64 batches).

    ``reserved_ids`` keeps the top that-many ids of the model's vocab out of
    the data on every path, so they can serve as special tokens. MLM tasks
    MUST pass ``reserved_ids=1`` to reserve the [MASK] id
    (``models/bert.py``): data ids stay in ``[0, vocab_size - reserved_ids)``
    (synthetic generation and the word vocab are capped; the byte path
    requires ``vocab_size - reserved_ids >= 256``).
    """
    if reserved_ids < 0 or reserved_ids >= vocab_size:
        raise ValueError(f"reserved_ids must be in [0, vocab_size), got {reserved_ids}")
    data_vocab = vocab_size - reserved_ids
    if corpus_path and os.path.exists(corpus_path):
        if tokenizer == "word":
            # vocab is *capped* (rare words -> <unk>), so the id range always
            # fits the model's embedding table minus any reserved ids.
            tokens, _ = word_tokenize_file(corpus_path, max_vocab=data_vocab)
        elif tokenizer == "byte":
            if data_vocab < 256:
                raise ValueError(
                    f"byte tokenizer emits ids up to 255 but only "
                    f"{data_vocab} unreserved ids exist "
                    f"(vocab_size={vocab_size}, reserved_ids={reserved_ids})"
                )
            tokens = byte_tokenize_file(corpus_path)
        else:
            raise ValueError(f"unknown tokenizer {tokenizer!r} (byte|word)")
    else:
        if n_tokens is None:
            n_tokens = context_length * batch_size * 64
        tokens = synthetic_tokens(n_tokens, data_vocab, seed=seed)
    return TokenDataset(tokens, context_length=context_length, batch_size=batch_size)
