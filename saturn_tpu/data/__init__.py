from saturn_tpu.data.prefetch import DevicePrefetcher

__all__ = ["DevicePrefetcher"]
