"""WikiText-scale corpus synthesis for the zero-egress CI image.

The reference tokenized real WikiText-103 (~500 MB raw, 103M tokens, 267k
word types capped to a 50k vocab — ``examples/wikitext103/dataloaders/
dataloaders.py:70-84``). This image has no network, so scale testing of the
data path needs a locally generated corpus with the same *shape*:

- word frequencies matching a natural rank-frequency (Zipf) curve — taken
  empirically from the bundled seed text rather than assumed;
- MORE distinct word types than the vocab cap, so the 50k-vocab build has
  real ``<unk>`` pressure and a non-trivial ranked cut;
- hundreds of MB of text, generated in seconds (vectorized sampling).

Token order is iid by design: the tokenizer under test builds an
order-independent frequency vocab and encodes greedily, so bigram realism
would cost generation time and change nothing measured. Deterministic in
``seed``.
"""

from __future__ import annotations

import os
import re
from collections import Counter
from typing import Optional

import numpy as np

_DEFAULT_SEED_TEXT = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "..", "..", "examples",
    "data", "corpus.txt",
)
_WORDS_PER_LINE = 18


def _seed_distribution(seed_path: str, n_extra_types: int):
    """(types, probabilities): empirical seed-word distribution extended
    with a Zipf tail of synthetic rare types (``w<i>q``) so the total
    type count exceeds any realistic vocab cap."""
    with open(seed_path, "rb") as f:
        data = f.read()
    toks = re.findall(rb"[a-z0-9]+", data.lower())
    counts = Counter(t.decode("ascii") for t in toks)
    types = list(counts)
    freqs = np.array([counts[t] for t in types], dtype=np.float64)
    # Synthetic tail continues the empirical curve: rank r gets weight
    # proportional to 1/(r0 + r), where r0 is the seed's type count.
    r0 = len(types)
    tail_ranks = np.arange(1, n_extra_types + 1, dtype=np.float64)
    tail = freqs.min() * r0 / (r0 + tail_ranks)
    types += [f"w{i}q" for i in range(n_extra_types)]
    p = np.concatenate([freqs, tail])
    return np.array(types), p / p.sum()


def generate_corpus(
    out_path: str,
    size_mb: float = 120.0,
    seed_path: Optional[str] = None,
    n_extra_types: int = 65536,
    seed: int = 0,
) -> dict:
    """Write ~``size_mb`` MB of synthetic text to ``out_path``.

    Returns {"bytes", "tokens", "types"}. Skips generation if the file
    already exists at >= the requested size AND a sidecar ``.meta.json``
    records the same generation parameters — a corpus written with a
    different seed / seed_path / n_extra_types is regenerated, not silently
    reused (size alone can't tell them apart). On reuse the sidecar's
    token/type counts are returned so benchmark metadata never sees None.
    """
    import json

    target = int(size_mb * 1e6)
    meta_path = out_path + ".meta.json"
    params = {
        "seed_path": os.path.abspath(seed_path or _DEFAULT_SEED_TEXT),
        "n_extra_types": int(n_extra_types),
        "seed": int(seed),
    }
    # The byte count is estimated from mean word length, so the written
    # size lands within a few percent of target; treat >= 90% as done.
    if os.path.exists(out_path) and os.path.getsize(out_path) >= 0.9 * target:
        meta = None
        try:
            with open(meta_path) as f:
                meta = json.load(f)
        except (OSError, ValueError):
            pass
        if meta is not None and meta.get("params") == params:
            return {"bytes": os.path.getsize(out_path),
                    "tokens": meta.get("tokens"), "types": meta.get("types"),
                    "reused": True}
        log_msg = ("existing corpus %s has %s generation parameters — "
                   "regenerating" % (out_path,
                                     "different" if meta else "unknown"))
        print(f"corpus_gen: {log_msg}")
    # Invalidate the sidecar BEFORE rewriting the body: an interrupted
    # regeneration must not leave a new-params body paired with old-params
    # metadata (a later call would silently reuse the wrong corpus).
    try:
        os.remove(meta_path)
    except OSError:
        pass
    types, p = _seed_distribution(seed_path or _DEFAULT_SEED_TEXT,
                                  n_extra_types)
    mean_len = float((np.char.str_len(types) * p).sum())
    per_tok = mean_len + 1.0  # the joining space / newline
    n_tokens = int(target / per_tok)
    rng = np.random.default_rng(seed)
    os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
    written = 0
    total_toks = 0
    chunk = 2_000_000
    with open(out_path, "w") as f:
        while total_toks < n_tokens:
            m = min(chunk, n_tokens - total_toks)
            ids = rng.choice(len(types), size=m, p=p)
            words = types[ids]
            lines = [
                " ".join(words[i:i + _WORDS_PER_LINE])
                for i in range(0, m, _WORDS_PER_LINE)
            ]
            s = "\n".join(lines) + "\n"
            f.write(s)
            written += len(s)
            total_toks += m
    with open(meta_path, "w") as f:
        json.dump({"params": params, "tokens": total_toks,
                   "types": len(types)}, f)
    return {"bytes": written, "tokens": total_toks, "types": len(types)}


def main() -> None:  # pragma: no cover - thin CLI
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", required=True)
    ap.add_argument("--size-mb", type=float, default=120.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--seed-text", default=None)
    args = ap.parse_args()
    info = generate_corpus(args.out, args.size_mb, args.seed_text,
                           seed=args.seed)
    print(info)


if __name__ == "__main__":  # pragma: no cover
    main()
