"""On-disk cache of AOT-compiled XLA executables (serialized, reloadable).

JAX's persistent *compilation* cache (``profile_cache.
maybe_enable_persistent_compile_cache``) is opt-in here because a cache
shared across execution contexts with different feature detection can load
mismatched entries (see ``tests/conftest.py``). This module is the narrower,
always-safe alternative for the programs saturn_tpu itself builds: each
``jit(...).lower(...)`` result is keyed by a content hash of its OWN HLO
text plus the runtime identity (jax version, backend, device kinds/count,
machine), and the compiled executable is serialized with
``jax.experimental.serialize_executable`` into a subdirectory of the
persistent profile-cache directory. On restart — the recovery replay path,
or an online admission re-building a previously-seen program — the
executable is deserialized instead of recompiled, cutting the cold-start
compile tax that dominates both paths.

Every failure mode (missing file, pickle/deserialize error, device-set
mismatch, API drift) degrades to a recompile, never an error: a wrong or
unloadable entry costs exactly what not having the cache costs. Entries are
plain pickle files in a local trusted cache directory — the same trust
domain as the profile entries beside them; delete the directory to
invalidate everything.

Environment:

- ``SATURN_TPU_AOT_CACHE=1`` forces the cache on, ``=0`` forces it off.
  Unset, it is on for TPU backends and OFF on CPU: the conftest-documented
  XLA:CPU hazard — AOT-loaded machine code from an execution context with
  different CPU feature detection executes anyway ("machine type doesn't
  match" is a warning, not an error) and silently wedges collective
  programs — applies to serialized executables exactly as it does to the
  persistent compilation cache, so CPU opts in per-context instead.
- ``SATURN_TPU_PROFILE_CACHE=0`` (the global profile-cache kill switch)
  disables it too, since it lives inside that directory.
- ``SATURN_TPU_PROFILE_CACHE_DIR`` moves the root (the ``aot/`` subdir).
"""

from __future__ import annotations

import hashlib
import logging
import os
import pickle
import platform
import threading
from typing import Any, Optional

log = logging.getLogger("saturn_tpu")

_ENV_TOGGLE = "SATURN_TPU_AOT_CACHE"
_SUBDIR = "aot"

#: Bump when the payload layout changes meaning — old entries then miss.
SCHEMA_VERSION = 1

_stats_lock = threading.Lock()
_stats = {"hits": 0, "misses": 0, "stores": 0, "errors": 0,
          "prewarms": 0, "warm_hits": 0}

# In-process warm pool fed by the compile-ahead service
# (``tenancy.compile_ahead``): executables compiled in the background
# between admission and first dispatch. Same-process, so none of the
# cross-context serialize/deserialize hazards apply — the warm pool is
# consulted even when the on-disk cache is disabled (CPU default).
_warm_lock = threading.Lock()
_warm: dict = {}


def stats() -> dict:
    """Copy of the process-lifetime hit/miss counters (telemetry, tests)."""
    with _stats_lock:
        return dict(_stats)


def _bump(k: str) -> None:
    with _stats_lock:
        _stats[k] += 1


def enabled() -> bool:
    from saturn_tpu.utils import profile_cache as _pc

    raw = os.environ.get(_ENV_TOGGLE)
    if raw is not None and raw.lower() in _pc._FALSEY:
        return False
    if raw is None:
        # default: TPU only — see the module docstring's CPU hazard note
        try:
            import jax

            if jax.default_backend() not in ("tpu",):
                return False
        except Exception:
            return False
    # riding inside the profile-cache directory means riding its kill switch
    return _pc.default_cache() is not None


def cache_dir() -> str:
    from saturn_tpu.utils import profile_cache as _pc

    return os.path.join(_pc.default_dir(), _SUBDIR)


def _fusion_version() -> int:
    """Fused-stacking machinery version (lazy: utils must not import
    parallel at module level; 0 = fusion unavailable)."""
    try:
        from saturn_tpu.parallel.fused import FUSION_SET_VERSION

        return int(FUSION_SET_VERSION)
    except Exception:
        return 0


def _runtime_identity() -> str:
    """Everything about the process that makes a serialized executable
    loadable: a hit compiled under a different jax, backend, device set or
    machine must miss (and would fail loudly at deserialize time anyway —
    the key check just makes the common case cheap)."""
    import jax

    from saturn_tpu.analysis import SCHEMA_VERSION as _ANALYSIS_SCHEMA
    from saturn_tpu.analysis.memlens import PASS_VERSION as _MEMLENS_PASS
    from saturn_tpu.analysis.shardflow import PASS_VERSION as _SHARDFLOW_PASS

    devs = jax.devices()
    return ";".join(
        [
            f"schema{SCHEMA_VERSION}",
            # analyzer rule-set version: diagnostics-driven plan repairs
            # must never deserialize executables cached under older rules
            f"lint{_ANALYSIS_SCHEMA}",
            # shardflow rule-set version: sharding findings gate what gets
            # compiled, so an executable cached under one rule set must
            # miss under another
            f"shardflow{_SHARDFLOW_PASS}",
            # memlens liveness-model version: static feasibility verdicts
            # gate what lowers at all, so executables cached under one
            # liveness model must miss under another
            f"memlens{_MEMLENS_PASS}",
            # fused-stacking version: the stacked step's HLO depends on the
            # fusion machinery, so executables cached under one stacked
            # program must miss when FUSION_SET_VERSION bumps
            f"fusion{_fusion_version()}",
            f"jax:{jax.__version__}",
            f"backend:{jax.default_backend()}",
            f"machine:{platform.machine()}",
            f"devices:{len(devs)}",
            "kinds:" + ",".join(sorted({getattr(d, "device_kind", "?") for d in devs})),
        ]
    )


def cache_key(lowered: Any, devices: Any = None) -> Optional[str]:
    """Content key for a ``jit(...).lower(...)`` result; None = uncacheable.

    The HLO text pins the program (shapes, dtypes, shardings, donation all
    lower into it); the runtime identity pins where it can load. ``devices``
    (the concrete device block the program was lowered for) MUST be part of
    the key whenever the caller compiles the same program for different
    blocks: GSPMD sharding annotations use logical device indices, so the
    physical assignment lives only in the executable — loading a twin
    program pinned to a different block would silently run on the wrong
    chips.
    """
    try:
        text = lowered.as_text()
    except Exception:
        return None
    h = hashlib.sha256()
    h.update(_runtime_identity().encode())
    h.update(b"\x00")
    if devices is not None:
        ids = ",".join(
            str(getattr(d, "id", i)) for i, d in enumerate(devices)
        )
        h.update(f"block:{ids}".encode())
        h.update(b"\x00")
    h.update(text.encode())
    return h.hexdigest()


def _path(key: str) -> str:
    return os.path.join(cache_dir(), f"{key}.jaxexec")


def _load(key: str) -> Optional[Any]:
    try:
        with open(_path(key), "rb") as f:
            payload, in_tree, out_tree = pickle.load(f)
        from jax.experimental.serialize_executable import deserialize_and_load

        return deserialize_and_load(payload, in_tree, out_tree)
    except FileNotFoundError:
        return None
    except Exception as e:
        # corrupt / stale / cross-context entry: a miss, never an error
        _bump("errors")
        log.info("aot cache entry %s unloadable (%r) — recompiling", key[:12], e)
        try:
            os.unlink(_path(key))
        except OSError:
            pass
        return None


def _store(key: str, compiled: Any) -> bool:
    try:
        from jax.experimental.serialize_executable import serialize

        payload, in_tree, out_tree = serialize(compiled)
        blob = pickle.dumps((payload, in_tree, out_tree))
    except Exception as e:
        _bump("errors")
        log.info("aot executable not serializable (%r) — caching skipped", e)
        return False
    path = _path(key)
    tmp = path + f".tmp.{os.getpid()}.{threading.get_ident()}"
    try:
        os.makedirs(cache_dir(), exist_ok=True)
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False
    _bump("stores")
    return True


def load_or_compile(lowered: Any, devices: Any = None) -> Any:
    """The compiled executable for ``lowered``, via the on-disk cache.

    Cache hit: deserialize and skip XLA compilation entirely. Miss (or the
    cache is disabled/unwritable/unloadable): ``lowered.compile()`` as
    before, then serialize the result for the next process. The deserialized
    executable runs the identical machine code a fresh compile would
    produce, so results — including donation/aliasing behavior — are
    unchanged. One caveat: ``memory_analysis()`` may be unavailable on a
    deserialized executable; ``utils.timing.hbm_bytes_required`` already
    degrades that to "feasible, with a warning".
    """
    key = cache_key(lowered, devices)
    if key is not None:
        # Compile-ahead warm pool first: same process, no load hazard,
        # works even where the disk cache is off (CPU default).
        with _warm_lock:
            warm = _warm.get(key)
        if warm is not None:
            _bump("warm_hits")
            return warm
    if not enabled():
        return lowered.compile()
    if key is None:
        return lowered.compile()
    hit = _load(key)
    if hit is not None:
        _bump("hits")
        return hit
    _bump("misses")
    compiled = lowered.compile()
    _store(key, compiled)
    return compiled


def prewarm(lowered: Any, devices: Any = None) -> Any:
    """Compile ``lowered`` now and park the executable in the warm pool.

    Called from compile-ahead worker threads. The executable goes two
    places: the in-process warm pool (always — that is what makes the
    admitted job's first ``load_or_compile`` free), and the on-disk
    cache via the normal :func:`load_or_compile` path when enabled (so
    the prewarm also survives a restart).
    """
    compiled = load_or_compile(lowered, devices)
    key = cache_key(lowered, devices)
    if key is not None:
        with _warm_lock:
            _warm[key] = compiled
        _bump("prewarms")
    return compiled


def clear_warm() -> None:
    """Drop the warm pool (tests; bounded-memory resets)."""
    with _warm_lock:
        _warm.clear()
