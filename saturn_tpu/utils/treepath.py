"""Single canonical tree-path stringifier.

Used both for checkpoint array keys (``utils/checkpoint.py``) and sharding
rule paths (``parallel/sharding.py``) — one implementation so saved keys and
rule patterns can never silently disagree.
"""

from __future__ import annotations


def path_str(path) -> str:
    """Stable '/'-joined key for a jax tree path (DictKey / SequenceKey /
    GetAttrKey / FlattenedIndexKey)."""
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)
