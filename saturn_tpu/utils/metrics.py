"""Structured metrics: append-only JSONL event stream.

The reference's observability was prints + stdlib logging + the Ray dashboard
(SURVEY.md §5 "Metrics / logging": "No metrics files, no TensorBoard"). This
fills that gap with the smallest thing that composes: every subsystem emits
typed events (trial results, interval timing/estimate error, solver
makespans, task failures) to one JSONL file a notebook or `jq` can consume.

Disabled unless configured — ``search(metrics_path=...)`` /
``orchestrate(metrics_path=...)`` or :func:`configure` directly.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import threading
import time
from typing import Optional

logger = logging.getLogger("saturn_tpu")


class MetricsWriter:
    """Thread-safe JSONL appender (the engine launches tasks from threads)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._fh = open(path, "a", buffering=1)

    def event(self, kind: str, **fields) -> None:
        rec = {"ts": time.time(), "kind": kind}
        rec.update(fields)
        line = json.dumps(rec, default=str)
        try:
            with self._lock:
                self._fh.write(line + "\n")
        except ValueError:
            # The module-level event() reads _WRITER without _CONF_LOCK, so a
            # racing configure()/scoped() may close this file between the read
            # and the write. Dropping the event is fine; raising inside an
            # engine launcher thread would record a spurious task failure.
            pass

    def close(self) -> None:
        """Close the stream, fsyncing first: ``configure``/``scoped`` rotate
        sinks by closing the old writer, so rotation is a durability point —
        a crash right after must not lose the rotated-out events to the page
        cache."""
        with self._lock:
            if not self._fh.closed:
                try:
                    self._fh.flush()
                    os.fsync(self._fh.fileno())
                except (OSError, ValueError):
                    pass
            self._fh.close()


_WRITER: Optional[MetricsWriter] = None
_CONF_LOCK = threading.Lock()


def configure(path: Optional[str]) -> None:
    """Point the global metrics stream at ``path`` (None disables)."""
    global _WRITER
    with _CONF_LOCK:
        if _WRITER is not None:
            _WRITER.close()
        _WRITER = MetricsWriter(path) if path else None


def event(kind: str, **fields) -> None:
    """Emit an event if metrics are configured; no-op otherwise."""
    w = _WRITER
    if w is not None:
        w.event(kind, **fields)


def read_events(path: str, kind: Optional[str] = None) -> list:
    """Read a JSONL metrics file back as dicts, optionally filtered by
    ``kind`` — the test/analysis counterpart to :func:`event`. Lines that
    fail to parse (a crashed writer's torn tail) are skipped with a
    WARNING — losing the last in-flight event to a crash is expected,
    losing it *silently* is not."""
    out = []
    with open(path) as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                logger.warning(
                    "metrics: skipping torn/corrupt line %d of %s "
                    "(%d bytes) — a crashed writer's unflushed tail",
                    lineno, path, len(line),
                )
                continue
            if kind is None or rec.get("kind") == kind:
                out.append(rec)
    return out


def tail_events(path: str, kind: Optional[str] = None,
                poll_s: float = 0.2, stop=None, follow: bool = True):
    """Generator over a live JSONL metrics stream (``tail -f`` semantics).

    Yields events from the start of the file, then keeps polling for
    appended lines every ``poll_s`` until ``stop`` (a ``threading.Event``)
    is set — or returns at EOF when ``follow=False``. A partial trailing
    line (the writer mid-append) is buffered, not parsed, so a torn tail
    never raises and never yields a truncated record; the line is delivered
    once its newline lands."""
    buf = ""
    with open(path) as fh:
        while True:
            chunk = fh.read(65536)
            if chunk:
                buf += chunk
                while "\n" in buf:
                    line, buf = buf.split("\n", 1)
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        # A mid-file torn line: a pre-crash writer's tail
                        # that a restarted writer appended past.
                        logger.warning(
                            "metrics: skipping torn/corrupt line in %s "
                            "(%d bytes)", path, len(line),
                        )
                        continue
                    if kind is None or rec.get("kind") == kind:
                        yield rec
                continue
            if not follow or (stop is not None and stop.is_set()):
                return
            time.sleep(poll_s)


@contextlib.contextmanager
def scoped(path: Optional[str]):
    """Route events to ``path`` for the enclosed region, then restore the
    previous sink and close the file — so ``orchestrate(metrics_path=...)``
    cannot leak its writer into later runs."""
    global _WRITER
    if not path:
        yield
        return
    mine = MetricsWriter(path)
    with _CONF_LOCK:
        prev = _WRITER
        _WRITER = mine
    try:
        yield
    finally:
        with _CONF_LOCK:
            # A configure() call inside the region may have replaced (and
            # closed) our writer — only close/restore what is still ours.
            if _WRITER is mine:
                _WRITER = prev
        mine.close()
