"""Structured metrics: append-only JSONL event stream.

The reference's observability was prints + stdlib logging + the Ray dashboard
(SURVEY.md §5 "Metrics / logging": "No metrics files, no TensorBoard"). This
fills that gap with the smallest thing that composes: every subsystem emits
typed events (trial results, interval timing/estimate error, solver
makespans, task failures) to one JSONL file a notebook or `jq` can consume.

Disabled unless configured — ``search(metrics_path=...)`` /
``orchestrate(metrics_path=...)`` or :func:`configure` directly.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import threading
import time
from typing import Optional

from saturn_tpu.analysis import concurrency as tsan

logger = logging.getLogger("saturn_tpu")


class MetricsWriter:
    """Thread-safe JSONL appender (the engine launches tasks from threads).

    Events are buffered in memory and written in batches — size-bounded
    (``max_buffered`` events) and time-bounded (``max_latency_s`` since the
    oldest unwritten event) — so emission stays off the step critical path:
    the old line-buffered stream paid a syscall + page-cache write per event
    from inside interval hot loops. Hot-path callers just append under the
    lock; the engine/orchestrator/service call :func:`flush` at interval
    boundaries, and ``close()`` always drains.

    Torn-tail guarantees are unchanged: each drain is a single ``write()``
    of whole ``\\n``-terminated lines, so a crash can tear at most the last
    line in flight — exactly what ``read_events``/``tail_events`` already
    skip-and-warn on. What buffering *does* change is the loss window: a
    crash between flushes drops the buffered (never-written) events, which
    is why the durability journal — not metrics — is the ledger of record.
    """

    def __init__(self, path: str, max_buffered: int = 256,
                 max_latency_s: float = 2.0):
        self.path = path
        self.max_buffered = max(1, int(max_buffered))
        self.max_latency_s = float(max_latency_s)
        self._lock = tsan.lock("metrics.writer")
        self._fh = open(path, "a")
        self._buf: list = []
        self._oldest: Optional[float] = None  # monotonic ts of _buf[0]

    def event(self, kind: str, **fields) -> None:
        rec = {"ts": time.time(), "kind": kind}
        rec.update(fields)
        line = json.dumps(rec, default=str)
        now = time.monotonic()
        with self._lock:
            if self._fh.closed:
                # The module-level event() reads _WRITER without _CONF_LOCK,
                # so a racing configure()/scoped() may close this writer
                # between the read and this call. Dropping the event is fine;
                # raising inside an engine launcher thread would record a
                # spurious task failure.
                return
            self._buf.append(line)
            if self._oldest is None:
                self._oldest = now
            if (len(self._buf) >= self.max_buffered
                    or now - self._oldest >= self.max_latency_s):
                self._drain_locked()

    def _drain_locked(self) -> None:
        if not self._buf or self._fh.closed:
            self._buf = []
            self._oldest = None
            return
        data = "\n".join(self._buf) + "\n"
        self._buf = []
        self._oldest = None
        try:
            self._fh.write(data)
            self._fh.flush()
        except (OSError, ValueError):
            pass

    def flush(self) -> None:
        """Write out everything buffered (interval-boundary durability for
        live ``tail_events`` followers and post-run ``read_events``)."""
        with self._lock:
            self._drain_locked()

    def close(self) -> None:
        """Drain, then close the stream, fsyncing first: ``configure``/
        ``scoped`` rotate sinks by closing the old writer, so rotation is a
        durability point — a crash right after must not lose the rotated-out
        events to the page cache."""
        with self._lock:
            self._drain_locked()
            if not self._fh.closed:
                try:
                    self._fh.flush()
                    # sanctioned-unlocked: close IS the rotation durability
                    # point; fsync under the lock keeps late event() callers
                    # from interleaving appends into a half-synced stream.
                    os.fsync(self._fh.fileno())
                except (OSError, ValueError):
                    pass
            self._fh.close()


_WRITER: Optional[MetricsWriter] = None
_CONF_LOCK = tsan.lock("metrics.conf")


def configure(path: Optional[str]) -> None:
    """Point the global metrics stream at ``path`` (None disables)."""
    global _WRITER
    with _CONF_LOCK:
        if _WRITER is not None:
            _WRITER.close()
        _WRITER = MetricsWriter(path) if path else None


def enabled() -> bool:
    """True when a metrics sink is configured — lets emitters skip *computing*
    expensive event fields (e.g. the task_interval MFU numerator's one-time
    shardflow trace) when every event would be dropped anyway."""
    # sanctioned-unlocked: single-reference read of a lock-managed global
    return _WRITER is not None


def event(kind: str, **fields) -> None:
    """Emit an event if metrics are configured; no-op otherwise."""
    # Invariant: _WRITER swaps are atomic (one assignment under _CONF_LOCK)
    # and a stale writer is drained-then-closed, where event() degrades to
    # a documented drop (see MetricsWriter.event) — taking _CONF_LOCK here
    # would put a mutex acquisition on every hot-path emission.
    # sanctioned-unlocked: single-reference read of a lock-managed global
    w = _WRITER
    if w is not None:
        w.event(kind, **fields)


def flush() -> None:
    """Drain the configured writer's buffer to disk; no-op when metrics are
    off. Called at interval boundaries (engine, orchestrator, service loop)
    so telemetry lands off the step critical path but before the next
    interval's work starts."""
    # sanctioned-unlocked: same single-reference-read contract as event()
    w = _WRITER
    if w is not None:
        w.flush()


def read_events(path: str, kind: Optional[str] = None) -> list:
    """Read a JSONL metrics file back as dicts, optionally filtered by
    ``kind`` — the test/analysis counterpart to :func:`event`. Lines that
    fail to parse (a crashed writer's torn tail) are skipped with a
    WARNING — losing the last in-flight event to a crash is expected,
    losing it *silently* is not."""
    out = []
    with open(path) as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                logger.warning(
                    "metrics: skipping torn/corrupt line %d of %s "
                    "(%d bytes) — a crashed writer's unflushed tail",
                    lineno, path, len(line),
                )
                continue
            if kind is None or rec.get("kind") == kind:
                out.append(rec)
    return out


def tail_events(path: str, kind: Optional[str] = None,
                poll_s: float = 0.2, stop=None, follow: bool = True):
    """Generator over a live JSONL metrics stream (``tail -f`` semantics).

    Yields events from the start of the file, then keeps polling for
    appended lines every ``poll_s`` until ``stop`` (a ``threading.Event``)
    is set — or returns at EOF when ``follow=False``. A partial trailing
    line (the writer mid-append) is buffered, not parsed, so a torn tail
    never raises and never yields a truncated record; the line is delivered
    once its newline lands."""
    buf = ""
    with open(path) as fh:
        while True:
            chunk = fh.read(65536)
            if chunk:
                buf += chunk
                while "\n" in buf:
                    line, buf = buf.split("\n", 1)
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        # A mid-file torn line: a pre-crash writer's tail
                        # that a restarted writer appended past.
                        logger.warning(
                            "metrics: skipping torn/corrupt line in %s "
                            "(%d bytes)", path, len(line),
                        )
                        continue
                    if kind is None or rec.get("kind") == kind:
                        yield rec
                continue
            if not follow or (stop is not None and stop.is_set()):
                return
            time.sleep(poll_s)


@contextlib.contextmanager
def scoped(path: Optional[str]):
    """Route events to ``path`` for the enclosed region, then restore the
    previous sink and close the file — so ``orchestrate(metrics_path=...)``
    cannot leak its writer into later runs."""
    global _WRITER
    if not path:
        yield
        return
    mine = MetricsWriter(path)
    with _CONF_LOCK:
        prev = _WRITER
        _WRITER = mine
    try:
        yield
    finally:
        with _CONF_LOCK:
            # A configure() call inside the region may have replaced (and
            # closed) our writer — only close/restore what is still ours.
            if _WRITER is mine:
                _WRITER = prev
        mine.close()
