"""Path-keyed pytree checkpointing with cross-technique resharding.

The reference checkpoints model state only, via ``torch.save`` of a state dict
(``Task.py:150-169``), and silently drops optimizer state between intervals
(``FSDP.py:220``, ``DDP.py:163``) — a wart SURVEY.md §5 flags to fix. Here we
save the **full train state** (params + optimizer state + step) keyed by tree
path; the data cursor is derived from ``step`` on restore, making resume
restart-safe.

Format (round 19, ROADMAP item 6): **sharded manifest**. The logical
checkpoint path holds a checksummed JSON manifest (tree structure, leaf
dtypes/shapes, per-leaf shard index→file map, PartitionSpec fingerprint);
the array bytes live beside it in per-rank ``.npz`` shard files named
``<path>.g<GEN>.r<RANK>.npz``. Each process writes only its
locally-addressable shards — the device→host copy is a pure local transfer,
with **no allgather and no replication funnel** (the SAT-X002 anti-pattern
the previous single-writer format needed two sanction markers for). The
global shard layout is computed from sharding *metadata* alone
(``Sharding.devices_indices_map`` is the same on every process), so the
manifest needs no communication either. ``GEN`` is a per-save generation id:
a crashed save can never tear the previously committed generation's files,
and the manifest rename is the single atomic commit point (stale generations
are garbage-collected only after it lands).

Saving by *path* rather than pickling tree structure is what makes
interval-boundary **technique switching** work (the reference's central
trick, ``executor.py:65`` kill-and-respawn + state-dict reload): any
technique can restore the same arrays under a *different* mesh/sharding,
because ``restore_sharded`` maps saved shards onto the destination
technique's shardings leaf by leaf — assembling only the blocks each
destination device needs, so no host materializes the full replicated tree.
A compatibility reader keeps pre-round-19 single-file ``.npz`` checkpoints
restorable (readers sniff JSON-vs-zip on the first byte).
"""

from __future__ import annotations

import glob
import hashlib
import json
import logging
import os
import re
import tempfile
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from saturn_tpu.utils.treepath import path_str as _path_str

log = logging.getLogger("saturn_tpu")

#: Manifest self-identification; readers sniff the first byte (``{`` vs
#: zip's ``PK``) and then check this field.
MANIFEST_FORMAT = "saturn-ckpt-manifest"
MANIFEST_VERSION = 1

#: Shard files committed beside a manifest: ``<path>.g<GEN>.r<RANK>.npz``.
_SHARD_RE = re.compile(r"\.g([0-9a-f]+)\.r(\d+)\.npz$")


class CheckpointCorruptError(RuntimeError):
    """A checkpoint exists on disk but cannot be read back (truncated write,
    bit rot, torn page, missing/corrupt shard file). The unreadable artifact
    has already been quarantined to a ``*.corrupt`` sidecar by the time this
    raises, so crash recovery can fall back to the *previous* published
    checkpoint instead of dying on the newest one."""

    def __init__(self, path: str, quarantined: str, cause: str):
        self.path = path
        self.quarantined = quarantined
        super().__init__(
            f"checkpoint {path} is corrupt ({cause}); quarantined to "
            f"{quarantined}"
        )


def quarantine(path: str) -> str:
    """Rename an unreadable artifact to a ``*.corrupt`` sidecar (never
    overwrite an earlier quarantine: ``.corrupt``, ``.corrupt.1``, ...).
    Returns the sidecar path; if the rename itself fails the original path
    is returned and the file is left in place (recovery treats both the
    same — the path is not a usable checkpoint)."""
    sidecar = path + ".corrupt"
    n = 0
    while os.path.exists(sidecar):
        n += 1
        sidecar = f"{path}.corrupt.{n}"
    try:
        os.replace(path, sidecar)
    except OSError:
        log.exception("failed to quarantine %s", path)
        return path
    return sidecar


# ------------------------------------------------------------ crash barriers
# The resilience crash harness installs a callback here to simulate SIGKILL
# at the two commit-critical crossings of a sharded save: ``mid-shard-write``
# (shard bytes staged, shard rename not yet done) and ``pre-manifest-rename``
# (all shards durable, manifest — the commit point — not yet renamed). A
# kill at either leaves the previous generation fully intact.
_CRASH_BARRIER: Optional[Callable[[str, Dict[str, Any]], None]] = None


def set_crash_barrier(cb: Optional[Callable[[str, Dict[str, Any]], None]]) -> None:
    """Install (None to clear) the crash-harness barrier callback; called as
    ``cb(point, ctx)`` from whichever thread performs the write."""
    global _CRASH_BARRIER
    _CRASH_BARRIER = cb


def _barrier(point: str, **ctx: Any) -> None:
    cb = _CRASH_BARRIER
    if cb is not None:
        cb(point, ctx)


# ---------------------------------------------------------------- sniff/read
def _is_manifest_file(path: str) -> bool:
    """Format sniff: a round-19 manifest is JSON (first byte ``{``); the
    legacy single-file format is a zip (``PK``). Raises OSError for a path
    that cannot be opened — callers decide how missing files surface."""
    with open(path, "rb") as f:
        return f.read(1) == b"{"


def _manifest_checksum(body: Dict[str, Any]) -> str:
    """CRC-32 of the canonical (sorted-key, no-whitespace) JSON body with the
    ``checksum`` field absent — a torn or hand-edited manifest fails closed."""
    scrubbed = {k: v for k, v in body.items() if k != "checksum"}
    canon = json.dumps(scrubbed, sort_keys=True, separators=(",", ":"))
    return f"{zlib.crc32(canon.encode('utf-8')) & 0xFFFFFFFF:08x}"


def _read_manifest(path: str) -> Dict[str, Any]:
    """Parse + integrity-check a manifest. Raises ``ValueError`` on any
    structural or checksum mismatch (callers wrap into quarantine)."""
    with open(path, "r", encoding="utf-8") as f:
        body = json.load(f)
    if body.get("format") != MANIFEST_FORMAT:
        raise ValueError(f"not a {MANIFEST_FORMAT} file")
    if int(body.get("version", -1)) > MANIFEST_VERSION:
        raise ValueError(f"manifest version {body['version']} is newer than "
                         f"this reader ({MANIFEST_VERSION})")
    want = body.get("checksum")
    got = _manifest_checksum(body)
    if want != got:
        raise ValueError(f"manifest checksum mismatch ({want} != {got})")
    return body


def verify(path: str) -> bool:
    """Integrity-check a published checkpoint without loading it into
    memory. Manifest format: the JSON body must checksum, every referenced
    shard file must exist, parse as a zip with every member CRC intact, and
    contain the referenced member keys; every leaf's shard extents must
    cover its full shape. Legacy ``.npz``: the zip central directory must
    parse and every member CRC must match. False for missing, truncated,
    partial or corrupt checkpoints — never raises."""
    import zipfile

    try:
        if _is_manifest_file(path):
            m = _read_manifest(path)
            d = os.path.dirname(os.path.abspath(path))
            members: Dict[str, set] = {}
            for entry in m["leaves"].values():
                covered = 0
                for sh in entry["shards"]:
                    members.setdefault(sh["file"], set()).add(sh["key"])
                    n = 1
                    for start, stop in sh["index"]:
                        n *= max(int(stop) - int(start), 0)
                    covered += n
                total = 1
                for dim in entry["shape"]:
                    total *= int(dim)
                if covered != total:
                    return False  # partial shard set (torn save)
            for fname, keys in members.items():
                fpath = os.path.join(d, fname)
                with zipfile.ZipFile(fpath) as zf:
                    if zf.testzip() is not None:
                        return False
                    have = {os.path.splitext(n)[0] for n in zf.namelist()}
                    if not keys <= have:
                        return False
            return True
        with zipfile.ZipFile(path) as zf:
            return zf.testzip() is None
    except Exception:
        return False


# Publication hooks: called as ``hook(task_or_stem, path)`` after the atomic
# manifest rename lands a checkpoint, from whichever thread performed the
# write (the async writer thread for ``save_async``). The durability layer
# registers one to journal every publication; hooks must be cheap and must
# not raise.
_PUBLISH_HOOKS: list = []


def add_publish_hook(hook) -> None:
    _PUBLISH_HOOKS.append(hook)


def remove_publish_hook(hook) -> None:
    try:
        _PUBLISH_HOOKS.remove(hook)
    except ValueError:
        pass


def _notify_published(path: str) -> None:
    if not _PUBLISH_HOOKS:
        return
    stem = os.path.splitext(os.path.basename(path))[0]
    for hook in list(_PUBLISH_HOOKS):
        try:
            hook(stem, os.path.abspath(path))
        except Exception:
            log.exception("checkpoint publish hook failed for %s", path)


def _writer_rank(tree: Any) -> int:
    """The process that writes this tree's *manifest*: the lowest process
    index that addresses its arrays. For a cross-host sharded/replicated
    state that is the coordinator; for a state living entirely on one host's
    devices it is that host (the coordinator never even sees the tree — the
    multi-host engine only calls execute() on processes local to the task's
    block). Host-only trees (plain numpy) default to rank 0."""
    for leaf in jax.tree_util.tree_leaves(tree):
        ds = getattr(getattr(leaf, "sharding", None), "device_set", None)
        if ds:
            return min(getattr(d, "process_index", 0) for d in ds)
    return 0


def _should_write(tree: Any) -> bool:
    from saturn_tpu.core import distributed

    if not distributed.is_multihost():
        return True
    return distributed.process_index() == _writer_rank(tree)


def _my_rank() -> int:
    from saturn_tpu.core import distributed

    return distributed.process_index() if distributed.is_multihost() else 0


def _stored(arr: np.ndarray) -> np.ndarray:
    # npz can't round-trip ml_dtypes (bfloat16/fp8); widen to float32 —
    # restore() narrows back to the template's dtype.
    if (arr.dtype.kind == "V" or "bfloat16" in str(arr.dtype)
            or "float8" in str(arr.dtype)):
        return arr.astype(np.float32)
    return arr


def _norm_index(index: Tuple, shape: Tuple[int, ...]) -> Tuple[Tuple[int, int], ...]:
    """Resolve a ``devices_indices_map`` slice tuple against ``shape`` into
    concrete ``(start, stop)`` extents — the manifest's shard coordinates."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = int(dim) if sl.stop is None else int(sl.stop)
        out.append((start, stop))
    return tuple(out)


def _pspec_fingerprint(tree: Any) -> str:
    """Stable digest of the tree's per-leaf partition specs — lets restore
    and the ``analysis ckpt`` CLI tell at a glance whether a checkpoint was
    written under the same layout (purely informational: restore reshards
    onto the destination regardless)."""
    items = []
    for p, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        spec = getattr(getattr(leaf, "sharding", None), "spec", None)
        items.append([_path_str(p), "host" if spec is None else str(spec)])
    canon = json.dumps(sorted(items), separators=(",", ":"))
    return hashlib.sha1(canon.encode("utf-8")).hexdigest()[:12]


class _Snapshot:
    """The synchronous half of a save: the global shard plan (manifest body)
    plus this process's shard payloads, already on host. Building one is the
    only part that touches devices — and only via local per-shard
    device→host copies (``shard.data``), never a gather."""

    __slots__ = ("manifest", "local", "rank", "gen", "writes_manifest")

    def __init__(self, manifest: Dict[str, Any], local: Dict[str, np.ndarray],
                 rank: int, gen: str, writes_manifest: bool):
        self.manifest = manifest
        self.local = local
        self.rank = rank
        self.gen = gen
        self.writes_manifest = writes_manifest


def _snapshot(path: str, tree: Any) -> _Snapshot:
    gen = f"{time.time_ns():x}"
    rank = _my_rank()
    wrank = _writer_rank(tree)
    base = os.path.basename(path)
    leaves: Dict[str, Any] = {}
    local: Dict[str, np.ndarray] = {}
    # which ranks own at least one shard — their files must exist on restore
    for tpath, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = _path_str(tpath)
        if key in leaves:
            raise ValueError(f"duplicate tree path key: {key!r}")
        sharding = getattr(leaf, "sharding", None)
        shape = tuple(int(s) for s in getattr(leaf, "shape", np.shape(leaf)))
        dtype = str(getattr(leaf, "dtype", np.asarray(leaf).dtype))
        shards: List[Dict[str, Any]] = []
        if sharding is not None and hasattr(sharding, "devices_indices_map"):
            # Global layout from metadata alone: devices_indices_map is
            # identical on every process, so each rank derives the same
            # plan with zero communication. Replicas dedupe to one owner
            # (lowest (process, device id)) so each block is written once.
            groups: Dict[Tuple, list] = {}
            for dev, index in sharding.devices_indices_map(shape).items():
                groups.setdefault(_norm_index(index, shape), []).append(dev)
            by_dev_id = {
                s.device.id: s for s in getattr(leaf, "addressable_shards", [])
            }
            stored_dtype = None
            for i, extent in enumerate(sorted(groups)):
                owner = min(
                    groups[extent],
                    key=lambda d: (getattr(d, "process_index", 0),
                                   getattr(d, "id", 0)),
                )
                orank = getattr(owner, "process_index", 0)
                member = f"{key}#s{i}"
                shards.append({
                    "index": [[a, b] for a, b in extent],
                    "file": f"{base}.g{gen}.r{orank}.npz",
                    "key": member,
                })
                if orank == rank:
                    dshard = by_dev_id[getattr(owner, "id", 0)]
                    arr = _stored(np.asarray(jax.device_get(dshard.data)))
                    local[member] = arr
                    stored_dtype = str(arr.dtype)
            if stored_dtype is None:  # no local shard: derive, don't copy
                widened = "bfloat16" in dtype or "float8" in dtype
                stored_dtype = "float32" if widened else str(np.dtype(dtype))
        else:
            # Host (plain numpy / python scalar) leaf: one full-extent
            # shard, written by the tree's writer rank.
            arr = _stored(np.asarray(leaf))
            member = f"{key}#s0"
            shards.append({
                "index": [[0, d] for d in shape],
                "file": f"{base}.g{gen}.r{wrank}.npz",
                "key": member,
            })
            stored_dtype = str(arr.dtype)
            if rank == wrank:
                local[member] = arr
        leaves[key] = {
            "shape": list(shape),
            "dtype": dtype,
            "stored_dtype": stored_dtype,
            "shards": shards,
        }
    manifest = {
        "format": MANIFEST_FORMAT,
        "version": MANIFEST_VERSION,
        "generation": gen,
        "pspec_fingerprint": _pspec_fingerprint(tree),
        "leaves": leaves,
    }
    return _Snapshot(manifest, local, rank, gen, rank == wrank)


def _gc_stale_generations(path: str, keep_gen: str) -> None:
    """After the manifest rename lands, older generations' shard files are
    unreachable — remove them (best-effort; a crash here only leaks disk,
    never correctness)."""
    for f in glob.glob(glob.escape(path) + ".g*.npz"):
        m = _SHARD_RE.search(f)
        if m and m.group(1) != keep_gen:
            try:
                os.unlink(f)
            except OSError:
                log.warning("could not GC stale checkpoint shard %s", f)


def _commit_snapshot(path: str, snap: _Snapshot) -> None:
    """The disk half of a save: stage + rename this rank's shard file, then
    (manifest writer only) stage + rename the manifest — the atomic commit
    point — and notify publication. Crash-barrier crossings bracket both
    renames; a kill at either leaves the previous generation untouched."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    if snap.local:
        fname = os.path.join(d, f"{os.path.basename(path)}"
                                f".g{snap.gen}.r{snap.rank}.npz")
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                np.savez(f, **snap.local)
            _barrier("mid-shard-write", path=fname, tmp=tmp, gen=snap.gen)
            os.replace(tmp, fname)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
    if not snap.writes_manifest:
        return
    body = dict(snap.manifest)
    body["checksum"] = _manifest_checksum(body)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            json.dump(body, f, separators=(",", ":"))
        _barrier("pre-manifest-rename", path=path, tmp=tmp, gen=snap.gen)
        os.replace(tmp, path)  # atomic: no torn checkpoints on crash
        _notify_published(path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    _gc_stale_generations(path, snap.gen)


def save(path: str, tree: Any) -> None:
    """Atomically write a sharded pytree checkpoint rooted at ``path``.

    Each process pulls only its locally-addressable shards to host (no
    collective of any kind) and writes them to its own generation-tagged
    shard file; the tree's writer rank additionally commits the manifest.
    The manifest rename is the commit point — a crash at any earlier moment
    leaves the previously published checkpoint fully readable."""
    snap = _snapshot(path, tree)
    _commit_snapshot(path, snap)


# --------------------------------------------------------------- async writes
# End-of-interval checkpoints are GB-scale (full train state incl. optimizer):
# the device->host transfer must happen synchronously (the engine may donate
# the buffers into the next interval's first step), but the DISK write can
# overlap the next interval's compute. One writer thread per path; restore()
# and a second save() to the same path wait for the in-flight write first.
# A failed write is recorded per path and re-raised at the next join point
# (exists/restore/save_async/flush) — a checkpoint that never hit disk must
# not be silently reported as saved.
_PENDING: Dict[str, threading.Thread] = {}
_FAILED: Dict[str, BaseException] = {}
_PENDING_LOCK = threading.Lock()


def _wait_pending(path: str) -> None:
    key = os.path.abspath(path)
    with _PENDING_LOCK:
        t = _PENDING.get(key)
    if t is not None:
        t.join()
    with _PENDING_LOCK:
        err = _FAILED.pop(key, None)
    if err is not None:
        raise RuntimeError(f"async checkpoint write to {path} failed") from err


def _record_async_failure(key: str, path: str, err: BaseException) -> None:
    """Park a background-write failure for the next join point. Keep-first:
    if an earlier failure for this path is still unconsumed, the new one is
    logged and dropped — the first error is the root cause a join point
    must surface (the engine's ``_record_error(keep_first)`` convention)."""
    with _PENDING_LOCK:
        prev = _FAILED.get(key)
        if prev is not None:
            log.warning(
                "async checkpoint write to %s failed again (%r); keeping "
                "first error %r", path, err, prev,
            )
        else:
            _FAILED[key] = err


def save_async(path: str, tree: Any) -> None:
    """``save`` with the disk write off the critical path.

    Blocks only for the local device->host shard transfer (``_snapshot``);
    the shard + manifest writes and atomic renames happen in a background
    thread. A crash mid-write leaves the previous checkpoint intact (same
    commit discipline as ``save``). ``flush()`` joins all outstanding
    writes; a failed write re-raises from the next join point on the same
    path (or ``flush``).

    Multi-host: every participating process snapshots its OWN shards (pure
    local copies — the sharded format removed the old collective gather)
    and writes its own shard file; only the tree's writer rank
    (``_writer_rank`` — lowest process addressing it) commits the manifest.
    The multi-host engine flushes + barriers at interval end so readers
    never race the write (``engine.py``)."""
    _wait_pending(path)  # at most one in-flight write per path
    snap = _snapshot(path, tree)
    key = os.path.abspath(path)

    def write():
        try:
            _commit_snapshot(path, snap)
        except BaseException as e:  # re-raised at the next join point
            log.exception("async checkpoint write to %s failed", path)
            _record_async_failure(key, path, e)
        finally:
            with _PENDING_LOCK:
                if _PENDING.get(key) is threading.current_thread():
                    del _PENDING[key]

    t = threading.Thread(target=write, name=f"ckpt-{os.path.basename(path)}", daemon=True)
    with _PENDING_LOCK:
        _PENDING[key] = t
    t.start()


def flush() -> None:
    """Join every outstanding async write; re-raise the first failure."""
    with _PENDING_LOCK:
        threads = list(_PENDING.values())
    for t in threads:
        t.join()
    with _PENDING_LOCK:
        errs = dict(_FAILED)
        _FAILED.clear()
    for path, err in errs.items():
        raise RuntimeError(f"async checkpoint write to {path} failed") from err


# -------------------------------------------------------------------- restore
class _ShardReader:
    """Lazily-opened shard files for one manifest; at most one ``NpzFile``
    per shard file stays open, so assembly is O(one leaf) of extra host
    memory, never the full tree."""

    def __init__(self, path: str):
        self._dir = os.path.dirname(os.path.abspath(path))
        self._open: Dict[str, Any] = {}

    def member(self, fname: str, key: str) -> np.ndarray:
        npz = self._open.get(fname)
        if npz is None:
            npz = np.load(os.path.join(self._dir, fname))
            self._open[fname] = npz
        return npz[key]

    def close(self) -> None:
        for npz in self._open.values():
            try:
                npz.close()
            except Exception:
                pass
        self._open.clear()


def _assemble_block(entry: Dict[str, Any], reader: _ShardReader,
                    block: Tuple[Tuple[int, int], ...],
                    dtype: Any) -> np.ndarray:
    """Materialize one hyper-rectangular block of a leaf from its shards
    (the lazy per-shard assembly ``restore_sharded`` builds device arrays
    from). ``block`` is concrete ``(start, stop)`` extents; a block exactly
    matching one source shard is returned without a copy beyond the dtype
    cast."""
    shape = tuple(bl[1] - bl[0] for bl in block)
    for sh in entry["shards"]:
        if tuple((int(a), int(b)) for a, b in sh["index"]) == block:
            arr = reader.member(sh["file"], sh["key"]).astype(dtype, copy=False)
            # NOT ascontiguousarray: that helper promotes 0-d to 1-d,
            # breaking scalar leaves like ``step``; npz members are
            # already contiguous.
            return arr
    out = np.empty(shape, dtype=dtype)
    covered = 0
    for sh in entry["shards"]:
        src_sel, dst_sel, n = [], [], 1
        for (bs, be), (ss, se) in zip(block, sh["index"]):
            lo, hi = max(bs, int(ss)), min(be, int(se))
            if lo >= hi:
                n = 0
                break
            src_sel.append(slice(lo - int(ss), hi - int(ss)))
            dst_sel.append(slice(lo - bs, hi - bs))
            n *= hi - lo
        if n == 0:
            continue
        data = reader.member(sh["file"], sh["key"])
        out[tuple(dst_sel)] = data[tuple(src_sel)].astype(dtype, copy=False)
        covered += n
    total = 1
    for dim in shape:
        total *= dim
    if covered < total:
        raise ValueError(
            f"shard set does not cover requested block {block} "
            f"({covered}/{total} elements)"
        )
    return out


def _full_extent(shape) -> Tuple[Tuple[int, int], ...]:
    return tuple((0, int(d)) for d in shape)


def _load_manifest_arrays(path: str,
                          manifest: Dict[str, Any]) -> Dict[str, np.ndarray]:
    reader = _ShardReader(path)
    try:
        out: Dict[str, np.ndarray] = {}
        for key, entry in manifest["leaves"].items():
            out[key] = _assemble_block(
                entry, reader, _full_extent(entry["shape"]),
                np.dtype(entry["stored_dtype"]),
            )
        return out
    finally:
        reader.close()


def load_arrays(path: str) -> Dict[str, np.ndarray]:
    """Read a checkpoint (either format) as a flat ``{tree/path: ndarray}``
    dict of full host arrays in *stored* dtype (bf16/fp8 leaves come back
    float32-widened, exactly as the legacy ``np.load`` view did) — the
    drop-in replacement for code that used to ``np.load`` the checkpoint
    file directly. Joins any in-flight async write; quarantines + raises
    :class:`CheckpointCorruptError` on unreadable/partial checkpoints."""
    _wait_pending(path)
    # Absent is not corrupt: callers branch on exists(). Only the *root*
    # file's absence means absent — a missing shard file below IS corruption
    # (partial shard set) and takes the quarantine path.
    is_manifest = _is_manifest_file(path)
    try:
        if is_manifest:
            return _load_manifest_arrays(path, _read_manifest(path))
        with np.load(path) as data:
            return {k: data[k] for k in data.files}
    except Exception as e:
        # Truncated / torn / bit-rotted manifest or shard set: quarantine
        # the checkpoint so the next reader (and crash recovery) falls back
        # to the previous one instead of re-hitting the same unreadable
        # file. Shard files of the quarantined generation are swept by the
        # next successful save's GC.
        sidecar = quarantine(path)
        log.warning("checkpoint %s unreadable (%r); quarantined to %s",
                    path, e, sidecar)
        raise CheckpointCorruptError(path, sidecar, repr(e)) from e


def restore(path: str, template: Any) -> Any:
    """Map saved arrays onto ``template``'s structure (host numpy leaves).

    ``template`` is a freshly-initialized train state (any technique's); leaves
    are replaced by the saved arrays with dtype preserved from the template so
    a bf16 param set restores as bf16 even though numpy stored it widened.

    Multi-host: the writer rank's _wait_pending joins its own in-flight
    write; OTHER ranks rely on the engine's interval-end flush+barrier
    (``engine._execute_multihost``) having run before any cross-rank read —
    no collective here, because a task local to one host restores on that
    host alone and a cluster-wide barrier would deadlock.
    """
    saved = load_arrays(path)

    def replace(tree_path, leaf):
        key = _path_str(tree_path)
        if key not in saved:
            raise KeyError(
                f"checkpoint at {path!r} missing array for tree path {key!r}"
            )
        arr = saved[key]
        want_dtype = getattr(leaf, "dtype", arr.dtype)
        want_shape = getattr(leaf, "shape", arr.shape)
        if tuple(arr.shape) != tuple(want_shape):
            raise ValueError(
                f"shape mismatch at {key!r}: saved {arr.shape} vs template {want_shape}"
            )
        return arr.astype(want_dtype)

    return jax.tree_util.tree_map_with_path(replace, template)


def _resolve_sharding(sharding: Any, template: Any):
    """Normalize the three ``restore_sharded`` sharding forms into a
    per-leaf callable ``(tree_path, shape_dtype) -> Sharding``."""
    if isinstance(sharding, jax.sharding.Sharding):
        # isinstance check FIRST: Sharding subclasses may be callable.
        return lambda p, sds: sharding
    if callable(sharding):
        return sharding
    by_key = {
        _path_str(p): s
        for p, s in jax.tree_util.tree_flatten_with_path(sharding)[0]
    }
    return lambda p, sds: by_key[_path_str(p)]


def _place_leaf(entry: Dict[str, Any], reader: _ShardReader,
                dst_sharding: Any, dtype: Any) -> Any:
    """Build one destination device array from source shards, assembling
    only the block each destination device actually needs. Falls back to
    full-leaf host assembly + ``device_put`` for non-device memory kinds
    (offloaded ``pinned_host`` state), where callback-placement support
    varies by backend."""
    shape = tuple(int(d) for d in entry["shape"])
    mk = getattr(dst_sharding, "memory_kind", None)
    if mk not in (None, "device"):
        full = _assemble_block(entry, reader, _full_extent(shape), dtype)
        return jax.device_put(full, dst_sharding)

    def cb(index):
        return _assemble_block(entry, reader, _norm_index(index, shape), dtype)

    return jax.make_array_from_callback(shape, dst_sharding, cb)


def restore_sharded(path: str, template: Any, sharding: Any) -> Any:
    """``restore`` + place every leaf on devices under ``sharding``.

    This is the cross-mesh migration primitive: a checkpoint written on one
    mesh shape restores onto a *different* one (half the devices after a
    slice preemption, twice after a grow), because the manifest holds
    mesh-agnostic ``(start, stop)`` extents keyed by tree path — nothing
    about the old mesh constrains the destination. For manifest checkpoints
    each leaf is assembled lazily per destination shard
    (``jax.make_array_from_callback``), so no host materializes the full
    replicated tree; legacy single-file checkpoints take the compat
    full-host path. ``sharding`` is one of:

    - a single ``jax.sharding.Sharding`` applied to every leaf (the common
      fully-replicated / uniform case),
    - a pytree of shardings matching ``template``'s structure,
    - a callable ``(tree_path, leaf_like) -> Sharding`` for per-leaf rules
      (``leaf_like`` has ``shape``/``dtype``/``ndim``).
    """
    _wait_pending(path)
    try:
        is_manifest = _is_manifest_file(path)
    except FileNotFoundError:
        raise
    if not is_manifest:
        host = restore(path, template)
        rule = _resolve_sharding(sharding, template)
        return jax.tree_util.tree_map_with_path(
            lambda p, leaf: jax.device_put(leaf, rule(p, leaf)), host
        )

    try:
        manifest = _read_manifest(path)
    except Exception as e:
        sidecar = quarantine(path)
        log.warning("checkpoint %s unreadable (%r); quarantined to %s",
                    path, e, sidecar)
        raise CheckpointCorruptError(path, sidecar, repr(e)) from e

    rule = _resolve_sharding(sharding, template)
    leaves = manifest["leaves"]
    reader = _ShardReader(path)
    try:

        def place(tree_path, tleaf):
            key = _path_str(tree_path)
            if key not in leaves:
                raise KeyError(
                    f"checkpoint at {path!r} missing array for tree path "
                    f"{key!r}"
                )
            entry = leaves[key]
            want_shape = tuple(getattr(tleaf, "shape", entry["shape"]))
            if tuple(entry["shape"]) != want_shape:
                raise ValueError(
                    f"shape mismatch at {key!r}: saved "
                    f"{tuple(entry['shape'])} vs template {want_shape}"
                )
            dtype = getattr(tleaf, "dtype", np.dtype(entry["stored_dtype"]))
            sds = jax.ShapeDtypeStruct(want_shape, dtype)
            return _place_leaf(entry, reader, rule(tree_path, sds), dtype)

        return jax.tree_util.tree_map_with_path(place, template)
    except (CheckpointCorruptError, KeyError, ValueError):
        raise
    except Exception as e:
        # A manifest that parsed but whose shard set is missing/torn on
        # read: quarantine so recovery falls back, same as load_arrays.
        sidecar = quarantine(path)
        log.warning("checkpoint %s shard set unreadable (%r); quarantined "
                    "to %s", path, e, sidecar)
        raise CheckpointCorruptError(path, sidecar, repr(e)) from e
    finally:
        reader.close()


def exists(path: str) -> bool:
    """True if a checkpoint exists (joining any in-flight async write first,
    so a just-scheduled save counts).

    Multi-host: consistency across ranks comes from the engine's
    interval-end flush+barrier — by the time any rank asks, the shared-FS
    file is durable, so every rank reads the same answer with no
    collective (which would deadlock for host-local tasks)."""
    _wait_pending(path)
    return os.path.exists(path)


def delete(path: str) -> None:
    """Remove a checkpoint: the manifest (or legacy single file) plus every
    generation's shard files. Quarantine sidecars are kept (they are
    evidence, not state). Missing paths are fine; joins any in-flight
    async write first so a just-scheduled save doesn't resurrect files."""
    try:
        _wait_pending(path)
    except RuntimeError:
        pass  # a failed write is moot — we are deleting the target
    try:
        os.unlink(path)
    except FileNotFoundError:
        pass
    for f in glob.glob(glob.escape(path) + ".g*.npz"):
        if _SHARD_RE.search(f):
            try:
                os.unlink(f)
            except OSError:
                log.warning("could not remove checkpoint shard %s", f)


# ------------------------------------------------------------------ CLI views
def summarize(path: str) -> Dict[str, Any]:
    """One checkpoint's manifest summary for ``python -m saturn_tpu.analysis
    ckpt``: format, shard/leaf counts, on-disk bytes, pspec fingerprint and
    verification verdict. Never raises — unreadable checkpoints report
    ``ok: False``."""
    out: Dict[str, Any] = {"path": path, "ok": False, "format": None,
                           "leaves": 0, "shards": 0, "shard_files": 0,
                           "bytes": 0, "pspec_fingerprint": None,
                           "generation": None}
    try:
        out["bytes"] = os.path.getsize(path)
        if _is_manifest_file(path):
            out["format"] = "sharded-manifest"
            m = _read_manifest(path)
            out["generation"] = m.get("generation")
            out["pspec_fingerprint"] = m.get("pspec_fingerprint")
            out["leaves"] = len(m["leaves"])
            d = os.path.dirname(os.path.abspath(path))
            files = set()
            for entry in m["leaves"].values():
                out["shards"] += len(entry["shards"])
                files.update(sh["file"] for sh in entry["shards"])
            out["shard_files"] = len(files)
            for fname in files:
                fpath = os.path.join(d, fname)
                if os.path.exists(fpath):
                    out["bytes"] += os.path.getsize(fpath)
        else:
            out["format"] = "legacy-npz"
            with np.load(path) as data:
                out["leaves"] = len(data.files)
                out["shards"] = len(data.files)
            out["shard_files"] = 1
        out["ok"] = verify(path)
    except Exception as e:
        out["error"] = repr(e)
    return out


def summarize_dir(directory: str) -> Dict[str, Any]:
    """Directory-level checkpoint inventory: every checkpoint (manifest or
    legacy), corrupt sidecars, and orphan shard files no manifest owns."""
    directory = os.path.abspath(directory)
    checkpoints, sidecars, shard_files = [], [], set()
    referenced = set()
    for name in sorted(os.listdir(directory)):
        full = os.path.join(directory, name)
        if not os.path.isfile(full):
            continue
        if ".corrupt" in name:
            sidecars.append(name)
            continue
        if _SHARD_RE.search(name):
            shard_files.add(name)
            continue
        if name.endswith(".npz"):
            summ = summarize(full)
            checkpoints.append(summ)
            if summ.get("format") == "sharded-manifest" and summ.get("ok"):
                try:
                    m = _read_manifest(full)
                    for entry in m["leaves"].values():
                        referenced.update(sh["file"] for sh in entry["shards"])
                except Exception:
                    pass
    return {
        "dir": directory,
        "checkpoints": checkpoints,
        "corrupt_sidecars": sidecars,
        "orphan_shards": sorted(shard_files - referenced),
        "total_bytes": sum(c.get("bytes", 0) for c in checkpoints),
    }
