"""Path-keyed pytree checkpointing with cross-technique resharding.

The reference checkpoints model state only, via ``torch.save`` of a state dict
(``Task.py:150-169``), and silently drops optimizer state between intervals
(``FSDP.py:220``, ``DDP.py:163``) — a wart SURVEY.md §5 flags to fix. Here we
save the **full train state** (params + optimizer state + step) as host numpy
arrays keyed by their tree path; the data cursor is derived from ``step`` on
restore, making resume restart-safe.

Saving by *path* rather than pickling tree structure is what makes
interval-boundary **technique switching** work (the reference's central trick,
``executor.py:65`` kill-and-respawn + state-dict reload): any technique can
restore the same arrays under a *different* mesh/sharding, because restore maps
host arrays onto a freshly-initialized template state and the caller then
``device_put``s them with its own sharding.
"""

from __future__ import annotations

import logging
import os
import tempfile
import threading
from typing import Any, Dict

import jax
import numpy as np

from saturn_tpu.utils.treepath import path_str as _path_str

log = logging.getLogger("saturn_tpu")


class CheckpointCorruptError(RuntimeError):
    """A checkpoint exists on disk but cannot be read back (truncated write,
    bit rot, torn page). The unreadable file has already been quarantined to
    a ``*.corrupt`` sidecar by the time this raises, so crash recovery can
    fall back to the *previous* published checkpoint instead of dying on
    the newest one."""

    def __init__(self, path: str, quarantined: str, cause: str):
        self.path = path
        self.quarantined = quarantined
        super().__init__(
            f"checkpoint {path} is corrupt ({cause}); quarantined to "
            f"{quarantined}"
        )


def quarantine(path: str) -> str:
    """Rename an unreadable artifact to a ``*.corrupt`` sidecar (never
    overwrite an earlier quarantine: ``.corrupt``, ``.corrupt.1``, ...).
    Returns the sidecar path; if the rename itself fails the original path
    is returned and the file is left in place (recovery treats both the
    same — the path is not a usable checkpoint)."""
    sidecar = path + ".corrupt"
    n = 0
    while os.path.exists(sidecar):
        n += 1
        sidecar = f"{path}.corrupt.{n}"
    try:
        os.replace(path, sidecar)
    except OSError:
        log.exception("failed to quarantine %s", path)
        return path
    return sidecar


def verify(path: str) -> bool:
    """Integrity-check a published ``.npz`` checkpoint without loading it
    into memory: the zip central directory must parse and every member's
    stored CRC-32 must match its payload (``testzip`` streams each entry).
    False for missing, truncated or corrupt files — never raises."""
    import zipfile

    try:
        with zipfile.ZipFile(path) as zf:
            return zf.testzip() is None
    except Exception:
        return False


# Publication hooks: called as ``hook(task_or_stem, path)`` after the atomic
# rename lands a checkpoint, from whichever thread performed the write (the
# async writer thread for ``save_async``). The durability layer registers one
# to journal every publication; hooks must be cheap and must not raise.
_PUBLISH_HOOKS: list = []


def add_publish_hook(hook) -> None:
    _PUBLISH_HOOKS.append(hook)


def remove_publish_hook(hook) -> None:
    try:
        _PUBLISH_HOOKS.remove(hook)
    except ValueError:
        pass


def _notify_published(path: str) -> None:
    if not _PUBLISH_HOOKS:
        return
    stem = os.path.splitext(os.path.basename(path))[0]
    for hook in list(_PUBLISH_HOOKS):
        try:
            hook(stem, os.path.abspath(path))
        except Exception:
            log.exception("checkpoint publish hook failed for %s", path)


def _writer_rank(tree: Any) -> int:
    """The process that writes this tree: the lowest process index that
    addresses its arrays. For a cross-host sharded/replicated state that is
    the coordinator; for a state living entirely on one host's devices it
    is that host (the coordinator never even sees the tree — the multi-host
    engine only calls execute() on processes local to the task's block).
    Host-only trees (plain numpy) default to rank 0."""
    for leaf in jax.tree_util.tree_leaves(tree):
        ds = getattr(getattr(leaf, "sharding", None), "device_set", None)
        if ds:
            return min(getattr(d, "process_index", 0) for d in ds)
    return 0


def _should_write(tree: Any) -> bool:
    from saturn_tpu.core import distributed

    if not distributed.is_multihost():
        return True
    return distributed.process_index() == _writer_rank(tree)


def flatten_to_host(tree: Any) -> Dict[str, np.ndarray]:
    """Flatten a (possibly sharded, device-resident) pytree to host numpy.

    Multi-host: a leaf sharded across processes is not fully addressable —
    ``device_get`` would raise — so it is allgathered first (every process
    pays the gather; only the coordinator writes, see ``save_async``)."""
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    out: Dict[str, np.ndarray] = {}
    for path, leaf in flat:
        key = _path_str(path)
        if key in out:
            raise ValueError(f"duplicate tree path key: {key!r}")
        if (
            hasattr(leaf, "is_fully_addressable")
            and not leaf.is_fully_addressable
        ):
            # Replicate over the leaf's OWN mesh — a transfer involving
            # exactly the processes that address it (all of which call
            # save, since the engine runs execute() on every block-local
            # rank). A cluster-wide allgather here would hang processes
            # that are not part of this task's block on 3+ host clusters.
            # device_put (not a per-leaf jit identity) so repeated saves
            # don't retrace/compile hundreds of leaves on the interval-end
            # critical path.
            from jax.sharding import NamedSharding, PartitionSpec

            mesh = getattr(leaf.sharding, "mesh", None)
            if mesh is not None:
                # sanctioned-shardflow: single-writer npz checkpoint needs
                # the whole leaf on one host; gather is bounded to the
                # leaf's own mesh and runs once per save, off the step hot
                # loop. Removing the funnel entirely is ROADMAP item 6's
                # sharded checkpoint I/O (per-host shard files).
                rep = jax.device_put(
                    leaf, NamedSharding(mesh, PartitionSpec())
                )
                leaf = rep.addressable_data(0)
            else:  # non-mesh sharding: fall back to the global gather
                from jax.experimental import multihost_utils

                # sanctioned-shardflow: rare non-mesh-sharding fallback for
                # the same single-writer save path; superseded by ROADMAP
                # item 6's sharded checkpoint I/O.
                leaf = multihost_utils.process_allgather(leaf, tiled=True)
        arr = np.asarray(jax.device_get(leaf))
        # npz can't round-trip ml_dtypes (bfloat16/fp8); widen to float32 —
        # restore() narrows back to the template's dtype.
        if arr.dtype.kind == "V" or "bfloat16" in str(arr.dtype) or "float8" in str(arr.dtype):
            arr = arr.astype(np.float32)
        out[key] = arr
    return out


def _write_atomic(path: str, arrays: Dict[str, np.ndarray]) -> None:
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, path)  # atomic: no torn checkpoints on crash
        _notify_published(path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def save(path: str, tree: Any) -> None:
    """Atomically write a pytree checkpoint to ``path`` (an ``.npz`` file).
    Multi-host: collective gather on every participating rank; the write
    happens on the tree's writer rank only (see ``_writer_rank``)."""
    should = _should_write(tree)
    arrays = flatten_to_host(tree)
    if should:
        _write_atomic(path, arrays)


# --------------------------------------------------------------- async writes
# End-of-interval checkpoints are GB-scale (full train state incl. optimizer):
# the device->host transfer must happen synchronously (the engine may donate
# the buffers into the next interval's first step), but the DISK write can
# overlap the next interval's compute. One writer thread per path; restore()
# and a second save() to the same path wait for the in-flight write first.
# A failed write is recorded per path and re-raised at the next join point
# (exists/restore/save_async/flush) — a checkpoint that never hit disk must
# not be silently reported as saved.
_PENDING: Dict[str, threading.Thread] = {}
_FAILED: Dict[str, BaseException] = {}
_PENDING_LOCK = threading.Lock()


def _wait_pending(path: str) -> None:
    key = os.path.abspath(path)
    with _PENDING_LOCK:
        t = _PENDING.get(key)
    if t is not None:
        t.join()
    with _PENDING_LOCK:
        err = _FAILED.pop(key, None)
    if err is not None:
        raise RuntimeError(f"async checkpoint write to {path} failed") from err


def save_async(path: str, tree: Any) -> None:
    """``save`` with the disk write off the critical path.

    Blocks only for the device->host transfer (``flatten_to_host``); the
    ``np.savez`` + atomic rename happens in a background thread. A crash
    mid-write leaves the previous checkpoint intact (same atomicity as
    ``save``). ``flush()`` joins all outstanding writes; a failed write
    re-raises from the next join point on the same path (or ``flush``).

    Multi-host: every participating process joins the device->host gather
    (a collective for cross-host arrays), but only the tree's writer rank
    (``_writer_rank`` — lowest process addressing it) touches the
    filesystem; N processes racing one atomic rename on shared storage
    would be wasted I/O at best. The multi-host engine flushes + barriers
    at interval end so readers never race the write (``engine.py``).
    """
    _wait_pending(path)  # at most one in-flight write per path
    should = _should_write(tree)
    arrays = flatten_to_host(tree)
    if not should:
        return
    key = os.path.abspath(path)

    def write():
        try:
            _write_atomic(path, arrays)
        except BaseException as e:  # re-raised at the next join point
            log.exception("async checkpoint write to %s failed", path)
            with _PENDING_LOCK:
                _FAILED[key] = e
        finally:
            with _PENDING_LOCK:
                if _PENDING.get(key) is threading.current_thread():
                    del _PENDING[key]

    t = threading.Thread(target=write, name=f"ckpt-{os.path.basename(path)}", daemon=True)
    with _PENDING_LOCK:
        _PENDING[key] = t
    t.start()


def flush() -> None:
    """Join every outstanding async write; re-raise the first failure."""
    with _PENDING_LOCK:
        threads = list(_PENDING.values())
    for t in threads:
        t.join()
    with _PENDING_LOCK:
        errs = dict(_FAILED)
        _FAILED.clear()
    for path, err in errs.items():
        raise RuntimeError(f"async checkpoint write to {path} failed") from err


def restore(path: str, template: Any) -> Any:
    """Map saved arrays onto ``template``'s structure (host numpy leaves).

    ``template`` is a freshly-initialized train state (any technique's); leaves
    are replaced by the saved arrays with dtype preserved from the template so
    a bf16 param set restores as bf16 even though numpy stored it widened.

    Multi-host: the writer rank's _wait_pending joins its own in-flight
    write; OTHER ranks rely on the engine's interval-end flush+barrier
    (``engine._execute_multihost``) having run before any cross-rank read —
    no collective here, because a task local to one host restores on that
    host alone and a cluster-wide barrier would deadlock.
    """
    _wait_pending(path)  # an async save to this path may still be in flight
    try:
        with np.load(path) as data:
            saved = {k: data[k] for k in data.files}
    except FileNotFoundError:
        raise  # absent is not corrupt: callers branch on exists()
    except Exception as e:
        # Truncated / torn / bit-rotted archive: quarantine it so the next
        # reader (and crash recovery) falls back to the previous checkpoint
        # instead of re-hitting the same unreadable file.
        sidecar = quarantine(path)
        log.warning("checkpoint %s unreadable (%r); quarantined to %s",
                    path, e, sidecar)
        raise CheckpointCorruptError(path, sidecar, repr(e)) from e

    def replace(tree_path, leaf):
        key = _path_str(tree_path)
        if key not in saved:
            raise KeyError(
                f"checkpoint at {path!r} missing array for tree path {key!r}"
            )
        arr = saved[key]
        want_dtype = getattr(leaf, "dtype", arr.dtype)
        want_shape = getattr(leaf, "shape", arr.shape)
        if tuple(arr.shape) != tuple(want_shape):
            raise ValueError(
                f"shape mismatch at {key!r}: saved {arr.shape} vs template {want_shape}"
            )
        return arr.astype(want_dtype)

    return jax.tree_util.tree_map_with_path(replace, template)


def restore_sharded(path: str, template: Any, sharding: Any) -> Any:
    """``restore`` + place every leaf on devices under ``sharding``.

    This is the cross-mesh migration primitive: a checkpoint written on one
    mesh shape restores onto a *different* one (half the devices after a
    slice preemption, twice after a grow), because the npz holds full host
    arrays keyed by tree path — nothing about the old mesh survives in the
    file. ``sharding`` is one of:

    - a single ``jax.sharding.Sharding`` applied to every leaf (the common
      fully-replicated / uniform case),
    - a pytree of shardings matching ``template``'s structure,
    - a callable ``(tree_path, host_leaf) -> Sharding`` for per-leaf rules.
    """
    host = restore(path, template)
    if isinstance(sharding, jax.sharding.Sharding):
        # isinstance check FIRST: Sharding subclasses may be callable.
        return jax.tree_util.tree_map(
            lambda leaf: jax.device_put(leaf, sharding), host
        )
    if callable(sharding):
        return jax.tree_util.tree_map_with_path(
            lambda p, leaf: jax.device_put(leaf, sharding(p, leaf)), host
        )
    return jax.tree_util.tree_map(jax.device_put, host, sharding)


def exists(path: str) -> bool:
    """True if a checkpoint exists (joining any in-flight async write first,
    so a just-scheduled save counts).

    Multi-host: consistency across ranks comes from the engine's
    interval-end flush+barrier — by the time any rank asks, the shared-FS
    file is durable, so every rank reads the same answer with no
    collective (which would deadlock for host-local tasks)."""
    _wait_pending(path)
    return os.path.exists(path)
