"""Persistent profile cache: trial results that outlive the driver process.

Profiling is the single most expensive phase of the pipeline — compile
dominates a trial (~1 min upper bound each, ``trial_runner/evaluator.py``) and
the grid is (task × sub-mesh size × technique). The Saturn paper notes this
cost is amortizable: a profile depends only on *what* is being timed (model,
data shape, optimizer, technique, sub-mesh size, accelerator topology, XLA
version), none of which changes between back-to-back sweeps. So every trial
outcome — feasible (params + per-batch seconds) or infeasible — is keyed on a
content fingerprint of exactly those inputs and written to one JSON file per
key. A repeated ``search()`` over an unchanged task list then performs zero
trial compiles.

Entries are upgraded in place by the orchestrator's realized-feedback loop
(``executor/orchestrator.py``): once a task actually runs, its measured
per-batch time replaces the trial estimate (``source="realized"``), so the
next process's sweep starts from production numbers, not solo-trial ones.

Corrupt, stale or partially-written files are treated as misses, never
errors: writes go through an atomic ``os.replace`` and reads re-validate the
embedded key and field types. Delete the cache directory to invalidate
everything.

Environment:

- ``SATURN_TPU_PROFILE_CACHE_DIR``: cache directory (default
  ``~/.cache/saturn_tpu/profiles``).
- ``SATURN_TPU_PROFILE_CACHE=0``: disable the default cache entirely.
- ``SATURN_TPU_COMPILE_CACHE_DIR``: additionally enable JAX's persistent
  *compilation* cache rooted there, so the XLA executables built by trial
  sweeps are reused by the execution engine's bundle build
  (``parallel/spmd_base.py::_build_uncached``) and by later processes.
  Off by default: on CPU test platforms a cache shared across execution
  contexts with different feature detection can load mismatched entries
  (see ``tests/conftest.py``).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time
from typing import Any, Dict, Optional

log = logging.getLogger("saturn_tpu")

#: Bump when the fingerprint payload or entry schema changes meaning —
#: old entries then miss instead of being misread.
SCHEMA_VERSION = 1

_ENV_DIR = "SATURN_TPU_PROFILE_CACHE_DIR"
_ENV_TOGGLE = "SATURN_TPU_PROFILE_CACHE"
_ENV_COMPILE_DIR = "SATURN_TPU_COMPILE_CACHE_DIR"

_FALSEY = ("0", "false", "off", "no")


# --------------------------------------------------------------- fingerprints
def _model_signature(task: Any) -> str:
    """Content signature of the task's model: config + abstract param tree.

    Uses ``jax.eval_shape`` via ``ModelSpec.abstract_init`` so no weights are
    materialized (the reference's lazy-instantiation rule, ``Task.py:92-97``).
    Factories that fail or specs without the ModelSpec surface degrade to
    whatever stable repr is available — a narrower key, never a wrong hit.
    """
    try:
        spec = task.get_model()
    except Exception:
        return f"factory:{type(task).__name__}"
    parts = [repr(getattr(spec, "config", type(spec).__name__))]
    abstract = getattr(spec, "abstract_init", None)
    if callable(abstract):
        try:
            import jax

            leaves, _ = jax.tree_util.tree_flatten_with_path(abstract())
            parts += [
                f"{jax.tree_util.keystr(path)}:{tuple(leaf.shape)}:{leaf.dtype}"
                for path, leaf in leaves
            ]
        except Exception:
            pass
    return ";".join(parts)


def _data_signature(task: Any) -> str:
    """Batch shape/dtype + batch size: what actually drives step time (token
    *values* don't — synthetic vs real corpora profile identically)."""
    try:
        ds = task.get_dataset()
    except Exception:
        return "none"
    parts = [type(ds).__name__, str(getattr(ds, "batch_size", None))]
    eb = getattr(ds, "example_batch", None)
    if callable(eb):
        try:
            b = eb()
            parts += [str(tuple(getattr(b, "shape", ()))), str(getattr(b, "dtype", ""))]
        except Exception:
            pass
    return ";".join(parts)


def _optimizer_signature(task: Any) -> str:
    opt = getattr(getattr(task, "hparams", None), "optimizer", None)
    if isinstance(opt, str) or opt is None:
        return str(opt)
    # a custom optax factory: the qualname is the best stable handle (repr
    # would embed a memory address and never match across processes)
    return f"custom:{getattr(opt, '__qualname__', type(opt).__name__)}"


def task_signature(task: Any) -> str:
    """Everything about a *task* that a per-batch profile depends on.

    Excludes lr, total batch count and the task name: the reference cloned
    searched tasks across learning rates precisely because lr doesn't change
    step time (``WikiText103.py:87-99``), and runtime is re-derived as
    ``per_batch_time * total_batches`` at use time. Scheduling-only hints
    (``priority``, ``deadline`` — written by the online job service for the
    replanner's eviction ordering) are likewise excluded: they never touch
    the compiled program, and the same model submitted at a different
    priority must stay a warm cache hit.
    """
    hp = getattr(task, "hparams", None)
    kwargs = dict(getattr(hp, "kwargs", {}) or {})
    hints = {
        k: v
        for k, v in dict(getattr(task, "hints", {}) or {}).items()
        if k not in ("priority", "deadline")
    }
    return json.dumps(
        {
            "model": _model_signature(task),
            "data": _data_signature(task),
            "optimizer": _optimizer_signature(task),
            "kwargs": kwargs,
            "hints": hints,
        },
        sort_keys=True,
        default=repr,
    )


def topology_signature(topo: Any) -> str:
    sig = getattr(topo, "signature", None)
    return sig() if callable(sig) else repr(topo)


def dispatch_signature() -> str:
    """How execute() dispatches batches — part of every fingerprint.

    Trials profile the dispatch mode execution will use (fused K-step scan
    windows vs per-step calls), and the two modes have genuinely different
    per-batch times — amortized dispatch/readback overhead is the point of
    fusing. A stale per-step profile warm-starting a fused sweep (or vice
    versa) would hand the MILP numbers execution never exhibits, so the
    mode (and its window cap) keys the cache. Imported lazily: utils must
    not import parallel at module level.
    """
    try:
        from saturn_tpu.parallel.spmd_base import dispatch_signature as _ds

        return _ds()
    except Exception:
        return "per-step"


def schedule_signature() -> str:
    """Version of the pipeline schedule set — part of every fingerprint.

    The pipeline executor's candidate grid carries the schedule kind
    (GPipe vs 1F1B) in each config, and the trial runner times both; a
    profile recorded before a schedule existed (or after one's program
    changed) describes a grid the sweep no longer runs, so stale entries
    must MISS rather than warm-start the solver with configs execution
    would route differently. Imported lazily like ``dispatch_signature``:
    utils must not import ops at module level.
    """
    try:
        from saturn_tpu.ops.pipeline import schedule_signature as _ss

        return _ss()
    except Exception:
        return "gpipe-only"


def fusion_signature() -> str:
    """Version of the fused-stacking machinery — part of every fingerprint.

    A profile's ``fused_per_batch_time`` (and the solver decisions priced on
    it) describes the stacked program of a specific fusion version; when the
    stacked step's semantics change (``parallel/fused.FUSION_SET_VERSION``)
    stale entries must MISS so groups re-trial instead of fusing on a
    measurement of a program that no longer exists. Lazy import like
    ``schedule_signature``: utils must not import parallel at module level.
    """
    try:
        from saturn_tpu.parallel.fused import fusion_signature as _fs

        return _fs()
    except Exception:
        return "no-fusion"


def overlap_signature() -> str:
    """Overlap lowering version + active per-op-class factor set.

    Two reasons an entry must MISS: (1) the overlapped programs changed
    shape (``ops/collective_matmul.OVERLAP_SET_VERSION`` — a serial profile
    must never price an overlapped lowering, and vice versa); (2) the
    overlap factors the prior priced it under moved (calibration or an env
    pin), so a plan warm-started from the entry would disagree with what
    admission and the solver now compute. Lazy imports like the signatures
    above: utils must not import ops/analysis at module level.
    """
    try:
        from saturn_tpu.ops.collective_matmul import overlap_signature as _os

        lowering = _os()
    except Exception:
        lowering = "no-overlap"
    try:
        from saturn_tpu.analysis.shardflow.prior import (
            overlap_factor_signature as _ofs,
        )

        factors = _ofs()
    except Exception:
        factors = "no-factors"
    return f"{lowering};{factors}"


def fingerprint(
    task_sig: str, technique: str, size: int, topo_sig: str,
    dispatch: Optional[str] = None,
) -> str:
    """Cache key for one (task, technique, sub-mesh size) grid point under
    one execution dispatch mode (``dispatch_signature()`` when None)."""
    try:
        import jax

        jax_version = jax.__version__
    except Exception:
        jax_version = "none"
    from saturn_tpu.analysis import SCHEMA_VERSION as _ANALYSIS_SCHEMA
    from saturn_tpu.analysis.memlens import PASS_VERSION as _MEMLENS_PASS
    from saturn_tpu.analysis.shardflow import PASS_VERSION as _SHARDFLOW_PASS

    payload = json.dumps(
        {
            "schema": SCHEMA_VERSION,
            # Analyzer rule-set version: a plan repaired under one
            # diagnostic schema must never warm-start from profiles
            # recorded under another (saturn-lint round 12).
            "analysis": _ANALYSIS_SCHEMA,
            # Shardflow propagation-rule version: static priors recorded
            # under one cost model must miss cleanly under another.
            "shardflow": _SHARDFLOW_PASS,
            # Memlens liveness-model version: memory-infeasibility entries
            # (including statically pruned points) recorded under one
            # liveness model must miss cleanly under another.
            "memlens": _MEMLENS_PASS,
            "task": task_sig,
            "technique": technique,
            "size": int(size),
            "topology": topo_sig,
            "jax": jax_version,
            "dispatch": dispatch_signature() if dispatch is None else dispatch,
            # Pipeline schedule-set version: a GPipe-only profile recorded
            # before 1F1B landed must miss — its cached params lack the
            # schedule key and its timing raced a narrower grid.
            "schedules": schedule_signature(),
            # Fusion-set version: entries recorded before cross-job stacking
            # existed (or under a different stacked-step program) must miss.
            "fusion": fusion_signature(),
            # Overlap lowering version + active overlap-factor set: serial
            # profiles must not price overlapped programs, and recalibrated
            # factors must invalidate plans priced under the old set.
            "overlap": overlap_signature(),
        },
        sort_keys=True,
    )
    return hashlib.sha256(payload.encode()).hexdigest()


# --------------------------------------------------------------------- store
class ProfileCache:
    """Directory of one-JSON-file-per-key trial outcomes."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.root, f"{key}.json")

    def get(self, key: Optional[str]) -> Optional[Dict[str, Any]]:
        """Validated entry dict, or None for missing/corrupt/foreign files."""
        if not key:
            return None
        try:
            with open(self._path(key)) as f:
                e = json.load(f)
        except (OSError, ValueError):
            return None
        if not isinstance(e, dict) or e.get("key") != key:
            return None  # stale schema or hash collision artifact: miss
        if not isinstance(e.get("feasible"), bool):
            return None
        if e["feasible"]:
            pbt = e.get("per_batch_time")
            if not isinstance(pbt, (int, float)) or pbt <= 0.0:
                return None
            if not isinstance(e.get("params"), dict):
                return None
        return e

    def put(
        self,
        key: Optional[str],
        *,
        technique: str,
        size: int,
        feasible: bool,
        params: Optional[Dict[str, Any]] = None,
        per_batch_time: Optional[float] = None,
        source: str = "trial",
        memory_infeasible: bool = False,
        host_fraction: float = 0.0,
    ) -> bool:
        """Atomically write one entry; False if the key or params aren't
        cacheable (non-JSON params from a plugin technique).

        ``host_fraction`` is the trial-measured staging-vs-compute split the
        solver's co-location term consumes; pre-existing entries without the
        field read back as 0.0 (never co-scheduled) via ``get``'s tolerance
        for missing fields."""
        if not key:
            return False
        entry = {
            "key": key,
            "schema": SCHEMA_VERSION,
            "technique": technique,
            "size": int(size),
            "feasible": bool(feasible),
            "params": params,
            "per_batch_time": per_batch_time,
            "source": source,
            "memory_infeasible": bool(memory_infeasible),
            "host_fraction": float(host_fraction),
            "written": time.time(),
        }
        try:
            blob = json.dumps(entry)
        except (TypeError, ValueError):
            return False
        tmp = self._path(key) + f".tmp.{os.getpid()}.{threading.get_ident()}"
        try:
            with open(tmp, "w") as f:
                f.write(blob)
            os.replace(tmp, self._path(key))
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return False
        return True

    def note_realized(
        self,
        key: Optional[str],
        per_batch_time: float,
        params: Optional[Dict[str, Any]],
        technique: str,
        size: int,
    ) -> bool:
        """Upgrade (or create) an entry from a *realized* interval measurement.

        Realized numbers supersede both trial profiles and interpolated
        estimates: they average a whole interval of production batches under
        real contention, which is exactly what the next sweep should predict.
        """
        if not key or per_batch_time <= 0.0:
            return False
        prev = self.get(key)
        if prev is not None and prev.get("feasible") and params is None:
            params = prev.get("params")
        # The realized interval measures wall time, not the staging split —
        # carry the trial's host fraction forward so an upgraded entry stays
        # co-schedulable.
        hf = prev.get("host_fraction", 0.0) if prev is not None else 0.0
        return self.put(
            key,
            technique=technique,
            size=size,
            feasible=True,
            params=params if isinstance(params, dict) else {},
            per_batch_time=float(per_batch_time),
            source="realized",
            host_fraction=float(hf) if isinstance(hf, (int, float)) else 0.0,
        )

    def __len__(self) -> int:
        try:
            return sum(1 for fn in os.listdir(self.root) if fn.endswith(".json"))
        except OSError:
            return 0


# ------------------------------------------------------------- default cache
_DEFAULT: Optional[ProfileCache] = None
_DEFAULT_LOCK = threading.Lock()


def default_dir() -> str:
    return os.environ.get(
        _ENV_DIR, os.path.join(os.path.expanduser("~"), ".cache", "saturn_tpu", "profiles")
    )


def default_cache() -> Optional[ProfileCache]:
    """Process-wide cache honoring the env toggles; None when disabled."""
    if os.environ.get(_ENV_TOGGLE, "1").lower() in _FALSEY:
        return None
    global _DEFAULT
    with _DEFAULT_LOCK:
        d = default_dir()
        if _DEFAULT is None or _DEFAULT.root != d:
            try:
                _DEFAULT = ProfileCache(d)
            except OSError:
                log.warning("profile cache dir %s not writable — caching off", d)
                return None
        return _DEFAULT


def resolve(spec: Any = None) -> Optional[ProfileCache]:
    """Map a ``search(profile_cache=...)`` argument to a cache instance.

    ``None`` -> the env-configured default (on unless disabled); ``False`` ->
    off for this sweep; a path string -> that directory; a ``ProfileCache``
    -> itself.
    """
    if spec is None:
        return default_cache()
    if spec is False:
        return None
    if isinstance(spec, ProfileCache):
        return spec
    if isinstance(spec, (str, os.PathLike)):
        try:
            return ProfileCache(os.fspath(spec))
        except OSError:
            log.warning("profile cache dir %s not writable — caching off", spec)
            return None
    raise TypeError(
        f"profile_cache must be None, False, a directory path or a "
        f"ProfileCache, got {type(spec).__name__}"
    )


# -------------------------------------------------- JAX compilation cache
_COMPILE_CACHE_STATE = {"decided": False}


def maybe_enable_persistent_compile_cache(path: Optional[str] = None) -> bool:
    """Point JAX's persistent compilation cache at ``path`` (or the env dir).

    Idempotent and cheap on the no-op path, so callers on the build hot path
    (``SPMDTechnique._build_uncached``) can invoke it unconditionally. The
    decision is made once per process: flipping the env var mid-run would
    otherwise mix cache roots inside one JAX runtime.
    """
    if _COMPILE_CACHE_STATE["decided"] and path is None:
        return _COMPILE_CACHE_STATE.get("enabled", False)
    explicit = path is not None
    path = path or os.environ.get(_ENV_COMPILE_DIR)
    if not explicit:
        _COMPILE_CACHE_STATE["decided"] = True
    if not path:
        _COMPILE_CACHE_STATE["enabled"] = False
        return False
    try:
        import jax

        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # Trials compile many small programs; default thresholds would skip
        # most of them and the cache would never amortize the sweep.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:
        log.warning("could not enable jax compilation cache at %s", path, exc_info=True)
        _COMPILE_CACHE_STATE["enabled"] = False
        return False
    _COMPILE_CACHE_STATE["decided"] = True
    _COMPILE_CACHE_STATE["enabled"] = True
    log.info("jax persistent compilation cache enabled at %s", path)
    return True
