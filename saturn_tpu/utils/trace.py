"""Profiler tracing: wrap a region in a jax.profiler trace.

The reference had no tracer at all — profiling was wall-clock timing only
(SURVEY.md §5 "Tracing / profiling: no tracer"). Here wall-clock timing stays
the scheduling signal (``utils/timing.py``), and this adds the TPU-native
deep-dive: XLA/TPU traces viewable in TensorBoard/Perfetto, produced by
passing ``trace_dir=`` to ``search``/``orchestrate``.
"""

from __future__ import annotations

import contextlib
import logging
from typing import Iterator, Optional

log = logging.getLogger("saturn_tpu")


@contextlib.contextmanager
def profile_trace(trace_dir: Optional[str]) -> Iterator[None]:
    """Trace the enclosed region to ``trace_dir`` (no-op when None)."""
    if not trace_dir:
        yield
        return
    import jax

    # The tunneled single-chip dev platform ("axon") wedges on profiler
    # start_trace (the remote terminal stops answering — observed 2026-07);
    # device tracing needs a directly-attached TPU runtime. Skip rather than
    # hang the run.
    if jax.devices()[0].platform == "axon":
        log.warning("profiler tracing unsupported on the axon tunnel; skipping")
        yield
        return

    # Tracing must never take down a training run: trace start/stop failures
    # are logged and swallowed; exceptions from the traced body propagate.
    started = False
    try:
        jax.profiler.start_trace(trace_dir)
        started = True
    except Exception as e:
        log.warning("profiler trace failed to start (%r); continuing", e)
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
                log.info("profiler trace written to %s", trace_dir)
            except Exception as e:
                log.warning("profiler trace failed to stop (%r)", e)
