"""Honest step timing under XLA jit.

The reference measured wall-clock for batch 2 of 2 so that CUDA warmup was
excluded (``FSDP.py:140-149``). Under jit the analog is: compile once (first
call), ``block_until_ready`` to sync, then time ``n`` steady-state steps.
"""

from __future__ import annotations

import logging
import timeit
from typing import Callable, Tuple

import jax

log = logging.getLogger("saturn_tpu")


def time_train_step(
    step: Callable, state, batch, n_timed: int = 3, n_warmup: int = 2
) -> float:
    """Mean seconds/step for a jitted ``(state, batch) -> (state, aux)`` step,
    excluding compile time.

    The updated state is threaded through every call: train steps donate their
    input state (``donate_argnums``), and re-passing a donated buffer makes
    one partition fail while the others wait in a collective — a deadlock, not
    an error. Never reuse the carry.

    Sync is a host read of the aux output (the loss scalar), not
    ``block_until_ready``: on the tunneled TPU platform block_until_ready has
    been observed returning before queued steps drain, which inflates
    throughput ~40x; a device_get round-trips through the device queue and is
    cheap for a scalar.
    """
    for _ in range(n_warmup):
        state, aux = step(state, batch)
    jax.device_get(aux)
    t0 = timeit.default_timer()
    for _ in range(n_timed):
        state, aux = step(state, batch)
    jax.device_get(aux)
    return (timeit.default_timer() - t0) / n_timed


def time_fused_window(
    fused: Callable, state, stage: Callable[[int], object], k: int,
    n_timed: int = 2, n_warmup: int = 1,
) -> float:
    """Mean seconds per BATCH for a fused K-step window program.

    ``stage(j)`` must return a FRESH device-staged (K, ...) window stack for
    call ``j``: the window program donates its batch buffers too, so a stack
    can be offered exactly once (same never-reuse rule as the carry above).

    All stacks are staged BEFORE the timed region. At execute() time the
    prefetcher overlaps staging with compute, so the trial must measure the
    device program alone — timing the transfers would hand the MILP
    per-batch numbers execute() never exhibits. Requires ``n_warmup >= 1``
    (the warmup call doubles as the compile + sync fence).
    """
    if n_warmup < 1:
        raise ValueError("time_fused_window needs n_warmup >= 1")
    windows = [stage(j) for j in range(n_warmup + n_timed)]
    for j in range(n_warmup):
        state, aux = fused(state, windows[j])
    jax.device_get(aux)
    t0 = timeit.default_timer()
    for j in range(n_warmup, n_warmup + n_timed):
        state, aux = fused(state, windows[j])
    jax.device_get(aux)
    return (timeit.default_timer() - t0) / (n_timed * k)


def hbm_bytes_required(compiled) -> int:
    """Peak HBM bytes from XLA's compile-time memory analysis.

    Replaces the reference's try/except OOM-probe loops (``Spilled.py:68-87``)
    with a deterministic check: a config is infeasible if its analyzed peak
    exceeds per-device HBM.
    """
    try:
        ma = compiled.memory_analysis()
        if ma is None:
            log.warning(
                "memory_analysis unavailable on this backend — treating "
                "config as feasible; trial execution becomes the OOM probe"
            )
            return 0
        total = (
            getattr(ma, "temp_size_in_bytes", 0)
            + getattr(ma, "argument_size_in_bytes", 0)
            + getattr(ma, "output_size_in_bytes", 0)
            - getattr(ma, "alias_size_in_bytes", 0)
        )
        return max(0, int(total))
    except Exception as e:
        # Returning 0 marks every config feasible — the memory check is
        # silently out of the loop, so say so (VERDICT r1 weak item 7).
        log.warning(
            "memory_analysis failed (%r) — treating config as feasible; "
            "trial execution becomes the OOM probe", e
        )
        return 0


def device_hbm_bytes(device) -> int:
    """Per-device memory capacity; 0 if the platform doesn't report it."""
    try:
        stats = device.memory_stats()
        if stats and "bytes_limit" in stats:
            return int(stats["bytes_limit"])
    except Exception:
        pass
    return 0
