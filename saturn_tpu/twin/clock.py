"""Virtual time for the twin: one clock, one deterministic event queue.

The twin's central trick is that the real control-plane code runs
*unmodified* against simulated time. While :meth:`VirtualClock.patch` is
active, every ``time.time()`` / ``time.monotonic()`` /
``timeit.default_timer()`` call anywhere in the process reads the virtual
clock, and ``time.sleep()`` advances it instead of blocking — so queue
timestamps, deadline slack, gateway backpressure cooldowns, journal ``ts``
fields and metrics timestamps all live on the simulated axis and are
bit-reproducible from a seed.

``time.perf_counter`` is deliberately **not** patched: the anytime solver
races its tier ladder against real CPU time, and that race — including any
deadline miss — is precisely what the twin must measure honestly rather
than simulate away. Wall-clock solver cost is the one "real" quantity a
campaign reports.

Single-threaded by contract: the campaign loop owns the process while the
patch is active. Patching module attributes is process-global, so nothing
else (no live service, no engine launcher threads) may run concurrently —
the runner enforces this by never calling ``start()`` on the gateway and
driving every step inline.
"""

from __future__ import annotations

import contextlib
import heapq
import itertools
import time
import timeit
from typing import Any, Iterator, List, Optional, Tuple


class VirtualClock:
    """Monotonic simulated clock (seconds, starts at ``start``)."""

    def __init__(self, start: float = 0.0):
        self._now = float(start)

    def now(self, *_args) -> float:
        return self._now

    def advance(self, dt: float) -> float:
        if dt < 0:
            raise ValueError(f"cannot advance the clock by {dt}s")
        self._now += float(dt)
        return self._now

    def advance_to(self, t: float) -> float:
        """Jump forward to absolute time ``t`` (no-op if already past it)."""
        if t > self._now:
            self._now = float(t)
        return self._now

    def sleep(self, dt: float) -> None:
        """Stand-in for ``time.sleep`` inside the patch: advances instead of
        blocking (negative durations clamp to zero like the real one)."""
        self.advance(max(0.0, float(dt)))

    @contextlib.contextmanager
    def patch(self) -> Iterator["VirtualClock"]:
        """Swap ``time.time``/``time.monotonic``/``time.sleep`` and
        ``timeit.default_timer`` for this clock; restore on exit.
        ``time.perf_counter`` stays real (see module docstring)."""
        saved = (time.time, time.monotonic, time.sleep, timeit.default_timer)
        time.time = self.now
        time.monotonic = self.now
        time.sleep = self.sleep
        timeit.default_timer = self.now
        try:
            yield self
        finally:
            (time.time, time.monotonic,
             time.sleep, timeit.default_timer) = saved


class EventQueue:
    """Deterministic time-ordered event queue.

    Ties on the timestamp break by insertion order (a monotone counter), so
    two runs that push the same events in the same order pop them in the
    same order — the property the bit-identical-replay tests rely on.
    """

    def __init__(self):
        self._heap: List[Tuple[float, int, str, Any]] = []
        self._counter = itertools.count()

    def push(self, at: float, kind: str, payload: Any = None) -> None:
        heapq.heappush(
            self._heap, (float(at), next(self._counter), kind, payload)
        )

    def peek_time(self) -> Optional[float]:
        return self._heap[0][0] if self._heap else None

    def pop_due(self, now: float) -> List[Tuple[float, str, Any]]:
        """Pop every event with timestamp <= ``now`` (in order)."""
        out: List[Tuple[float, str, Any]] = []
        while self._heap and self._heap[0][0] <= now:
            at, _n, kind, payload = heapq.heappop(self._heap)
            out.append((at, kind, payload))
        return out

    def __len__(self) -> int:
        return len(self._heap)

    @property
    def empty(self) -> bool:
        return not self._heap
