"""The campaign runner: real control plane, virtual fleet, simulated clock.

:class:`TwinCampaign` is the twin's event loop. It is deliberately a
*mirror* of ``service.server.SaturnService._run_loop`` — same ten steps, in
the same order, calling the same production code:

- arrivals enter through the **real** ``GatewayServer`` admission path
  (``_op_submit``: draining check, request-budget deadline, dedup table,
  pressure-shrunk inflight window, ``task_provider`` rebuild) — the server
  is constructed but never ``start()``-ed, so no sockets exist and frames
  are handed to it directly;
- admission verdicts come from the **real** ``AdmissionController``;
- every re-solve is the **real** ``anytime.anytime_resolve`` tier ladder,
  racing the *real* CPU clock against its deadline (``VirtualClock.patch``
  leaves ``perf_counter`` alone on purpose — a twin that froze the solver's
  stopwatch would report a tier mix reality never produces);
- deadline-pressure shedding is the **real** ``project_pressure_shed``;
- topology changes run the **real** ``_handle_topology_change`` →
  ``ElasticReplanner`` migration path, fed by the real
  ``FleetHealthMonitor`` + ``FaultInjector`` driven from the virtual
  fleet's seeded failure schedules;
- the only substitutions are the leaves: :class:`~saturn_tpu.twin.engine.
  VirtualEngine` instead of chip time, :class:`~saturn_tpu.twin.oracle.
  StaticOracle` instead of profiling sweeps, and a :class:`~saturn_tpu.
  twin.clock.VirtualClock` patched under ``time.time``/``monotonic``/
  ``sleep`` so a 100k-job day of traffic runs in seconds of wall time.

Outputs per campaign directory:

- ``events.jsonl`` — the canonical deterministic event log (virtual
  timestamps and decision outcomes only; no wall-clock-dependent fields).
  Same config + seed (+ trace) ⇒ bit-identical file.
- ``ledger.json`` — the final verdict ledger (admission mix, solver tier
  counts, completion/failure/eviction totals). Deterministic.
- ``summary.json`` — ledger + shares + fidelity-comparable side + real
  ``wall_s`` (the one intentionally non-deterministic field).
- ``journal/`` — a real write-ahead journal (the service's own format), so
  twin campaigns are themselves replayable traces.
- ``metrics.jsonl`` — ordinary telemetry (``solver_tier`` events carry real
  ``wall_s``; not part of the determinism contract). Disable with
  ``CampaignConfig(metrics=False)`` for very large runs.
"""

from __future__ import annotations

import json
import os
import time
import timeit
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from saturn_tpu.twin.arrivals import arrival_stream
from saturn_tpu.twin.clock import VirtualClock
from saturn_tpu.twin.engine import VirtualEngine, forecast, rollback_forecast
from saturn_tpu.twin.fleet import SliceSpec, VirtualFleet
from saturn_tpu.twin.oracle import StaticOracle


@dataclass
class CampaignConfig:
    """Everything a campaign is a deterministic function of."""

    # ---- workload (synthesized unless ``trace_dir`` is set)
    n_jobs: int = 200
    total_batches: int = 3
    deadline_s: Optional[float] = None   # per-job deadline (pressure shed)
    max_retries: int = 1
    base_rate_hz: float = 12.0
    burst_rate_hz: float = 80.0
    trace_dir: Optional[str] = None      # replay a journaled real run
    # ---- virtual fleet
    n_slices: int = 4
    chips_per_slice: int = 8
    hbm_gib: float = 16.0
    # ---- control plane
    interval_s: float = 60.0             # SIMULATED seconds per interval
    solve_deadline_s: float = 2.0        # REAL seconds: the solver's race
    threshold: float = 0.0
    max_inflight: int = 64
    session: Optional[str] = None        # exercise the per-session window
    pressure_policy: str = "evict-lowest-priority"
    recovery_policy: str = "pause-resolve-resume"
    replan_degrade_factor: float = 2.0
    # ---- oracle
    n_families: int = 16
    flat_per_batch_s: Optional[float] = None  # trace-replay cost mode
    # ---- chaos (both schedules are pure functions of (fleet, seed))
    p_preempt: float = 0.0               # per-slice renewal reclaim prob.
    outage_intervals: int = 2
    storm: bool = False                  # seeded_schedule-based chaos storm
    storm_p_preempt: float = 0.15
    storm_p_crash: float = 0.1
    storm_p_straggler: float = 0.05
    dedup_every: int = 0                 # >0: every Nth job resubmits its
    #                                      predecessor's dedup key (retry
    #                                      storm: exercises idempotency)
    tenant_mix: Optional[Dict[str, float]] = None  # tenant -> arrival
    #                                      weight: synthesized arrivals are
    #                                      tenant-tagged (noisy-neighbour
    #                                      fairness campaigns); drawn from a
    #                                      separate RNG stream, so historic
    #                                      seeds replay draw for draw
    # ---- run control
    seed: int = 7
    max_intervals: int = 1000
    compact_every: int = 32              # queue.compact() cadence
    metrics: bool = True
    journal_plan_max_tasks: int = 1024   # skip plan JSON above this size

    def describe(self) -> dict:
        return asdict(self)


@dataclass
class _Counters:
    submitted: int = 0
    duplicates: int = 0
    completed: int = 0
    failed: int = 0
    evicted: int = 0
    preemption_requeues: int = 0
    retries: int = 0
    crashes: int = 0
    topology_changes: int = 0
    backlog_drained: int = 0
    pressure_sheds: int = 0
    solves: int = 0
    deadline_misses: int = 0
    verdicts: Dict[str, int] = field(default_factory=dict)
    tiers: Dict[str, int] = field(default_factory=dict)
    gateway_sheds: Dict[str, int] = field(default_factory=dict)
    tenant_submitted: Dict[str, int] = field(default_factory=dict)
    tenant_sheds: Dict[str, int] = field(default_factory=dict)


def _shares(counts: Dict[str, int]) -> Dict[str, float]:
    total = sum(counts.values())
    if total == 0:
        return {}
    return {k: round(v / total, 6) for k, v in sorted(counts.items())}


class TwinCampaign:
    """One deterministic run of the control plane against a virtual fleet.

    Construct with a config and an output directory, then :meth:`run` —
    everything time-dependent is built *inside* the virtual-clock patch so
    journals and event logs carry simulated timestamps.
    """

    def __init__(self, cfg: CampaignConfig, out_dir: str):
        self.cfg = cfg
        self.out_dir = out_dir
        os.makedirs(out_dir, exist_ok=True)
        self.clock = VirtualClock()
        spec = SliceSpec(
            chips=cfg.chips_per_slice, hbm_gib=cfg.hbm_gib,
            p_preempt=cfg.p_preempt, outage_intervals=cfg.outage_intervals,
        )
        self.fleet = VirtualFleet(cfg.n_slices, spec)
        self.oracle = StaticOracle(
            self.fleet, seed=cfg.seed, n_families=cfg.n_families,
            flat_per_batch_s=cfg.flat_per_batch_s,
        )
        self._arrivals = self._build_arrivals()
        self._next_arrival = 0
        self.counters = _Counters()
        self._events: List[str] = []
        self.summary: Optional[dict] = None
        # Service-shim surface (GatewayServer/_check_window/pressure read
        # these off ``self`` exactly as off a SaturnService):
        self.interval = cfg.interval_s
        self.task_provider = self.oracle.task_provider()
        self.last_pressure_shed: Optional[float] = None
        self.recovered_dedup: Dict[str, str] = {}
        self.journal = None
        self.queue = None
        self.tenancy = None  # twin runs tenant-tagged but unquota'd

    # ----------------------------------------------------------- arrivals
    def _build_arrivals(self) -> List[Tuple[float, dict]]:
        """(at_s, submit frame) list, ascending — a pure function of cfg."""
        cfg = self.cfg
        out: List[Tuple[float, dict]] = []
        if cfg.trace_dir is not None:
            from saturn_tpu.twin.trace import load_trace

            for j in load_trace(cfg.trace_dir).jobs:
                out.append((j.at_s, {
                    "op": "submit",
                    "job": {
                        "name": j.name, "total_batches": j.total_batches,
                        "priority": j.priority, "deadline_s": j.deadline_s,
                        "max_retries": cfg.max_retries, "spec": j.spec,
                    },
                    "dedup_key": j.dedup_key,
                }))
            out.sort(key=lambda p: p[0])
            return out
        trace = arrival_stream(
            cfg.n_jobs, base_rate_hz=cfg.base_rate_hz,
            burst_rate_hz=cfg.burst_rate_hz, seed=cfg.seed,
            tenant_mix=cfg.tenant_mix,
        )
        for arr in trace:
            name = f"twin-{arr.index:06d}"
            key = name
            if cfg.dedup_every > 0 and arr.index > 0 \
                    and arr.index % cfg.dedup_every == 0:
                # A retry storm: this submission repeats the previous job's
                # idempotency key and must collapse to a dedup hit.
                key = f"twin-{arr.index - 1:06d}"
            out.append((arr.at_s, {
                "op": "submit",
                "job": {
                    "name": name, "total_batches": cfg.total_batches,
                    "priority": arr.priority, "deadline_s": cfg.deadline_s,
                    "max_retries": cfg.max_retries, "spec": None,
                    "tenant": arr.tenant,
                },
                "dedup_key": key,
            }))
        return out

    # ------------------------------------------------------------- logging
    def _event(self, kind: str, **fields) -> None:
        """Canonical deterministic log line: virtual time + decision fields
        only. Never put a real-clock quantity here."""
        rec = {"t": round(self.clock.now(), 6), "kind": kind}
        rec.update(fields)
        self._events.append(json.dumps(rec, sort_keys=True))

    def _observe_job(self, event: str, rec, **fields) -> None:
        """Queue observer → write-ahead journal; the same record mapping as
        ``SaturnService._observe_job`` so twin journals replay with the
        production recovery/trace tooling."""
        jnl = self.journal
        if jnl is None:
            return
        if event == "submitted":
            jnl.log(
                "job_submitted", job=rec.job_id, task=rec.name,
                priority=rec.request.priority,
                deadline_s=rec.request.deadline_s,
                max_retries=rec.request.max_retries,
                total_batches=getattr(rec.task, "total_batches", None),
                spec=rec.request.spec,
                dedup_key=rec.request.dedup_key,
            )
        elif event == "state":
            jnl.append(
                "job_state", job=rec.job_id, state=rec.state.value,
                attempts=rec.attempts, requeues=rec.requeues,
                error=rec.error,
            )

    # ----------------------------------------------------------------- run
    def run(self) -> dict:
        from saturn_tpu.utils import metrics

        t_wall = timeit.default_timer()
        metrics_path = (
            os.path.join(self.out_dir, "metrics.jsonl")
            if self.cfg.metrics else None
        )
        with self.clock.patch():
            with metrics.scoped(metrics_path):
                self._setup()
                try:
                    status = self._loop()
                finally:
                    self.journal.close()
        wall_s = timeit.default_timer() - t_wall
        return self._finish(status, wall_s, metrics_path)

    def _setup(self) -> None:
        """Build the control plane *under the patched clock* so every
        journal/queue timestamp is simulated time."""
        from saturn_tpu.durability.journal import Journal
        from saturn_tpu.resilience.faults import FaultInjector
        from saturn_tpu.resilience.health import FleetHealthMonitor
        from saturn_tpu.resilience.replan import ElasticReplanner
        from saturn_tpu.service.admission import AdmissionController
        from saturn_tpu.service.gateway.server import GatewayServer
        from saturn_tpu.service.queue import SubmissionQueue

        cfg = self.cfg
        self.topology = self.fleet.topology()
        self._base_topo = self.topology
        # sync=False: a simulator does not pay fsync per record; the journal
        # format (and replayability) is identical.
        self.journal = Journal(os.path.join(self.out_dir, "journal"),
                               sync=False)
        self.queue = SubmissionQueue(observer=self._observe_job)
        # ``twin-virtual`` is not a registered library technique, so the
        # memlens/builtin rosters resolve empty and no profiling sweep can
        # start; tasks arrive pre-strategized by the oracle anyway.
        self.admission = AdmissionController(
            self.topology, self.queue, technique_names=["twin-virtual"],
        )
        self.admission.journal = self.journal
        # The REAL grow coordinator: grow events journal, the DEFER backlog
        # drains with attribution. Virtual tasks carry no device-resident
        # live state, so the occupancy gate fails open and defrag waves
        # plan empty — the grow path itself runs for real and stays
        # deterministic (every journaled/evented field is interval-indexed).
        from saturn_tpu.resilience.grow import GrowCoordinator

        self.grow = GrowCoordinator(journal=self.journal)
        self.health = FleetHealthMonitor.for_topology(self.topology)
        self.replanner = ElasticReplanner(
            policy=cfg.recovery_policy,
            degrade_factor=cfg.replan_degrade_factor,
        )
        schedule = []
        if cfg.storm:
            schedule = self.fleet.storm_schedule(
                cfg.seed, cfg.max_intervals,
                p_preempt=cfg.storm_p_preempt, p_crash=cfg.storm_p_crash,
                p_straggler=cfg.storm_p_straggler,
                outage_intervals=cfg.outage_intervals,
            )
        elif cfg.p_preempt > 0.0:
            schedule = self.fleet.failure_schedule(cfg.seed,
                                                   cfg.max_intervals)
        self.faults = FaultInjector(schedule) if schedule else None
        self.engine = VirtualEngine(self.health, self.faults)
        # The REAL gateway, never start()-ed: no sockets, no threads —
        # frames go straight into ``_op_submit`` (dedup, window, shed and
        # task-rebuild logic all run for real).
        self.gateway = GatewayServer(self, max_inflight=cfg.max_inflight)

    # ------------------------------------------------------ arrival inject
    def _inject_until(self, horizon: float) -> None:
        """Submit every arrival due at or before ``horizon``, advancing the
        virtual clock to each arrival instant (the gateway stamps
        ``time.monotonic()`` as the wire-arrival time)."""
        from saturn_tpu.service.gateway.protocol import GatewayError

        c = self.counters
        while self._next_arrival < len(self._arrivals):
            at_s, frame = self._arrivals[self._next_arrival]
            if at_s > horizon:
                break
            self._next_arrival += 1
            self.clock.advance_to(max(self.clock.now(), at_s))
            arrival = time.monotonic()
            tenant = frame["job"].get("tenant")
            try:
                out = self.gateway._op_submit(dict(frame), self.cfg.session,
                                              arrival)
            except GatewayError as e:
                c.gateway_sheds[e.code] = c.gateway_sheds.get(e.code, 0) + 1
                if tenant is not None:
                    c.tenant_sheds[tenant] = \
                        c.tenant_sheds.get(tenant, 0) + 1
                self._event("gateway_shed", name=frame["job"]["name"],
                            code=e.code, tenant=tenant)
                continue
            if out.get("duplicate"):
                c.duplicates += 1
                self._event("dedup_hit", name=frame["job"]["name"],
                            job=out["job_id"])
            else:
                c.submitted += 1
                if tenant is not None:
                    c.tenant_submitted[tenant] = \
                        c.tenant_submitted.get(tenant, 0) + 1

    def _arrivals_left(self) -> bool:
        return self._next_arrival < len(self._arrivals)

    # ------------------------------------------------------------ the loop
    def _loop(self) -> str:
        """The service loop, transliterated — step numbers match
        ``SaturnService._run_loop``."""
        from saturn_tpu import analysis
        from saturn_tpu.executor.orchestrator import (
            _handle_topology_change,
            fold_realized_feedback,
        )
        from saturn_tpu.resilience.faults import PreemptedError
        from saturn_tpu.service.admission import ADMIT, DEFER, compute_weight
        from saturn_tpu.service.queue import JobRecord, JobState
        from saturn_tpu.service.server import project_pressure_shed
        from saturn_tpu.solver import anytime
        from saturn_tpu.utils import metrics

        cfg = self.cfg
        c = self.counters
        jnl = self.journal
        topo = self.topology
        plan = None
        jobs: Dict[str, JobRecord] = {}
        interval_index = 0

        # Arrivals strictly before the first interval boundary seed the run.
        self._inject_until(0.0)
        while True:
            if not jobs and self.queue.depth() == 0:
                if not self._arrivals_left():
                    break
                # Idle skip: jump straight to the next arrival (the real
                # loop parks on the queue condition; the twin jumps time).
                next_at = self._arrivals[self._next_arrival][0]
                self.clock.advance_to(max(self.clock.now(), next_at))
                self._inject_until(self.clock.now())
                continue
            if interval_index >= cfg.max_intervals:
                self._intervals = interval_index
                return "max-intervals"

            # 1. health poll / topology change
            grew = False
            if self.faults is not None:
                self.faults.apply_due(interval_index, self.health)
            change = self.health.poll()
            if change is not None and change.kind in ("shrink", "grow"):
                c.topology_changes += 1
                evicted_names: dict = {}
                tasks = [r.task for r in jobs.values()]
                tasks, topo, plan = _handle_topology_change(
                    tasks, self._base_topo, self.health, self.replanner,
                    change, plan, cfg.solve_deadline_s, evicted_names,
                )
                for name in sorted(evicted_names):
                    rec = jobs.pop(name, None)
                    if rec is not None:
                        self.queue.mark(rec, JobState.EVICTED,
                                        error=evicted_names[name])
                        c.evicted += 1
                        self._event("job_evicted", task=name,
                                    reason="topology-change")
                jnl.append("topology_change", **change.to_fields())
                self._event("topology_change", change=change.kind,
                            lost=list(change.lost),
                            gained=list(change.gained))
                if change.kind == "grow":
                    # Recovery half: journal the grow event (the twin has
                    # no guardian benches to release).
                    grew = True
                    self.grow.note_grow(
                        change, interval_index,
                        n_deferred=len(self.admission.deferred),
                        capacity=topo.capacity,
                    )
                    self._event("grow_event", gained=list(change.gained),
                                n_deferred=len(self.admission.deferred))
            elif change is not None:  # degrade: advisory only
                metrics.event("topology_change", **change.to_fields())
                self._event("topology_change", change=change.kind,
                            stragglers=list(change.stragglers))

            # 2. drain arrivals through admission (the real controller)
            deferred_before = set(self.admission.deferred)
            newly_admitted: List[JobRecord] = []
            for rec in self.queue.drain():
                dec = self.admission.admit(rec, topo)
                c.verdicts[dec.action] = c.verdicts.get(dec.action, 0) + 1
                self._event("admission", job=rec.job_id, task=rec.name,
                            decision=dec.action)
                if dec.action == ADMIT:
                    jobs[rec.name] = rec
                    newly_admitted.append(rec)
                elif dec.action == DEFER:
                    self.queue.requeue(rec)
                else:  # REJECT
                    self.queue.mark(rec, JobState.FAILED, error=dec.reason)
                    c.failed += 1
            drained = sorted(
                deferred_before & {r.job_id for r in newly_admitted}
            )
            if drained:
                trigger = "grow" if grew else "interval"
                self.grow.note_drained(drained, interval_index,
                                       trigger=trigger)
                c.backlog_drained += len(drained)
                self._event("backlog_drain", jobs=drained, trigger=trigger)

            # 3. (no cancel sweep: the twin has no interactive clients)

            # 4. admission pressure — the identical module-level projection
            shed, proj, limit = project_pressure_shed(
                jobs, topo, plan, cfg.pressure_policy
            )
            if shed:
                self.last_pressure_shed = time.monotonic()
            for rec in shed:
                jobs.pop(rec.name, None)
                self.queue.mark(rec, JobState.EVICTED,
                                error="admission-pressure")
                c.evicted += 1
                c.pressure_sheds += 1
                self._event("pressure_shed", task=rec.name,
                            projection=round(proj, 6),
                            limit=round(limit, 6))

            if not jobs:
                plan = None
                interval_index += 1
                boundary = self.clock.now() + cfg.interval_s
                self._inject_until(boundary)
                self.clock.advance_to(boundary)
                continue

            # 5. incremental re-solve: the REAL anytime tier ladder racing
            #    the REAL cpu clock (perf_counter is unpatched) against
            #    solve_deadline_s.
            tasks = [r.task for r in jobs.values()]
            now_v = time.monotonic()
            weights = {}
            for r in jobs.values():
                slack = (r.deadline_at - now_v
                         if r.deadline_at is not None else None)
                feas = r.task.feasible_strategies()
                est = min((s.runtime for s in feas.values()), default=0.0)
                r.weight = compute_weight(r.request.priority, slack, est)
                weights[r.name] = r.weight
            candidate = anytime.anytime_resolve(
                tasks, topo, plan, cfg.interval_s, cfg.threshold,
                deadline=cfg.solve_deadline_s, weights=weights,
                source="twin", seed=cfg.seed,
            )
            try:
                analysis.verify_or_raise(
                    candidate, topology=topo, tasks=tasks,
                    source="twin-re-solve",
                )
            except analysis.PlanVerificationError as e:
                codes = sorted({d.code for d in e.report.errors})
                jnl.log("plan_quarantine", interval=interval_index,
                        source="twin-re-solve", codes=codes)
                self._event("plan_quarantine", codes=codes)
                if plan is None:
                    raise
            else:
                plan = candidate
            rep = getattr(plan, "anytime", None)
            if rep is not None:
                c.solves += 1
                t = str(rep.tier)
                c.tiers[t] = c.tiers.get(t, 0) + 1
                if rep.deadline_missed:
                    c.deadline_misses += 1
                self._event("solve", interval=interval_index,
                            tier=rep.tier, tier_name=rep.tier_name,
                            outcome=rep.outcome, n_tasks=len(tasks),
                            makespan=round(plan.makespan, 6))
            if len(plan.assignments) <= cfg.journal_plan_max_tasks:
                jnl.append("plan_commit", interval=interval_index,
                           makespan=plan.makespan, plan=plan.to_json())
            else:
                # A 100k-task plan JSON per interval would dominate the
                # journal; commit the decision without the payload.
                jnl.append("plan_commit", interval=interval_index,
                           makespan=plan.makespan, plan=None)
            jnl.commit()
            for rec in newly_admitted:
                if rec.name in jobs:
                    self.queue.mark(rec, JobState.SCHEDULED)

            # 6. forecast + virtual gang-execute one interval
            run_tasks, batches, completed = forecast(
                tasks, cfg.interval_s, plan
            )
            errors: Dict[str, Exception] = {}
            if run_tasks:
                errors = self.engine.execute(
                    run_tasks, batches, cfg.interval_s, plan, topo,
                    interval_index=interval_index,
                    on_task_start=self._on_start(jobs),
                    on_task_done=self._on_done(jobs),
                )

            # The interval's simulated wall time elapses here; arrivals due
            # during it hit the gateway at their exact virtual instants.
            boundary = self.clock.now() + cfg.interval_s
            self._inject_until(boundary)
            self.clock.advance_to(boundary)

            # 7. estimate feedback (REAL EWMA fold)
            fold_realized_feedback(run_tasks)

            preempted = {n: e for n, e in errors.items()
                         if isinstance(e, PreemptedError)}
            failed = {n: e for n, e in errors.items() if n not in preempted}

            # 8. preemptions requeue through the queue, no retry consumed
            for name in sorted(preempted):
                rec = jobs.pop(name)
                rollback_forecast(rec.task, batches.get(name, 0))
                self.queue.requeue(rec)
                c.preemption_requeues += 1
                self._event("task_preempted", task=name)
            completed = [t for t in completed if t.name not in preempted]

            # 9. real failures: retry within budget, else FAIL
            for name, err in sorted(failed.items()):
                rec = jobs[name]
                rec.attempts += 1
                c.crashes += 1
                if rec.attempts <= rec.request.max_retries:
                    rollback_forecast(rec.task, batches.get(name, 0))
                    c.retries += 1
                    self._event("task_retry", task=name,
                                attempt=rec.attempts)
                else:
                    jobs.pop(name)
                    self.queue.mark(rec, JobState.FAILED, error=repr(err))
                    c.failed += 1
                    self._event("job_failed", job=rec.job_id, task=name)
            completed = [t for t in completed if t.name not in failed]

            # 10. retire completions
            for t in completed:
                rec = jobs.pop(t.name)
                self.queue.mark(rec, JobState.DONE)
                c.completed += 1
                self._event("job_completed", job=rec.job_id, task=t.name,
                            requeues=rec.requeues, attempts=rec.attempts)

            jnl.commit()
            metrics.flush()
            interval_index += 1
            if cfg.compact_every > 0 \
                    and interval_index % cfg.compact_every == 0:
                self.queue.compact()
        self._intervals = interval_index
        return "ok"

    def _on_start(self, jobs):
        from saturn_tpu.service.queue import JobState

        def on_start(name: str) -> None:
            rec = jobs.get(name)
            if rec is not None and rec.state is JobState.SCHEDULED:
                self.queue.mark(rec, JobState.RUNNING)

        return on_start

    def _on_done(self, jobs):
        jnl = self.journal
        ids = {name: rec.job_id for name, rec in jobs.items()}

        def on_done(name: str, batches: int) -> None:
            if batches > 0:
                jnl.append("task_progress", task=name, job=ids.get(name),
                           batches=int(batches))

        return on_done

    # -------------------------------------------------------------- outputs
    def _finish(self, status: str, wall_s: float,
                metrics_path: Optional[str]) -> dict:
        c = self.counters
        with open(os.path.join(self.out_dir, "events.jsonl"), "w") as fh:
            fh.write("\n".join(self._events))
            if self._events:
                fh.write("\n")
        ledger = {
            "status": status,
            "n_arrivals": len(self._arrivals),
            "submitted": c.submitted,
            "duplicates": c.duplicates,
            "gateway_sheds": dict(sorted(c.gateway_sheds.items())),
            "shed_total": sum(c.gateway_sheds.values()),
            "admission": dict(sorted(c.verdicts.items())),
            "tier_counts": dict(sorted(c.tiers.items())),
            "solves": c.solves,
            "deadline_misses": c.deadline_misses,
            "completed": c.completed,
            "failed": c.failed,
            "evicted": c.evicted,
            "preemption_requeues": c.preemption_requeues,
            "retries": c.retries,
            "crashes": c.crashes,
            "topology_changes": c.topology_changes,
            "backlog_drained": c.backlog_drained,
            "pressure_sheds": c.pressure_sheds,
            "intervals": getattr(self, "_intervals", 0),
            "makespan_s": round(self.clock.now(), 6),
        }
        if c.tenant_submitted or c.tenant_sheds:
            ledger["tenant_submitted"] = dict(
                sorted(c.tenant_submitted.items()))
            ledger["tenant_sheds"] = dict(sorted(c.tenant_sheds.items()))
        with open(os.path.join(self.out_dir, "ledger.json"), "w") as fh:
            json.dump(ledger, fh, indent=1, sort_keys=True)
            fh.write("\n")
        summary = dict(ledger)
        summary.update({
            "config": self.cfg.describe(),
            "fleet": self.fleet.describe(),
            "tier_shares": _shares(c.tiers),
            "verdict_shares": _shares(c.verdicts),
            "wall_s": round(wall_s, 3),         # real seconds — the one
            #                                     non-deterministic field
            "sim_speedup": round(
                self.clock.now() / wall_s, 2) if wall_s > 0 else None,
            "out_dir": self.out_dir,
            "metrics_path": metrics_path,
        })
        with open(os.path.join(self.out_dir, "summary.json"), "w") as fh:
            json.dump(summary, fh, indent=1, sort_keys=True)
            fh.write("\n")
        self.summary = summary
        return summary


def run_campaign(cfg: CampaignConfig, out_dir: str) -> dict:
    """Build + run one campaign; returns (and writes) its summary."""
    return TwinCampaign(cfg, out_dir).run()


def run_what_if(base: CampaignConfig, out_dir: str) -> dict:
    """Capacity planning: the base campaign vs (a) one more virtual slice
    vs (b) every per-job deadline relaxed 2×. Same seed, same arrivals —
    the verdict deltas are attributable to the knob alone."""
    from dataclasses import replace

    scenarios = {
        "base": base,
        "add-slice": replace(base, n_slices=base.n_slices + 1),
        "relax-deadlines": replace(
            base,
            deadline_s=(base.deadline_s * 2.0
                        if base.deadline_s is not None else None),
        ),
    }
    results = {
        name: run_campaign(cfg, os.path.join(out_dir, name))
        for name, cfg in scenarios.items()
    }
    keys = ("completed", "failed", "evicted", "shed_total",
            "deadline_misses", "makespan_s", "pressure_sheds")
    comparison = {
        name: {k: res[k] for k in keys} for name, res in results.items()
    }
    verdict = {"comparison": comparison, "out_dir": out_dir}
    with open(os.path.join(out_dir, "whatif.json"), "w") as fh:
        json.dump(verdict, fh, indent=1, sort_keys=True)
        fh.write("\n")
    verdict["results"] = results
    return verdict
