"""Seeded Poisson + diurnal-burst arrival synthesis.

Extracted from ``benchmarks/online_arrivals.py`` so the twin and the bench
draw the *same* trace from the same seed and can never drift. The generator
consumes its RNG in exactly the order the bench's submit loop always did —
one ``expovariate`` gap, then one ``randint`` priority, per arrival — so
seed 7 still produces the historical gateway-bench trace draw for draw.

Traffic shape: a Poisson base rate modulated by periodic diurnal bursts —
every ``burst_every`` arrivals, a window of ``burst_len`` arrivals comes in
at ``burst_rate_hz`` instead of ``base_rate_hz`` (the arrival pattern a
serving front door actually sees). Scaling the rates up by orders of
magnitude (the twin's "million-user" campaigns) preserves the shape.
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import List, Mapping, Optional

#: Diurnal-burst cycle defaults (historically the bench module constants).
BURST_EVERY = 50          # every 50 arrivals, a burst window opens...
BURST_LEN = 20            # ...for 20 arrivals


@dataclass(frozen=True)
class Arrival:
    """One synthesized arrival on the stream's own time axis."""

    index: int
    at_s: float       # offset from stream start (cumulative gaps)
    gap_s: float      # the inter-arrival gap drawn for this arrival
    priority: float   # integer-valued priority class, 0.0 .. 2.0
    in_burst: bool    # whether this arrival fell inside a burst window
    tenant: Optional[str] = None  # owning tenant (tenant_mix runs only)


def arrival_stream(n_jobs: int, *,
                   base_rate_hz: float,
                   burst_rate_hz: float,
                   burst_every: int = BURST_EVERY,
                   burst_len: int = BURST_LEN,
                   seed: int = 0,
                   tenant_mix: Optional[Mapping[str, float]] = None,
                   ) -> List[Arrival]:
    """Synthesize a deterministic arrival trace.

    Same ``(n_jobs, rates, cycle, seed)`` → the identical list, on every
    platform CPython's Mersenne Twister runs on. Raises on nonsensical
    rates rather than emitting an empty or divergent stream.

    ``tenant_mix`` maps tenant name → positive arrival weight: each
    arrival is tagged with a tenant drawn from the mix (a 10:1 weight
    skew yields the noisy-neighbour traffic the fairness benchmarks
    need). Tenant draws come from a *separate* RNG stream seeded as
    ``f"{seed}:tenant"`` so the primary gap/priority draw order — one
    ``expovariate`` plus one ``randint`` per arrival — is untouched:
    adding tenants to a historical seed reproduces the historical trace
    draw for draw, just tagged.
    """
    if n_jobs < 0:
        raise ValueError(f"n_jobs must be >= 0, got {n_jobs}")
    if base_rate_hz <= 0 or burst_rate_hz <= 0:
        raise ValueError(
            f"arrival rates must be positive, got base={base_rate_hz} "
            f"burst={burst_rate_hz}"
        )
    if burst_every <= 0 or burst_len < 0:
        raise ValueError(
            f"burst cycle must satisfy burst_every > 0 and burst_len >= 0, "
            f"got every={burst_every} len={burst_len}"
        )
    tenants = None
    weights = None
    tenant_rng = None
    if tenant_mix:
        if any(w <= 0 for w in tenant_mix.values()):
            raise ValueError(
                f"tenant_mix weights must be positive, got {tenant_mix}"
            )
        tenants = list(tenant_mix)
        weights = [float(tenant_mix[t]) for t in tenants]
        tenant_rng = random.Random(f"{seed}:tenant")
    rng = random.Random(seed)
    out: List[Arrival] = []
    t = 0.0
    for i in range(n_jobs):
        in_burst = (i % burst_every) < burst_len
        rate = burst_rate_hz if in_burst else base_rate_hz
        gap = rng.expovariate(rate)
        priority = float(rng.randint(0, 2))
        tenant = (tenant_rng.choices(tenants, weights=weights)[0]
                  if tenant_rng is not None else None)
        t += gap
        out.append(Arrival(index=i, at_s=t, gap_s=gap,
                           priority=priority, in_burst=in_burst,
                           tenant=tenant))
    return out
