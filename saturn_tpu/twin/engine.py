"""VirtualEngine: the engine surface with simulated execution.

The real engine (``executor/engine.py``) is two things: pure forecast
arithmetic (which tasks run this interval, for how many batches) and a
gang launch that actually burns chip time. The twin keeps the first —
:func:`forecast` / :func:`rollback_forecast` are re-exported *verbatim*,
because the batch-budget math is part of what the twin exists to validate
— and replaces the second with bookkeeping on the virtual clock:

- mid-interval fault events land on the health monitor directly (the real
  engine arms wall-clock watchdog timers; in virtual time the interval is
  atomic, so "during the interval" means "before its work retires");
- a task whose assigned block lost a device raises
  :class:`~saturn_tpu.resilience.faults.PreemptedError` into the errors
  dict — the same requeue-without-retry contract the service loop applies;
- ``FaultInjector.crashes`` answers transient-crash queries exactly as the
  real engine asks them;
- straggler slowdowns inflate the *realized* per-batch time fed back
  through ``note_realized_per_batch`` and ``health.note_step`` — so EWMA
  correction, straggler detection and degrade replans all run on the
  production code paths, driven by simulated observations.

No threads, no techniques, no sleeps: an interval's execution is a pure
function of (plan, health, faults) and completes instantly in wall time
while representing ``interval`` seconds of simulated time.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

# Re-exported real arithmetic — the twin must never fork this math.
from saturn_tpu.executor.engine import forecast, rollback_forecast  # noqa: F401
from saturn_tpu.resilience.faults import FaultKind, PreemptedError


class VirtualEngine:
    """Drop-in for the service loop's ``engine.execute`` call."""

    def __init__(self, health=None, faults=None):
        self.health = health
        self.faults = faults

    def execute(
        self,
        run_tasks,
        batches: Dict[str, int],
        interval: float,
        plan,
        topo,
        *,
        interval_index: int = 0,
        on_task_start: Optional[Callable[[str], None]] = None,
        on_task_done: Optional[Callable[[str, int], None]] = None,
        **_ignored,
    ) -> Dict[str, Exception]:
        """Simulate one interval; returns ``{task_name: error}`` exactly like
        the real engine (empty dict = everything retired cleanly).

        Tasks are visited in (planned start, name) order — a deterministic
        serialization of the gang schedule. ``on_task_done`` fires only for
        tasks that retired their full budget, matching the real engine's
        all-or-nothing interval contract.
        """
        health, faults = self.health, self.faults
        errors: Dict[str, Exception] = {}
        # Mid-interval chaos fires before any work retires (see module doc).
        if faults is not None and health is not None:
            faults.apply_due(interval_index, health, mid_interval=True)
        for task in sorted(
            run_tasks, key=lambda t: (plan.assignments[t.name].start, t.name)
        ):
            a = plan.assignments[task.name]
            if on_task_start is not None:
                on_task_start(task.name)
            if faults is not None and faults.crashes(task.name, interval_index):
                errors[task.name] = RuntimeError(
                    f"injected transient crash: {task.name} "
                    f"interval {interval_index}"
                )
                continue
            idx = []
            if health is not None:
                idx = health.indices_of(topo.block_devices(a.block))
                if idx and health.any_lost(idx):
                    errors[task.name] = PreemptedError(
                        f"{task.name}: block {a.block} lost a device during "
                        f"interval {interval_index}"
                    )
                    continue
            strat = task.strategies[a.apportionment]
            slow = health.max_slowdown(idx) if (health and idx) else 1.0
            realized = strat.per_batch_time * slow
            task.select_strategy(a.apportionment)
            note = getattr(task, "note_realized_per_batch", None)
            if note is not None:
                note(realized)
            if health is not None and idx:
                # The monitor folds injected slowdowns itself — feed the
                # nominal time, as a real technique's timer would.
                health.note_step(idx, strat.per_batch_time)
            if on_task_done is not None:
                on_task_done(task.name, batches.get(task.name, 0))
        return errors
