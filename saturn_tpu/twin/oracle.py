"""Static cost/memory oracle: priors replace execution, no chips involved.

The twin cannot run trials, so where the real service gets per-batch times
from profiling sweeps (and shardflow/memlens fill cold-start gaps), the
twin gets *everything* from a seeded analytic model in the same shape those
analyzers emit:

- **cost**: per-family Amdahl + communication roofline,
  ``pbt(g) = serial + parallel/g + comm * log2(g)``, with a DCN penalty on
  the comm term once a block must span slices — the shardflow-style scaling
  curve, deterministic from ``(seed, family)``.
- **memory**: ``peak(g) = 3 * model_bytes / g + activation_bytes``
  (params+grads+optimizer sharded, activations replicated); a size whose
  projected peak overflows the virtual chips' HBM gets **no strategy** —
  the memlens-style residency gate, applied before admission ever sees the
  task.

Strategies carry ``static_prior=True`` — exactly the flag shardflow-admitted
jobs carry in production — so twin plans are auditable as prior-built, and
realized (simulated) feedback clears the flag through the same
``apply_realized_feedback`` path the orchestrator uses for real tasks.

Nothing here imports jax: :class:`VirtualTechnique` is a dispatch-surface
stub that must never execute (the VirtualEngine advances the clock
instead), and ``technique_names=["twin-virtual"]`` keeps the admission
controller's built-in roster empty so no sweep is attempted.
"""

from __future__ import annotations

import math
import random
import zlib
from typing import Dict, List, Optional, Sequence

from saturn_tpu.core.strategy import Strategy

#: Matches ``core.task.Task.EWMA_ALPHA`` — realized feedback folds the same.
EWMA_ALPHA = 0.7


class VirtualTechnique:
    """Executor stub: satisfies ``Strategy.feasible`` (executor is not None)
    and identity probes; raises if anything tries to actually run it."""

    name = "twin-virtual"
    technique = None

    def execute(self, *a, **k):
        raise RuntimeError(
            "VirtualTechnique.execute called — the twin must route all "
            "execution through VirtualEngine, never a real dispatch"
        )

    def search(self, *a, **k):
        raise RuntimeError("VirtualTechnique has no profiling sweep")


class TwinTask:
    """Duck-typed Task: everything admission/solver/replanner/engine-forecast
    touch, nothing that needs a runtime. Mirrors the real Task's realized-
    feedback surface (``note_realized_per_batch`` + no-arg
    ``apply_realized_feedback``) so ``orchestrator.fold_realized_feedback``
    works on it unmodified."""

    EWMA_ALPHA = EWMA_ALPHA

    def __init__(self, name: str, total_batches: int, family: int = 0,
                 hints: Optional[dict] = None):
        self.name = name
        self.total_batches = int(total_batches)
        self.current_batch = 0
        self.epoch_length = 1000
        self.family = family
        self.hints = dict(hints or {})
        self.chip_range = None
        self.strategies: Dict[int, Strategy] = {}
        self.selected_strategy: Optional[Strategy] = None
        self._pending_realized = None

    def feasible_strategies(self) -> Dict[int, Strategy]:
        return {g: s for g, s in self.strategies.items() if s.feasible}

    def select_strategy(self, g: int) -> None:
        self.selected_strategy = self.strategies[g]

    def reconfigure(self, n: int) -> None:
        self.current_batch = (self.current_batch + n) % self.epoch_length

    # ------------------------------------------------- realized feedback
    def note_realized_per_batch(self, per_batch_s: float) -> None:
        if self.selected_strategy is not None and per_batch_s > 0.0:
            self._pending_realized = (self.selected_strategy, per_batch_s)

    def apply_realized_feedback(self):
        pending = self._pending_realized
        self._pending_realized = None
        if pending is None:
            return None
        strat, realized = pending
        if not strat.feasible:
            return None
        old = strat.per_batch_time
        strat.per_batch_time = (
            self.EWMA_ALPHA * realized + (1.0 - self.EWMA_ALPHA) * old
            if old > 0.0 else realized
        )
        strat.runtime = strat.per_batch_time * self.total_batches
        # Simulated evidence landed: the prior did its cold-start job.
        strat.static_prior = False
        strat.interpolated = False
        return (old, strat.per_batch_time)


def family_of(name: str, n_families: int) -> int:
    """Stable task-name → family hash (CRC32, not ``hash()`` — the latter is
    salted per process and would break cross-run determinism)."""
    return zlib.crc32(name.encode("utf-8")) % max(1, n_families)


class StaticOracle:
    """Seeded per-family cost/memory model + task factory.

    ``flat_per_batch_s`` switches to trace-replay mode: every strategy gets
    that constant per-batch time with ``static_prior=False`` — mirroring
    the gateway bench's pre-profiled tasks, so a replayed bench trace is
    costed the way the real run was.
    """

    def __init__(self, fleet, seed: int = 0, n_families: int = 16,
                 flat_per_batch_s: Optional[float] = None,
                 dcn_penalty: float = 4.0):
        self.fleet = fleet
        self.seed = seed
        self.n_families = max(1, n_families)
        self.flat_per_batch_s = flat_per_batch_s
        self.dcn_penalty = dcn_penalty
        self.technique = VirtualTechnique()
        self._profiles: Dict[int, dict] = {}

    # ------------------------------------------------------------ the model
    def profile(self, family: int) -> dict:
        prof = self._profiles.get(family)
        if prof is None:
            rng = random.Random((self.seed << 20) ^ (family * 2654435761 % (1 << 31)))
            prof = {
                "serial_s": rng.uniform(0.02, 0.10),
                "parallel_s": rng.uniform(0.5, 4.0),
                "comm_s": rng.uniform(0.002, 0.012),
                "model_bytes": int(rng.uniform(0.5, 8.0) * (1 << 30)),
                "activation_bytes": int(rng.uniform(0.1, 1.0) * (1 << 30)),
            }
            self._profiles[family] = prof
        return prof

    def per_batch_time(self, family: int, g: int) -> float:
        if self.flat_per_batch_s is not None:
            return self.flat_per_batch_s
        p = self.profile(family)
        comm = p["comm_s"] * math.log2(g) if g > 1 else 0.0
        if g > self.fleet.chips:
            comm *= self.dcn_penalty  # block spans slices: DCN, not ICI
        return p["serial_s"] + p["parallel_s"] / g + comm

    def peak_bytes(self, family: int, g: int) -> int:
        p = self.profile(family)
        return 3 * p["model_bytes"] // g + p["activation_bytes"]

    def fits(self, family: int, g: int) -> bool:
        if self.flat_per_batch_s is not None:
            return True  # trace mode: the real run already admitted these
        hbm = min(d.hbm_bytes for d in self.fleet.devices)
        return self.peak_bytes(family, g) <= hbm

    # --------------------------------------------------------- task factory
    def candidate_sizes(self, capacity: int) -> List[int]:
        out, g = [], 1
        while g <= capacity:
            out.append(g)
            g *= 2
        return out

    def strategize(self, task: TwinTask,
                   sizes: Optional[Sequence[int]] = None) -> TwinTask:
        """Fill ``task.strategies`` with prior-built strategies at every
        HBM-feasible size (the memory gate: an OOM-projected size simply
        does not exist as an option)."""
        capacity = self.fleet.topology().capacity
        for g in (sizes or self.candidate_sizes(capacity)):
            g = int(g)
            if g < 1 or g > capacity or not self.fits(task.family, g):
                continue
            pbt = self.per_batch_time(task.family, g)
            prior = self.flat_per_batch_s is None
            task.strategies[g] = Strategy(
                self.technique, g, {}, pbt * task.total_batches, pbt,
                static_prior=prior, interpolated=prior,
            )
        return task

    def make_task(self, name: str, total_batches: int,
                  family: Optional[int] = None,
                  sizes: Optional[Sequence[int]] = None) -> TwinTask:
        if family is None:
            family = family_of(name, self.n_families)
        return self.strategize(
            TwinTask(name, total_batches, family=family), sizes=sizes
        )

    def task_provider(self):
        """``task_provider(payload) -> task`` closure in the gateway /
        crash-recovery rebuild contract (``service.server.task_provider``):
        the payload is the journaled submission spec."""

        def provide(payload: dict) -> TwinTask:
            spec = payload.get("spec") or {}
            return self.make_task(
                payload["task"],
                total_batches=int(payload.get("remaining_batches") or 1),
                family=spec.get("family"),
                sizes=spec.get("sizes"),
            )

        return provide
