"""saturn-twin: a deterministic discrete-event simulator for the control
plane.

The twin runs the **real** production code — ``solver/anytime.py``,
``service/admission.py``, ``resilience/replan.py``, the gateway's
shedding/dedup path — against *virtual* slices: chip counts, HBM and
failure processes are parameters, shardflow/memlens-style static priors
stand in for execution as the cost/memory oracle, and a
:class:`~saturn_tpu.twin.engine.VirtualEngine` satisfies the engine
surface by advancing a simulated clock instead of running training steps.

Modules:

- ``clock``    — virtual time (``time.*`` patch) + deterministic event queue
- ``arrivals`` — seeded Poisson + diurnal-burst arrival synthesis (shared
  with ``benchmarks/online_arrivals.py`` so bench and twin cannot drift)
- ``fleet``    — virtual devices/slices and seeded per-slice failure
  schedules
- ``oracle``   — static cost/memory model: prior-built strategies, no chips
- ``engine``   — the VirtualEngine dispatch surface (re-exports the real
  forecast arithmetic)
- ``trace``    — journal → arrival trace loading + fidelity comparison
- ``runner``   — the campaign loop mirroring ``SaturnService._run_loop``

Entry points: ``python -m saturn_tpu.analysis twin`` (campaign CLI view)
and ``benchmarks/twin_scale.py`` (the 100k-job scale + fidelity rows).
"""

from saturn_tpu.twin.arrivals import Arrival, arrival_stream  # noqa: F401
from saturn_tpu.twin.clock import EventQueue, VirtualClock  # noqa: F401
from saturn_tpu.twin.fleet import SliceSpec, VirtualFleet  # noqa: F401
