"""Trace loading + fidelity comparison: journals from real runs feed the twin.

``load_trace`` folds a durability journal (written by a real
``SaturnService`` run — e.g. the gateway bench with ``durability_dir`` set)
into an arrival trace plus the run's *reference distributions*: admission
verdict mix and, when a metrics file rode along, ``solver_tier`` shares.
Multi-incarnation journals are handled by
``durability.journal.replay_reconciled`` — the stable ``(seq,
incarnation)`` merge — so a service that crashed and restarted mid-run
still replays as one valid trace.

``fidelity_compare`` is the calibrated-instrument check: the twin replays
the trace and its tier shares / verdict mix / makespan must agree with
journaled reality within the documented band (see ``DEFAULT_BAND`` — the
values asserted by ``tests/test_twin.py`` and reported by
``benchmarks/twin_scale.py``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from saturn_tpu.durability import journal as jmod
from saturn_tpu.utils.metrics import read_events

#: The documented fidelity band (docs/architecture.md, round 22):
#: - per-tier solver share absolute delta <= 0.25 (tier choice is a race
#:   against real CPU time on both sides; shares, not sequences, must agree)
#: - admission verdict share absolute delta <= 0.10 (the decision logic is
#:   the identical code; only arrival interleaving differs)
#: - makespan ratio within [0.3, 3.0] (the twin quantizes work to interval
#:   boundaries; the real run pays wire + scheduling wall time)
DEFAULT_BAND = {
    "tier_share_delta": 0.25,
    "verdict_share_delta": 0.10,
    "makespan_ratio": (0.3, 3.0),
}


@dataclass(frozen=True)
class TraceJob:
    """One replayable submission from a journaled run."""

    job_id: str
    name: str
    at_s: float                      # arrival offset from the trace start
    priority: float = 0.0
    deadline_s: Optional[float] = None
    total_batches: int = 1
    spec: Optional[dict] = None
    dedup_key: Optional[str] = None


@dataclass
class TwinTrace:
    """A journal folded into twin-consumable form."""

    jobs: List[TraceJob] = field(default_factory=list)
    admission_mix: Dict[str, int] = field(default_factory=dict)
    incarnations: int = 1
    span_s: float = 0.0              # first..last submission offset

    @property
    def verdict_shares(self) -> Dict[str, float]:
        total = sum(self.admission_mix.values())
        if total == 0:
            return {}
        return {k: v / total for k, v in sorted(self.admission_mix.items())}


def load_trace(durability_dir: str) -> TwinTrace:
    """Fold a journal directory into a :class:`TwinTrace`.

    Arrival offsets come from each ``job_submitted`` record's commit
    timestamp relative to the first one — the journaled submit is fsync'd
    before the client's ACK, so it is an honest arrival-order clock.
    """
    trace = TwinTrace()
    first_ts: Optional[float] = None
    last_ts: float = 0.0
    segments_opened = 0
    for rec in jmod.replay_reconciled(durability_dir):
        kind, d = rec.get("kind"), rec.get("data", {})
        if kind == "segment_open":
            segments_opened += 1
            continue
        if kind == "recovery":
            trace.incarnations += 1
            continue
        if kind == "job_submitted":
            ts = float(rec.get("ts", 0.0))
            if first_ts is None:
                first_ts = ts
            last_ts = ts
            trace.jobs.append(TraceJob(
                job_id=d.get("job", ""),
                name=d["task"],
                at_s=ts - first_ts,
                priority=float(d.get("priority") or 0.0),
                deadline_s=d.get("deadline_s"),
                total_batches=int(d.get("total_batches") or 1),
                spec=d.get("spec"),
                dedup_key=d.get("dedup_key"),
            ))
        elif kind == "job_admission":
            dec = d.get("decision", "unknown")
            trace.admission_mix[dec] = trace.admission_mix.get(dec, 0) + 1
    if first_ts is not None:
        trace.span_s = last_ts - first_ts
    return trace


def tier_shares(metrics_path: str) -> Dict[str, float]:
    """Per-tier share of ``solver_tier`` events in a metrics file (keys are
    tier numbers as strings — JSON-stable)."""
    counts: Dict[str, int] = {}
    for e in read_events(metrics_path, kind="solver_tier"):
        t = str(e.get("tier"))
        counts[t] = counts.get(t, 0) + 1
    total = sum(counts.values())
    if total == 0:
        return {}
    return {t: n / total for t, n in sorted(counts.items())}


def _share_deltas(a: Dict[str, float], b: Dict[str, float]) -> Dict[str, float]:
    return {
        k: round(abs(a.get(k, 0.0) - b.get(k, 0.0)), 6)
        for k in sorted(set(a) | set(b))
    }


def fidelity_compare(twin: dict, real: dict,
                     band: Optional[dict] = None) -> dict:
    """Compare a twin campaign against journaled reality.

    Both sides are dicts with ``tier_shares`` (str tier -> share),
    ``verdict_shares`` (decision -> share) and ``makespan_s``. Returns the
    per-key deltas, the band they were checked against, and ``within_band``.
    Empty distributions on *both* sides compare equal (delta 0); one-sided
    emptiness shows up as the full share delta, as it should.
    """
    band = dict(DEFAULT_BAND, **(band or {}))
    tier_deltas = _share_deltas(
        twin.get("tier_shares", {}), real.get("tier_shares", {})
    )
    verdict_deltas = _share_deltas(
        twin.get("verdict_shares", {}), real.get("verdict_shares", {})
    )
    tm, rm = twin.get("makespan_s", 0.0), real.get("makespan_s", 0.0)
    ratio = (tm / rm) if rm > 0 else (1.0 if tm == 0 else float("inf"))
    lo, hi = band["makespan_ratio"]
    ok = (
        all(dv <= band["tier_share_delta"] for dv in tier_deltas.values())
        and all(dv <= band["verdict_share_delta"]
                for dv in verdict_deltas.values())
        and lo <= ratio <= hi
    )
    return {
        "tier_share_deltas": tier_deltas,
        "verdict_share_deltas": verdict_deltas,
        "makespan_ratio": round(ratio, 4),
        "band": {
            "tier_share_delta": band["tier_share_delta"],
            "verdict_share_delta": band["verdict_share_delta"],
            "makespan_ratio": list(band["makespan_ratio"]),
        },
        "within_band": ok,
    }
