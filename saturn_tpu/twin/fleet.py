"""Virtual fleets: the accelerator pool the twin schedules, minus the chips.

A :class:`VirtualFleet` is N identical slices of M virtual devices each,
arranged slice-major into the same :class:`~saturn_tpu.core.mesh.
SliceTopology` the real service binds — so block alignment, slice-crossing
(DCN) penalties and capacity arithmetic are exactly the production code
paths. Devices are inert descriptor objects (no jax, no memory_stats), so
memlens sees "capacity unknown" and the twin's own oracle
(:mod:`saturn_tpu.twin.oracle`) is the memory gate instead.

Failure processes come in two flavors, both seeded and deterministic:

- :meth:`VirtualFleet.failure_schedule` — per-slice Bernoulli preemption
  renewal processes (each live slice is reclaimed with ``p_preempt`` per
  interval and returns ``outage_intervals`` later), the spot-fleet shape.
- :meth:`VirtualFleet.storm_schedule` — the generic chaos generator
  (``resilience.faults.seeded_schedule``: block preemptions, stragglers,
  transient crashes) *sanitized* so the fleet never loses its last live
  slice — a zero-capacity mesh has no plan to verify, and real reclaim
  systems likewise never take the final slice of a reservation.

Both return plain ``FaultEvent`` lists for ``resilience.faults.
FaultInjector`` — the same injector/monitor machinery the real
orchestrator uses.
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from saturn_tpu.core.mesh import SliceTopology
from saturn_tpu.resilience.faults import FaultEvent, FaultKind, seeded_schedule


@dataclass(frozen=True)
class VirtualDevice:
    """Inert device descriptor: satisfies every ``getattr``-probing consumer
    (mesh binding, health monitor identity maps) without any runtime."""

    index: int              # global device index (slice-major)
    slice_id: int
    hbm_bytes: int
    platform: str = "twin"
    device_kind: str = "virtual-tpu"

    @property
    def process_index(self) -> int:
        return self.slice_id  # one virtual host per slice

    def __repr__(self) -> str:
        return f"VirtualDevice(d{self.index}/s{self.slice_id})"


@dataclass(frozen=True)
class SliceSpec:
    """Shape + failure parameters for one (or every) virtual slice."""

    chips: int = 8
    hbm_gib: float = 16.0
    ici_gbps: float = 1200.0      # intra-slice interconnect (descriptive)
    dcn_gbps: float = 25.0        # cross-slice fabric (descriptive)
    p_preempt: float = 0.0        # per-interval whole-slice reclaim prob.
    outage_intervals: int = 2     # intervals until a reclaimed slice returns


class VirtualFleet:
    """``n_slices`` virtual slices sharing one :class:`SliceSpec` shape.

    Slice chip counts must be uniform (that is what ``SliceTopology``'s
    explicit ``slice_size`` encodes); HBM and failure parameters may vary
    per slice via ``overrides``.
    """

    def __init__(self, n_slices: int = 4, spec: SliceSpec = SliceSpec(),
                 overrides: Optional[Dict[int, SliceSpec]] = None):
        if n_slices < 1:
            raise ValueError(f"need at least one slice, got {n_slices}")
        self.spec = spec
        self.specs: List[SliceSpec] = [
            (overrides or {}).get(s, spec) for s in range(n_slices)
        ]
        if any(sp.chips != spec.chips for sp in self.specs):
            raise ValueError(
                "slice chip counts must be uniform (SliceTopology encodes "
                "one slice_size); vary HBM/failure params instead"
            )
        self.n_slices = n_slices
        self.chips = spec.chips
        self.devices: List[VirtualDevice] = []
        for s, sp in enumerate(self.specs):
            hbm = int(sp.hbm_gib * (1 << 30))
            for c in range(sp.chips):
                self.devices.append(
                    VirtualDevice(index=s * sp.chips + c, slice_id=s,
                                  hbm_bytes=hbm)
                )

    # ------------------------------------------------------------- topology
    def topology(self) -> SliceTopology:
        return SliceTopology(list(self.devices), slice_size=self.chips)

    def slice_indices(self, slice_id: int) -> Tuple[int, ...]:
        if not 0 <= slice_id < self.n_slices:
            raise IndexError(f"no slice {slice_id} in a {self.n_slices}-slice fleet")
        base = slice_id * self.chips
        return tuple(range(base, base + self.chips))

    def describe(self) -> dict:
        return {
            "n_slices": self.n_slices,
            "chips_per_slice": self.chips,
            "n_devices": len(self.devices),
            "hbm_gib_per_chip": self.spec.hbm_gib,
            "ici_gbps": self.spec.ici_gbps,
            "dcn_gbps": self.spec.dcn_gbps,
        }

    # ------------------------------------------------------------- failures
    def failure_schedule(self, seed: int, n_intervals: int) -> List[FaultEvent]:
        """Per-slice seeded preemption renewal process.

        Each interval, every *live* slice is independently reclaimed with
        its spec's ``p_preempt``; a reclaimed slice returns whole after
        ``outage_intervals``. The last live slice is never taken (see
        module docstring). RNG draws happen in (interval, slice) order, so
        the schedule is a pure function of (seed, n_intervals, specs).
        """
        rng = random.Random(seed)
        events: List[FaultEvent] = []
        down_until = [0] * self.n_slices   # interval index the slice returns
        for i in range(n_intervals):
            for s, sp in enumerate(self.specs):
                if i < down_until[s]:
                    continue
                if sp.p_preempt <= 0.0 or rng.random() >= sp.p_preempt:
                    continue
                live_others = sum(
                    1 for o in range(self.n_slices)
                    if o != s and i >= down_until[o]
                )
                if live_others == 0:
                    continue  # never empty the fleet
                devs = self.slice_indices(s)
                events.append(FaultEvent(
                    i, FaultKind.SLICE_PREEMPTION, devices=devs,
                    after_s=0.001,  # mid-interval: running work is lost
                ))
                back = i + max(1, sp.outage_intervals)
                down_until[s] = back
                events.append(FaultEvent(
                    back, FaultKind.DEVICE_RETURN, devices=devs,
                ))
        return events

    def storm_schedule(self, seed: int, n_intervals: int, *,
                       p_preempt: float = 0.15, p_crash: float = 0.1,
                       p_straggler: float = 0.05,
                       outage_intervals: int = 2) -> List[FaultEvent]:
        """Chaos storm: ``resilience.faults.seeded_schedule`` over the whole
        fleet, sanitized for a long-running campaign.

        The raw generator emits block preemptions with no matching returns
        and no floor on surviving capacity. Here every preemption gets a
        ``DEVICE_RETURN`` ``outage_intervals`` later, and a preemption that
        would leave fewer than one full slice of live devices is dropped —
        the fleet always retains schedulable capacity.
        """
        raw = seeded_schedule(
            seed, n_intervals, len(self.devices),
            p_preempt=p_preempt, p_crash=p_crash, p_straggler=p_straggler,
        )
        events: List[FaultEvent] = []
        down: Dict[int, int] = {}   # device index -> return interval
        for ev in sorted(raw, key=lambda e: (e.at_interval, e.after_s, e.kind)):
            if ev.kind != FaultKind.SLICE_PREEMPTION:
                events.append(ev)
                continue
            i = ev.at_interval
            for d, back in list(down.items()):
                if back <= i:
                    del down[d]
            taking = [d for d in ev.devices if d not in down]
            survivors = len(self.devices) - len(down) - len(taking)
            if not taking or survivors < self.chips:
                continue  # keep at least one slice's worth of capacity
            events.append(FaultEvent(
                i, FaultKind.SLICE_PREEMPTION, devices=tuple(taking),
                after_s=ev.after_s,
            ))
            back = i + max(1, outage_intervals)
            events.append(FaultEvent(
                back, FaultKind.DEVICE_RETURN, devices=tuple(taking),
            ))
            for d in taking:
                down[d] = back
        return events
