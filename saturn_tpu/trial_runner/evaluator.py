"""Trial runner: profile every (task × sub-mesh size × technique) combination.

Reference: ``saturn/trial_runner/PerformanceEvaluator.py:21-115``. Same
semantics — fan the grid out, keep the **fastest feasible technique per
size** (``:101-115``), seed unsearched sizes with an infeasible dummy
(``:96-99``), scale per-batch time to total runtime (``:26``) — with two
TPU-native differences:

- Trials run as **threads on the host that drives the slice** instead of as
  Ray remote tasks: one Python process owns all chips, and concurrent trials
  of sub-mesh size ``g`` run on *disjoint* aligned blocks (the analog of the
  reference scheduling ``num_gpus=g`` remotes across the node,
  ``PerformanceEvaluator.py:74-84``). Timing is position-independent on the
  ICI ring — and DCN-correct for free: with power-of-two slice sizes, every
  aligned block of a given size has the same DCN-crossing status
  (``core/mesh.py``), so a profile measured on block 0 prices any block the
  solver may later pick, including the cross-slice collectives of
  larger-than-slice sizes. On the CPU test platform trials stay sequential — virtual
  devices share host cores, so concurrency would skew the measurements.
- Infeasible configs are rejected by XLA memory analysis inside each
  technique's ``search`` (see ``SPMDTechnique._fits_memory``) rather than
  try/except CUDA OOM probing.

Profiling cost is the most expensive phase of the whole pipeline (compile
dominates a trial; ~1 min upper bound each), so three layers keep the sweep
cheap (see ``docs/architecture.md`` "Profiling cost & caching"):

1. **Persistent profile cache** (``utils/profile_cache.py``): every grid
   point is looked up by content fingerprint before anything compiles and
   every trial outcome is written back, so a repeated ``search()`` over an
   unchanged task list performs zero trial executions.
2. **Cost-model pruning**: on grids of >= ``PRUNE_MIN_GRID`` sizes per
   (task, technique), only anchor sizes (min, max, one midpoint) are
   profiled; the rest are filled from an Amdahl-style fit
   ``t(g) = a + b/g`` as *interpolated* strategies (flagged on
   ``Strategy``). The solver still sees a complete per-size table, and the
   orchestrator's realized-feedback loop upgrades interpolated entries to
   measured ones as tasks actually run.
3. **Monotone infeasibility propagation**: sizes are profiled largest-first,
   and once XLA memory analysis rejects a technique at size ``g``
   (``technique.memory_monotone`` + the search report saying memory was the
   binding constraint), every smaller size — whose per-chip memory is the
   same or strictly higher — is skipped instead of compiled-to-fail.
"""

from __future__ import annotations

import logging
import os
import queue
import random
import threading
import time
import timeit
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Sequence, Tuple

from saturn_tpu import library as lib
from saturn_tpu.core.mesh import SliceTopology
from saturn_tpu.core.strategy import Strategy
from saturn_tpu.utils import metrics, trace
from saturn_tpu.utils import profile_cache as pcache

logger = logging.getLogger("saturn_tpu")

DUMMY_RUNTIME = 1e6  # reference's unsearched-size sentinel (``:99``)

#: Anchor-size pruning engages only when a (task, technique) pair has at
#: least this many valid sizes — below it the anchors ARE the whole grid.
PRUNE_MIN_GRID = 4


def search(
    tasks: Sequence,
    technique_names: Optional[List[str]] = None,
    log: bool = False,
    topology: Optional[SliceTopology] = None,
    metrics_path: Optional[str] = None,
    trace_dir: Optional[str] = None,
    parallel_trials: Optional[int] = None,
    profile_cache: Any = None,
    prune: bool = True,
    compile_cache_dir: Optional[str] = None,
    trial_retries: int = 2,
    retry_backoff_s: float = 0.05,
) -> Dict[str, int]:
    """Fill ``task.strategies`` for every task in place.

    ``technique_names=None`` uses the whole library (registering the built-in
    default library if the user registered nothing — the reference required
    explicit registration, ``WikiText103.py:53-54``). ``metrics_path``
    appends per-trial JSONL events; ``trace_dir`` wraps the sweep in a
    jax.profiler trace. ``parallel_trials`` caps how many same-size trials
    run concurrently on disjoint blocks (default: 4 on accelerators, 1 on
    the CPU test platform where concurrency would skew timings).

    ``profile_cache``: ``None`` uses the env-configured persistent cache
    (default on; ``SATURN_TPU_PROFILE_CACHE=0`` disables), ``False`` turns
    caching off for this sweep, a path string uses that directory.
    ``prune`` toggles anchor-size cost-model pruning. ``compile_cache_dir``
    additionally roots JAX's persistent compilation cache there for this
    process (same effect as ``SATURN_TPU_COMPILE_CACHE_DIR``).

    ``trial_retries``: extra attempts for a trial whose technique *raises*
    (transient fleet flake — a device hiccup mid-compile, an injected
    crash); each retry backs off ``retry_backoff_s * 2^attempt`` seconds
    plus deterministic jitter and emits a ``trial_retry`` event. A clean
    infeasible verdict (memory analysis rejection) is a *result*, not a
    flake, and is never retried — retrying it would only re-pay the
    compile; conversely, without retries a transient crash would be
    cached as permanently infeasible.

    Returns sweep stats ``{"trials_run", "cache_hits", "pruned",
    "interpolated"}`` — the online admission controller uses ``trials_run``
    to distinguish warm (zero-trial) from cold arrivals.
    """
    if log:
        logging.basicConfig(level=logging.INFO)
    if compile_cache_dir:
        pcache.maybe_enable_persistent_compile_cache(compile_cache_dir)
    cache = pcache.resolve(profile_cache)
    with metrics.scoped(metrics_path), trace.profile_trace(trace_dir):
        return _search_inner(
            tasks, technique_names, topology, parallel_trials, cache, prune,
            trial_retries=trial_retries, retry_backoff_s=retry_backoff_s,
        )


def _default_parallelism(topo: SliceTopology) -> int:
    platform = getattr(topo.devices[0], "platform", "cpu") if topo.devices else "cpu"
    return 4 if platform != "cpu" else 1


def _anchor_sizes(sizes: Sequence[int]) -> set:
    """min, max and one midpoint of the valid sizes: the three points an
    Amdahl-style fit needs, and the cheapest/most constrained ends of the
    grid (GSPMD's observation that per-size runtimes scale smoothly)."""
    ss = sorted(sizes)
    return {ss[0], ss[-1], ss[len(ss) // 2]}


def _fit_scaling_model(points: Sequence[Tuple[int, float]]):
    """Least-squares Amdahl fit ``t(g) = a + b/g`` over measured
    (size, per-batch seconds) points; degenerate fits clamp to the
    pure-serial / pure-parallel edge instead of going negative."""
    import numpy as np

    g = np.asarray([p[0] for p in points], dtype=float)
    t = np.asarray([p[1] for p in points], dtype=float)
    A = np.stack([np.ones_like(g), 1.0 / g], axis=1)
    try:
        (a, b), *_ = np.linalg.lstsq(A, t, rcond=None)
    except np.linalg.LinAlgError:
        a, b = float(t.mean()), 0.0
    if a < 0.0 or b < 0.0:
        if b < 0.0:  # "runtime grows with chips" noise -> flat (serial) model
            a, b = float(t.mean()), 0.0
        else:
            a, b = 0.0, float((t * g).mean())
    return lambda size: a + b / float(size)


class _Lane:
    """Per-(task, technique) sweep state: which sizes are resolved and how."""

    __slots__ = (
        "task", "name", "tech", "sizes", "keys", "done", "to_run", "to_fill",
        "infeasible_floor",
    )

    def __init__(self, task, name, tech, sizes):
        self.task = task
        self.name = name
        self.tech = tech
        self.sizes = sorted(sizes)
        self.keys: Dict[int, Optional[str]] = {}
        # size -> (feasible, params, per_batch_time, source)
        self.done: Dict[int, tuple] = {}
        self.to_run: List[int] = []
        self.to_fill: List[int] = []
        # Largest size rejected by XLA memory analysis (memory-monotone
        # techniques only): everything smaller needs at least as much
        # per-chip memory and is pruned without compiling.
        self.infeasible_floor: Optional[int] = None

    def pruned(self, g: int) -> bool:
        return self.infeasible_floor is not None and g < self.infeasible_floor


class _EtaTracker:
    """Running-average trial-time ETA, replacing the fixed ~1 min/trial log.

    Cache hits and pruned grid points cost ~0 and are excluded from the
    average; the ETA covers only the trials still waiting to compile."""

    def __init__(self, planned: int, hits: int, deferred: int):
        self.planned = planned
        self.hits = hits
        self.deferred = deferred
        self.completed = 0
        self.pruned = 0
        self.spent = 0.0
        self._lock = threading.Lock()

    def start_message(self) -> str:
        return (
            f"trial runner: {self.planned} trials to run "
            f"({self.hits} profile-cache hits, {self.deferred} grid points "
            f"deferred to the cost model; cold upper bound ~{self.planned:.0f} min)"
        )

    def trial_done(self, dt: float) -> str:
        with self._lock:
            self.completed += 1
            self.spent += dt
            remaining = max(self.planned - self.pruned - self.completed, 0)
            avg = self.spent / self.completed
            return (
                f"trial runner: {self.completed}/{self.planned - self.pruned} "
                f"trials done, avg {avg:.1f}s/trial, ETA {remaining * avg:.0f}s"
            )

    def trial_pruned(self) -> None:
        with self._lock:
            self.pruned += 1


def _search_inner(
    tasks, technique_names, topology, parallel_trials=None, cache=None,
    prune=True, trial_retries=2, retry_backoff_s=0.05,
) -> Dict[str, int]:
    topo = topology if topology is not None else SliceTopology()
    if technique_names is None and not lib.registered_names():
        lib.register_default_library()
    classes = lib.retrieve(technique_names)
    techniques = [(cls.name if hasattr(cls, "name") else cls.__name__, cls()) for cls in classes]
    for _, tech in techniques:
        # Candidate grids may depend on the pool shape — e.g. the pipeline
        # executor only proposes cross-slice ``stage_major`` layouts when
        # the sweep's blocks can actually outgrow a slice.
        try:
            tech.topology = topo
        except Exception:
            pass  # plugin with __slots__/frozen surface: grid stays topology-blind

    update_lock = threading.Lock()

    # One lane per (task, technique): the unit pruning and interpolation
    # reason about (reference grid build, ``:86-91``).
    lanes: List[_Lane] = []
    # NB ``is not None``: ProfileCache defines __len__, so a still-empty
    # cache is falsy — a bare truthiness test would fingerprint the first
    # run with a blank topology signature and never hit again.
    topo_sig = pcache.topology_signature(topo) if cache is not None else ""
    # Trials profile whatever dispatch mode execute() will run (fused
    # K-step windows vs per-step — ``SPMDTechnique._try_config``), so the
    # mode is part of every cache key: a per-step profile recorded before
    # fused dispatch landed (or with a different window cap) must MISS, not
    # warm-start the sweep with numbers execution won't reproduce.
    dispatch = pcache.dispatch_signature()
    for task in tasks:
        sizes = topo.valid_sizes()
        if task.chip_range is not None:
            sizes = [s for s in sizes if s in task.chip_range]
        task_sig = None
        if cache is not None:
            try:
                task_sig = pcache.task_signature(task)
            except Exception:
                logger.info("task %s not fingerprintable — caching off for it",
                            task.name, exc_info=True)
        for name, tech in techniques:
            lane = _Lane(task, name, tech, sizes)
            if task_sig is not None:
                for g in lane.sizes:
                    lane.keys[g] = pcache.fingerprint(
                        task_sig, name, g, topo_sig, dispatch
                    )
            lanes.append(lane)

    def install(
        lane: _Lane, g: int, params, per_batch: float, source: str,
        host_fraction: float = 0.0,
    ) -> None:
        """Fastest feasible technique per size wins (``:101-115``) —
        measured, cached and interpolated entries all compete.

        ``host_fraction`` feeds the solver's co-location term; interpolated
        entries pass the 0.0 default on purpose — a co-schedule decision
        needs a measured staging/compute split, not a fitted guess. The
        schedule-bubble fraction, by contrast, is analytic in the config
        (``config_bubble_fraction``), so every path — trial, cache hit,
        interpolated fill — recomputes it here identically."""
        total = per_batch * lane.task.total_batches  # reference ``:26``
        bubble = 0.0
        bf = getattr(lane.tech, "config_bubble_fraction", None)
        if callable(bf) and params:
            try:
                bubble = min(max(float(bf(params)), 0.0), 1.0)
            except Exception:
                bubble = 0.0
        with update_lock:
            cur = lane.task.strategies.get(g)
            if cur is None or not cur.feasible or total < cur.runtime:
                lane.task.strategies[g] = Strategy(
                    executor=lane.tech,
                    apportionment=g,
                    params=params,
                    runtime=total,
                    per_batch_time=per_batch,
                    interpolated=(source == "interpolated"),
                    cache_key=lane.keys.get(g),
                    host_fraction=float(host_fraction or 0.0),
                    bubble_fraction=bubble,
                )

    def note_memory_floor(lane: _Lane, g: int) -> None:
        if getattr(lane.tech, "memory_monotone", False):
            with update_lock:
                if lane.infeasible_floor is None or g > lane.infeasible_floor:
                    lane.infeasible_floor = g

    # ------------------------------------------------------------ cache pass
    # Consult the persistent profile cache for EVERY grid point before any
    # trial runs: hits — feasible or infeasible — cost a file read.
    n_hits = 0
    for lane in lanes:
        for g in lane.sizes:
            entry = cache.get(lane.keys.get(g)) if cache is not None else None
            if entry is None:
                continue
            n_hits += 1
            feasible = entry["feasible"]
            metrics.event(
                "profile_cache", hit=True, task=lane.task.name, size=g,
                technique=lane.name, feasible=feasible,
                source=entry.get("source", "trial"),
            )
            if feasible:
                hf = entry.get("host_fraction", 0.0)
                hf = float(hf) if isinstance(hf, (int, float)) else 0.0
                lane.done[g] = (True, entry["params"], entry["per_batch_time"],
                                entry.get("source", "trial"))
                install(lane, g, entry["params"], entry["per_batch_time"],
                        "cache", host_fraction=hf)
            else:
                lane.done[g] = (False, None, None, entry.get("source", "trial"))
                if entry.get("memory_infeasible"):
                    note_memory_floor(lane, g)

    # -------------------------------------------------------- pruning split
    # Uncached grid points either run for real (anchors, or everything when
    # pruning is off / the grid is small) or wait for the cost-model fill.
    for lane in lanes:
        missing = [g for g in lane.sizes if g not in lane.done]
        if prune and len(lane.sizes) >= PRUNE_MIN_GRID:
            anchors = _anchor_sizes(lane.sizes)
            lane.to_run = [g for g in missing if g in anchors]
            lane.to_fill = [g for g in missing if g not in anchors]
        else:
            lane.to_run = missing

    eta = _EtaTracker(
        planned=sum(len(l.to_run) for l in lanes),
        hits=n_hits,
        deferred=sum(len(l.to_fill) for l in lanes),
    )
    logger.info("%s", eta.start_message())

    workers = parallel_trials if parallel_trials is not None else _default_parallelism(topo)

    def run_trial(tid, lane: _Lane, g: int, block):
        devices = block.devices_of(topo.devices)
        task, name, tech = lane.task, lane.name, lane.tech
        if cache is not None and lane.keys.get(g):
            metrics.event("profile_cache", hit=False, task=task.name, size=g,
                          technique=name)
        t0 = timeit.default_timer()
        params = per_batch_time = None
        attempt = 0
        while True:
            try:
                params, per_batch_time = tech.search(task, devices, tid)
                break
            except Exception as e:  # a broken trial must not kill the sweep (``:27-28``)
                from saturn_tpu.analysis.jax_lint import ShardingLintError

                if isinstance(e, ShardingLintError):
                    # Static sharding-lint refusal is deterministic — the
                    # rule emits the same illegal spec on every retry, so
                    # burning the backoff budget buys nothing. Record the
                    # file:line diagnostics and mark the size infeasible.
                    logger.info(
                        "trial (%s, g=%d, %s): sharding lint refused: %s",
                        task.name, g, name, e,
                    )
                    metrics.event(
                        "sharding_lint", task=task.name, size=g,
                        technique=name,
                        codes=[d.code for d in e.diagnostics],
                    )
                    params, per_batch_time = None, None
                    break
                if attempt >= max(0, trial_retries):
                    logger.info(
                        "trial (%s, g=%d, %s) raised on attempt %d "
                        "(budget exhausted): %r",
                        task.name, g, name, attempt + 1, e,
                    )
                    params, per_batch_time = None, None
                    break
                # Exponential backoff with deterministic jitter — seeded per
                # (trial, attempt) so concurrent lanes desynchronize but runs
                # stay reproducible.
                delay = retry_backoff_s * (2 ** attempt)
                jitter = random.Random(
                    f"{task.name}:{g}:{name}:{attempt}"
                ).random()
                delay *= 1.0 + jitter
                metrics.event(
                    "trial_retry", task=task.name, size=g, technique=name,
                    attempt=attempt + 1, backoff_s=round(delay, 6),
                    error=repr(e),
                )
                logger.info(
                    "trial (%s, g=%d, %s) raised (attempt %d/%d), retrying "
                    "in %.3fs: %r",
                    task.name, g, name, attempt + 1, trial_retries + 1,
                    delay, e,
                )
                time.sleep(delay)
                attempt += 1
        dt = timeit.default_timer() - t0
        if params is None or per_batch_time is None:
            report = None
            reporter = getattr(tech, "search_report", None)
            if callable(reporter):
                report = reporter(task.name, g)
            memory_bound = bool(report and report.get("memory_infeasible"))
            logger.info("trial (%s, g=%d, %s): infeasible%s", task.name, g, name,
                        " (memory)" if memory_bound else "")
            metrics.event("trial", task=task.name, size=g, technique=name,
                          feasible=False, memory_infeasible=memory_bound)
            with update_lock:
                lane.done[g] = (False, None, None, "trial")
            if memory_bound:
                note_memory_floor(lane, g)
            if cache is not None:
                cache.put(lane.keys.get(g), technique=name, size=g, feasible=False,
                          memory_infeasible=memory_bound)
            logger.info("%s", eta.trial_done(dt))
            return
        total = per_batch_time * task.total_batches  # reference ``:26``
        # The staging-vs-compute split the technique measured alongside the
        # per-batch time (``SPMDTechnique.host_fraction_report``, pop-once);
        # plain BaseTechnique plugins report nothing -> 0.0 -> never
        # co-scheduled.
        hf = 0.0
        hf_reporter = getattr(tech, "host_fraction_report", None)
        if callable(hf_reporter):
            hf = hf_reporter(task.name, g) or 0.0
        metrics.event("trial", task=task.name, size=g, technique=name,
                      feasible=True, per_batch_s=per_batch_time,
                      est_total_s=total, params=params,
                      host_fraction=round(float(hf), 4))
        logger.info(
            "trial (%s, g=%d, %s): %.4fs/batch, est total %.1fs (trial took %.1fs)",
            task.name, g, name, per_batch_time, total, dt,
        )
        with update_lock:
            lane.done[g] = (True, params, per_batch_time, "trial")
        install(lane, g, params, per_batch_time, "trial", host_fraction=hf)
        if cache is not None:
            cache.put(lane.keys.get(g), technique=name, size=g, feasible=True,
                      params=params, per_batch_time=per_batch_time,
                      host_fraction=float(hf))
        logger.info("%s", eta.trial_done(dt))

    def prune_point(lane: _Lane, g: int, reason: str, planned: bool) -> None:
        if planned:  # only planned trials count against the ETA denominator
            eta.trial_pruned()
        with update_lock:
            lane.done[g] = (False, None, None, "pruned")
        metrics.event("trial_pruned", task=lane.task.name, size=g,
                      technique=lane.name, reason=reason)
        logger.info("trial (%s, g=%d, %s): pruned (%s)",
                    lane.task.name, g, lane.name, reason)

    # ------------------------------------------------------------ trial pass
    # Size classes run LARGEST-FIRST with a barrier between classes, so a
    # memory rejection at size g prunes every smaller (>= per-chip memory)
    # size before it compiles. Within a class the existing disjoint-block
    # fan-out applies unchanged.
    tid_counter = [0]

    def next_tid() -> int:
        with update_lock:
            tid_counter[0] += 1
            return tid_counter[0]

    # memlens static pre-lowering prune: with a known per-device HBM
    # capacity, a grid point whose statically predicted peak clears the
    # OOM margin for EVERY candidate config never lowers at all. The
    # compile-time _fits_memory check stays the authoritative backstop
    # for everything that does run (and feeds SAT-M005 calibration).
    memlens_cap = 0
    ml_passes = None
    if prune and os.environ.get("SATURN_TPU_MEMLENS_PRUNE", "1") != "0":
        try:
            from saturn_tpu.analysis.memlens import passes as ml_passes
            memlens_cap = ml_passes.hbm_capacity_bytes(topo.devices)
        except Exception:
            memlens_cap = 0

    def memlens_infeasible(lane: _Lane, g: int) -> bool:
        if memlens_cap <= 0:
            return False
        try:
            devices = topo.blocks(g)[0].devices_of(topo.devices)
            return ml_passes.grid_point_infeasible(
                lane.tech, lane.task, devices, memlens_cap)
        except Exception:
            return False

    run_sizes = sorted({g for lane in lanes for g in lane.to_run}, reverse=True)
    for g in run_sizes:
        items: List[_Lane] = []
        for lane in lanes:
            if g not in lane.to_run:
                continue
            if lane.pruned(g):
                prune_point(lane, g, "memory_monotone", planned=True)
            elif memlens_infeasible(lane, g):
                prune_point(lane, g, "memlens_static", planned=True)
                note_memory_floor(lane, g)
            else:
                items.append(lane)
        if not items:
            continue
        blocks = topo.blocks(g)
        n_workers = min(workers, len(blocks), len(items))
        if n_workers <= 1:
            for lane in items:
                run_trial(next_tid(), lane, g, blocks[0])
            continue
        # Concurrent same-size trials on DISJOINT blocks (the reference's
        # Ray fan-out, ``:74-84``, without Ray): a bounded pool per size
        # class, each in-flight trial holding its own block from a free list.
        free: queue.Queue = queue.Queue()
        for b in blocks[:n_workers]:
            free.put(b)

        def with_block(lane):
            block = free.get()
            try:
                run_trial(next_tid(), lane, g, block)
            finally:
                free.put(block)

        with ThreadPoolExecutor(
            max_workers=n_workers, thread_name_prefix=f"trial-g{g}"
        ) as pool:
            futures = [pool.submit(with_block, lane) for lane in items]
            for f in futures:
                f.result()

    # ------------------------------------------------------- cost-model fill
    # Remaining grid points get interpolated strategies from the Amdahl fit
    # over this lane's measured feasible points — flagged so the realized
    # feedback loop knows to upgrade them. Points below a memory floor stay
    # infeasible (their per-chip memory is >= an XLA-rejected size's); lanes
    # with fewer than two measured points have no scaling signal and leave
    # the dummy seeding below to mark the gap.
    for lane in lanes:
        if not lane.to_fill:
            continue
        pts = [
            (g, pbt)
            for g, (feasible, _params, pbt, source) in lane.done.items()
            if feasible and source != "interpolated"
        ]
        model = _fit_scaling_model(pts) if len(pts) >= 2 else None
        for g in lane.to_fill:
            if lane.pruned(g):
                prune_point(lane, g, "memory_monotone", planned=False)
                continue
            if model is None:
                continue
            # Feasibility is only trusted between measured feasible sizes:
            # extrapolating below the smallest one would claim memory room
            # no trial ever checked.
            lo = min(p[0] for p in pts)
            if g < lo:
                continue
            per_batch = max(float(model(g)), 1e-9)
            nearest = min(pts, key=lambda p: abs(p[0] - g))[0]
            params = dict(lane.done[nearest][1] or {})
            with update_lock:
                lane.done[g] = (True, params, per_batch, "interpolated")
            install(lane, g, params, per_batch, "interpolated")
            metrics.event(
                "trial_interpolated", task=lane.task.name, size=g,
                technique=lane.name, per_batch_s=per_batch,
                anchor_size=nearest,
            )

    n_interp = sum(
        1 for l in lanes for d in l.done.values() if d[3] == "interpolated"
    )
    if eta.planned or n_hits:
        logger.info(
            "trial runner: sweep complete — %d trials run, %d cache hits, "
            "%d pruned, %d interpolated",
            eta.completed, n_hits, eta.pruned, n_interp,
        )

    # Seed unsearched sizes with an infeasible dummy (``:96-99``) so the
    # solver's bookkeeping sees a complete table.
    for task in tasks:
        for g in topo.valid_sizes():
            if g not in task.strategies:
                task.strategies[g] = Strategy(None, g, None, DUMMY_RUNTIME)

    # Fused-stacking trials: propose same-fingerprint groups and measure
    # the stacked per-step cost so the solver can price fusion against the
    # solo/co-scheduled grid (``milp.fusion_priced_groups`` refuses groups
    # without a measured ``fused_per_batch_time``). Fail open per group — a
    # group that cannot build or trace keeps ``fused_per_batch_time=None``
    # and is simply never fused.
    fused_groups = 0
    try:
        from saturn_tpu.parallel import fused as _fused

        fusion_names = _fused.fusion_candidates(list(tasks))
    except Exception:
        fusion_names = []
    if fusion_names:
        by_name = {t.name: t for t in tasks}
        for group_names in fusion_names:
            group = [by_name[n] for n in group_names if n in by_name]
            if len(group) < 2:
                continue
            try:
                # metrics_path=None: the caller (``search``) already scoped
                # the ambient writer, so trial_fused events land there.
                measured = profile_fused_group(group, topology=topo)
            except Exception:
                logger.exception(
                    "fused trial for group %s failed (fail-open)",
                    group_names,
                )
                continue
            if any(v > 0 for v in measured.values()):
                fused_groups += 1
        if fused_groups:
            logger.info(
                "trial runner: %d fused group(s) measured", fused_groups
            )

    return {
        "trials_run": eta.completed,
        "cache_hits": n_hits,
        "pruned": eta.pruned,
        "interpolated": n_interp,
        "dispatch": dispatch,
        "fused_groups": fused_groups,
    }


def profile_fused_group(
    tasks: Sequence,
    sizes: Optional[Sequence[int]] = None,
    topology: Optional[SliceTopology] = None,
    steps: int = 3,
    warmup: int = 1,
    metrics_path: Optional[str] = None,
) -> Dict[int, float]:
    """Profile the FUSED stack of ``tasks`` and price its lockstep step.

    The fused-stacking analog of the per-job grid sweep: builds the stacked
    program for the group at each candidate sub-mesh size, times a few
    lockstep steps on freshly-initialized member states, and writes the
    measured seconds-per-lockstep-step into every member's
    ``Strategy.fused_per_batch_time`` at that size. The solver fuses strictly
    on these measurements (``solver/milp.fusion_priced_groups``) — a size
    this function never priced keeps ``fused_per_batch_time=None`` and is
    never fused on guesswork.

    Pure measurement: unlike ``parallel.fused.run_fused_interval`` this
    neither checkpoints nor advances any task's cursor — member states are
    init-from-scratch throwaways and batches are read (not consumed) via
    ``batch_at(0)``.

    ``sizes=None`` profiles every size at which ALL members already hold a
    feasible (searched) strategy — run :func:`search` first. Returns
    ``{size: measured_per_lockstep_step_seconds}``.
    """
    import jax
    import numpy as np

    from saturn_tpu.core import distributed as _dist
    from saturn_tpu.ops import stacking
    from saturn_tpu.parallel import fused as _fused

    members = list(tasks)
    if len(members) < 2:
        raise ValueError("a fused group needs at least 2 members")
    fps = {_fused.fusion_fingerprint(t) for t in members}
    if len(fps) != 1 or None in fps:
        raise ValueError(
            "tasks are not fusable: fusion fingerprints differ or are None "
            f"({[t.name for t in members]})"
        )

    topo = topology or SliceTopology()
    if sizes is None:
        candidates = [
            g for g in topo.valid_sizes()
            if all(
                g in t.strategies and t.strategies[g].feasible
                for t in members
            )
        ]
    else:
        valid = set(topo.valid_sizes())
        candidates = [int(g) for g in sizes if int(g) in valid]

    measured: Dict[int, float] = {}
    with metrics.scoped(metrics_path):
        for g in candidates:
            block = topo.blocks(g)[0]
            devs = _fused.usable_devices(
                block.devices_of(topo.devices), len(members)
            )
            try:
                prog = _fused.build_fused_program(members, devs)
                state = _dist.put_tree_global(
                    stacking.stack_trees(
                        [prog.init_member_host(m.hparams.lr) for m in members]
                    ),
                    prog.state_shardings,
                )
                lrs_dev = _dist.put_global(
                    np.asarray(
                        [m.hparams.lr for m in members], dtype=np.float32
                    ),
                    prog.lr_sharding,
                )
                batch_dev = _dist.put_global(
                    stacking.stack_member_batches(
                        [m.batch_at(0) for m in members],
                        member_names=[m.name for m in members],
                    ),
                    prog.batch_sharding,
                )
                fn = prog.single_compiled()
                for _ in range(max(int(warmup), 0)):
                    state, loss = fn(state, batch_dev, lrs_dev)
                jax.block_until_ready(state)
                n = max(int(steps), 1)
                t0 = timeit.default_timer()
                for _ in range(n):
                    state, loss = fn(state, batch_dev, lrs_dev)
                jax.block_until_ready((state, loss))
                per_step = (timeit.default_timer() - t0) / n
            except Exception as e:
                # A size the stacked program cannot run (e.g. XLA memory
                # rejection of the N-way stack) is a result, not a flake:
                # fused_per_batch_time stays None and the solver never
                # fuses at this size.
                logger.info(
                    "fused trial (%s, g=%d): infeasible (%r)",
                    "+".join(t.name for t in members), g, e,
                )
                metrics.event(
                    "trial_fused", tasks=[t.name for t in members], size=g,
                    n_members=len(members), feasible=False, error=repr(e),
                )
                continue
            for m in members:
                strat = m.strategies.get(g)
                if strat is not None and strat.feasible:
                    strat.fused_per_batch_time = per_step
            measured[g] = per_step
            logger.info(
                "fused trial (%s, g=%d, N=%d): %.4fs/lockstep step",
                "+".join(t.name for t in members), g, len(members), per_step,
            )
            metrics.event(
                "trial_fused", tasks=[t.name for t in members], size=g,
                n_members=len(members), feasible=True, per_step_s=per_step,
            )
    return measured
