"""Trial runner: profile every (task × sub-mesh size × technique) combination.

Reference: ``saturn/trial_runner/PerformanceEvaluator.py:21-115``. Same
semantics — fan the grid out, keep the **fastest feasible technique per
size** (``:101-115``), seed unsearched sizes with an infeasible dummy
(``:96-99``), scale per-batch time to total runtime (``:26``) — with two
TPU-native differences:

- Trials run as **threads on the host that drives the slice** instead of as
  Ray remote tasks: one Python process owns all chips, and concurrent trials
  of sub-mesh size ``g`` run on *disjoint* aligned blocks (the analog of the
  reference scheduling ``num_gpus=g`` remotes across the node,
  ``PerformanceEvaluator.py:74-84``). Timing is position-independent on the
  ICI ring — and DCN-correct for free: with power-of-two slice sizes, every
  aligned block of a given size has the same DCN-crossing status
  (``core/mesh.py``), so a profile measured on block 0 prices any block the
  solver may later pick, including the cross-slice collectives of
  larger-than-slice sizes. On the CPU test platform trials stay sequential — virtual
  devices share host cores, so concurrency would skew the measurements.
- Infeasible configs are rejected by XLA memory analysis inside each
  technique's ``search`` (see ``SPMDTechnique._fits_memory``) rather than
  try/except CUDA OOM probing.
"""

from __future__ import annotations

import logging
import queue
import threading
import timeit
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional, Sequence

from saturn_tpu import library as lib
from saturn_tpu.core.mesh import SliceTopology
from saturn_tpu.core.strategy import Strategy
from saturn_tpu.utils import metrics, trace

logger = logging.getLogger("saturn_tpu")

DUMMY_RUNTIME = 1e6  # reference's unsearched-size sentinel (``:99``)


def search(
    tasks: Sequence,
    technique_names: Optional[List[str]] = None,
    log: bool = False,
    topology: Optional[SliceTopology] = None,
    metrics_path: Optional[str] = None,
    trace_dir: Optional[str] = None,
    parallel_trials: Optional[int] = None,
) -> None:
    """Fill ``task.strategies`` for every task in place.

    ``technique_names=None`` uses the whole library (registering the built-in
    default library if the user registered nothing — the reference required
    explicit registration, ``WikiText103.py:53-54``). ``metrics_path``
    appends per-trial JSONL events; ``trace_dir`` wraps the sweep in a
    jax.profiler trace. ``parallel_trials`` caps how many same-size trials
    run concurrently on disjoint blocks (default: 4 on accelerators, 1 on
    the CPU test platform where concurrency would skew timings).
    """
    if log:
        logging.basicConfig(level=logging.INFO)
    with metrics.scoped(metrics_path), trace.profile_trace(trace_dir):
        _search_inner(tasks, technique_names, topology, parallel_trials)


def _default_parallelism(topo: SliceTopology) -> int:
    platform = getattr(topo.devices[0], "platform", "cpu") if topo.devices else "cpu"
    return 4 if platform != "cpu" else 1


def _search_inner(tasks, technique_names, topology, parallel_trials=None) -> None:
    topo = topology if topology is not None else SliceTopology()
    if technique_names is None and not lib.registered_names():
        lib.register_default_library()
    classes = lib.retrieve(technique_names)
    techniques = [(cls.name if hasattr(cls, "name") else cls.__name__, cls()) for cls in classes]

    # Trial grid + ETA estimate (reference ``:86-91``).
    grid = []
    for task in tasks:
        sizes = topo.valid_sizes()
        if task.chip_range is not None:
            sizes = [s for s in sizes if s in task.chip_range]
        for g in sizes:
            for name, tech in techniques:
                grid.append((task, g, name, tech))
    # ETA estimate: compile dominates a trial; ~1 min upper bound per trial
    # matches the reference's ~1.2 min rule of thumb (``:86-91``).
    logger.info(
        "trial runner: %d trials queued (≤ ~%.0f min)", len(grid), len(grid) * 1.0
    )

    workers = parallel_trials if parallel_trials is not None else _default_parallelism(topo)
    update_lock = threading.Lock()

    def run_trial(tid, task, g, name, tech, block):
        devices = block.devices_of(topo.devices)
        t0 = timeit.default_timer()
        try:
            params, per_batch_time = tech.search(task, devices, tid)
        except Exception as e:  # a broken trial must not kill the sweep (``:27-28``)
            logger.info("trial (%s, g=%d, %s) raised: %r", task.name, g, name, e)
            params, per_batch_time = None, None
        if params is None or per_batch_time is None:
            logger.info("trial (%s, g=%d, %s): infeasible", task.name, g, name)
            metrics.event("trial", task=task.name, size=g, technique=name,
                          feasible=False)
            return
        total = per_batch_time * task.total_batches  # reference ``:26``
        metrics.event("trial", task=task.name, size=g, technique=name,
                      feasible=True, per_batch_s=per_batch_time,
                      est_total_s=total, params=params)
        logger.info(
            "trial (%s, g=%d, %s): %.4fs/batch, est total %.1fs (trial took %.1fs)",
            task.name, g, name, per_batch_time, total, timeit.default_timer() - t0,
        )
        with update_lock:
            cur = task.strategies.get(g)
            # fastest feasible technique per size wins (``:101-115``)
            if cur is None or not cur.feasible or total < cur.runtime:
                task.strategies[g] = Strategy(
                    executor=tech,
                    apportionment=g,
                    params=params,
                    runtime=total,
                    per_batch_time=per_batch_time,
                )

    if workers <= 1:
        for tid, (task, g, name, tech) in enumerate(grid):
            run_trial(tid, task, g, name, tech, topo.blocks(g)[0])
    else:
        # Concurrent same-size trials on DISJOINT blocks (the reference's
        # Ray fan-out, ``:74-84``, without Ray): a bounded pool per size
        # class, each in-flight trial holding its own block from a free list.
        by_size: dict = {}
        for tid, item in enumerate(grid):
            by_size.setdefault(item[1], []).append((tid, item))
        for g, items in by_size.items():
            blocks = topo.blocks(g)
            n_workers = min(workers, len(blocks), len(items))
            if n_workers <= 1:
                for tid, (task, g_, name, tech) in items:
                    run_trial(tid, task, g_, name, tech, blocks[0])
                continue
            free: queue.Queue = queue.Queue()
            for b in blocks[:n_workers]:
                free.put(b)

            def with_block(tid, task, g_, name, tech):
                block = free.get()
                try:
                    run_trial(tid, task, g_, name, tech, block)
                finally:
                    free.put(block)

            with ThreadPoolExecutor(
                max_workers=n_workers, thread_name_prefix=f"trial-g{g}"
            ) as pool:
                futures = [
                    pool.submit(with_block, tid, task, g_, name, tech)
                    for tid, (task, g_, name, tech) in items
                ]
                for f in futures:
                    f.result()

    # Seed unsearched sizes with an infeasible dummy (``:96-99``) so the
    # solver's bookkeeping sees a complete table.
    for task in tasks:
        for g in topo.valid_sizes():
            if g not in task.strategies:
                task.strategies[g] = Strategy(None, g, None, DUMMY_RUNTIME)
