"""Profiling layer: the trial sweep that populates task strategies.

Public entry point: :func:`search` — profile every (task, technique, size)
cell and attach the resulting strategies to each task.
"""

from saturn_tpu.trial_runner.evaluator import search

__all__ = ["search"]
