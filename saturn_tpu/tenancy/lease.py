"""Epoch-fenced leader lease for gateway replicas sharing one journal.

N gateway replicas front the same :class:`SaturnService` (one queue,
one durability journal, one dedup table). Exactly-once admission across
*replica failover* needs one more invariant than the journaled dedup
table gives us: a replica that was deposed mid-request must not record
a dedup entry or ACK a submission *after* its successor has taken over
— otherwise a client that already retried against the new leader could
see two job ids for one logical submit.

The lease provides that fence:

- One replica holds the lease at a time; holding it is what authorizes
  recording new admissions. ``ensure(owner)`` acquires (bumping the
  **epoch**) when the lease is free, expired past ``ttl_s``, or the
  holder was marked dead; it renews when ``owner`` already holds it;
  otherwise it raises :class:`LeaseHeld` (the gateway maps this to a
  retriable ``GW_RETRY_AFTER``).
- ``check(owner, epoch)`` is the fence, evaluated at the admission
  commit point (under the dedup lock, immediately before the dedup
  record is written): a deposed replica — one whose epoch is no longer
  current — gets ``False`` and must refuse with ``GW_STALE_EPOCH``
  instead of admitting.
- Every acquisition appends a durable ``gateway_lease`` record
  ``{epoch, owner, prev_owner}``; recovery folds the max epoch so a
  restarted control plane continues the epoch sequence instead of
  reusing fenced epochs.

The journal write happens *outside* the lease lock (the record is
decided under the lock, written after release) — fsync under a lock is
exactly the SAT-C003 stall saturn-tsan exists to catch. Two concurrent
acquisitions may therefore journal out of order; recovery takes the max
epoch, so ordering of the durable records is immaterial.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

from saturn_tpu.analysis import concurrency as tsan

__all__ = ["LeaseHeld", "ReplicaLease"]


class LeaseHeld(RuntimeError):
    """The lease is held by a live peer; retry after ``retry_after_s``."""

    def __init__(self, holder: str, retry_after_s: float) -> None:
        super().__init__(f"lease held by {holder!r}")
        self.holder = holder
        self.retry_after_s = retry_after_s


class ReplicaLease:
    """In-process lease shared by the gateway replicas of one service.

    Replicas here are threads (accept loops) over one journal, so the
    lease itself is a lock-guarded object; the *durable* part — the
    epoch sequence — is journaled, which is what makes fencing survive
    a control-plane restart.
    """

    def __init__(self, journal: Any = None, *, ttl_s: float = 2.0,
                 epoch: int = 0, owner: Optional[str] = None) -> None:
        self._lock = tsan.rlock("gateway.lease")
        #: Durable journal for gateway_lease records (wired by the service;
        #: replays seed ``epoch`` so fenced epochs are never reused).
        self.journal = journal
        self.ttl_s = float(ttl_s)
        self._epoch = int(epoch)
        self._owner = owner
        self._renewed_at: Optional[float] = None
        self._dead: set = set()
        #: In-process acquisition history [(epoch, owner, prev_owner)].
        self.history: List[Tuple[int, str, Optional[str]]] = []

    # -- introspection --------------------------------------------------

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    @property
    def owner(self) -> Optional[str]:
        with self._lock:
            return self._owner

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "epoch": self._epoch,
                "owner": self._owner,
                "ttl_s": self.ttl_s,
                "dead": sorted(self._dead),
                "acquisitions": len(self.history),
            }

    # -- the protocol ---------------------------------------------------

    def ensure(self, owner: str) -> int:
        """Hold (or take) the lease for ``owner``; returns the epoch.

        The returned epoch is what the caller must later present to
        :meth:`check` at its commit point — holding a *stale* epoch is
        how a deposed replica discovers it was fenced.
        """
        now = time.monotonic()
        record = None
        with self._lock:
            if self._owner == owner:
                self._renewed_at = now
                epoch = self._epoch
            else:
                holder = self._owner
                expired = (
                    self._renewed_at is None
                    or now - self._renewed_at >= self.ttl_s
                )
                if holder is not None and holder not in self._dead \
                        and not expired:
                    remaining = self.ttl_s - (now - (self._renewed_at or now))
                    raise LeaseHeld(holder, max(0.01, remaining))
                self._epoch += 1
                self._owner = owner
                self._renewed_at = now
                self._dead.discard(owner)
                epoch = self._epoch
                record = (epoch, owner, holder)
                self.history.append(record)
        if record is not None and self.journal is not None:
            self.journal.log("gateway_lease", epoch=record[0],
                            owner=record[1], prev_owner=record[2])
        return epoch

    def check(self, owner: str, epoch: int) -> bool:
        """The fence: is ``owner``'s ``epoch`` still the current lease?"""
        with self._lock:
            return self._owner == owner and self._epoch == int(epoch)

    def mark_dead(self, owner: str) -> None:
        """Declare ``owner`` gone (clean shutdown, or a failure detector's
        verdict) so a peer can take over without waiting out the ttl. The
        epoch does NOT advance here — only the successor's acquisition
        bumps it, which is what fences the dead replica's stragglers."""
        with self._lock:
            self._dead.add(owner)

    def release(self, owner: str) -> None:
        """Drop the lease iff ``owner`` holds it (clean drain handoff)."""
        with self._lock:
            if self._owner == owner:
                self._owner = None
                self._renewed_at = None
