"""Tenant identity, quotas, and the weighted fair-share ledger.

A *tenant* is the billing/fairness principal a submission runs under.
Every ``JobRequest`` carries an optional ``tenant`` id (``None`` folds
to :data:`DEFAULT_TENANT`), and one :class:`TenantLedger` — shared by
the admission controller, every gateway replica, and the pressure
shedder — answers three questions about it:

- **quota**: is this tenant allowed another live job / another inflight
  submission, and does it still have chip-seconds budget?
- **fair share**: given who is live right now, is this tenant over its
  weighted slice of the fleet, and how should its next job's solver
  weight be scaled?
- **ledger**: what has it admitted, shed, and burned so far?

Chip-second charges are journaled as ``tenant_charge`` records so the
budget survives crash-replay exactly-once (recovery folds the records
and :meth:`TenantLedger.restore` re-seats the counters). Everything
else is derivable: live counts come from the queue, admit/shed tallies
from ``job_admission`` / ``gateway_shed`` records.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Iterable, Mapping, Optional

from saturn_tpu.analysis import concurrency as tsan

__all__ = [
    "DEFAULT_TENANT",
    "TenantQuota",
    "TenantLedger",
]

#: The tenant a tag-less submission is accounted under. Single-tenant
#: deployments never name a tenant and behave exactly as before.
DEFAULT_TENANT = "default"

#: Fair-share weight multipliers are clamped to this band so a wildly
#: over/under-share tenant cannot zero out (or dominate) the solver's
#: priority/deadline weighting — fairness nudges, deadlines still rule.
_FAIR_SHARE_CLAMP = (0.25, 4.0)


@dataclass(frozen=True)
class TenantQuota:
    """Per-tenant limits; ``None`` means unlimited (the default quota).

    ``max_live_jobs`` caps jobs in non-terminal states (admission DEFERs
    past it); ``chip_seconds`` is a cumulative burn budget (admission
    REJECTs once exhausted); ``max_inflight`` is the gateway-side
    submission window (sheds with ``GW_TENANT_OVER_QUOTA``);
    ``weight`` is the fair-share weight (2.0 = entitled to twice the
    slice of a weight-1.0 tenant); ``retry_after_s`` rides tenant sheds
    so a bursty client backs off on its own schedule.
    """

    max_live_jobs: Optional[int] = None
    chip_seconds: Optional[float] = None
    max_inflight: Optional[int] = None
    weight: float = 1.0
    retry_after_s: Optional[float] = None


class TenantLedger:
    """Quota book + fair-share arithmetic for every known tenant.

    Thread-safe: the gateway replicas' accept loops, the service loop's
    admission pass, and recovery all touch it. Lock order: the ledger
    lock (``tenancy.quota``) may be held while appending to the journal
    (``tenancy.quota`` -> ``journal.lock`` mirrors the existing
    ``queue.lock`` -> ``journal.lock`` edge); nothing acquires the
    ledger lock while holding a gateway or queue lock's *inner* locks.
    """

    def __init__(
        self,
        quotas: Optional[Mapping[str, TenantQuota]] = None,
        *,
        default: Optional[TenantQuota] = None,
    ) -> None:
        self._lock = tsan.rlock("tenancy.quota")
        self._quotas: Dict[str, TenantQuota] = dict(quotas or {})
        self._default = default if default is not None else TenantQuota()
        self._charged: Dict[str, float] = {}   # tenant -> chip-seconds burned
        self._admitted: Dict[str, int] = {}    # tenant -> jobs ADMITted
        self._shed: Dict[str, int] = {}        # tenant -> gateway sheds
        #: Durable journal for tenant_charge records (wired by the service).
        self.journal = None

    # -- quota lookup ---------------------------------------------------

    @staticmethod
    def resolve(tenant: Optional[str]) -> str:
        """Fold a missing tenant tag to the accounting default."""
        return tenant if tenant else DEFAULT_TENANT

    def quota(self, tenant: Optional[str]) -> TenantQuota:
        with self._lock:
            return self._quotas.get(self.resolve(tenant), self._default)

    def set_quota(self, tenant: str, quota: TenantQuota) -> None:
        with self._lock:
            self._quotas[self.resolve(tenant)] = quota

    def tenants(self) -> list:
        """Every tenant with a quota or any ledger activity, sorted."""
        with self._lock:
            names = set(self._quotas)
            names.update(self._charged)
            names.update(self._admitted)
            names.update(self._shed)
            return sorted(names)

    # -- the ledger -----------------------------------------------------

    def charge(self, tenant: Optional[str], chip_s: float,
               *, job: Optional[str] = None) -> float:
        """Burn ``chip_s`` chip-seconds against ``tenant``'s budget.

        Returns the tenant's cumulative burn. Journals a durable
        ``tenant_charge`` record (buffered append — the caller's next
        group commit makes it durable, same contract as task_progress).
        """
        t = self.resolve(tenant)
        with self._lock:
            total = self._charged.get(t, 0.0) + float(chip_s)
            self._charged[t] = total
            jnl = self.journal
            if jnl is not None:
                jnl.append("tenant_charge", tenant=t,
                           chip_s=round(float(chip_s), 6), job=job)
        return total

    def charged(self, tenant: Optional[str]) -> float:
        with self._lock:
            return self._charged.get(self.resolve(tenant), 0.0)

    def budget_exhausted(self, tenant: Optional[str]) -> bool:
        t = self.resolve(tenant)
        with self._lock:
            q = self._quotas.get(t, self._default)
            if q.chip_seconds is None:
                return False
            return self._charged.get(t, 0.0) >= q.chip_seconds

    def note_admit(self, tenant: Optional[str]) -> None:
        t = self.resolve(tenant)
        with self._lock:
            self._admitted[t] = self._admitted.get(t, 0) + 1

    def note_shed(self, tenant: Optional[str]) -> None:
        t = self.resolve(tenant)
        with self._lock:
            self._shed[t] = self._shed.get(t, 0) + 1

    # -- fair share -----------------------------------------------------

    def fair_target(self, tenant: Optional[str],
                    live_by_tenant: Mapping[str, int]) -> float:
        """``tenant``'s weighted share of the currently-live job count.

        Weights are taken over the tenants that are live right now plus
        the queried tenant (an idle tenant's entitlement is computed as
        if it joined): target_t = total_live * w_t / sum(w_active).
        """
        t = self.resolve(tenant)
        with self._lock:
            active = {self.resolve(k) for k, n in live_by_tenant.items()
                      if n > 0}
            active.add(t)
            total = sum(int(n) for n in live_by_tenant.values() if n > 0)
            wsum = sum(
                self._quotas.get(a, self._default).weight for a in active
            )
            w = self._quotas.get(t, self._default).weight
        if wsum <= 0.0:
            return float(total)
        return total * (w / wsum)

    def over_fair_share(self, tenant: Optional[str],
                        live_by_tenant: Mapping[str, int]) -> bool:
        """True when ``tenant`` holds strictly more than its weighted
        slice of the live jobs (pressure and shedding target it first)."""
        t = self.resolve(tenant)
        live = int(live_by_tenant.get(t, 0))
        if live <= 0:
            return False
        return live > self.fair_target(t, live_by_tenant)

    def fair_share_multiplier(self, tenant: Optional[str],
                              live_by_tenant: Mapping[str, int]) -> float:
        """Scale factor for the admission weight of ``tenant``'s next job.

        ``(target + 1) / (live + 1)``: a tenant at its fair share gets
        ~1.0, an over-share tenant's new work is deprioritized, an
        under-share tenant's is boosted — clamped so deadlines and
        priorities still dominate the solver objective.
        """
        t = self.resolve(tenant)
        live = int(live_by_tenant.get(t, 0))
        target = self.fair_target(t, live_by_tenant)
        lo, hi = _FAIR_SHARE_CLAMP
        return max(lo, min(hi, (target + 1.0) / (live + 1.0)))

    def over_share_tenants(
            self, live_by_tenant: Mapping[str, int]) -> set:
        """Tenants currently over their weighted slice (shed these first)."""
        return {self.resolve(t) for t, n in live_by_tenant.items()
                if n > 0 and self.over_fair_share(t, live_by_tenant)}

    # -- recovery -------------------------------------------------------

    def restore(self, charges: Mapping[str, float]) -> None:
        """Re-seat chip-second burn folded from ``tenant_charge`` records.

        Replaces (not adds to) the in-memory counters: recovery replays
        the whole journal, so the folded totals ARE the ground truth.
        """
        with self._lock:
            for t, v in charges.items():
                self._charged[self.resolve(t)] = float(v)

    def snapshot(self) -> Dict[str, Any]:
        """One JSON-able view per tenant (operator CLI / drain records)."""
        with self._lock:
            out: Dict[str, Any] = {}
            names: Iterable[str] = set(self._quotas) | set(self._charged) \
                | set(self._admitted) | set(self._shed)
            for t in sorted(names):
                q = self._quotas.get(t, self._default)
                out[t] = {
                    "admitted": self._admitted.get(t, 0),
                    "shed": self._shed.get(t, 0),
                    "charged_chip_s": round(self._charged.get(t, 0.0), 6),
                    "chip_seconds_budget": q.chip_seconds,
                    "max_live_jobs": q.max_live_jobs,
                    "max_inflight": q.max_inflight,
                    "weight": q.weight,
                }
            return out
