"""Compile-ahead: move XLA compile latency off the first-dispatch path.

Admission picks a strategy from shardflow/memlens static priors — often
minutes before the solver actually places the job's first task on a
slice. Today the price of that strategy's XLA compile is paid at first
dispatch, inside the execution interval. The pool here pays it in the
background instead: the service submits a compile thunk the moment a
job is ADMITted, worker threads compile it (writing through
``utils/aot_cache`` so the executable is also durable on disk when the
cache is enabled), and the dispatch path ``acquire``s the finished
executable — a *hit* means zero compile wait.

Every lifecycle step journals a ``compile_ahead`` event
(``requested`` / ``ready`` / ``error`` / ``hit`` / ``miss``) so the
hit/miss ledger survives in the durable record and the operator CLI can
report the warm-phase hit rate.

Compilation is arbitrary user code to this module: thunks run strictly
OUTSIDE the pool lock (a multi-minute XLA compile under a lock is the
SAT-C003 stall class), and a thunk's exception is a ledger entry, not a
pool crash.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Optional

from saturn_tpu.analysis import concurrency as tsan

__all__ = ["CompileAheadPool"]


class CompileAheadPool:
    """Background compile workers + the hit/miss ledger.

    Keys are caller-chosen strings; for real SPMD bundles use
    :meth:`prewarm_lowered` (keys by ``aot_cache.cache_key`` so the
    disk cache and the pool agree on identity), for tests/benchmarks
    any stable string works.
    """

    def __init__(self, *, workers: int = 2, journal: Any = None) -> None:
        self._lock = tsan.lock("tenancy.compile_pool")
        self._cond = tsan.condition(self._lock, "tenancy.compile_pool.cond")
        self._pending: deque = deque()   # (key, thunk, job, tenant)
        self._inflight: set = set()      # keys queued or compiling
        self._ready: Dict[str, Any] = {}
        self._errors: Dict[str, str] = {}
        self._counts: Dict[str, int] = {
            "requested": 0, "ready": 0, "errors": 0,
            "ahead_hits": 0, "ahead_misses": 0, "duplicates": 0,
        }
        self._closed = False
        self._workers = max(1, int(workers))
        self._threads: list = []
        #: Durable journal for compile_ahead events (wired by the service).
        self.journal = journal

    # -- producer side --------------------------------------------------

    def prewarm(self, key: str, thunk: Callable[[], Any], *,
                job: Optional[str] = None,
                tenant: Optional[str] = None) -> bool:
        """Queue ``thunk`` to compile ``key`` in the background.

        Returns False (and counts a duplicate) when ``key`` is already
        ready, inflight, or failed — re-admitting a requeued job must
        not recompile.
        """
        with self._lock:
            if self._closed:
                return False
            if key in self._ready or key in self._inflight \
                    or key in self._errors:
                self._counts["duplicates"] += 1
                return False
            self._inflight.add(key)
            self._counts["requested"] += 1
            self._pending.append((key, thunk, job, tenant))
            self._spawn_locked()
            self._cond.notify()
        self._journal_event("requested", key, job=job, tenant=tenant)
        return True

    def prewarm_lowered(self, lowered: Any, devices: Any = None, *,
                        job: Optional[str] = None,
                        tenant: Optional[str] = None) -> Optional[str]:
        """Prewarm a real lowered computation through the AOT cache.

        Returns the cache key (also usable with :meth:`acquire`), or
        None when the lowering has no stable identity. The compiled
        executable additionally lands in ``aot_cache``'s in-process warm
        pool, so ``Bundle.compiled`` — which calls
        ``aot_cache.load_or_compile`` — hits it with no dispatch-path
        changes.
        """
        from saturn_tpu.utils import aot_cache

        if devices is None:
            devices = ()
        try:
            key = aot_cache.cache_key(lowered, devices)
        except Exception:
            key = None
        if key is None:
            return None
        self.prewarm(key, lambda: aot_cache.prewarm(lowered, devices),
                     job=job, tenant=tenant)
        return key

    # -- consumer side --------------------------------------------------

    def acquire(self, key: str, timeout: float = 0.0) -> Optional[Any]:
        """Fetch the compiled artifact for ``key`` if compile-ahead won.

        Returns the artifact on a hit (counts ``ahead_hits``); None on a
        miss (never requested, failed, or not ready within ``timeout``)
        — the caller compiles synchronously exactly as before.
        """
        deadline = time.monotonic() + max(0.0, float(timeout))
        with self._lock:
            while True:
                if key in self._ready:
                    self._counts["ahead_hits"] += 1
                    result = self._ready[key]
                    hit = True
                    break
                waitable = key in self._inflight and not self._closed
                remaining = deadline - time.monotonic()
                if not waitable or remaining <= 0.0:
                    self._counts["ahead_misses"] += 1
                    result, hit = None, False
                    break
                self._cond.wait(timeout=min(remaining, 0.5))
        self._journal_event("hit" if hit else "miss", key)
        return result

    def error(self, key: str) -> Optional[str]:
        with self._lock:
            return self._errors.get(key)

    def ledger(self) -> Dict[str, Any]:
        """Counts + derived hit rate (None until anything was acquired)."""
        with self._lock:
            out: Dict[str, Any] = dict(self._counts)
            out["pending"] = len(self._pending)
            out["inflight"] = len(self._inflight)
        asked = out["ahead_hits"] + out["ahead_misses"]
        out["hit_rate"] = (out["ahead_hits"] / asked) if asked else None
        return out

    def wait_idle(self, timeout: Optional[float] = None) -> bool:
        """Block until every queued compile finished (tests/benchmarks)."""
        deadline = None if timeout is None \
            else time.monotonic() + float(timeout)
        with self._lock:
            while self._pending or self._inflight:
                remaining = 0.5 if deadline is None \
                    else deadline - time.monotonic()
                if remaining <= 0.0:
                    return False
                self._cond.wait(timeout=min(remaining, 0.5))
        return True

    def close(self, timeout: float = 5.0) -> None:
        with self._lock:
            self._closed = True
            self._cond.notify_all()
            threads = list(self._threads)
        for t in threads:
            t.join(timeout=timeout)

    # -- workers --------------------------------------------------------

    def _spawn_locked(self) -> None:
        # Called under self._lock: lazily grow the worker set up to the
        # cap so an idle service never carries compile threads.
        while len(self._threads) < self._workers \
                and len(self._threads) < len(self._pending) + len(
                    self._inflight):
            t = threading.Thread(
                target=self._worker,
                name=f"compile-ahead-{len(self._threads)}",
                daemon=True,
            )
            self._threads.append(t)
            t.start()

    def _worker(self) -> None:
        while True:
            with self._lock:
                while not self._pending and not self._closed:
                    self._cond.wait(timeout=0.5)
                if self._closed and not self._pending:
                    return
                key, thunk, job, tenant = self._pending.popleft()
            try:
                result = thunk()
                err = None
            except Exception as e:  # a thunk's failure is a ledger entry
                result, err = None, f"{type(e).__name__}: {e}"
            with self._lock:
                self._inflight.discard(key)
                if err is None:
                    self._ready[key] = result
                    self._counts["ready"] += 1
                else:
                    self._errors[key] = err
                    self._counts["errors"] += 1
                self._cond.notify_all()
            if err is None:
                self._journal_event("ready", key, job=job, tenant=tenant)
            else:
                self._journal_event("error", key, job=job, tenant=tenant,
                                    error=err)

    def _journal_event(self, status: str, key: str, **extra: Any) -> None:
        jnl = self.journal
        if jnl is None:
            return
        try:
            jnl.append("compile_ahead", status=status, key=key,
                       **{k: v for k, v in extra.items() if v is not None})
        except Exception:
            pass  # a closed/rotating journal must not break compiles
