"""saturn-tenancy: multi-tenant control plane for the shared fleet.

Saturn's framing is explicitly multi-client — many users submitting
batches of training jobs against one shared fleet (arxiv 2311.02840) —
but a queue + single gateway treats every submitter as the same
principal. This package adds the three pieces that make the front door
a control plane instead of a socket:

- ``model`` — tenant identity, per-tenant quotas (max live jobs,
  chip-seconds budget, inflight window) and the weighted fair-share
  ledger the admission controller and gateway consult. Charges are
  journaled (``tenant_charge``) so budgets survive crash-replay.
- ``lease`` — an epoch-fenced leader lease shared by gateway replicas
  over one durability journal: exactly-once admission across replica
  failover, with a deposed replica's late admissions refused by fence.
- ``compile_ahead`` — a background compile pool over the AOT executable
  cache that starts compiling the moment admission picks a strategy,
  so an admitted job's first dispatch never blocks on XLA.

Import-light (stdlib + saturn-tsan factories only at import time): the
gateway and service import this on their hot paths.
"""

from __future__ import annotations

from saturn_tpu.tenancy.compile_ahead import CompileAheadPool
from saturn_tpu.tenancy.lease import LeaseHeld, ReplicaLease
from saturn_tpu.tenancy.model import DEFAULT_TENANT, TenantLedger, TenantQuota

__all__ = [
    "DEFAULT_TENANT",
    "TenantQuota",
    "TenantLedger",
    "ReplicaLease",
    "LeaseHeld",
    "CompileAheadPool",
]
