"""Multi-host entry points: cluster rendezvous and the global topology.

The reference's multi-worker story was per-job NCCL process groups
rendezvousing over localhost (``FSDP.py:44-50``, ``DDP.py:28-34``) under a
Ray control plane, and its solver forbade cross-node jobs outright
(``milp.py:134-137``). The TPU-native story is inverted: **one** JAX
distributed runtime spans all hosts (each host drives its local slice), and
after :func:`initialize` every host sees the same global ``jax.devices()``
list. From there, everything is ordinary saturn_tpu — a slice-aware
:class:`~saturn_tpu.core.mesh.SliceTopology` over the global device list,
meshes over contiguous blocks, XLA collectives over ICI within a slice and
DCN across slices (the sharding layout puts only the ``data`` axis across
DCN; see ``SliceTopology``).

Single-host runs never need this module.
"""

from __future__ import annotations

import logging
from typing import Optional

log = logging.getLogger("saturn_tpu")


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join the multi-host cluster (idempotent).

    Thin wrapper over ``jax.distributed.initialize``; with no arguments, JAX
    auto-detects the TPU pod environment (the common case on Cloud TPU VMs).
    Call once per host, before any other JAX API touches devices.
    """
    import jax

    already = getattr(jax.distributed, "is_initialized", lambda: False)()
    if already:
        # idempotent for notebook reruns; a second initialize would raise
        # "must be called before any JAX calls"
        log.info("jax.distributed already initialized; continuing")
    else:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    log.info(
        "multi-host: process %d/%d, %d local / %d global devices",
        jax.process_index(), jax.process_count(),
        jax.local_device_count(), jax.device_count(),
    )


def global_topology():
    """Slice-aware topology over every device in the cluster.

    Blocks of at most one slice stay on ICI; larger (slice-multiple) blocks
    put their leading mesh axis across DCN.
    """
    from saturn_tpu.core.mesh import SliceTopology

    return SliceTopology()  # groups jax.devices() by process_index


def process_index() -> int:
    """This process's rank; 0 on single-host runs (without importing a
    backend when jax was never initialized by us)."""
    import jax

    try:
        return jax.process_index()
    except Exception:  # backend not initialized yet
        return 0


def is_coordinator() -> bool:
    """True on the process that owns host-side effects — checkpoint writes
    (``utils/checkpoint.py``) and metrics files. Rank 0 by convention; the
    reference had no analog because it never ran multi-host."""
    return process_index() == 0


def sync(name: str = "saturn_tpu_sync") -> None:
    """Cross-process barrier (no-op single-process): lets the coordinator
    finish a host-side effect before other processes proceed past it."""
    import jax

    if jax.process_count() <= 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)
