"""Multi-host entry points: cluster rendezvous and the global topology.

The reference's multi-worker story was per-job NCCL process groups
rendezvousing over localhost (``FSDP.py:44-50``, ``DDP.py:28-34``) under a
Ray control plane, and its solver forbade cross-node jobs outright
(``milp.py:134-137``). The TPU-native story is inverted: **one** JAX
distributed runtime spans all hosts (each host drives its local slice), and
after :func:`initialize` every host sees the same global ``jax.devices()``
list. From there, everything is ordinary saturn_tpu — a slice-aware
:class:`~saturn_tpu.core.mesh.SliceTopology` over the global device list,
meshes over contiguous blocks, XLA collectives over ICI within a slice and
DCN across slices (the sharding layout puts only the ``data`` axis across
DCN; see ``SliceTopology``).

Single-host runs never need this module.
"""

from __future__ import annotations

import logging
from typing import Optional

log = logging.getLogger("saturn_tpu")


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join the multi-host cluster (idempotent).

    Thin wrapper over ``jax.distributed.initialize``; with no arguments, JAX
    auto-detects the TPU pod environment (the common case on Cloud TPU VMs).
    Call once per host, before any other JAX API touches devices.
    """
    import jax

    already = getattr(jax.distributed, "is_initialized", lambda: False)()
    if already:
        # idempotent for notebook reruns; a second initialize would raise
        # "must be called before any JAX calls"
        log.info("jax.distributed already initialized; continuing")
    else:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    log.info(
        "multi-host: process %d/%d, %d local / %d global devices",
        jax.process_index(), jax.process_count(),
        jax.local_device_count(), jax.device_count(),
    )


def global_topology():
    """Slice-aware topology over every device in the cluster.

    Blocks of at most one slice stay on ICI; larger (slice-multiple) blocks
    put their leading mesh axis across DCN.
    """
    from saturn_tpu.core.mesh import SliceTopology

    return SliceTopology()  # groups jax.devices() by process_index


def process_index() -> int:
    """This process's rank; 0 on single-host runs (without importing a
    backend when jax was never initialized by us)."""
    import jax

    try:
        return jax.process_index()
    except Exception:  # backend not initialized yet
        return 0


def process_count() -> int:
    import jax

    try:
        return jax.process_count()
    except Exception:
        return 1


def is_multihost() -> bool:
    return process_count() > 1


def is_coordinator() -> bool:
    """True on the process that owns host-side effects — checkpoint writes
    (``utils/checkpoint.py``) and metrics files. Rank 0 by convention; the
    reference had no analog because it never ran multi-host."""
    return process_index() == 0


def sync(name: str = "saturn_tpu_sync") -> None:
    """Cross-process barrier (no-op single-process): lets the coordinator
    finish a host-side effect before other processes proceed past it."""
    import jax

    if jax.process_count() <= 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def broadcast_json(obj, src: int = 0):
    """Process ``src``'s ``obj`` (json-serializable) to every process.

    The control-plane primitive behind multi-host orchestration: plans and
    corrected profiles are DECIDED on one rank and broadcast, never
    recomputed per rank — a time-limited solver (HiGHS) and wall-clock
    profiling are not deterministic across processes, and divergent plans
    would interleave collective programs differently per process (the
    multi-controller deadlock). Two-phase: fixed-shape length first, then
    the utf-8 payload (``broadcast_one_to_all`` needs same-shaped inputs
    everywhere). Cluster-wide: every process must call it.
    """
    import json

    import numpy as np

    if not is_multihost():
        return obj
    from jax.experimental import multihost_utils

    is_src = process_index() == src
    payload = np.frombuffer(
        json.dumps(obj).encode("utf-8"), dtype=np.uint8
    ) if is_src else np.zeros(0, np.uint8)
    n = multihost_utils.broadcast_one_to_all(
        np.asarray(payload.size, np.int64), is_source=is_src
    )
    buf = payload if is_src else np.zeros(int(n), np.uint8)
    out = multihost_utils.broadcast_one_to_all(buf, is_source=is_src)
    return json.loads(np.asarray(out).tobytes().decode("utf-8"))


def put_global(host_array, sharding):
    """``device_put`` that also works when ``sharding`` spans processes.

    Every process holds the FULL host value (saturn_tpu datasets are
    deterministic and instantiated per process); each device takes its own
    slice, so nothing crosses DCN for batch placement."""
    import jax

    if not is_multihost():
        return jax.device_put(host_array, sharding)
    import numpy as np

    arr = np.asarray(host_array)
    return jax.make_array_from_callback(arr.shape, sharding,
                                        lambda idx: arr[idx])


def put_tree_global(tree, shardings):
    """Tree version of :func:`put_global` (checkpoint-restore placement)."""
    import jax

    if not is_multihost():
        return jax.device_put(tree, shardings)
    return jax.tree_util.tree_map(put_global, tree, shardings)


def host_scalar(x) -> float:
    """Read a (replicated) device scalar on every process — ``device_get``
    refuses arrays that are not fully addressable."""
    return float(host_array(x))


def host_array(x):
    """Read a (replicated) device array on every process — the array-valued
    sibling of :func:`host_scalar`, for the fused multi-step window's (K,)
    per-step loss vector. Replicated outputs are whole on every device, so
    one addressable shard carries the full value."""
    import jax
    import numpy as np

    if getattr(x, "is_fully_addressable", True):
        return np.asarray(jax.device_get(x))
    return np.asarray(x.addressable_data(0))


def sync_task_state(task_list, src_ranks=None, updates=None) -> dict:
    """Make every rank's strategy numbers identical — the multi-host
    forecast precondition (budgets derive from per-batch times; divergent
    budgets mean divergent collective program counts = deadlock).

    ``src_ranks``: task name -> the process whose numbers win. The
    orchestrator passes each task's executing (lowest-block) rank so
    realized-feedback corrections from host-local tasks survive; with no
    plan yet (the pre-loop profile sync) rank 0 wins. One broadcast per
    distinct source rank, deterministic order, every process participates.

    ``updates``: this rank's {task: (old, new)} feedback corrections; each
    source rank's entries for its own tasks ride the broadcast, and the
    MERGED map is returned on every rank — so the coordinator (the only
    metrics writer in multi-host runs) can emit estimate_update events for
    corrections that happened on other hosts.
    """
    if not is_multihost():
        return dict(updates or {})
    src_ranks = src_ranks or {}
    updates = updates or {}
    by_src: dict = {}
    for t in task_list:
        by_src.setdefault(int(src_ranks.get(t.name, 0)), []).append(t)
    merged_updates: dict = {}
    for src in sorted(by_src):
        group = by_src[src]
        payload = None
        if process_index() == src:
            payload = {
                "state": {
                    t.name: {
                        # The correction anchors ride along: without them a
                        # rank that never executed this task would re-anchor
                        # "trial" baselines from already-corrected values and
                        # clobber self-measured siblings after a re-solve
                        # moves the task to its block (round-5 review).
                        str(g): [s.per_batch_time, s.runtime,
                                 getattr(s, "_trial_per_batch", None),
                                 bool(getattr(s, "_self_measured", False))]
                        for g, s in t.strategies.items()
                    }
                    for t in group
                },
                "updates": {
                    t.name: list(updates[t.name])
                    for t in group if t.name in updates
                },
            }
        payload = broadcast_json(payload, src=src)
        for t in group:
            for g_str, vals in payload["state"].get(t.name, {}).items():
                s = t.strategies.get(int(g_str))
                if s is not None:
                    s.per_batch_time = vals[0]
                    s.runtime = vals[1]
                    if len(vals) > 2:
                        if vals[2] is not None:
                            s._trial_per_batch = vals[2]
                        s._self_measured = bool(vals[3])
        for name, pair in payload["updates"].items():
            merged_updates[name] = tuple(pair)
    return merged_updates
