"""Task and HParams: the job descriptors users hand to the system.

Reference: ``saturn/core/representations/Task.py``. A Task bundles lazy model /
dataloader factories, a loss, hyperparameters, and the profiled ``strategies``
table the solver consumes. TPU-native deltas:

- ``chip_range`` replaces ``gpu_range`` (``Task.py:80-82,106``): it restricts
  the *sub-mesh sizes* (powers of two) the trial runner profiles.
- The data cursor supports O(1) random access (``Dataset.batch(i)``), fixing
  the reference's O(position) iterator-draining resume (``Task.py:138-139``).
- Checkpoints are full train state (params + opt state + step), written by the
  executing technique via ``saturn_tpu.utils.checkpoint`` — not model-only
  ``torch.save`` (``Task.py:150-153``).
"""

from __future__ import annotations

import os
import secrets
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from saturn_tpu.core.strategy import Strategy

_OPTIMIZERS = ("adamw", "adam", "sgd")


@dataclass
class HParams:
    """Hyperparameters (reference ``Task.py:23-62``).

    Exactly one of ``epochs`` / ``batch_count`` must be set (validated like
    ``Task.py:42-44``). ``optimizer`` is an optax factory name or a callable
    ``lr -> optax.GradientTransformation``. ``kwargs`` are forwarded to the
    task's ``get_model`` factory (``Task.py:166-169``).
    """

    lr: float = 1e-4
    epochs: Optional[int] = None
    batch_count: Optional[int] = None
    optimizer: Any = "adamw"
    batch_size: Optional[int] = None
    kwargs: Dict[str, Any] = field(default_factory=dict)

    def __post_init__(self) -> None:
        if (self.epochs is None) == (self.batch_count is None):
            raise ValueError(
                "exactly one of epochs / batch_count must be specified"
            )
        if isinstance(self.optimizer, str) and self.optimizer not in _OPTIMIZERS:
            raise ValueError(
                f"unknown optimizer {self.optimizer!r}; use one of {_OPTIMIZERS} "
                "or pass a callable lr -> optax.GradientTransformation"
            )

    def make_optimizer(self):
        """Instantiate the optax transformation for this task."""
        import optax

        if callable(self.optimizer):
            return self.optimizer(self.lr)
        if self.optimizer == "adamw":
            return optax.adamw(self.lr)
        if self.optimizer == "adam":
            return optax.adam(self.lr)
        return optax.sgd(self.lr)


class Task:
    """One training job in the batch (reference ``Task.py:65-179``)."""

    def __init__(
        self,
        get_model: Callable[..., Any],
        get_dataloader: Callable[[], Any],
        loss_fn: Callable[[Any, Any], Any],
        hparams: HParams,
        chip_range: Optional[List[int]] = None,
        hints: Optional[Dict[str, Any]] = None,
        name: Optional[str] = None,
        save_dir: str = "saturn_ckpts",
    ):
        self._get_model = get_model
        self._get_dataloader = get_dataloader
        self.loss_fn = loss_fn
        self.hparams = hparams
        self.chip_range = chip_range  # allowed sub-mesh sizes; None = all
        self.hints = dict(hints or {})
        # Random 16-char name like the reference (``Task.py:107-109``).
        self.name = name if name is not None else secrets.token_hex(8)
        self.save_dir = save_dir
        os.makedirs(save_dir, exist_ok=True)

        self._dataset = None  # cached dataloader instance
        # Eager epoch length, mirroring ``Task.py:127-128`` (this may trigger
        # dataset tokenization/caching on construction — intentional parity).
        self.epoch_length = len(self.get_dataset())
        if hparams.epochs is not None:
            self.total_batches = self.epoch_length * hparams.epochs
        else:
            self.total_batches = hparams.batch_count

        self.current_batch = 0  # data cursor, persists across intervals
        # Quarantine skip-list (health guardian): dataset indices excluded
        # from the training sequence. The cursor walks the SURVIVING sequence
        # — sorted non-quarantined indices — so every consumer (``batch_at``,
        # the prefetcher's staging callback, checkpoint-restore cursor math)
        # agrees on which batch step k maps to.
        self._quarantined: set = set()
        self._surviving: Optional[List[int]] = None  # cache, None = dirty
        self.strategies: Dict[int, Strategy] = {}
        self.selected_strategy: Optional[Strategy] = None
        # Device-resident train state from the most recent interval, keyed by
        # (technique, config, block) — lets consecutive intervals under an
        # unchanged assignment skip the checkpoint disk round-trip.
        self._live_state: Optional[tuple] = None
        # (strategy, realized per-batch seconds) noted by the executor, folded
        # in by the orchestrator between intervals (see note_realized_per_batch)
        self._pending_realized: Optional[tuple] = None
        # The strategy the most recent apply_realized_feedback measured —
        # the orchestrator persists its per-batch time to the profile cache.
        self.last_feedback_strategy: Optional[Strategy] = None

    def release_live_state(self) -> None:
        """Drop the cached device train state (frees HBM). Safe on a task
        that will run again (retry path): the next interval restores from the
        checkpoint; compiled programs stay cached."""
        self._live_state = None

    def release_compiled(self) -> None:
        """Release this task's compiled-program cache in every technique that
        profiled it. Only for tasks that will NOT run again (completed or
        permanently dropped) — a retried task would pay a full XLA recompile
        (minutes at scale) for nothing."""
        seen = set()
        for strat in self.strategies.values():
            ex = getattr(strat, "executor", None)
            release = getattr(ex, "release_task", None)
            if release is not None and id(ex) not in seen:
                seen.add(id(ex))
                release(self.name)

    # ------------------------------------------------------------------ model
    def get_model(self, **overrides):
        """Instantiate the ModelSpec (lazy — never cached on the task, so the
        reference's DO-NOT-pre-instantiate rule ``Task.py:92-97`` holds).

        ``overrides`` come from a technique's autotune config (e.g.
        ``remat=True``), merged over the user's ``hparams.kwargs`` — the
        TPU analog of the reference's search grid toggling activation
        checkpointing on the wrapper (``FSDP.py:72-78,127-129``).
        """
        kw = dict(self.hparams.kwargs)
        kw.update(overrides)
        return self._get_model(**kw)

    # ------------------------------------------------------------------- data
    def get_dataset(self):
        if self._dataset is None:
            self._dataset = self._get_dataloader()
        return self._dataset

    def batch_at(self, step: int):
        """O(1) random access to the batch for global step ``step``,
        skipping quarantined dataset indices."""
        return self.get_dataset().batch(self.dataset_index(step))

    # ------------------------------------------------------------- quarantine
    def quarantine_batches(self, indices) -> None:
        """Exclude dataset indices from the training sequence (health
        guardian skip-list). Refuses to quarantine the whole dataset — a
        job with zero surviving batches is an eviction, not a skip."""
        add = {int(i) % max(self.epoch_length, 1) for i in indices}
        if len(self._quarantined | add) >= self.epoch_length:
            raise ValueError(
                f"task {self.name}: quarantining {sorted(add)} would leave "
                "no surviving batches"
            )
        self._quarantined |= add
        self._surviving = None

    def unquarantine_batches(self, indices=None) -> None:
        """Lift quarantine for ``indices`` (or all, when ``None``)."""
        if indices is None:
            self._quarantined.clear()
        else:
            self._quarantined -= {int(i) for i in indices}
        self._surviving = None

    @property
    def quarantined_batches(self) -> tuple:
        return tuple(sorted(self._quarantined))

    @property
    def surviving_epoch_length(self) -> int:
        """Epoch length after quarantine — the modulus for cursor math."""
        return self.epoch_length - len(self._quarantined)

    def _surviving_indices(self) -> List[int]:
        if self._surviving is None:
            q = self._quarantined
            self._surviving = [
                i for i in range(self.epoch_length) if i not in q
            ]
        return self._surviving

    def dataset_index(self, step: int) -> int:
        """Map a cursor step to its dataset index through the skip-list."""
        if not self._quarantined:
            return step % max(self.epoch_length, 1)
        surviving = self._surviving_indices()
        return surviving[step % len(surviving)]

    def cursor_for_step(self, step: int) -> int:
        """Normalize a restored global step onto the surviving sequence
        (checkpoint restore after quarantine replay)."""
        return step % max(self.surviving_epoch_length, 1)

    # ------------------------------------------------------------ checkpoints
    @property
    def ckpt_path(self) -> str:
        return os.path.join(self.save_dir, f"{self.name}.npz")

    def has_ckpt(self) -> bool:
        from saturn_tpu.utils import checkpoint as _ckpt

        # routes through the checkpoint module so an in-flight async save
        # counts as existing (utils/checkpoint.py save_async)
        return _ckpt.exists(self.ckpt_path)

    def clear_ckpt(self) -> None:
        from saturn_tpu.utils import checkpoint as _ckpt

        # delete removes the manifest AND its shard files (sharded format),
        # joining any in-flight async save first.
        _ckpt.delete(self.ckpt_path)

    # -------------------------------------------------------------- schedule
    def reconfigure(self, batch_count: int) -> None:
        """Advance the data cursor after an interval ran ``batch_count``
        batches (reference ``Task.py:155-157``)."""
        self.current_batch = (self.current_batch + batch_count) % max(
            self.surviving_epoch_length, 1
        )

    def select_strategy(self, apportionment: int) -> None:
        """Pin the solver's chosen strategy (reference ``Task.py:171-172``)."""
        self.selected_strategy = self.strategies[apportionment]

    # ------------------------------------------- profiled-vs-realized feedback
    # The reference re-estimated remaining runtime online but never corrected
    # the per-batch profile itself (``executor.py:126-129,165-177`` logs the
    # error and moves on); saturn_tpu's round-3 sweeps showed +278-398%
    # interval error surviving forever because forecast consumed the original
    # trial profile every round. The executor notes the realized per-batch
    # time here (a plain attribute write — safe while the overlapped re-solve
    # thread reads strategy state), and the orchestrator folds it in via
    # ``apply_realized_feedback`` only after joining that solve.
    EWMA_ALPHA = 0.7  # weight on the new measurement (each one already
    #                   averages a whole interval's batches, so favor recency:
    #                   a 2x profile error decays to <10% in two intervals)

    def note_realized_per_batch(self, per_batch_s: float) -> None:
        """Record the realized per-batch seconds for the currently selected
        strategy. Called by the technique at the end of its interval run."""
        if self.selected_strategy is not None and per_batch_s > 0.0:
            self._pending_realized = (self.selected_strategy, per_batch_s)

    def apply_realized_feedback(self) -> Optional[tuple]:
        """Fold the noted measurement into the executed strategy (EWMA) and
        rescale its remaining runtime. Returns (old, new) per-batch seconds
        when an update happened, else None. Must only run while no solver
        thread is reading strategy state (the orchestrator calls it after
        joining the overlapped re-solve).

        Sibling strategies are corrected too: estimate error is dominated by
        systemic effects (contention, shape mis-profiling) that hit every
        apportionment alike, and correcting only the executed one would make
        the re-solve ping-pong to whichever sibling still carries its
        optimistic trial profile. To keep alternating re-solves from
        cross-multiplying strategy-specific errors without bound, the
        correction is *replaced, not compounded*: each never-executed sibling
        is set to ``trial_profile x (executed_now / executed_trial)`` —
        anchored to both strategies' original trial profiles — and a sibling
        that has ever produced its own measurement is left alone (its own
        EWMA is better evidence than a cross-strategy ratio)."""
        pending = getattr(self, "_pending_realized", None)
        self._pending_realized = None
        if pending is None:
            return None
        strat, realized = pending
        if not strat.feasible:
            return None
        # Stash every strategy's original trial profile on first feedback so
        # sibling corrections stay anchored to it forever after.
        for s in self.strategies.values():
            if s.feasible and getattr(s, "_trial_per_batch", None) is None:
                s._trial_per_batch = s.per_batch_time
        old = strat.per_batch_time
        strat.per_batch_time = (
            self.EWMA_ALPHA * realized + (1.0 - self.EWMA_ALPHA) * old
            if old > 0.0 else realized
        )
        strat._self_measured = True
        # A realized measurement upgrades a cost-model estimate to a measured
        # entry — the trial runner only profiled anchor sizes and
        # interpolated this one (``trial_runner/evaluator.py``).
        strat.interpolated = False
        # Same for a shardflow cold-start prior: once the job has produced a
        # realized interval, the static roofline estimate is superseded —
        # SAT-X005 (``analysis/shardflow/prior.py:audit_task``) then compares
        # the two and flags a miscalibrated prior.
        strat.static_prior = False
        self.last_feedback_strategy = strat
        strat.runtime = strat.per_batch_time * max(self.total_batches, 0)
        trial_base = getattr(strat, "_trial_per_batch", 0.0) or 0.0
        if trial_base > 0.0:
            cum_ratio = strat.per_batch_time / trial_base
            for s in self.strategies.values():
                if (
                    s is not strat
                    and s.feasible
                    and not getattr(s, "_self_measured", False)
                    and (getattr(s, "_trial_per_batch", 0.0) or 0.0) > 0.0
                ):
                    s.per_batch_time = s._trial_per_batch * cum_ratio
                    s.runtime = s.per_batch_time * max(self.total_batches, 0)
        return old, strat.per_batch_time

    def feasible_strategies(self) -> Dict[int, Strategy]:
        return {g: s for g, s in self.strategies.items() if s.feasible}

    def clone(self, name: Optional[str] = None, **hparam_overrides) -> "Task":
        """A new task sharing this one's factories and profiled strategies.

        The reference deep-copied searched tasks to fan one profile out over
        several learning rates without re-profiling (``WikiText103.py:87-99``)
        — valid because lr doesn't change step time. Strategy objects are
        copied (not aliased): ``forecast`` mutates remaining runtimes per task.
        """
        import copy
        from dataclasses import replace as dc_replace

        hp = dc_replace(self.hparams, **hparam_overrides) if hparam_overrides else copy.copy(self.hparams)
        t = Task(
            get_model=self._get_model,
            # Feed the already-built dataset through so the eager epoch_length
            # computation in __init__ doesn't re-tokenize per clone; the true
            # factory is restored below.
            get_dataloader=lambda: self.get_dataset(),
            loss_fn=self.loss_fn,
            hparams=hp,
            chip_range=self.chip_range,
            hints=dict(self.hints),
            name=name,
            save_dir=self.save_dir,
        )
        t._get_dataloader = self._get_dataloader
        t.strategies = {g: copy.copy(s) for g, s in self.strategies.items()}
        return t

    def __repr__(self) -> str:  # pragma: no cover
        return (
            f"Task(name={self.name!r}, total_batches={self.total_batches}, "
            f"strategies={list(self.strategies)})"
        )
