"""BaseTechnique: the two-method plugin contract every parallelism executor obeys.

Reference: ``saturn/core/executors/Technique.py:24-45``. The entire extension
surface of the system is this ABC: a technique must be able to (a) *autotune and
profile* itself on a given sub-mesh (``search``) and (b) *run a bounded number of
batches* on a given sub-mesh, resuming from and writing checkpoints
(``execute``). Everything else (solver, orchestrator, trial runner) only ever
talks to these two methods.

TPU-native deltas from the reference contract:

- ``devices`` is a list of ``jax.Device`` forming a contiguous ICI sub-mesh,
  not a list of integer GPU ids (reference passed ``[0..g-1]``,
  ``executor.py:82-83``).
- ``search`` must exclude XLA compile time from the reported per-batch time
  (the reference timed batch 2-of-2 to skip warmup, ``FSDP.py:140-149``; under
  jit we compile once, sync, then time steady-state steps).
- Techniques should use XLA compile-time memory analysis
  (``compiled.memory_analysis()``) to reject configurations that won't fit in
  HBM instead of try/except OOM probing (reference ``Spilled.py:68-87``).
"""

from __future__ import annotations

import abc
from typing import Any, Dict, List, Optional, Sequence, Tuple


class BaseTechnique(abc.ABC):
    """Abstract parallelism technique ("UDP" in the reference's terms)."""

    #: Optional friendly name used when registering into the library.
    name: str = "base"

    #: Which built-in technique family this is (``Techniques`` enum member),
    #: None for user-defined plugins. Consumed by ``library.retrieve`` (enum
    #: lookup) and ``Strategy.technique`` (plan introspection) — the reference
    #: declared its enum but nothing ever read it (``Strategy.py:25-34``).
    technique = None  # type: ignore[assignment]  # Optional[Techniques]

    #: Declares that this technique's per-chip memory footprint is
    #: non-increasing in sub-mesh size (smaller block => per-chip memory the
    #: same or strictly higher). True for every sharding-based technique:
    #: replicated state is constant per chip while sharded state shrinks as
    #: the block grows. The trial runner uses it to propagate XLA memory
    #: infeasibility monotonically — a memory rejection at size ``g`` skips
    #: the trials at every smaller size instead of compiling them to fail.
    #: Techniques additionally expose the rejection reason via
    #: ``search_report`` (see ``SPMDTechnique``); without a report claiming
    #: the rejection was memory-bound, nothing is propagated (a batch
    #: divisibility failure at a LARGE size says nothing about small ones).
    memory_monotone: bool = False

    @abc.abstractmethod
    def execute(
        self,
        task: Any,
        devices: Sequence[Any],
        tid: int,
        override_batch_count: Optional[int] = None,
    ) -> None:
        """Train ``task`` on ``devices`` for ``override_batch_count`` batches.

        Must resume from the task's checkpoint if one exists and write a full
        train-state checkpoint (params AND optimizer state — fixing the
        reference's dropped-optimizer wart, ``FSDP.py:220``) when the batch
        budget is exhausted. Reference contract: ``Technique.py:31-34``.
        """

    @abc.abstractmethod
    def search(
        self,
        task: Any,
        devices: Sequence[Any],
        tid: int,
    ) -> Tuple[Optional[Dict[str, Any]], Optional[float]]:
        """Autotune internal knobs on ``devices``; return ``(params, per_batch_time)``.

        ``params`` is the technique's chosen configuration (e.g. remat on/off,
        microbatch count); ``(None, None)`` means the technique cannot run this
        task on this sub-mesh. Reference contract: ``Technique.py:42-45``.
        """
