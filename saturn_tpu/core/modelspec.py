"""ModelSpec: the functional model contract techniques consume.

The reference's ``Task.get_model`` returned an ``nn.Sequential`` torch module
(``GPTJ.py:502-526`` flattens GPT-J into a Sequential precisely so GPipe /
OffloadModel can partition it). The TPU-native analog is a *functional* spec:
pure ``init``/``apply`` functions plus a config that exposes the structure
techniques need (layer count for pipeline balancing, hints for remat and
tensor-parallel rules). Params are a plain pytree, so every technique shards
the same arrays with its own ``PartitionSpec`` rules — no wrapper classes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple


@dataclass
class ModelSpec:
    """Functional model bundle returned by a task's ``get_model`` factory.

    - ``init_fn(rng) -> params``: build (host or device) params.
    - ``apply_fn(params, inputs) -> logits``: pure forward pass, jit-safe.
    - ``abstract_init() -> params_shapes``: ``jax.eval_shape`` of ``init_fn`` —
      lets the trial runner do memory analysis without materializing weights
      (honoring the reference's lazy-instantiation rule, ``Task.py:92-97``).
    - ``config``: model hyperparams; must expose ``n_layers`` and example input
      shapes via ``example_inputs`` for tracing.
    - ``hints``: free-form dict mirroring the reference's transformer hints
      (``Task.py:121-124``), e.g. ``{"block_param_key": "blocks"}`` telling
      pipeline/FSDP executors where the scanned layer stack lives.
    """

    init_fn: Callable[[Any], Any]
    apply_fn: Callable[[Any, Any], Any]
    config: Any
    hints: Dict[str, Any] = field(default_factory=dict)
    # Optional: ``(params, inputs) -> (logits, aux_loss)`` for models with an
    # auxiliary training loss (e.g. MoE load balancing); techniques that know
    # about it (parallel/ep.py) add ``aux_loss`` to the objective, everything
    # else uses the plain ``apply_fn``.
    apply_with_aux_fn: Optional[Callable[[Any, Any], Tuple[Any, Any]]] = None
    # Optional: ``(params, inputs) -> loss`` computing the model's STANDARD
    # training objective end-to-end with a fused head+loss (ops/ce.py — no
    # (B,T,V) logits tensor). Executors use it in place of
    # ``loss_fn(apply_fn(...))`` only when the task's loss_fn carries a
    # ``supports_fused_head`` tag equal to ``fused_loss_objective`` — the
    # tag pairing guarantees the fused function computes exactly the task's
    # loss (custom/mismatched losses always get the logits path).
    fused_loss_fn: Optional[Callable[[Any, Any], Any]] = None
    # Same objective as ``(loss_sum, valid_count)`` — for sharded execution
    # (the data-parallel shard_map wrapper psums both parts globally before
    # dividing; per-shard means would misweight uneven mask counts).
    fused_loss_parts_fn: Optional[Callable[[Any, Any], Any]] = None
    fused_loss_objective: Optional[str] = None
    # Optional: ``(params, inputs) -> final hidden states`` (pre-head
    # forward) — lets wrappers (models/bert.py) build their own fused
    # objectives on top of this model's trunk.
    hidden_fn: Optional[Callable[[Any, Any], Any]] = None

    def abstract_init(self):
        import jax

        return jax.eval_shape(self.init_fn, jax.random.PRNGKey(0))
