"""Strategy: the (technique, sub-mesh size, params, runtime) tuple the solver picks.

TPU-native analog of the reference's ``saturn/core/representations/Strategy.py:50-76``.
Differences from the reference (intentional, idiomatic-TPU):

- The allocation unit is a **contiguous ICI sub-mesh size** (power-of-two number of
  chips of the pod slice), not a flat GPU count. The solver later picks *which*
  aligned block of that size the job runs on (buddy-style allocation preserves ICI
  contiguity on the torus).
- ``Techniques`` lists the techniques the built-in library actually ships. The
  reference declared ``MEGATRON = 4`` but never implemented it
  (``Strategy.py:34``); here tensor parallelism is a real executor.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, Optional


class Techniques(enum.Enum):
    """Built-in parallelism techniques (reference: ``Strategy.py:25-34``)."""

    DP = 1          # batch-sharded pjit over a 1-D `data` mesh axis
    FSDP = 2        # GSPMD fully-sharded params (ZeRO-3 style)
    PIPELINE = 3    # stage-sharded layers, microbatched (GPipe-style)
    OFFLOAD = 4     # host-memory param/activation offload ("spilling")
    TENSOR = 5      # Megatron-style tensor parallelism over a `model` axis
    RING = 6        # sequence/context parallelism with ring attention
    ULYSSES = 7     # sequence parallelism with all-to-all head resharding
    EXPERT = 8      # expert parallelism for mixture-of-experts models
    # Aliases matching the reference's member names (``Strategy.py:31-34``)
    # so users switching from it can keep their spelling.
    SPILLED = 4     # reference's name for offload
    MEGATRON = 5    # reference's name for tensor parallelism


@dataclass
class Strategy:
    """One profiled execution option for a task.

    Reference: ``Strategy.py:50-73`` — (executor, gpu_apportionment, params,
    runtime). Here ``apportionment`` is the number of chips in the contiguous
    sub-mesh; ``params`` are the technique's autotuned knobs returned by
    ``BaseTechnique.search``; ``runtime`` is the estimated *remaining* runtime in
    seconds for the task under this strategy (decremented by the forecast loop as
    batches complete — reference ``executor.py:165-172``).
    """

    executor: Any                      # BaseTechnique instance (or None = dummy)
    apportionment: int                 # number of chips (power of two)
    params: Optional[Dict[str, Any]]   # autotuned knobs; None = infeasible
    runtime: float                     # est. remaining runtime, seconds
    per_batch_time: float = field(default=0.0)  # seconds per batch (profiled)
    # Cost-model estimate, not a measured trial: the trial runner profiles
    # only anchor sizes and fills the rest from an Amdahl-style fit
    # (``trial_runner/evaluator.py``). Cleared the first time a realized
    # interval measurement lands on this strategy (``Task.apply_realized_feedback``).
    interpolated: bool = field(default=False)
    # Synthesized by the shardflow cold-start prior
    # (``analysis/shardflow/prior.py``): runtime comes from the static
    # roofline + communication-ledger model, not from any trial. Like
    # ``interpolated``, cleared the moment real evidence lands — a trial
    # profile replaces the strategy wholesale, and
    # ``Task.apply_realized_feedback`` clears the flag on the first realized
    # interval. Journaled as ``static_prior`` in admission/solver events so
    # plans built on untested estimates are auditable (SAT-X005).
    static_prior: bool = field(default=False)
    # Persistent profile-cache fingerprint for this (task, technique, size)
    # grid point (``utils/profile_cache.py``) — lets the orchestrator write
    # realized measurements back to the cache.
    cache_key: Optional[str] = field(default=None)
    # Fraction of a steady-state batch spent on HOST work (staging, pinned
    # host transfers) rather than device compute, in [0, 1]. Measured by the
    # trial runner (``SPMDTechnique._try_config``); the solver's co-location
    # term uses it to predict which job pairs can fill each other's bubbles
    # when their windows interleave on a shared block. 0.0 (the default, and
    # what pre-existing cache entries report) predicts no overlap win, so a
    # strategy without a measurement is never co-scheduled.
    host_fraction: float = field(default=0.0)
    # Seconds per LOCKSTEP step of a fused stack this task belongs to —
    # every member of the stack advances one batch per lockstep step — as
    # measured by the trial runner's fused-group profile
    # (``trial_runner/evaluator.profile_fused_group``). None means the fused
    # program was never profiled at this (task, size) point, and the solver
    # must not fuse on guesswork: fusion is priced strictly on measured cost
    # (``solver/milp.solve``), exactly like every other grid point. Updated
    # by realized fused-interval feedback (EWMA, the
    # ``apply_realized_feedback`` pattern) via the engine's fused launcher.
    fused_per_batch_time: Optional[float] = field(default=None)
    # Analytic schedule-bubble fraction of a steady-state step, in [0, 1):
    # device-idle time (pipeline warmup/cooldown) a co-scheduled partner's
    # device windows could fill. Recomputed from ``params`` by every install
    # path (``BaseTechnique.config_bubble_fraction``) rather than measured —
    # GPipe pays (S-1)/(M+S-1), 1F1B only (S-1)/(M+2(S-1)), and the solver's
    # co-location term adds it to ``host_fraction`` so a 1F1B job is priced
    # as the worse gap-filler partner it is.
    bubble_fraction: float = field(default=0.0)

    def __post_init__(self) -> None:
        if self.apportionment < 1:
            raise ValueError("apportionment must be a positive chip count")

    @property
    def feasible(self) -> bool:
        """Reference treats params=None as an un-runnable strategy
        (``PerformanceEvaluator.py:96-99,110``)."""
        return self.params is not None and self.executor is not None

    @property
    def technique(self) -> Optional[Techniques]:
        """Which built-in technique family this strategy uses (None for
        user-defined plugins) — plan introspection, e.g. metrics/logs."""
        return getattr(self.executor, "technique", None)
