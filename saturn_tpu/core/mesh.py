"""Contiguous ICI sub-mesh topology and allocation.

This is the TPU-native replacement for the reference's flat-GPU resource model.
The reference allocated integer GPU counts on a node (``milp.py:184-227``) and
relied on Ray's GPU bookkeeping for placement (``executor.py:59-62``). On a TPU
pod slice, the resource is a **contiguous sub-mesh**: a set of chips that are
neighbors on the ICI torus, so that XLA collectives ride ICI instead of DCN.

We model the slice as a flat ring of ``N`` devices (JAX's default device order
is a space-filling order over the physical torus, so contiguous, size-aligned
index ranges correspond to physically compact sub-slices). Allocation is
**buddy-style**: sub-mesh sizes are powers of two and a block of size ``s`` must
start at an offset that is a multiple of ``s``. This guarantees (a) two blocks
either nest or are disjoint, and (b) every block is contiguous on the ring —
which is exactly the property the MILP needs for its non-overlap constraints.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

log = logging.getLogger("saturn_tpu")


def _is_pow2(n: int) -> bool:
    return n >= 1 and (n & (n - 1)) == 0


@dataclass(frozen=True)
class Block:
    """A contiguous, size-aligned run of devices: the allocatable unit."""

    offset: int
    size: int

    def __post_init__(self) -> None:
        if not _is_pow2(self.size):
            raise ValueError(f"block size must be a power of two, got {self.size}")
        if self.offset % self.size != 0:
            raise ValueError(
                f"block offset {self.offset} not aligned to size {self.size}"
            )

    @property
    def end(self) -> int:
        return self.offset + self.size

    def overlaps(self, other: "Block") -> bool:
        return self.offset < other.end and other.offset < self.end

    def devices_of(self, devices: Sequence[Any]) -> List[Any]:
        return list(devices[self.offset : self.end])


class SliceTopology:
    """The accelerator pool the scheduler allocates from — one pod slice, or
    several slices joined by DCN (multi-host / multi-slice).

    Replaces the reference's ``ray.nodes()`` GPU discovery (``milp.py:53-62``,
    including its hardcoded ``DEBUG=True`` 8-GPUs-per-node stub — we take an
    explicit device list instead). The reference pinned every job to one node
    (``milp.py:134-137``) because its data plane was single-node NCCL; here
    the analogous *soft* constraint falls out of buddy allocation: devices
    are ordered **slice-major**, so with power-of-two slice sizes an aligned
    block of ≤ one slice never crosses a slice boundary (its collectives ride
    ICI), and only whole-multiple-of-slice blocks span DCN — at which point
    the leading (``data``) mesh axis is the one crossing DCN, the standard
    multi-slice recipe (grad all-reduce over DCN once per step).

    ``slice_size``: devices per ICI domain. Default: inferred by grouping
    ``device.process_index`` (every host drives its own slice); single-host
    device sets form one slice.
    """

    def __init__(
        self,
        devices: Optional[Sequence[Any]] = None,
        slice_size: Optional[int] = None,
    ):
        if devices is None:
            import jax

            devices = jax.devices()
        devices = list(devices)
        if slice_size is None:
            groups: dict = {}
            for d in devices:
                groups.setdefault(getattr(d, "process_index", 0), []).append(d)
            sizes = {len(g) for g in groups.values()}
            if len(groups) > 1 and len(sizes) == 1 and _is_pow2(next(iter(sizes))):
                slice_size = next(iter(sizes))
                # slice-major order: sort groups by process index
                devices = [
                    d for _, g in sorted(groups.items()) for d in g
                ]
            else:
                slice_size = len(devices)  # one ICI domain
        self.slice_size = slice_size
        self.devices: List[Any] = devices
        n = len(self.devices)
        # Usable capacity is the largest power of two <= N so buddy allocation
        # is well-formed even on odd-sized device sets (e.g. CPU test meshes).
        self.capacity = 1 << (n.bit_length() - 1)
        if self.capacity != n:
            log.warning(
                "SliceTopology: %d of %d devices stranded (buddy allocation "
                "uses the largest power-of-two capacity, %d); devices "
                "[%d:%d] will never be scheduled",
                n - self.capacity, n, self.capacity, self.capacity, n,
            )

    def signature(self) -> str:
        """Stable content signature of the accelerator pool, for the
        persistent profile cache (``utils/profile_cache.py``): per-batch
        timings measured on one topology must never be served on another —
        platform, device generation, capacity, slice boundaries and host
        count all change collective costs."""
        d0 = self.devices[0] if self.devices else None
        procs = len({getattr(d, "process_index", 0) for d in self.devices})
        return "|".join(
            str(p)
            for p in (
                len(self.devices),
                self.capacity,
                self.slice_size,
                procs,
                getattr(d0, "platform", "cpu"),
                getattr(d0, "device_kind", "unknown"),
            )
        )

    def crosses_dcn(self, block: Block) -> bool:
        """Does this block span more than one ICI slice?"""
        return (block.offset // self.slice_size) != (
            (block.end - 1) // self.slice_size
        )

    def valid_sizes(self, max_size: Optional[int] = None) -> List[int]:
        """All allocatable sub-mesh sizes: powers of two up to capacity."""
        cap = self.capacity if max_size is None else min(max_size, self.capacity)
        out, s = [], 1
        while s <= cap:
            out.append(s)
            s <<= 1
        return out

    def subset(self, indices: Sequence[int]) -> "SliceTopology":
        """A new topology over the surviving devices at ``indices`` (sorted,
        re-indexed from 0) — the elastic replanner's shrink/grow primitive
        (``resilience/replan.py``).

        Slice boundaries are preserved where they survive intact: if every
        original slice contributes the same power-of-two number of devices,
        that count is the new ``slice_size``; otherwise the survivors form
        one ICI domain (after losing part of a slice the contiguity
        guarantee is gone anyway, and collectives must be assumed to cross
        the reclaimed gap).
        """
        idx = sorted(set(indices))
        if not idx:
            raise ValueError("cannot build a topology over zero devices")
        if idx[0] < 0 or idx[-1] >= len(self.devices):
            raise ValueError(
                f"device indices {idx[0]}..{idx[-1]} out of range for "
                f"{len(self.devices)} devices"
            )
        devs = [self.devices[i] for i in idx]
        per_slice: dict = {}
        for i in idx:
            per_slice.setdefault(i // self.slice_size, []).append(i)
        sizes = {len(g) for g in per_slice.values()}
        ss = None
        if len(per_slice) > 1 and len(sizes) == 1 and _is_pow2(next(iter(sizes))):
            ss = next(iter(sizes))
        return SliceTopology(devs, slice_size=ss)

    def blocks(self, size: int) -> List[Block]:
        """All aligned blocks of a given size (the MILP's placement domain)."""
        if size not in self.valid_sizes():
            raise ValueError(f"invalid sub-mesh size {size} for capacity {self.capacity}")
        return [Block(off, size) for off in range(0, self.capacity, size)]

    def block_devices(self, block: Block) -> List[Any]:
        return block.devices_of(self.devices)


def make_submesh(
    devices: Sequence[Any],
    axis_names: Tuple[str, ...],
    axis_sizes: Optional[Tuple[int, ...]] = None,
):
    """Build a ``jax.sharding.Mesh`` over a contiguous device block.

    This is the TPU analog of the reference's NCCL process-group formation
    (``FSDP.py:44-50``): where the reference rendezvoused worker processes into
    a communicator, we reshape a contiguous device block into a logical mesh
    whose axes carry the parallelism (data / model / stage / seq).

    ``axis_sizes`` must multiply to ``len(devices)``; a single ``-1`` entry is
    inferred. Default: one axis spanning all devices.
    """
    from jax.sharding import Mesh

    devs = np.asarray(list(devices), dtype=object)
    n = devs.size
    if axis_sizes is None:
        axis_sizes = tuple([n] + [1] * (len(axis_names) - 1))
    sizes = list(axis_sizes)
    if sizes.count(-1) > 1:
        raise ValueError("at most one axis size may be -1")
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1])) if len(sizes) > 1 else 1
        if n % known != 0:
            raise ValueError(f"cannot infer axis: {n} devices not divisible by {known}")
        sizes[sizes.index(-1)] = n // known
    if int(np.prod(sizes)) != n:
        raise ValueError(f"axis sizes {sizes} do not multiply to {n} devices")
    return Mesh(devs.reshape(sizes), axis_names)
