"""Training-health guardian: numeric-fault detection and recovery policy.

Three layers, each usable alone:

- :mod:`~saturn_tpu.health.sentinel` — the numeric-health sentinel. The
  technique's interval finalization folds the interval's carried per-step
  losses through one jitted ``lax.scan`` on-device (``jnp.isfinite`` + EWMA
  spike score); the single host readback the interval already paid now
  returns the fold report instead of a bare scalar, so detection adds no
  host sync to the hot path. A non-finite or spiking loss raises a
  structured :class:`~saturn_tpu.health.sentinel.NumericFaultError`.
- :mod:`~saturn_tpu.health.guardian` — the engine-level recovery policy.
  :class:`~saturn_tpu.health.guardian.TrainingGuardian` classifies health
  faults per (task, cause), rolls the job back to its last published
  checkpoint (via the caller's ``rollback_forecast``), re-dispatches with
  exponential backoff under a per-cause retry budget distinct from both the
  preemption path and ``max_task_retries``, quarantines the offending batch
  range (a skip-list ``Task.batch_at`` / the ``DevicePrefetcher`` staging
  path honor), and detaches a repeatedly-faulting member from its
  co-schedule group. Every transition is journaled (``health_*`` records)
  so kill-replay restores quarantine state.
- the hung-dispatch watchdog (also in :mod:`guardian`) — deadlines each
  task's interval at ``floor + k x profiled window time`` and surfaces a
  :class:`~saturn_tpu.health.guardian.HungDispatchError` the guardian
  escalates timeout -> rollback -> evict.
"""

from saturn_tpu.health.guardian import (
    GuardianConfig,
    HungDispatchError,
    HEALTH_EVENT_CODES,
    TrainingGuardian,
)
from saturn_tpu.health.sentinel import (
    NumericFaultError,
    SentinelConfig,
)

__all__ = [
    "GuardianConfig",
    "HEALTH_EVENT_CODES",
    "HungDispatchError",
    "NumericFaultError",
    "SentinelConfig",
    "TrainingGuardian",
]
