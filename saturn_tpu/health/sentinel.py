"""Numeric-health sentinel: on-device loss screening for fused intervals.

The async step pipeline (``parallel/spmd_base.py``) already carries every
step's loss on-device — the old finalization read back only the LAST scalar
and let a NaN at step 3 of a 64-step interval silently poison the published
checkpoint. The sentinel folds the interval's full per-step loss vector
through one jitted ``lax.scan`` **on the device** (``jnp.isfinite`` plus an
EWMA spike score), producing a fixed-shape 6-float report; the single host
readback the interval already paid now transfers that report instead of the
bare scalar. Detection therefore costs one tiny fused program per interval
and ZERO additional host syncs on the hot path — and the report's last slot
is the interval's final loss, bit-identical to what the bare readback
returned, so enabling the sentinel never perturbs the loss trajectory.

Fault taxonomy (the ``cause`` on :class:`NumericFaultError`):

- ``nonfinite`` — any step's loss is NaN/Inf (always checked);
- ``loss_spike`` — a finite loss exceeded ``spike_factor x`` the running
  EWMA after ``warmup_steps`` folded steps (off by default:
  ``spike_factor <= 0`` disables the score — divergence thresholds are
  workload policy, non-finiteness is not).

The EWMA carry ``[ewma, steps]`` is persisted host-side between intervals
on ``task._sentinel_carry`` and only advanced when the interval was
healthy: a faulted interval's carry is discarded with the rest of its
state, so the retry folds from exactly the pre-fault statistics.
"""

from __future__ import annotations

import functools
import os
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

#: ``cause`` values (also the fold's on-device kind codes 1 / 2).
CAUSE_NONFINITE = "nonfinite"
CAUSE_SPIKE = "loss_spike"
_KIND_TO_CAUSE = {1: CAUSE_NONFINITE, 2: CAUSE_SPIKE}

#: Report vector layout (shape ``(6,)`` float32).
REP_EWMA = 0          # post-interval EWMA (healthy steps only)
REP_STEPS = 1         # total healthy steps folded, across intervals
REP_BAD_COUNT = 2     # bad steps in THIS interval
REP_FIRST_BAD = 3     # interval-relative offset of the first bad step (-1)
REP_FIRST_KIND = 4    # kind code of the first bad step (0 = none)
REP_LAST_LOSS = 5     # the interval's final loss (the old bare readback)


class NumericFaultError(RuntimeError):
    """A window's carried loss failed the sentinel's numeric screen.

    Raised from the technique's interval finalization BEFORE the
    end-of-interval checkpoint write and live-state republish — a faulted
    interval never becomes durable state, so the last published checkpoint
    stays the rollback target. Structured fields drive the guardian's
    per-cause policy and the quarantine skip-list.
    """

    def __init__(
        self,
        job: str,
        window: int,
        cause: str,
        step: Optional[int] = None,
        loss: Optional[float] = None,
        batch_indices: Tuple[int, ...] = (),
        bad_count: int = 0,
    ):
        self.job = job
        self.window = window
        self.cause = cause
        self.step = step
        self.loss = loss
        self.batch_indices = tuple(int(i) for i in batch_indices)
        self.bad_count = int(bad_count)
        super().__init__(
            f"numeric fault in job {job}: {cause} at window {window} "
            f"(interval step {step}, loss {loss!r}, "
            f"{self.bad_count} bad step(s), "
            f"dataset batches {list(self.batch_indices)})"
        )


@dataclass(frozen=True)
class SentinelConfig:
    """Sentinel policy knobs (resolved once per interval).

    ``spike_factor <= 0`` disables the EWMA spike score; non-finiteness is
    always screened while ``enabled``.
    """

    enabled: bool = True
    spike_factor: float = 0.0
    ewma_alpha: float = 0.3
    warmup_steps: int = 8

    @classmethod
    def from_env(cls) -> "SentinelConfig":
        """``SATURN_TPU_SENTINEL`` (0/off disables),
        ``SATURN_TPU_SENTINEL_SPIKE`` (factor, 0 = off),
        ``SATURN_TPU_SENTINEL_ALPHA``, ``SATURN_TPU_SENTINEL_WARMUP``."""
        raw = os.environ.get("SATURN_TPU_SENTINEL", "1").strip().lower()
        enabled = raw not in ("0", "off", "false", "no")
        return cls(
            enabled=enabled,
            spike_factor=float(
                os.environ.get("SATURN_TPU_SENTINEL_SPIKE", "0") or 0.0
            ),
            ewma_alpha=float(
                os.environ.get("SATURN_TPU_SENTINEL_ALPHA", "0.3") or 0.3
            ),
            warmup_steps=int(
                os.environ.get("SATURN_TPU_SENTINEL_WARMUP", "8") or 8
            ),
        )


_override: Optional[SentinelConfig] = None


def set_config(cfg: Optional[SentinelConfig]) -> None:
    """Process-wide override (tests / campaigns); ``None`` restores env."""
    global _override
    _override = cfg


def get_config() -> SentinelConfig:
    return _override if _override is not None else SentinelConfig.from_env()


def carry_init() -> np.ndarray:
    """Fresh EWMA carry ``[ewma, steps]``."""
    return np.zeros(2, dtype=np.float32)


@functools.lru_cache(maxsize=32)
def _fold_fn(spike_factor: float, alpha: float, warmup: int) -> Callable:
    """The jitted per-config fold. Cached per policy tuple; jax's own shape
    cache handles the per-``n`` retraces (one per distinct interval batch
    budget — the same cardinality the fused window programs already have)."""
    import jax
    import jax.numpy as jnp

    def fold(carry, losses):
        losses = losses.astype(jnp.float32)

        def step(c, x):
            ewma, steps, bad, first_off, first_kind, idx = c
            finite = jnp.isfinite(x)
            if spike_factor > 0.0:
                spike = (
                    finite
                    & (steps >= float(warmup))
                    & (ewma > 0.0)
                    & (x > spike_factor * ewma)
                )
            else:
                spike = jnp.zeros((), dtype=bool)
            kind = jnp.where(
                ~finite, jnp.float32(1.0),
                jnp.where(spike, jnp.float32(2.0), jnp.float32(0.0)),
            )
            is_bad = kind > 0.0
            is_first = jnp.logical_and(bad == 0.0, is_bad)
            first_off = jnp.where(is_first, idx, first_off)
            first_kind = jnp.where(is_first, kind, first_kind)
            bad = bad + jnp.where(is_bad, 1.0, 0.0)
            # Only healthy steps advance the running statistics: a bad step
            # must not drag the EWMA toward the value that tripped it.
            healthy = jnp.logical_not(is_bad)
            ewma = jnp.where(
                healthy,
                jnp.where(steps > 0.0, alpha * x + (1.0 - alpha) * ewma, x),
                ewma,
            )
            steps = steps + jnp.where(healthy, 1.0, 0.0)
            return (ewma, steps, bad, first_off, first_kind, idx + 1.0), None

        init = (
            carry[0], carry[1],
            jnp.float32(0.0), jnp.float32(-1.0), jnp.float32(0.0),
            jnp.float32(0.0),
        )
        (ewma, steps, bad, first_off, first_kind, _), _ = jax.lax.scan(
            step, init, losses
        )
        return jnp.stack(
            [ewma, steps, bad, first_off, first_kind, losses[-1]]
        )

    return jax.jit(fold)


def fold(carry: Any, losses: Any, cfg: SentinelConfig):
    """Run the on-device fold; returns the (6,) report as a device array.
    ``carry`` is the (2,) host/device carry, ``losses`` the interval's
    flattened per-step loss vector."""
    return _fold_fn(
        float(cfg.spike_factor), float(cfg.ewma_alpha), int(cfg.warmup_steps)
    )(carry, losses)


def inspect(report: np.ndarray) -> Optional[Tuple[str, int, int]]:
    """Host-side report decode: ``(cause, first_bad_offset, bad_count)`` on
    a fault, ``None`` when the interval is numerically healthy."""
    bad = int(report[REP_BAD_COUNT])
    if bad <= 0:
        return None
    cause = _KIND_TO_CAUSE.get(int(report[REP_FIRST_KIND]), CAUSE_NONFINITE)
    return cause, int(report[REP_FIRST_BAD]), bad


def poison_overrides(
    plan: Dict[str, Any],
    n: int,
    dataset_index_of: Callable[[int], int],
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Decode a fault injector's numeric plan into ``(positions, values)``
    to overwrite in the interval's OBSERVED loss vector.

    ``plan["steps"]`` keys interval-relative step offsets; ``plan["batches"]``
    keys dataset indices (persistent poisoning — the fault follows the batch
    through rollbacks and cursor moves, which is what makes the quarantine
    path deterministic). Injection happens at the observation level only:
    the train state itself is never corrupted, so the post-rollback retry's
    trajectory is genuinely the fault-free one.
    """
    if not plan:
        return None
    steps = plan.get("steps") or {}
    batches = plan.get("batches") or {}
    pos, vals = [], []
    for j in range(int(n)):
        v = steps.get(j)
        if v is None and batches:
            v = batches.get(dataset_index_of(j))
        if v is not None:
            pos.append(j)
            vals.append(v)
    if not pos:
        return None
    return (
        np.asarray(pos, dtype=np.int32),
        np.asarray(vals, dtype=np.float32),
    )
