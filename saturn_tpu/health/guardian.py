"""Engine-level recovery policy for health faults, plus the hung-dispatch
watchdog's error type and deadline rule.

The guardian owns the *policy* half of training health: the sentinel (and
the engine watchdog) detect, the orchestrator/service rolls back, and this
class decides what happens next — retry with exponential backoff, quarantine
the offending batch range, detach the task from its co-schedule group, or
evict. Its budgets are deliberately separate ledgers from both the
preemption path (never charged — losing chips is the fleet's fault) and
``max_task_retries`` (ordinary crashes): a job that NaNs twice and then
trains clean should neither burn its crash budget nor be whitewashed by a
preemption requeue.

Policy, per (task, cause) with CONSECUTIVE counting (a clean interval
resets the streak via :meth:`TrainingGuardian.note_success`):

1. every fault: roll back to the last published checkpoint (caller runs
   ``rollback_forecast``), then park the task for ``backoff_base * 2^(k-1)``
   intervals (capped);
2. a repeated data-cause fault (``quarantine_after``-th consecutive)
   additionally quarantines the faulting window's dataset indices — the
   cursor rolled back, so a deterministic bad batch re-faults at the same
   indices and the skip-list is exactly the fix;
3. a grouped task at ``detach_after`` faults is detached from its
   co-schedule group (the re-solve excludes it from the co-location term)
   so healthy partners keep interleaving without it;
4. past ``retry_budget`` (``hung_budget`` for hung dispatches) the task is
   evicted through the caller's failure path.

Every transition is journaled (``health_fault`` / ``health_backoff``
buffered; ``health_quarantine`` / ``health_detach`` group-commit
immediately — rare, load-bearing for kill-replay) and mirrored to metrics
with stable ``SAT-H*`` event codes (see ``docs/architecture.md`` runbook).
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass
from typing import Any, Dict, FrozenSet, Iterable, List, Optional, Tuple

from saturn_tpu.analysis import concurrency as tsan
from saturn_tpu.health.sentinel import NumericFaultError
from saturn_tpu.utils import metrics

logger = logging.getLogger("saturn_tpu")

#: Stable operator-facing event codes (``metrics`` events + runbook).
HEALTH_EVENT_CODES = {
    "numeric_fault": "SAT-H001",
    "hung_dispatch": "SAT-H002",
    "backoff": "SAT-H003",
    "quarantine": "SAT-H010",
    "unquarantine": "SAT-H011",
    "detach": "SAT-H020",
    "evict": "SAT-H030",
}


class HungDispatchError(RuntimeError):
    """A task's interval dispatch exceeded its watchdog deadline.

    Raised *on the task's behalf* by the engine's join-side watchdog (the
    launcher thread itself is wedged — that is the point); the attempt is
    abandoned, the last published checkpoint stays ground truth, and the
    guardian escalates timeout -> rollback -> evict.
    """

    def __init__(self, job: str, deadline_s: float, elapsed_s: float):
        self.job = job
        self.deadline_s = float(deadline_s)
        self.elapsed_s = float(elapsed_s)
        super().__init__(
            f"hung dispatch: job {job} exceeded its watchdog deadline "
            f"({elapsed_s:.1f}s elapsed > {deadline_s:.1f}s allowed)"
        )


CAUSE_HUNG = "hung_dispatch"


@dataclass(frozen=True)
class GuardianConfig:
    """Recovery-policy knobs.

    ``watchdog_floor_s`` is generous by default because the FIRST interval
    of a task pays XLA compilation inside its window — the deadline is
    ``floor + factor x profiled window time``, so the profiled term only
    dominates once windows are long enough for compile noise not to matter.
    """

    retry_budget: int = 3        # consecutive numeric faults before evict
    hung_budget: int = 2         # consecutive hung dispatches before evict
    quarantine_after: int = 2    # consecutive data faults before quarantine
    detach_after: int = 2        # consecutive faults before group detach
    backoff_base: int = 1        # cooldown intervals after the 1st fault
    backoff_cap: int = 8         # cooldown ceiling (intervals)
    watchdog: bool = True
    watchdog_factor: float = 8.0   # k in  k x profiled window time
    watchdog_floor_s: float = 60.0

    @classmethod
    def from_env(cls) -> "GuardianConfig":
        def _f(name: str, default: float) -> float:
            return float(os.environ.get(name, "") or default)

        return cls(
            retry_budget=int(_f("SATURN_TPU_HEALTH_RETRIES", cls.retry_budget)),
            hung_budget=int(_f("SATURN_TPU_HUNG_RETRIES", cls.hung_budget)),
            backoff_cap=int(_f("SATURN_TPU_HEALTH_BACKOFF_CAP", cls.backoff_cap)),
            watchdog=os.environ.get("SATURN_TPU_WATCHDOG", "1").strip().lower()
            not in ("0", "off", "false", "no"),
            watchdog_factor=_f("SATURN_TPU_WATCHDOG_FACTOR", cls.watchdog_factor),
            watchdog_floor_s=_f("SATURN_TPU_WATCHDOG_FLOOR_S", cls.watchdog_floor_s),
        )


@dataclass(frozen=True)
class FaultDecision:
    """What the guardian decided for one fault."""

    action: str                       # "retry" | "evict"
    cause: str
    attempt: int                      # consecutive fault count for this cause
    cooldown: int = 0                 # backoff, in intervals (retry only)
    quarantined: Tuple[int, ...] = () # dataset indices quarantined just now
    detached: bool = False            # detached from its group just now


class TrainingGuardian:
    """Per-run health policy state.

    Policy *decisions* are made from the single loop thread (orchestrator
    loop, service loop) after the engine's interval barrier; the streak /
    bench / detach ledgers are nevertheless guarded by ``_mu`` because
    read paths (``benched``, ``detached_names``) are reachable from other
    threads (status endpoints, engine launcher callbacks) and a torn
    read-modify-write of a streak counter silently mis-counts a fault.
    The lock is leaf-level: nothing is called while holding it, so it can
    never participate in a lock-order cycle."""

    def __init__(self, config: Optional[GuardianConfig] = None, journal=None):
        self.config = config if config is not None else GuardianConfig.from_env()
        self.journal = journal
        self._mu = tsan.lock("guardian.lock")
        # (task, cause) -> consecutive faults; cleared by note_success.
        self._streak: Dict[Tuple[str, str], int] = {}
        # task -> consecutive faults of ANY cause (drives group detach).
        self._total: Dict[str, int] = {}
        self._detached: set = set()
        # task -> first interval index it may run again (backoff parking).
        self._benched: Dict[str, int] = {}

    # ------------------------------------------------------- classification
    @staticmethod
    def owns(err: BaseException) -> bool:
        """Is this a health fault the guardian manages (vs an ordinary task
        failure charged to ``max_task_retries``)?"""
        return isinstance(err, (NumericFaultError, HungDispatchError))

    @staticmethod
    def cause_of(err: BaseException) -> str:
        if isinstance(err, NumericFaultError):
            return err.cause
        return CAUSE_HUNG

    @property
    def watchdog_enabled(self) -> bool:
        return self.config.watchdog

    # ------------------------------------------------------------ watchdog
    def window_deadline_s(self, expected_s: float) -> float:
        """Deadline for an interval expected to take ``expected_s`` of
        profiled window time: ``floor + factor x expected``."""
        return self.config.watchdog_floor_s + self.config.watchdog_factor * max(
            float(expected_s), 0.0
        )

    # -------------------------------------------------------------- policy
    def on_fault(
        self, task: Any, err: BaseException, interval_index: int,
        in_group: bool = False,
    ) -> FaultDecision:
        """Classify one health fault and decide retry/evict. The caller has
        already rolled the task back (release_live_state +
        ``rollback_forecast``); this only mutates policy state, the task's
        quarantine skip-list, and the journal."""
        cause = self.cause_of(err)
        key = (task.name, cause)
        with self._mu:
            streak = self._streak[key] = self._streak.get(key, 0) + 1
            total = self._total[task.name] = self._total.get(task.name, 0) + 1
        code = HEALTH_EVENT_CODES.get(
            "hung_dispatch" if cause == CAUSE_HUNG else "numeric_fault"
        )
        metrics.event(
            "health", code=code, task=task.name, cause=cause,
            attempt=streak, interval=interval_index,
        )
        self._journal(
            "health_fault", task=task.name, cause=cause, attempt=streak,
            interval=interval_index, error=repr(err),
        )

        quarantined: Tuple[int, ...] = ()
        if (
            isinstance(err, NumericFaultError)
            and err.batch_indices
            and streak >= self.config.quarantine_after
        ):
            quarantined = self.quarantine(task, err.batch_indices)

        detached = False
        if (
            in_group
            and task.name not in self._detached
            and total >= self.config.detach_after
        ):
            self.detach(task.name)
            detached = True

        budget = (
            self.config.hung_budget if cause == CAUSE_HUNG
            else self.config.retry_budget
        )
        if streak > budget:
            metrics.event(
                "health", code=HEALTH_EVENT_CODES["evict"], task=task.name,
                cause=cause, attempt=streak,
            )
            logger.error(
                "guardian: evicting %s after %d consecutive %s fault(s)",
                task.name, streak, cause,
            )
            return FaultDecision(
                "evict", cause=cause, attempt=streak,
                quarantined=quarantined, detached=detached,
            )

        cooldown = min(
            self.config.backoff_cap,
            max(1, self.config.backoff_base) * (2 ** (streak - 1)),
        )
        resume_at = interval_index + 1 + cooldown
        with self._mu:
            self._benched[task.name] = resume_at
        metrics.event(
            "health", code=HEALTH_EVENT_CODES["backoff"], task=task.name,
            cause=cause, attempt=streak, cooldown_intervals=cooldown,
        )
        self._journal(
            "health_backoff", task=task.name, cause=cause, attempt=streak,
            cooldown_intervals=cooldown,
            resume_interval=resume_at,
        )
        logger.warning(
            "guardian: %s fault #%d on %s — rolled back, retrying after "
            "%d-interval backoff%s%s",
            cause, streak, task.name, cooldown,
            f", quarantined batches {list(quarantined)}" if quarantined else "",
            ", detached from co-schedule group" if detached else "",
        )
        return FaultDecision(
            "retry", cause=cause, attempt=streak, cooldown=cooldown,
            quarantined=quarantined, detached=detached,
        )

    def note_success(self, name: str) -> None:
        """A clean interval resets the consecutive-fault ledgers (quarantine
        and detach state persist — they are corrections, not penalties)."""
        with self._mu:
            self._total.pop(name, None)
            for key in [k for k in self._streak if k[0] == name]:
                del self._streak[key]

    # ---------------------------------------------------------- quarantine
    def quarantine(self, task: Any, indices: Iterable[int]) -> Tuple[int, ...]:
        """Add dataset indices to the task's skip-list; journaled with an
        immediate group commit — a kill during the subsequent rollback must
        replay the quarantine or the restart deterministically re-faults."""
        idx = tuple(sorted({int(i) for i in indices}))
        if not idx:
            return ()
        try:
            task.quarantine_batches(idx)
        except ValueError as e:
            # The task refused (skip-listing these would empty the dataset).
            # Don't crash the recovery path: keep retrying under the budget
            # and let eviction handle a job whose every batch faults.
            logger.warning("guardian: quarantine refused for %s: %s",
                           task.name, e)
            return ()
        metrics.event(
            "health", code=HEALTH_EVENT_CODES["quarantine"], task=task.name,
            batches=list(idx),
        )
        self._journal(
            "health_quarantine", task=task.name, indices=list(idx),
            durable=True,
        )
        return idx

    def detach(self, name: str) -> None:
        """Exclude the task from co-schedule candidate generation at every
        future (re-)solve."""
        with self._mu:
            self._detached.add(name)
        metrics.event(
            "health", code=HEALTH_EVENT_CODES["detach"], task=name,
        )
        self._journal("health_detach", task=name, durable=True)

    def detached_names(self) -> FrozenSet[str]:
        with self._mu:
            return frozenset(self._detached)

    # -------------------------------------------------------------- parking
    def benched(self, name: str, interval_index: int) -> bool:
        """Is the task still inside its backoff window? Clears the bench
        entry once the resume interval is reached."""
        with self._mu:
            resume = self._benched.get(name)
            if resume is None:
                return False
            if interval_index >= resume:
                del self._benched[name]
                return False
            return True

    def resume_interval(self, name: str) -> Optional[int]:
        with self._mu:
            return self._benched.get(name)

    def unbench_all(self, cause: str = "grow") -> Tuple[str, ...]:
        """Short-circuit every remaining backoff window (grow event: fresh
        capacity should run parked work *now*, not ``ceil(backoff)``
        intervals later). The consecutive-fault streak ledgers are
        deliberately untouched — the next fault of a flaky task still sees
        its full history and backs off harder, exactly as if the bench had
        expired naturally."""
        with self._mu:
            released = tuple(sorted(self._benched))
            self._benched.clear()
        for name in released:
            metrics.event(
                "health", code=HEALTH_EVENT_CODES["backoff"], task=name,
                cause=cause, unbenched=True,
            )
        if released:
            self._journal(
                "health_unbench", tasks=list(released), cause=cause,
            )
        return released

    # ------------------------------------------------------------- recovery
    def restore(
        self,
        quarantined: Dict[str, List[int]],
        detached: Iterable[str],
        tasks: Iterable[Any] = (),
    ) -> None:
        """Re-apply journaled health state after a crash: quarantine
        skip-lists onto the rebuilt task objects, detach set onto the
        guardian. Budgets/backoff deliberately reset — an incarnation
        boundary is a clean slate for transient-fault counting."""
        by_name = {t.name: t for t in tasks}
        for name, idx in (quarantined or {}).items():
            t = by_name.get(name)
            if t is not None and idx:
                t.quarantine_batches(idx)
                logger.info(
                    "recovery: re-applied quarantine of %d batch(es) to %s",
                    len(idx), name,
                )
        with self._mu:
            self._detached.update(detached or ())

    # -------------------------------------------------------------- journal
    def _journal(self, kind: str, durable: bool = False, **data) -> None:
        jnl = self.journal
        if jnl is None:
            return
        if durable:
            jnl.log(kind, **data)
        else:
            jnl.append(kind, **data)
