"""Technique library: register / deregister / retrieve parallelism plugins.

Reference: ``saturn/library/library.py:19-73``, which dill-serialized UDP
classes to ``$SATURN_LIBRARY_PATH/<name>.udp`` so they could cross Ray worker
process boundaries by value. Our control plane is single-process (threads on
the pod host — SURVEY.md §5 "Ray is unnecessary"), so the primary registry is
an in-process dict; dill persistence to ``$SATURN_TPU_LIBRARY_PATH`` is kept as
an *optional* compatibility layer so user-defined techniques survive across
driver processes exactly as in the reference.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Type, Union

from saturn_tpu.core.strategy import Techniques
from saturn_tpu.core.technique import BaseTechnique

_REGISTRY: Dict[str, Type[BaseTechnique]] = {}

_ENV_PATH = "SATURN_TPU_LIBRARY_PATH"


def _persist_dir() -> Optional[str]:
    return os.environ.get(_ENV_PATH)


def register(name: str, technique_cls: Type[BaseTechnique]) -> None:
    """Register a technique class under ``name`` (reference ``library.py:19-35``).

    Type-checks the BaseTechnique contract like the reference's issubclass
    check (``library.py:28``); persists via dill only if the env path is set.
    """
    if not (isinstance(technique_cls, type) and issubclass(technique_cls, BaseTechnique)):
        raise TypeError(
            f"{technique_cls!r} is not a subclass of BaseTechnique; "
            "techniques must implement search() and execute()"
        )
    _REGISTRY[name] = technique_cls
    d = _persist_dir()
    if d:
        import dill

        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, f"{name}.udp"), "wb") as f:
            dill.dump(technique_cls, f)


def deregister(name: str) -> None:
    """Remove a technique (reference ``library.py:38-49``)."""
    _REGISTRY.pop(name, None)
    d = _persist_dir()
    if d:
        p = os.path.join(d, f"{name}.udp")
        if os.path.exists(p):
            os.unlink(p)


def retrieve(
    names: Union[None, str, "Techniques", List] = None,
) -> Union[Type[BaseTechnique], List[Type[BaseTechnique]]]:
    """Fetch one / several / all registered techniques (``library.py:52-73``).

    ``None`` returns all (insertion order); a string or a ``Techniques`` enum
    member returns one class; a list returns a list of classes. Falls back to
    the dill store for names not in the in-process registry.
    """
    if names is None:
        _load_persisted_missing()
        return list(_REGISTRY.values())
    if isinstance(names, (str, Techniques)):
        return _retrieve_one(names)
    return [_retrieve_one(n) for n in names]


def registered_names() -> List[str]:
    _load_persisted_missing()
    return list(_REGISTRY.keys())


def _retrieve_one(name) -> Type[BaseTechnique]:
    if isinstance(name, Techniques):
        _load_persisted_missing()
        for cls in _REGISTRY.values():
            # own attribute only: a user subclass of a builtin that doesn't
            # explicitly claim the enum member must not shadow the builtin
            # (registration order would otherwise decide which one wins)
            if cls.__dict__.get("technique") is name:
                return cls
        raise KeyError(
            f"no registered technique implements {name!r}; "
            "call register_default_library() first"
        )
    if name in _REGISTRY:
        return _REGISTRY[name]
    d = _persist_dir()
    if d:
        p = os.path.join(d, f"{name}.udp")
        if os.path.exists(p):
            import dill

            with open(p, "rb") as f:
                cls = dill.load(f)
            _REGISTRY[name] = cls
            return cls
    raise KeyError(f"no technique registered under {name!r}")


def _load_persisted_missing() -> None:
    d = _persist_dir()
    if not d or not os.path.isdir(d):
        return
    for fn in os.listdir(d):
        if fn.endswith(".udp"):
            name = fn[: -len(".udp")]
            if name not in _REGISTRY:
                try:
                    _retrieve_one(name)
                except Exception:
                    pass


def register_default_library() -> List[str]:
    """Register the built-in executors (the 'default library' the reference's
    CONTRIBUTING.md invites but never ships — SURVEY.md §1)."""
    from saturn_tpu.parallel import BUILTIN_TECHNIQUES

    for name, cls in BUILTIN_TECHNIQUES.items():
        register(name, cls)
    return list(BUILTIN_TECHNIQUES.keys())
