"""Orchestrator: the interval loop with overlapped re-solving ("introspection").

Reference: ``saturn/orchestrator.py:21-75``. Structure preserved exactly:
initial blocking solve (``:55-56``), then per interval — forecast, drop
finished tasks, kick off an **async re-solve for the next interval that
overlaps the current interval's execution** (``:69-71``), execute, join the
solve, decode. The async solver runs in a worker thread instead of a Ray
remote reserving ¼ of the node's CPUs (``:21-23``).

The reference's first solve call had a positional-arg bug (gurobi=1000,
interval=500 — ``orchestrator.py:55`` vs ``:22``; SURVEY.md §3.2 says to
replicate the intent, not the bug): here both solves use the same, correct
arguments — solver time limit = interval/2 (``:55``).
"""

from __future__ import annotations

import logging
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional

from saturn_tpu import analysis
from saturn_tpu.core.mesh import SliceTopology
from saturn_tpu.executor import engine
from saturn_tpu.solver import anytime, milp
from saturn_tpu.utils import metrics, trace

logger = logging.getLogger("saturn_tpu")


def _gate_resolved_plan(candidate, previous, topo, tasks, interval,
                        journal, interval_index):
    """Static-verification gate on a re-solved plan (compare-and-swap side).

    A candidate that fails :func:`saturn_tpu.analysis.verify_or_raise` is
    QUARANTINED — never adopted — and the orchestrator falls back to the
    previous interval's plan slid down by ``interval`` (exactly the keep
    path of ``milp.resolve``), which passed the same gate last interval.
    Only when no covering fallback exists (first plan, or new tasks the old
    plan can't place) does the failure propagate.

    Deterministic: multihost ranks gate the identical broadcast payload and
    reach the identical adopt/quarantine decision.
    """
    try:
        analysis.verify_or_raise(candidate, topology=topo, tasks=tasks,
                                 source="re-solve")
        return candidate
    except analysis.PlanVerificationError as e:
        codes = sorted({d.code for d in e.report.errors})
        logger.error("re-solve plan quarantined (%s): %s", codes, e)
        metrics.event("plan_quarantine", source="re-solve", codes=codes)
        if journal is not None:
            journal.append("plan_quarantine", interval=interval_index + 1,
                           source="re-solve", codes=codes)
        cur = {t.name for t in tasks}
        if previous is None or (cur - set(previous.assignments)):
            raise  # no covering fallback — refuse loudly, don't launch it
        slid = milp.Plan(
            assignments={
                n: milp.Assignment(a.apportionment, a.block,
                                   max(0.0, a.start - interval), a.runtime)
                for n, a in previous.assignments.items() if n in cur
            },
            makespan=max(0.0, previous.makespan - interval),
            coschedule=[
                kept for grp in previous.coschedule
                if len(kept := [n for n in grp if n in cur]) >= 2
            ],
        )
        slid.compute_dependencies()
        return slid


def orchestrate(
    task_list: List,
    log: bool = False,
    interval: float = 1000.0,
    topology: Optional[SliceTopology] = None,
    threshold: float = 0.0,
    solver_time_limit: Optional[float] = None,
    failure_policy: str = "raise",
    max_task_retries: int = 1,
    metrics_path: Optional[str] = None,
    trace_dir: Optional[str] = None,
    fault_injector=None,
    health_monitor=None,
    recovery_policy: str = "pause-resolve-resume",
    replan_degrade_factor: float = 2.0,
    resume_dir: Optional[str] = None,
    health_guardian=None,
    crash_barrier=None,
) -> dict:
    """Run every task to completion, minimizing batch makespan.

    ``interval``: seconds of execution per scheduling round (reference default
    1000, ``orchestrator.py:32``). ``threshold``: makespan improvement needed
    to adopt a re-solved plan (``milp.py:376-379``). ``failure_policy``:
    ``"raise"`` (reference crash-the-batch semantics), ``"drop"`` (evict the
    failed task, keep the rest running), or ``"retry"`` (keep the failed task
    in the batch for up to ``max_task_retries`` more attempts — it resumes
    from its last checkpoint at the next interval — then evict like
    ``"drop"``). ``metrics_path`` appends JSONL events (``utils/metrics.py``);
    ``trace_dir`` wraps the run in a jax.profiler trace.

    Elasticity (``saturn_tpu.resilience``): passing ``health_monitor`` (a
    ``FleetHealthMonitor``) — or a ``fault_injector`` / setting
    ``SATURN_TPU_FAULTS`` — turns the fixed-topology loop elastic. Each
    interval starts with a health poll; on a shrink/grow
    ``TopologyChange`` the ``ElasticReplanner`` rebuilds topology + plan
    over the surviving mesh under ``recovery_policy``
    (``resilience.RECOVERY_POLICIES``). Mid-interval device loss
    aborts-and-requeues the affected tasks (``PreemptedError`` — requeued
    WITHOUT counting against ``max_task_retries``); migrated tasks resume
    from their checkpoints on the new mesh. Single-host only.

    Durability (``saturn_tpu.durability``): ``resume_dir`` points the run at
    a write-ahead journal directory. Every interval's realized iterations,
    plan commits, completions/failures and checkpoint publications are
    group-committed there; re-running ``orchestrate(resume_dir=...)`` after
    a crash replays the journal (torn trailing records are quarantined and
    rolled back to the last durable cut), drops journaled-completed tasks,
    subtracts durably realized batches from each survivor's budget, and
    resumes — no durably completed iteration re-runs. Single-host only.

    Training health (``saturn_tpu.health``): a ``TrainingGuardian`` is
    active by default on single-host runs — the sentinel screens every
    interval's losses on-device, the engine watchdog deadlines every
    launcher, and a health fault rolls the task back to its last published
    checkpoint and retries under exponential backoff (quarantining repeat
    bad batches, detaching repeat offenders from co-schedule groups,
    evicting past the per-cause budget). Pass ``health_guardian=False`` to
    disable, or your own ``TrainingGuardian`` to customize policy. Health
    transitions are journaled when ``resume_dir`` is set, so kill-replay
    restores quarantine state. ``crash_barrier`` (a
    ``resilience.CrashInjector``) is test-only: it threads kill points into
    the journal and the health recovery path.

    Returns ``{"completed": [names], "failed": {name: error string}}``.
    """
    if log:
        logging.basicConfig(level=logging.INFO)
    if failure_policy not in ("raise", "drop", "retry"):
        raise ValueError(
            f"failure_policy must be 'raise', 'drop' or 'retry', got {failure_policy!r}"
        )
    from saturn_tpu.core import distributed

    if distributed.is_multihost() and failure_policy != "raise":
        # drop/retry mutate the task set from a per-rank error view; until
        # errors are all-gathered, divergent task lists would interleave
        # collective programs differently per process (multi-controller
        # deadlock). A failed rank aborts the cluster through the jax
        # coordination service instead.
        raise ValueError(
            "multi-host orchestration supports failure_policy='raise' only"
        )
    topo = topology if topology is not None else SliceTopology()

    if fault_injector is None:
        from saturn_tpu.resilience.faults import FaultInjector

        fault_injector = FaultInjector.from_env()
    if fault_injector is not None and health_monitor is None:
        from saturn_tpu.resilience.health import FleetHealthMonitor

        health_monitor = FleetHealthMonitor.for_topology(topo)
    replanner = None
    if health_monitor is not None:
        if distributed.is_multihost():
            # Elastic recovery mutates topology/plan from one process's
            # health view; until changes are broadcast like plans are, a
            # divergent topology means divergent collective programs.
            raise ValueError(
                "elastic resilience (health_monitor/fault_injector) is "
                "single-host only"
            )
        from saturn_tpu.resilience.replan import ElasticReplanner

        replanner = ElasticReplanner(
            policy=recovery_policy, degrade_factor=replan_degrade_factor
        )
    names = [t.name for t in task_list]
    if len(set(names)) != len(names):
        dupes = sorted({n for n in names if names.count(n) > 1})
        raise ValueError(
            f"duplicate task names {dupes}: every subsystem (plan, engine, "
            "checkpoints) keys on task.name — give tasks unique names"
        )
    for t in task_list:
        if not t.feasible_strategies():
            raise ValueError(
                f"task {t.name} has no profiled strategies — run saturn_tpu.search first"
            )
    tlimit = solver_time_limit if solver_time_limit is not None else interval / 2

    task_list = list(task_list)
    all_completed: List[str] = []
    all_failed: dict = {}
    retries: dict = {}  # task name -> failed attempts so far

    journal = None
    ckpt_hook = None
    recovered_state = None
    if crash_barrier is not None and resume_dir is None:
        raise ValueError("crash_barrier requires resume_dir (it instruments "
                         "the durability journal)")
    if resume_dir is not None:
        if distributed.is_multihost():
            raise ValueError(
                "resume_dir (crash-safe durability) is single-host only — "
                "multi-controller journal consensus is future work"
            )
        from saturn_tpu.durability import journal as jmod
        from saturn_tpu.durability import recovery as rmod
        from saturn_tpu.utils import checkpoint as _ckpt

        journal = jmod.Journal(resume_dir, barrier=crash_barrier)  # recovers torn tails on open
        state = recovered_state = rmod.replay_batch_state(resume_dir)
        if state.plan:
            # Journal-replay audit: the orchestrator always re-solves on
            # resume, but a committed plan the static verifier rejects
            # means the pre-crash process launched (or was about to launch)
            # a corrupt schedule — quarantine it on the record so the
            # incident is durable and debuggable.
            try:
                replayed_report = analysis.verify_plan(
                    milp.Plan.from_json(state.plan), subject="journal-replay"
                )
            except Exception as e:
                replayed_report = None
                logger.warning("replayed plan_commit undecodable: %s", e)
            if replayed_report is not None and not replayed_report.ok:
                codes = sorted({d.code for d in replayed_report.errors})
                logger.warning(
                    "journal's committed plan fails static verification "
                    "(%s) — quarantined; resuming from a fresh solve", codes,
                )
                journal.log("plan_quarantine", source="journal-replay",
                            codes=codes)
        if state.checkpoints:
            rmod.reconcile_checkpoints(state.checkpoints)
        task_list = _fold_batch_recovery(
            task_list, state, all_completed, all_failed
        )
        journal.log(
            "recovery", replayed_seq=state.last_seq,
            replayed_records=state.n_records,
            completed=len(all_completed), remaining=len(task_list),
        )

        def ckpt_hook(task_name, path):
            journal.append("ckpt_published", task=task_name, path=path)

        _ckpt.add_publish_hook(ckpt_hook)

    # Training-health guardian: on by default single-host. ``False``
    # disables; a caller-supplied guardian is adopted as-is (its journal is
    # wired up if it has none and this run is durable).
    guardian = None
    if health_guardian is not False and not distributed.is_multihost():
        from saturn_tpu.health import TrainingGuardian

        guardian = (
            health_guardian if health_guardian is not None
            else TrainingGuardian(journal=journal)
        )
        if guardian.journal is None and journal is not None:
            guardian.journal = journal
        if recovered_state is not None:
            # Kill-replay: re-apply journaled quarantine skip-lists and
            # co-schedule detachments to the rebuilt tasks.
            guardian.restore(
                getattr(recovered_state, "quarantined", {}) or {},
                getattr(recovered_state, "detached", ()) or (),
                task_list,
            )

    try:
        return _orchestrate_loop(
            task_list, topo, interval, threshold, tlimit, failure_policy,
            max_task_retries, metrics_path, trace_dir,
            all_completed, all_failed, retries,
            health_monitor, fault_injector, replanner, journal,
            guardian,
        )
    finally:
        import sys

        from saturn_tpu.resilience.crash import SimulatedKill
        from saturn_tpu.utils import checkpoint as ckpt

        if ckpt_hook is not None:
            ckpt.remove_publish_hook(ckpt_hook)
        # A simulated SIGKILL runs no handlers: no checkpoint flush, no
        # journal flush/close — buffered records die with the "process",
        # exactly like the service loop's kill path. Recovery is the next
        # incarnation's problem (that is the point).
        if not isinstance(sys.exc_info()[1], SimulatedKill):
            try:
                # join outstanding async checkpoint writes on EVERY other
                # exit path — a caller catching a failure must still see
                # landed checkpoints
                ckpt.flush()
            except Exception:
                if sys.exc_info()[1] is None:
                    raise  # clean exit: surface the write failure
                logger.exception(
                    "async checkpoint flush failed during error unwind"
                )
            if journal is not None:
                # Buffered records describe work that really happened
                # (task_progress only fires post-success), so committing
                # them on an error unwind is correct; a hard crash skips
                # this and loses only re-runnable work.
                try:
                    journal.close()
                except Exception:
                    logger.exception("journal close failed during unwind")


def _fold_batch_recovery(task_list, state, all_completed, all_failed) -> List:
    """Apply replayed journal state to a fresh task list: journaled-terminal
    tasks never re-run, and durably realized batches come off each
    survivor's budget (strategy runtimes re-derived from per-batch
    profiles). The journal is authoritative — it only records iterations
    that actually executed."""
    out = []
    for t in task_list:
        if t.name in state.completed:
            all_completed.append(t.name)
            logger.info("resume: %s already completed durably — skipping",
                        t.name)
            continue
        if t.name in state.failed:
            all_failed[t.name] = state.failed[t.name]
            logger.info("resume: %s failed durably — not retrying", t.name)
            continue
        realized = state.progress.get(t.name, 0)
        if realized > 0:
            t.total_batches = max(0, t.total_batches - realized)
            for s in t.strategies.values():
                if s.feasible:
                    s.runtime = s.per_batch_time * t.total_batches
            logger.info(
                "resume: %s has %d durably realized batch(es) — %d remain",
                t.name, realized, t.total_batches,
            )
            if t.total_batches <= 0:
                all_completed.append(t.name)
                continue
        out.append(t)
    return out


def _persist_realized(task) -> None:
    """Write the task's freshly measured per-batch time back to the
    persistent profile cache (``utils/profile_cache.py``).

    This is what upgrades interpolated trial-sweep entries to measured ones
    *across processes*: the in-process upgrade happens in
    ``Task.apply_realized_feedback`` (flag cleared, EWMA folded in), and this
    write makes the next driver's ``search()`` start from realized numbers
    instead of solo-trial or cost-model estimates. Only the self-measured
    strategy is persisted — sibling ratio corrections are derived, not
    evidence."""
    strat = getattr(task, "last_feedback_strategy", None)
    key = getattr(strat, "cache_key", None) if strat is not None else None
    if not key or not strat.feasible:
        return
    from saturn_tpu.utils import profile_cache as pcache

    cache = pcache.default_cache()
    if cache is None:
        return
    try:
        wrote = cache.note_realized(
            key, strat.per_batch_time, strat.params,
            technique=getattr(strat.executor, "name", "unknown"),
            size=strat.apportionment,
        )
        if wrote:
            metrics.event(
                "profile_cache", op="realized_writeback", task=task.name,
                size=strat.apportionment, per_batch_s=strat.per_batch_time,
            )
    except Exception:
        logger.debug("profile cache write-back failed for %s", task.name,
                     exc_info=True)


def fold_realized_feedback(run_tasks) -> dict:
    """Fold each executed task's realized per-batch time into its strategy
    (EWMA via ``Task.apply_realized_feedback``) and persist the measured
    number to the profile cache. Returns ``{name: (old, new)}`` for the tasks
    that produced an update. Call only while no solver thread is reading
    strategy state. Shared by the interval loop and the online job service."""
    updates = {}
    for t in run_tasks:
        apply_fb = getattr(t, "apply_realized_feedback", None)
        upd = apply_fb() if apply_fb is not None else None
        if upd is not None:
            updates[t.name] = upd
            _persist_realized(t)
    return updates


def _fusion_proposals(tasks) -> Optional[List[List[str]]]:
    """Candidate fusion groups for a solve call (same-fingerprint task
    names, ``parallel/fused.fusion_candidates``). Proposing is free: only
    groups whose members all carry a measured ``fused_per_batch_time`` can
    win the pricing (``milp.fusion_priced_groups`` refuses guesswork), so
    an unprofiled sweep degrades to exactly the pre-fusion plan. Fail open
    on any trouble — fusion is an optimization, never a launch blocker."""
    try:
        from saturn_tpu.parallel import fused as _fused

        return _fused.fusion_candidates(tasks) or None
    except Exception:
        logger.exception("fusion candidate proposal failed (fail-open)")
        return None


def _memlens_fusion_gate(topo):
    """Adapt memlens' stacked-residency pass to the solver's
    ``fusion_fits(member_tasks, size, n_members)`` contract: an explicit
    False (the ×N stacked params would blow past the OOM margin) vetoes
    that size before any compile; None (analyzer unavailable, capacity
    unknown, untraceable config) never prunes — the zero-compile
    feasibility-prior contract."""
    def fits(member_tasks, size, n_members):
        try:
            from saturn_tpu.analysis.memlens import passes as ml_passes

            rep = member_tasks[0]
            strat = rep.feasible_strategies().get(size)
            if strat is None or strat.executor is None:
                return None
            blocks = topo.blocks(size)
            if not blocks:
                return None
            return ml_passes.fused_stack_fits(
                strat.executor, rep, topo.block_devices(blocks[0]),
                n_members, config=strat.params or None,
            )
        except Exception:
            return None

    return fits


def _handle_topology_change(
    task_list, base_topo, health, replanner, change, plan, tlimit,
    all_failed,
):
    """Pre-interval elastic hook: rebuild topology + plan over the monitor's
    surviving device set, evict the unschedulable, release migrated tasks'
    live device state so their next interval restores from checkpoint on
    the new mesh (cross-mesh migration, ``utils/checkpoint.py``)."""
    import timeit as _timeit

    t_detect = _timeit.default_timer()
    metrics.event("topology_change", **change.to_fields())
    logger.warning(
        "topology change (%s): lost=%s gained=%s stragglers=%s — replanning",
        change.kind, change.lost, change.gained, change.stragglers,
    )
    result = replanner.replan(
        task_list, base_topo, health.alive_indices(), change,
        previous_plan=plan, time_limit=tlimit,
    )
    evicted = set(result.evicted)
    for name in sorted(evicted):
        all_failed[name] = f"evicted on topology change ({change.kind})"
        metrics.event("task_failed", task=name,
                      error=f"evicted on topology change ({change.kind})")
    by_name = {t.name: t for t in task_list}
    for name, d in sorted(result.migrations.items()):
        if not d["moved"] or name in evicted:
            continue
        t = by_name.get(name)
        if t is not None:
            release = getattr(t, "release_live_state", None)
            if release is not None:
                release()  # next interval restores from ckpt on the new mesh
        metrics.event("migration", task=name, moved_from=d["from"],
                      moved_to=d["to"])
    task_list = [t for t in task_list if t.name not in evicted]
    metrics.event(
        "recovery", policy=replanner.policy,
        replan_latency_s=_timeit.default_timer() - t_detect,
        capacity=result.topology.capacity, n_tasks=len(task_list),
    )
    # Mandatory adoption gate (migration path): the replanner's plan targets
    # a topology the running plan never saw — verify it against the NEW
    # slice before any task is migrated onto it. There is no covering
    # fallback plan on a changed topology, so a failure propagates.
    analysis.verify_or_raise(result.plan, topology=result.topology,
                             tasks=task_list, source="migration-replan")
    return task_list, result.topology, result.plan


def _orchestrate_loop(
    task_list, topo, interval, threshold, tlimit, failure_policy,
    max_task_retries, metrics_path, trace_dir,
    all_completed, all_failed, retries,
    health=None, faults=None, replanner=None, journal=None,
    guardian=None,
) -> dict:
    from saturn_tpu.core import distributed
    from saturn_tpu.resilience.faults import PreemptedError

    multihost = distributed.is_multihost()
    if multihost and not distributed.is_coordinator():
        # One writer per metrics file: every rank appending the same JSONL
        # on shared storage would duplicate each event N-fold (and NFS
        # O_APPEND interleaving is not line-atomic).
        metrics_path = None
    if not task_list:
        # Nothing left to run — e.g. a resumed batch whose journal already
        # records every task terminal (restart after a crash-after-finish).
        logger.info("orchestration complete (%d completed, %d failed)",
                    len(all_completed), len(all_failed))
        return {"completed": all_completed, "failed": all_failed}
    with metrics.scoped(metrics_path), trace.profile_trace(trace_dir):
        if multihost:
            # Profile sync BEFORE the first forecast: per-process wall-clock
            # profiling yields slightly different per-batch times, and
            # forecast budgets derived from divergent numbers mean divergent
            # collective program counts (multi-controller deadlock). The
            # coordinator's trial numbers win here; per-interval syncs below
            # use each task's executing rank.
            distributed.sync_task_state(task_list)
        # Multi-host: ONLY the coordinator solves (a time-limited HiGHS run
        # is not deterministic across processes); every rank executes the
        # same broadcast plan. Single-host: unchanged.
        if not multihost or distributed.is_coordinator():
            # Initial blocking solve through the anytime tier ladder: a
            # small batch degenerates to the exact MILP (single-partition
            # tier 1); a big queue lands inside tlimit via the cheaper
            # tiers instead of blowing the first interval.
            plan = anytime.anytime_resolve(
                task_list, topo, None, interval, deadline=tlimit,
                source="orchestrator-initial",
                fusion=_fusion_proposals(task_list),
                fusion_fits=_memlens_fusion_gate(topo),
            )
        else:
            plan = None
        if multihost:
            plan = milp.Plan.from_json(
                distributed.broadcast_json(plan.to_json() if plan else None)
            )
        # Mandatory adoption gate (fresh-solve path): a malformed initial
        # plan fails HERE, with structured diagnostics, not at gang launch.
        analysis.verify_or_raise(plan, topology=topo, tasks=task_list,
                                 source="fresh-solve")
        logger.info("initial plan: makespan %.1fs, %d tasks", plan.makespan, len(task_list))
        metrics.event("solve", makespan_s=plan.makespan, n_tasks=len(task_list))
        if journal is not None:
            journal.append("plan_commit", interval=0,
                           makespan=plan.makespan, plan=plan.to_json())

        on_done = None
        if journal is not None:
            def on_done(name, n):  # buffered; durable at interval end
                if n > 0:
                    journal.append("task_progress", task=name,
                                   batches=int(n))

        base_topo = topo  # health-monitor indices refer to the pre-fault fleet
        interval_index = 0
        # Tasks parked by the guardian's exponential backoff: out of the
        # forecast/re-solve set entirely until their resume interval.
        parked: List = []
        with ThreadPoolExecutor(max_workers=1, thread_name_prefix="solver") as pool:
            while task_list or parked:
                if parked:
                    back = [
                        t for t in parked
                        if guardian is None
                        or not guardian.benched(t.name, interval_index)
                    ]
                    if back:
                        names_back = {t.name for t in back}
                        parked = [
                            t for t in parked if t.name not in names_back
                        ]
                        task_list.extend(back)
                        logger.info(
                            "guardian: backoff expired for %s — re-admitted",
                            sorted(names_back),
                        )
                if not task_list:
                    # Everyone is benched: burn an idle interval so the
                    # backoff clock advances.
                    interval_index += 1
                    continue
                if health is not None:
                    # Pre-interval health poll (elastic hook point): apply
                    # scheduled interval-start faults, then consume at most
                    # one aggregated TopologyChange into a replan.
                    if faults is not None:
                        faults.apply_due(interval_index, health)
                    change = health.poll()
                    if change is not None and change.kind in ("shrink", "grow"):
                        if change.kind == "grow" and journal is not None:
                            journal.log(
                                "grow_event", interval=interval_index,
                                gained=list(change.gained),
                                cause=change.cause,
                                n_parked=len(parked),
                                capacity=base_topo.capacity,
                            )
                        if change.kind == "grow" and parked:
                            # Elastic scale-up: fresh capacity runs parked
                            # work NOW — short-circuit remaining backoff
                            # (streak ledgers untouched) and fold the parked
                            # tasks into the replan set so the grow re-solve
                            # covers live ∪ parked.
                            if guardian is not None:
                                guardian.unbench_all(cause="grow")
                            names_back = sorted(t.name for t in parked)
                            task_list.extend(parked)
                            parked = []
                            if journal is not None:
                                # log, not append: durable alongside the
                                # grow_event so a crash cannot drop the
                                # drain attribution record.
                                journal.log(
                                    "backlog_drain",
                                    interval=interval_index,
                                    jobs=names_back, trigger="grow",
                                )
                            metrics.event(
                                "backlog_drain", interval=interval_index,
                                jobs=names_back, trigger="grow",
                            )
                            logger.info(
                                "grow: re-admitted parked %s ahead of "
                                "backoff", names_back,
                            )
                        task_list, topo, plan = _handle_topology_change(
                            task_list, base_topo, health, replanner, change,
                            plan, tlimit, all_failed,
                        )
                        if not task_list:
                            break
                    elif change is not None:  # degrade: advisory, no replan
                        metrics.event("topology_change", **change.to_fields())
                        logger.warning(
                            "degraded fleet: stragglers %s (policy %s keeps "
                            "running)", change.stragglers, replanner.policy,
                        )
                run_tasks, batches, completed = engine.forecast(task_list, interval, plan)
                remaining = [t for t in task_list if t not in completed]

                future = None
                if remaining and (not multihost or distributed.is_coordinator()):
                    # overlap next-interval solve with this interval's execution
                    # (``orchestrator.py:69-71``)
                    future = pool.submit(
                        anytime.anytime_resolve, remaining, topo, plan,
                        interval, threshold, deadline=tlimit,
                        coschedule_exclude=(
                            guardian.detached_names() if guardian is not None
                            else None
                        ),
                        source="orchestrator",
                        fusion=_fusion_proposals(remaining),
                        fusion_exclude=(
                            guardian.detached_names() if guardian is not None
                            else None
                        ),
                        fusion_fits=_memlens_fusion_gate(topo),
                    )

                # Snapshot the EXECUTED plan's assignments before the
                # re-solve broadcast replaces `plan`: feedback source ranks
                # must name the rank that actually ran each task, not where
                # the next plan happens to move it.
                executed_assignments = {
                    t.name: plan.assignments.get(t.name) for t in run_tasks
                }
                errors: dict = {}
                if run_tasks:
                    errors = engine.execute(
                        run_tasks, batches, interval, plan, topo,
                        failure_policy="raise" if failure_policy == "raise" else "drop",
                        health=health, faults=faults,
                        interval_index=interval_index,
                        on_task_done=on_done,
                        guardian=guardian,
                    )
                    if guardian is not None:
                        # Consecutive-fault streaks reset on a clean interval
                        # (quarantine/detach state persists — corrections,
                        # not penalties).
                        for t in run_tasks:
                            if t.name not in errors:
                                guardian.note_success(t.name)
                    if journal is not None:
                        journal.barrier("mid-interval",
                                        interval=interval_index)
                elif remaining:
                    # nothing scheduled inside this interval (all starts beyond
                    # it): the slide in resolve() brings work forward next round.
                    logger.info("idle interval: no task starts within %.1fs", interval)

                if multihost and remaining:
                    # Every rank must reach this broadcast; the coordinator
                    # contributes its joined re-solve. A coordinator-side
                    # solve failure must still be broadcast — as an error
                    # sentinel every rank raises on — or the other ranks
                    # block inside broadcast_json until the distributed
                    # failure detector fires (opaque cluster hang; same
                    # fail-fast rationale as engine._execute_multihost).
                    new_plan = None
                    if future is not None:
                        try:
                            new_plan = future.result().to_json()
                        except Exception as e:
                            new_plan = {
                                "__solve_error__": f"{type(e).__name__}: {e}"
                            }
                    future = None
                    payload = distributed.broadcast_json(new_plan)
                    if isinstance(payload, dict) and "__solve_error__" in payload:
                        raise RuntimeError(
                            "re-solve failed on coordinator: "
                            + payload["__solve_error__"]
                        )
                    plan = _gate_resolved_plan(
                        milp.Plan.from_json(payload), plan, topo, remaining,
                        interval, None, interval_index,
                    )
                    logger.info("re-solve: makespan %.1fs", plan.makespan)
                    metrics.event("solve", makespan_s=plan.makespan,
                                  n_tasks=len(remaining))
                elif future is not None:
                    # Join the overlapped solve BEFORE the failure handling
                    # below mutates Task/Strategy state the solver thread
                    # reads (retry rollback rewrites strategy runtimes).
                    plan = _gate_resolved_plan(
                        future.result(), plan, topo, remaining, interval,
                        journal, interval_index,
                    )
                    future = None
                    # Evictions happen after the solve was submitted: the
                    # plan may still cover dropped tasks; their slots simply
                    # idle for one interval and vanish at the next re-solve.
                    logger.info("re-solve: makespan %.1fs", plan.makespan)
                    metrics.event("solve", makespan_s=plan.makespan,
                                  n_tasks=len(remaining))
                    if journal is not None:
                        journal.append("plan_commit",
                                       interval=interval_index + 1,
                                       makespan=plan.makespan,
                                       plan=plan.to_json())

                # Estimate feedback: fold each task's realized per-batch time
                # into its executed strategy (EWMA) now that no solver thread
                # is reading strategy state; the NEXT re-solve and forecast
                # consume the corrected numbers. The reference only logged
                # this error (``executor.py:126-129``).
                local_updates = fold_realized_feedback(run_tasks)
                all_updates = local_updates
                if multihost and run_tasks:
                    # All ranks must forecast from identical numbers. Each
                    # task's numbers come from the rank that actually ran it
                    # (the lowest process of its EXECUTED block) —
                    # broadcasting the coordinator's view would throw away
                    # realized-feedback corrections for tasks on other
                    # hosts' blocks forever. The merged update map rides the
                    # same broadcast so the coordinator (sole metrics
                    # writer) records corrections made on other hosts.
                    src = {}
                    for t in run_tasks:
                        a = executed_assignments.get(t.name)
                        if a is not None:
                            devs = topo.block_devices(a.block)
                            src[t.name] = min(
                                getattr(d, "process_index", 0) for d in devs
                            )
                    all_updates = distributed.sync_task_state(
                        run_tasks, src, local_updates
                    )
                for name, (old, new) in sorted(all_updates.items()):
                    metrics.event(
                        "estimate_update", task=name,
                        profiled_s=round(old, 6), updated_s=round(new, 6),
                    )
                    if abs(new - old) > 0.25 * max(old, 1e-9):
                        logger.info(
                            "estimate correction for %s: %.3fs -> %.3fs "
                            "per batch", name, old, new,
                        )

                preempted = {
                    n: e for n, e in errors.items()
                    if isinstance(e, PreemptedError)
                }
                if preempted:
                    # Abort-and-requeue: preemption is the fleet's fault, not
                    # the task's — roll back forecast's accounting and requeue
                    # WITHOUT counting against max_task_retries; the next
                    # loop-top health poll replans onto the surviving mesh
                    # and the task resumes from its checkpoint there.
                    errors = {
                        n: e for n, e in errors.items() if n not in preempted
                    }
                    by_name = {t.name: t for t in run_tasks}
                    for name, err in sorted(preempted.items()):
                        t = by_name[name]
                        release = getattr(t, "release_live_state", None)
                        if release is not None:
                            release()  # device state died with the chips
                        engine.rollback_forecast(t, batches.get(name, 0))
                        metrics.event("task_preempted", task=name,
                                      error=repr(err))
                        logger.warning(
                            "task %s preempted — requeued for replan: %r",
                            name, err,
                        )
                        if t not in remaining:
                            remaining.append(t)  # was forecast-completed
                    completed = [
                        t for t in completed if t.name not in preempted
                    ]

                health_errs = (
                    {n: e for n, e in errors.items() if guardian.owns(e)}
                    if guardian is not None and errors else {}
                )
                if health_errs:
                    # Guardian path: rollback to the last published
                    # checkpoint + backoff/quarantine/detach/evict — a ledger
                    # separate from both preemption and max_task_retries.
                    errors = {
                        n: e for n, e in errors.items()
                        if n not in health_errs
                    }
                    by_name = {t.name: t for t in run_tasks}
                    group_of = plan.coschedule_group_of()
                    for name, err in sorted(health_errs.items()):
                        t = by_name[name]
                        release = getattr(t, "release_live_state", None)
                        if release is not None:
                            release()  # poisoned/hung device state is dead
                        engine.rollback_forecast(t, batches.get(name, 0))
                        decision = guardian.on_fault(
                            t, err, interval_index,
                            in_group=name in group_of,
                        )
                        if journal is not None:
                            # Kill point: quarantine/detach records are
                            # already durable (guardian journals them with
                            # an immediate commit) — a kill here must replay
                            # them on restart.
                            journal.barrier("post-rollback", task=name,
                                            interval=interval_index)
                        if decision.action == "retry":
                            parked.append(t)
                            logger.warning(
                                "task %s health fault (%s, attempt %d) — "
                                "rolled back, parked for %d interval(s)",
                                name, decision.cause, decision.attempt,
                                decision.cooldown,
                            )
                        else:
                            all_failed[name] = repr(err)
                            if journal is not None:
                                journal.append("task_failed", task=name,
                                               error=repr(err))
                            metrics.event("task_failed", task=name,
                                          error=repr(err))
                            logger.error(
                                "evicting task %s after exhausted health "
                                "retry budget: %r", name, err,
                            )
                            release_c = getattr(t, "release_compiled", None)
                            if release_c is not None:
                                release_c()
                    remaining = [
                        t for t in remaining if t.name not in health_errs
                    ]
                    completed = [
                        t for t in completed if t.name not in health_errs
                    ]

                if errors:  # "drop": evict failed tasks; "retry": give them
                    # max_task_retries more intervals first
                    by_name = {t.name: t for t in run_tasks}
                    retried: List = []
                    for name, err in errors.items():
                        t = by_name[name]
                        release = getattr(t, "release_live_state", None)
                        if release is not None:
                            release()  # free HBM before the block is reused
                        retries[name] = retries.get(name, 0) + 1
                        if (
                            failure_policy == "retry"
                            and retries[name] <= max_task_retries
                        ):
                            # Roll back forecast's optimistic accounting: the
                            # batches it pre-deducted never ran (the checkpoint
                            # is the ground truth the retry resumes from).
                            engine.rollback_forecast(t, batches.get(name, 0))
                            retried.append(t)
                            metrics.event("task_retry", task=name,
                                          attempt=retries[name], error=repr(err))
                            logger.warning(
                                "task %s failed (attempt %d/%d) — retrying "
                                "next interval from its last checkpoint: %r",
                                name, retries[name], max_task_retries + 1, err,
                            )
                        else:
                            all_failed[name] = repr(err)
                            if journal is not None:
                                journal.append("task_failed", task=name,
                                               error=repr(err))
                            metrics.event("task_failed", task=name, error=repr(err))
                            logger.warning("evicting failed task %s: %r", name, err)
                            # permanently dropped: also free its compiled
                            # programs (a retried task keeps them — recompiling
                            # an identical program is the cost the cache avoids)
                            release_c = getattr(t, "release_compiled", None)
                            if release_c is not None:
                                release_c()
                    keep = {t.name for t in retried}
                    remaining = [
                        t for t in remaining
                        if t.name not in errors or t.name in keep
                    ]
                    for t in retried:
                        if t not in remaining:
                            remaining.append(t)  # was forecast-completed
                    completed = [t for t in completed if t.name not in errors]

                for t in completed:
                    all_completed.append(t.name)
                    if journal is not None:
                        journal.append("task_completed", task=t.name)
                    metrics.event("task_completed", task=t.name)
                    release = getattr(t, "release_live_state", None)
                    if release is not None:
                        release()  # free HBM held by finished tasks
                    release_c = getattr(t, "release_compiled", None)
                    if release_c is not None:
                        release_c()  # and their compiled programs
                task_list = remaining
                if journal is not None:
                    # Interval-end group commit: one fsync covers this
                    # interval's progress, plan and completion records.
                    journal.append("interval_commit",
                                   interval=interval_index)
                    journal.commit()
                # Interval boundary for the buffered metrics writer too:
                # telemetry rides the buffer during the hot loop and lands
                # here, with the journal commit.
                metrics.flush()
                interval_index += 1
    logger.info("orchestration complete (%d completed, %d failed)",
                len(all_completed), len(all_failed))
    return {"completed": all_completed, "failed": all_failed}
