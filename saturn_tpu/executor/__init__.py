"""Execution layer: interval orchestration + gang engine.

Public entry point: :func:`orchestrate` — run a task batch to completion
under the MILP interval loop (``from saturn_tpu.executor import orchestrate``).
"""

from saturn_tpu.executor.orchestrator import orchestrate

__all__ = ["orchestrate"]
