"""Execution engine: forecast + dependency-gated gang launch for one interval.

Reference: ``saturn/executor/executor.py:25-178``. The reference's control
plane was Ray actors — ``DependencyHolder`` (asyncio events, ``:25-47``),
``LauncherActor`` (blocks on deps, spawns an ``ExecutorActor`` pinned to a
node with ``num_gpus`` reserved, ``:51-67``). One host drives an entire TPU
slice, so the TPU-native control plane is plain threads + ``threading.Event``
(SURVEY.md §5: "Ray is unnecessary"): each task gets a launcher thread that
waits for its dependency events, runs the technique on its assigned device
block, then signals completion. Device isolation comes from the plan itself —
the MILP guarantees concurrently-running tasks occupy disjoint blocks.
"""

from __future__ import annotations

import logging
import threading
import timeit
from typing import Dict, List, Optional, Sequence, Tuple

from saturn_tpu.core.mesh import SliceTopology
from saturn_tpu.solver.milp import Plan
from saturn_tpu.utils import metrics

logger = logging.getLogger("saturn_tpu")


def forecast(
    task_list: Sequence,
    interval: float,
    plan: Plan,
) -> Tuple[List, Dict[str, int], List]:
    """Which tasks run this interval, for how many batches, and which finish.

    Near-verbatim port of the reference's pure-arithmetic forecast
    (``executor.py:132-178``): a task runs if its planned start falls inside
    the interval; its batch budget is the remaining interval time divided by
    its profiled per-batch time, capped at remaining batches. Side effects
    mirror the reference's online re-estimation (``:165-177``): remaining
    ``total_batches`` and every strategy's remaining ``runtime`` are
    decremented by the work about to run.
    """
    relevant, batches, completed = [], {}, []
    for task in task_list:
        a = plan.assignments.get(task.name)
        if a is None or a.start >= interval:
            continue
        strat = task.strategies[a.apportionment]
        pbt = max(strat.per_batch_time, 1e-9)
        # A task scheduled inside the interval always gets >= 1 batch: a
        # per-batch time longer than the interval must still make progress,
        # otherwise the orchestrator livelocks re-solving forever.
        budget = max(1, int((interval - a.start) / pbt))
        n = min(budget, task.total_batches)
        if n <= 0:
            continue
        relevant.append(task)
        batches[task.name] = n
        # online re-estimation: all strategies advance by the same batch count
        # (``executor.py:165-172``)
        task.total_batches -= n
        for s in task.strategies.values():
            if s.feasible:
                s.runtime = max(0.0, s.per_batch_time * task.total_batches)
        if task.total_batches <= 0:
            completed.append(task)
    return relevant, batches, completed


def rollback_forecast(task, n_batches: int) -> None:
    """Undo :func:`forecast`'s optimistic accounting for a task whose
    interval never ran to durable completion (preemption, retryable failure):
    the pre-deducted batches go back on the budget and every feasible
    strategy's remaining runtime is re-derived from its per-batch profile —
    the checkpoint is the ground truth the next attempt resumes from.
    Shared by the batch orchestrator's retry/preemption paths and the online
    service's requeue path.

    Window granularity (fused multi-step dispatch) changes nothing here:
    an interval is all-or-nothing — ``on_task_done`` only fires after the
    technique ran every budgeted batch, so a preemption mid-window (or
    mid-tail) discards the whole attempt and this rollback restores the
    FULL forecast deduction, exactly. There is no partial-window credit to
    account for: device state from a half-run scan program is unreachable,
    and the end-of-interval checkpoint never happened.
    """
    task.total_batches += n_batches
    for s in task.strategies.values():
        if s.feasible:
            s.runtime = s.per_batch_time * task.total_batches


def pick_window(n_batches: int) -> int:
    """Fused multi-step window K for an interval batch budget — the engine
    side of the async step pipeline: K comes from the forecast's budget so
    the technique runs ``n // K`` fused windows plus an exact per-step tail.
    Delegates to the technique layer's policy (``SATURN_TPU_MAX_WINDOW``
    cap); imported lazily to keep executor -> parallel a call-time edge."""
    from saturn_tpu.parallel.spmd_base import choose_window

    return choose_window(n_batches)


def _execute_kwargs(tech, n_batches: int) -> Dict[str, int]:
    """The optional kwargs this technique's ``execute`` accepts. Gated on
    ``supports_windows`` so plugin techniques (and test fakes) with the bare
    ``BaseTechnique`` signature keep working unchanged."""
    if getattr(tech, "supports_windows", False):
        return {"window_size": pick_window(n_batches)}
    return {}


def _check_disjoint(run_tasks, plan) -> None:
    """Device-race + deadlock guard for the gang launch. The MILP's plans
    satisfy both properties by construction; a hand-built or corrupted plan
    that violates them would either run two XLA programs on the same chips
    concurrently (silent corruption, not a crash) or park launcher threads
    on events that never fire (silent hang) — the engine refuses loudly
    instead (SURVEY §5 concurrency-safety: detection, not just avoidance).

    - Two launched tasks may share devices only if the dependency graph
      serializes them — TRANSITIVELY: the launcher's event-waits chain, so
      a→b→c serializes (a, c) without a direct edge.
    - The dependency graph restricted to launched tasks must be acyclic:
      the launcher only waits on running tasks, and a cycle parks every
      thread in it forever.
    """
    running = {t.name for t in run_tasks}
    deps = {
        n: [d for d in plan.dependencies.get(n, ()) if d in running]
        for n in running
    }

    # Reachability over the running-task dependency DAG; cycle check rides
    # the same DFS (a node reaching itself).
    reach: Dict[str, set] = {}

    def reachable(n: str) -> set:
        if n in reach:
            return reach[n]
        reach[n] = set()  # placeholder breaks self-recursion on cycles
        out = set()
        for d in deps[n]:
            out.add(d)
            out |= reachable(d)
        reach[n] = out
        return out

    for n in running:
        if n in reachable(n):
            raise RuntimeError(
                f"plan dependency cycle through task {n!r}: the gang "
                "launch would deadlock (every thread in the cycle waits "
                "on another's completion event)"
            )

    items = [(t.name, plan.assignments.get(t.name)) for t in run_tasks]
    for i, (n1, a1) in enumerate(items):
        if a1 is None:
            continue
        for n2, a2 in items[i + 1:]:
            if a2 is None or not a1.block.overlaps(a2.block):
                continue
            if n1 not in reachable(n2) and n2 not in reachable(n1):
                raise RuntimeError(
                    f"plan races tasks {n1!r} and {n2!r}: blocks "
                    f"[{a1.block.offset}:{a1.block.end}] and "
                    f"[{a2.block.offset}:{a2.block.end}] overlap with no "
                    "ordering path between them"
                )


def execute(
    run_tasks: Sequence,
    batches: Dict[str, int],
    interval: float,
    plan: Plan,
    topology: SliceTopology,
    failure_policy: str = "raise",
    health=None,
    faults=None,
    interval_index: int = 0,
    on_task_start=None,
    on_task_done=None,
) -> Dict[str, BaseException]:
    """Gang-execute one interval (reference ``executor.py:88-129``).

    Per task: wait on dependency events (the MILP's ordering edges), run the
    selected technique on the assigned contiguous block, advance the data
    cursor, signal completion. Ends with a barrier + under/over-estimate log
    (``:123-129``).

    ``failure_policy``: ``"raise"`` re-raises the first task failure after
    the barrier (the reference's crash-the-batch behavior,
    ``my_multiprocessing.py:108-176``); ``"drop"`` returns the failures so
    the orchestrator can evict those tasks and keep the batch running —
    failure isolation the reference lacks (SURVEY.md §5 "no elasticity").
    Either way every other task finishes its interval first.

    ``health`` (a ``resilience.FleetHealthMonitor``) turns on the elastic
    hooks: per-block step timings feed straggler detection, and a device
    that dies mid-interval (``faults`` watchdog, or a real platform notice)
    aborts-and-requeues — not-yet-launched tasks and tasks whose block lost
    a chip surface as ``PreemptedError`` (never raised even under
    ``"raise"``: preemption is the fleet's fault, the orchestrator requeues
    and replans). ``faults`` additionally injects this interval's scheduled
    transient crashes and arms the mid-interval watchdog timers. Elastic
    hooks are single-host only (the multi-host path ignores them; the
    orchestrator refuses the combination up front).

    ``on_task_start`` (single-host only): callback invoked with the task name
    from its launcher thread once dependencies and the preemption gate have
    cleared, immediately before the technique runs. The online job service
    uses it to mark jobs RUNNING at the true launch instant.

    ``on_task_done`` (single-host only): callback ``(name, n_batches)``
    invoked from the launcher thread only after the task's interval fully
    succeeded — technique executed, mid-run preemption gate cleared, data
    cursor advanced. The durability layer journals realized iterations from
    here: a batch count passed to ``on_task_done`` really ran, so a failed
    or preempted attempt never reaches the ledger.
    """
    from saturn_tpu.core import distributed

    if distributed.is_multihost():
        return _execute_multihost(run_tasks, batches, interval, plan,
                                  topology, failure_policy)

    _check_disjoint(run_tasks, plan)

    from saturn_tpu.resilience.faults import PreemptedError

    events = {t.name: threading.Event() for t in run_tasks}
    running = {t.name for t in run_tasks}
    errors: Dict[str, BaseException] = {}

    abort = threading.Event()
    timers = (
        faults.arm_watchdog(interval_index, health, abort)
        if faults is not None and health is not None
        else []
    )

    def launcher(task, tid: int):
        try:
            for dep in plan.dependencies.get(task.name, ()):
                if dep in running:
                    events[dep].wait()
            a = plan.assignments[task.name]
            devices = topology.block_devices(a.block)
            didx = health.indices_of(devices) if health is not None else []
            if faults is not None and faults.crashes(task.name, interval_index):
                raise RuntimeError(
                    f"injected transient trial crash for {task.name}"
                )
            if abort.is_set() or (didx and health.any_lost(didx)):
                # abort-and-requeue: the fleet changed under this interval —
                # don't start work the replan will move anyway
                raise PreemptedError(
                    f"task {task.name} preempted before launch "
                    f"(block [{a.block.offset}:{a.block.end}])"
                )
            task.select_strategy(a.apportionment)
            if on_task_start is not None:
                on_task_start(task.name)
            tech = task.selected_strategy.executor
            n = batches[task.name]
            logger.info(
                "interval: launching %s on block [%d:%d] for %d batches",
                task.name, a.block.offset, a.block.end, n,
            )
            t_run = timeit.default_timer()
            tech.execute(task, devices, tid, override_batch_count=n,
                         **_execute_kwargs(tech, n))
            dt_run = timeit.default_timer() - t_run
            if didx and health.any_lost(didx):
                # chips died under the run: the device state is gone, the
                # work is discarded — the last checkpoint is ground truth
                raise PreemptedError(
                    f"task {task.name} lost devices mid-run "
                    f"(block [{a.block.offset}:{a.block.end}])"
                )
            task.reconfigure(n)  # data-cursor advance (``executor.py:84``)
            if didx:
                health.note_step(didx, dt_run / max(n, 1))
            if on_task_done is not None:
                on_task_done(task.name, n)
        except BaseException as e:  # surface after the barrier
            errors[task.name] = e
            if isinstance(e, PreemptedError):
                logger.warning("%s", e)
            else:
                logger.exception("task %s failed during interval", task.name)
        finally:
            events[task.name].set()

    t0 = timeit.default_timer()
    threads = [
        threading.Thread(target=launcher, args=(t, i), daemon=True, name=f"launch-{t.name}")
        for i, t in enumerate(run_tasks)
    ]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    for tm in timers:
        tm.cancel()
    elapsed = timeit.default_timer() - t0
    metrics.event(
        "interval",
        elapsed_s=elapsed,
        planned_s=interval,
        n_tasks=len(run_tasks),
        failed=sorted(
            n for n, e in errors.items() if not isinstance(e, PreemptedError)
        ),
        preempted=sorted(
            n for n, e in errors.items() if isinstance(e, PreemptedError)
        ),
    )
    # Interval boundary: drain the buffered metrics writer — emission is off
    # the step critical path, but an interval's telemetry must land before
    # the next interval starts (live tail_events followers, crash windows).
    metrics.flush()
    if failure_policy == "raise":
        real = {
            n: e for n, e in errors.items() if not isinstance(e, PreemptedError)
        }
        if real:
            name, err = next(iter(real.items()))
            raise RuntimeError(
                f"interval execution failed for task {name}"
            ) from err
    # estimate-error feedback (``executor.py:126-129``)
    if elapsed > interval:
        logger.info("interval overran: %.1fs vs planned %.1fs", elapsed, interval)
    else:
        logger.info("interval finished early: %.1fs of %.1fs", elapsed, interval)
    return errors


def _execute_multihost(
    run_tasks, batches, interval, plan, topology, failure_policy,
) -> Dict[str, BaseException]:
    """Multi-process interval: SEQUENTIAL, deterministic program order.

    Multi-controller JAX requires every pair of processes to enqueue their
    shared programs in the same order — the single-host thread gang cannot
    guarantee that, so cross-host intervals serialize tasks by planned
    (start, name). Each process executes only tasks whose block touches its
    local devices (a program over purely-remote devices has no local
    computation) but advances EVERY task's bookkeeping, keeping per-rank
    task state identical. Ordering edges are satisfied by construction: an
    overlap dependency always has an earlier planned start.
    """
    import jax

    from saturn_tpu.core import distributed

    my_proc = jax.process_index()
    errors: Dict[str, BaseException] = {}
    ordered = sorted(
        run_tasks, key=lambda t: (plan.assignments[t.name].start, t.name)
    )
    t0 = timeit.default_timer()
    for tid, task in enumerate(ordered):
        a = plan.assignments[task.name]
        task.select_strategy(a.apportionment)
        devices = topology.block_devices(a.block)
        local = any(
            getattr(d, "process_index", 0) == my_proc for d in devices
        )
        try:
            if local:
                n = batches[task.name]
                logger.info(
                    "interval[mh]: %s on block [%d:%d] for %d batches",
                    task.name, a.block.offset, a.block.end, n,
                )
                tech = task.selected_strategy.executor
                tech.execute(
                    task, devices, tid, override_batch_count=n,
                    **_execute_kwargs(tech, n)
                )
            task.reconfigure(batches[task.name])
        except BaseException as e:
            # Fail FAST, before any barrier or further collective: healthy
            # ranks may be ahead in cross-process programs, and this rank
            # parking at a barrier while they wait in a collective is a
            # mutual hang. Raising here exits the process; the jax
            # coordination service then aborts the rest of the cluster
            # (multi-host supports failure_policy='raise' only).
            logger.exception("task %s failed during interval", task.name)
            metrics.event(
                "interval", elapsed_s=timeit.default_timer() - t0,
                planned_s=interval, n_tasks=len(run_tasks),
                failed=[task.name],
            )
            raise RuntimeError(
                f"interval execution failed for task {task.name}"
            ) from e
    # Interval-end durability point: join this rank's async checkpoint
    # writes, then barrier. Forfeits the single-host write/compute overlap,
    # but guarantees every rank sees identical shared-FS state before the
    # next interval's exists()/restore() decisions — the alternative
    # (collectives inside checkpoint reads) deadlocks for host-local tasks.
    from saturn_tpu.utils import checkpoint as _ckpt

    _ckpt.flush()
    distributed.sync("interval-end")
    elapsed = timeit.default_timer() - t0
    metrics.event(
        "interval", elapsed_s=elapsed, planned_s=interval,
        n_tasks=len(run_tasks), failed=[],
    )
    metrics.flush()
    return errors
