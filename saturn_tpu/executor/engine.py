"""Execution engine: forecast + dependency-gated gang launch for one interval.

Reference: ``saturn/executor/executor.py:25-178``. The reference's control
plane was Ray actors — ``DependencyHolder`` (asyncio events, ``:25-47``),
``LauncherActor`` (blocks on deps, spawns an ``ExecutorActor`` pinned to a
node with ``num_gpus`` reserved, ``:51-67``). One host drives an entire TPU
slice, so the TPU-native control plane is plain threads + ``threading.Event``
(SURVEY.md §5: "Ray is unnecessary"): each task gets a launcher thread that
waits for its dependency events, runs the technique on its assigned device
block, then signals completion. Device isolation comes from the plan itself —
the MILP guarantees concurrently-running tasks occupy disjoint blocks.
"""

from __future__ import annotations

import logging
import threading
import time as _time
import timeit
from typing import Dict, List, Optional, Sequence, Tuple

from saturn_tpu.analysis import concurrency as tsan
from saturn_tpu.analysis.concurrency import sched_point
from saturn_tpu.core.mesh import SliceTopology
from saturn_tpu.solver.milp import Plan
from saturn_tpu.utils import metrics

logger = logging.getLogger("saturn_tpu")


def forecast(
    task_list: Sequence,
    interval: float,
    plan: Plan,
) -> Tuple[List, Dict[str, int], List]:
    """Which tasks run this interval, for how many batches, and which finish.

    Near-verbatim port of the reference's pure-arithmetic forecast
    (``executor.py:132-178``): a task runs if its planned start falls inside
    the interval; its batch budget is the remaining interval time divided by
    its profiled per-batch time, capped at remaining batches. Side effects
    mirror the reference's online re-estimation (``:165-177``): remaining
    ``total_batches`` and every strategy's remaining ``runtime`` are
    decremented by the work about to run.
    """
    relevant, batches, completed = [], {}, []
    for task in task_list:
        a = plan.assignments.get(task.name)
        if a is None or a.start >= interval:
            continue
        strat = task.strategies[a.apportionment]
        pbt = max(strat.per_batch_time, 1e-9)
        # A task scheduled inside the interval always gets >= 1 batch: a
        # per-batch time longer than the interval must still make progress,
        # otherwise the orchestrator livelocks re-solving forever.
        budget = max(1, int((interval - a.start) / pbt))
        n = min(budget, task.total_batches)
        if n <= 0:
            continue
        relevant.append(task)
        batches[task.name] = n
        # online re-estimation: all strategies advance by the same batch count
        # (``executor.py:165-172``)
        task.total_batches -= n
        for s in task.strategies.values():
            if s.feasible:
                s.runtime = max(0.0, s.per_batch_time * task.total_batches)
        if task.total_batches <= 0:
            completed.append(task)
    return relevant, batches, completed


def rollback_forecast(task, n_batches: int) -> None:
    """Undo :func:`forecast`'s optimistic accounting for a task whose
    interval never ran to durable completion (preemption, retryable failure):
    the pre-deducted batches go back on the budget and every feasible
    strategy's remaining runtime is re-derived from its per-batch profile —
    the checkpoint is the ground truth the next attempt resumes from.
    Shared by the batch orchestrator's retry/preemption paths and the online
    service's requeue path.

    Window granularity (fused multi-step dispatch) changes nothing here:
    an interval is all-or-nothing — ``on_task_done`` only fires after the
    technique ran every budgeted batch, so a preemption mid-window (or
    mid-tail) discards the whole attempt and this rollback restores the
    FULL forecast deduction, exactly. There is no partial-window credit to
    account for: device state from a half-run scan program is unreachable,
    and the end-of-interval checkpoint never happened.
    """
    task.total_batches += n_batches
    for s in task.strategies.values():
        if s.feasible:
            s.runtime = s.per_batch_time * task.total_batches


def pick_window(n_batches: int, cap: Optional[int] = None) -> int:
    """Fused multi-step window K for an interval batch budget — the engine
    side of the async step pipeline: K comes from the forecast's budget so
    the technique runs ``n // K`` fused windows plus an exact per-step tail.
    Delegates to the technique layer's policy; imported lazily to keep
    executor -> parallel a call-time edge.

    ``cap`` is the window ceiling the caller resolved ONCE at interval start
    (:func:`_window_cap`): ``execute`` passes it to every launcher so a
    mid-run ``SATURN_TPU_MAX_WINDOW`` flip cannot split one interval across
    two window policies. ``None`` re-reads the env (standalone callers)."""
    from saturn_tpu.parallel.spmd_base import choose_window

    return choose_window(n_batches, cap=cap)


def _window_cap() -> int:
    """Resolve the fused-window ceiling (env ``SATURN_TPU_MAX_WINDOW``) —
    called exactly once per interval, at the top of ``execute``."""
    from saturn_tpu.parallel.spmd_base import max_window

    return max_window()


def _execute_kwargs(tech, n_batches: int, cap: Optional[int] = None) -> Dict[str, int]:
    """The optional kwargs this technique's ``execute`` accepts. Gated on
    ``supports_windows`` so plugin techniques (and test fakes) with the bare
    ``BaseTechnique`` signature keep working unchanged."""
    if getattr(tech, "supports_windows", False):
        return {"window_size": pick_window(n_batches, cap)}
    return {}


def _coschedule_find(run_tasks, plan):
    """Union-find root function over the plan's co-schedule groups,
    restricted to the launched tasks. Members of one group are one condensed
    node: they run interleaved on one shared launcher, so ordering and race
    properties are checked between groups, never inside one. Groups that
    share a member merge (one launcher must own a task).

    Thin delegate: the implementation lives in
    ``analysis.plan_verifier.coschedule_find`` — one condensed-graph
    construction shared by the dynamic guard and the static verifier."""
    from saturn_tpu.analysis import plan_verifier

    return plan_verifier.coschedule_find((t.name for t in run_tasks), plan)


def _check_disjoint(run_tasks, plan) -> None:
    """Device-race + deadlock guard for the gang launch. The MILP's plans
    satisfy both properties by construction; a hand-built or corrupted plan
    that violates them would either run two XLA programs on the same chips
    concurrently (silent corruption, not a crash) or park launcher threads
    on events that never fire (silent hang) — the engine refuses loudly
    instead (SURVEY §5 concurrency-safety: detection, not just avoidance).

    Thin delegate into the static analyzer
    (``analysis.plan_verifier.check_launch_invariants``): the race / cycle /
    intra-group-edge rules are ONE implementation with two call sites —
    here, at the last line of defense before launch, and in the plan
    verifier that gates every adoption path (solve, re-solve, journal
    replay, migration). Raises ``RuntimeError`` with the historical
    message on the first violation, in the historical check order
    (groupmate edges, then cycles, then pairwise races)."""
    from saturn_tpu.analysis import plan_verifier

    plan_verifier.check_launch_invariants([t.name for t in run_tasks], plan)


def _coschedule_groups(run_tasks, plan) -> List[List]:
    """The co-schedule groups actually launching this interval: lists of
    Task objects (>= 2 running members each), one shared launcher per list.
    Tasks not in any group (or whose groupmates aren't running this
    interval) launch on the normal per-task path.

    Callers pass ``run_tasks`` with fusion-group members already removed
    (:func:`_fused_groups` claims them first): the condensed union-find
    merges fused groups too, so leaving them in would hand a stacked group
    to the interleaving launcher."""
    find = _coschedule_find(run_tasks, plan)
    by_root: Dict[str, List] = {}
    for t in run_tasks:
        by_root.setdefault(find(t.name), []).append(t)
    return [g for g in by_root.values() if len(g) >= 2]


def _fused_groups(run_tasks, plan) -> List[List]:
    """The fusion groups actually launching this interval: lists of Task
    objects (>= 2 running members each, in the plan's stack order), one
    stacked program per list (``parallel/fused.run_fused_interval``). A
    group whose running membership shrank below 2 degenerates to the normal
    per-task path — a stack of one is just the solo program with an extra
    axis."""
    by_name = {t.name: t for t in run_tasks}
    out: List[List] = []
    claimed: set = set()
    for grp in getattr(plan, "fused", None) or []:
        members = [by_name[n] for n in grp
                   if n in by_name and n not in claimed]
        if len(members) >= 2:
            out.append(members)
            claimed.update(t.name for t in members)
    return out


def _join_with_watchdog(watch, t0, hung, hung_lock, errors, events) -> None:
    """Join launcher threads under per-thread watchdog deadlines.

    ``watch`` is ``[(thread, member task names, deadline_s | None)]``. A
    thread still alive past its deadline is ABANDONED: each of its tasks
    gets a ``HungDispatchError`` recorded on its behalf (the thread itself
    is wedged — it cannot raise), its completion event fires so dependents
    unblock, and the engine stops joining the thread. The daemon thread may
    wake later; every state commit in the launchers is gated on the hung
    set, so a late wake cannot overwrite this verdict.
    """
    from saturn_tpu.health.guardian import HungDispatchError

    pending = list(watch)
    while pending:
        for entry in list(pending):
            th, names, deadline = entry
            th.join(timeout=0.02)
            if not th.is_alive():
                pending.remove(entry)
                continue
            if deadline is None:
                continue
            elapsed = timeit.default_timer() - t0
            if elapsed > deadline:
                logger.error(
                    "watchdog: abandoning launcher %s after %.1fs "
                    "(deadline %.1fs) — task(s) %s marked hung",
                    th.name, elapsed, deadline, names,
                )
                with hung_lock:
                    for name in names:
                        if name not in hung:
                            hung.add(name)
                            errors[name] = HungDispatchError(
                                name, deadline, elapsed
                            )
                for name in names:
                    events[name].set()
                pending.remove(entry)


def execute(
    run_tasks: Sequence,
    batches: Dict[str, int],
    interval: float,
    plan: Plan,
    topology: SliceTopology,
    failure_policy: str = "raise",
    health=None,
    faults=None,
    interval_index: int = 0,
    on_task_start=None,
    on_task_done=None,
    guardian=None,
) -> Dict[str, BaseException]:
    """Gang-execute one interval (reference ``executor.py:88-129``).

    Per task: wait on dependency events (the MILP's ordering edges), run the
    selected technique on the assigned contiguous block, advance the data
    cursor, signal completion. Ends with a barrier + under/over-estimate log
    (``:123-129``).

    ``failure_policy``: ``"raise"`` re-raises the first task failure after
    the barrier (the reference's crash-the-batch behavior,
    ``my_multiprocessing.py:108-176``); ``"drop"`` returns the failures so
    the orchestrator can evict those tasks and keep the batch running —
    failure isolation the reference lacks (SURVEY.md §5 "no elasticity").
    Either way every other task finishes its interval first.

    ``health`` (a ``resilience.FleetHealthMonitor``) turns on the elastic
    hooks: per-block step timings feed straggler detection, and a device
    that dies mid-interval (``faults`` watchdog, or a real platform notice)
    aborts-and-requeues — not-yet-launched tasks and tasks whose block lost
    a chip surface as ``PreemptedError`` (never raised even under
    ``"raise"``: preemption is the fleet's fault, the orchestrator requeues
    and replans). ``faults`` additionally injects this interval's scheduled
    transient crashes and arms the mid-interval watchdog timers. Elastic
    hooks are single-host only (the multi-host path ignores them; the
    orchestrator refuses the combination up front).

    ``on_task_start`` (single-host only): callback invoked with the task name
    from its launcher thread once dependencies and the preemption gate have
    cleared, immediately before the technique runs. The online job service
    uses it to mark jobs RUNNING at the true launch instant.

    ``on_task_done`` (single-host only): callback ``(name, n_batches)``
    invoked from the launcher thread only after the task's interval fully
    succeeded — technique executed, mid-run preemption gate cleared, data
    cursor advanced. The durability layer journals realized iterations from
    here: a batch count passed to ``on_task_done`` really ran, so a failed
    or preempted attempt never reaches the ledger.

    ``guardian`` (a ``health.TrainingGuardian``) turns on the hung-dispatch
    watchdog: each launcher thread is deadlined at ``floor + k x`` its
    profiled window work; past the deadline the engine ABANDONS the thread
    (records a ``HungDispatchError`` on its task(s), fires their completion
    events so dependents unblock, stops joining it) and returns. The
    abandoned daemon thread is gated out of every state commit (cursor
    advance, ``on_task_done``, error recording) the moment it is declared
    hung. One benign race remains by design: a launcher that passes the gate
    and is declared hung DURING its technique's final checkpoint write can
    leave a newer checkpoint than the rollback target — the retry then
    resumes slightly ahead and re-trains the difference, which costs
    makespan, never correctness. With a guardian attached, health faults
    (``NumericFaultError``/``HungDispatchError``) are also exempt from
    ``failure_policy="raise"`` — like preemptions, they belong to the
    caller's recovery policy, not the crash-the-batch path.
    """
    from saturn_tpu.core import distributed

    if distributed.is_multihost():
        return _execute_multihost(run_tasks, batches, interval, plan,
                                  topology, failure_policy)

    _check_disjoint(run_tasks, plan)

    from saturn_tpu.resilience.faults import PreemptedError

    # Resolve the fused-window ceiling ONCE for the whole interval: every
    # launcher below receives this cap, so a mid-run SATURN_TPU_MAX_WINDOW
    # flip cannot split one interval across two window policies.
    window_cap = _window_cap()

    sched_point("engine.execute")
    events = {t.name: threading.Event() for t in run_tasks}
    running = {t.name for t in run_tasks}
    errors: Dict[str, BaseException] = {}

    # Hung-dispatch watchdog state: tasks whose launcher was abandoned. Every
    # error write and post-run commit below is gated on membership, so a
    # wedged thread that eventually wakes cannot overwrite the watchdog's
    # verdict or advance state the caller already rolled back.
    hung: set = set()
    hung_lock = tsan.lock("engine.hung")

    def _abandoned(name: str) -> bool:
        with hung_lock:
            return name in hung

    def _record_error(
        name: str, e: BaseException, keep_first: bool = False
    ) -> None:
        with hung_lock:
            if name not in hung:
                if keep_first:
                    errors.setdefault(name, e)
                else:
                    errors[name] = e

    def _stall_then_check(name: str) -> bool:
        """Apply an injected dispatch stall; True iff this launcher was
        watchdog-abandoned during the stall (caller must bail without
        touching task state — the attempt already failed)."""
        stall = (
            faults.dispatch_stall_s(name, interval_index)
            if faults is not None and hasattr(faults, "dispatch_stall_s")
            else 0.0
        )
        if stall > 0.0:
            logger.warning(
                "injected dispatch stall: wedging %s for %.1fs", name, stall
            )
            _time.sleep(stall)
        return _abandoned(name)

    def _set_poison(name: str, task) -> None:
        """Hand the sentinel this interval's observation-level loss poisoning
        (chaos injection), if any is scheduled for this task."""
        if faults is not None and hasattr(faults, "numeric_plan"):
            p = faults.numeric_plan(name, interval_index)
            if p:
                task._health_poison = p

    abort = threading.Event()
    timers = (
        faults.arm_watchdog(interval_index, health, abort)
        if faults is not None and health is not None
        else []
    )

    def launcher(task, tid: int):
        sched_point("engine.launcher")
        try:
            for dep in plan.dependencies.get(task.name, ()):
                if dep in running:
                    events[dep].wait()
            a = plan.assignments[task.name]
            devices = topology.block_devices(a.block)
            didx = health.indices_of(devices) if health is not None else []
            if faults is not None and faults.crashes(task.name, interval_index):
                raise RuntimeError(
                    f"injected transient trial crash for {task.name}"
                )
            if abort.is_set() or (didx and health.any_lost(didx)):
                # abort-and-requeue: the fleet changed under this interval —
                # don't start work the replan will move anyway
                raise PreemptedError(
                    f"task {task.name} preempted before launch "
                    f"(block [{a.block.offset}:{a.block.end}])"
                )
            task.select_strategy(a.apportionment)
            if on_task_start is not None:
                on_task_start(task.name)
            tech = task.selected_strategy.executor
            n = batches[task.name]
            logger.info(
                "interval: launching %s on block [%d:%d] for %d batches",
                task.name, a.block.offset, a.block.end, n,
            )
            if _stall_then_check(task.name):
                return  # watchdog abandoned this attempt during the stall
            _set_poison(task.name, task)
            t_run = timeit.default_timer()
            tech.execute(task, devices, tid, override_batch_count=n,
                         **_execute_kwargs(tech, n, window_cap))
            dt_run = timeit.default_timer() - t_run
            if _abandoned(task.name):
                logger.warning(
                    "task %s finished after watchdog abandonment; "
                    "discarding the attempt", task.name,
                )
                return
            if didx and health.any_lost(didx):
                # chips died under the run: the device state is gone, the
                # work is discarded — the last checkpoint is ground truth
                raise PreemptedError(
                    f"task {task.name} lost devices mid-run "
                    f"(block [{a.block.offset}:{a.block.end}])"
                )
            task.reconfigure(n)  # data-cursor advance (``executor.py:84``)
            if didx:
                health.note_step(didx, dt_run / max(n, 1))
            if on_task_done is not None:
                on_task_done(task.name, n)
        except BaseException as e:  # surface after the barrier
            _record_error(task.name, e)
            if isinstance(e, PreemptedError):
                logger.warning("%s", e)
            else:
                logger.exception("task %s failed during interval", task.name)
        finally:
            events[task.name].set()

    def group_launcher(members: List, tids: List[int]):
        """One shared launcher for a co-schedule group.

        Two-phase interleave: (1) round-robin the members' dispatch
        generators, advancing each one window per visit — a member whose
        batch staging isn't ready yields "waiting" and the launcher moves to
        the next member, which is exactly how a stage-bound job's host
        phases get filled by a compute-bound neighbor's device windows; (2)
        once every member has enqueued all its device work ("drain"), resume
        each past drain to run its blocking finalization (loss readback,
        checkpoint). Completion events fire only at GROUP end: a dependent
        of any member must wait for the whole group, since the members
        share the block until the last one drains.

        Each member's dispatch ORDER (and therefore its loss/checkpoint
        trajectory) is identical to a solo run — only the wall-clock packing
        between members changes. Per-member realized feedback comes from
        attributing the group's wall time by profiled work share; a member
        whose technique lacks generator support runs sequentially on this
        same thread after the interleaved members (correct, unoverlapped).
        """
        sched_point("engine.group_launcher")
        names = {t.name for t in members}
        active: List[Dict] = []
        t_group0 = timeit.default_timer()
        try:
            for t in members:
                for dep in plan.dependencies.get(t.name, ()):
                    if dep in running and dep not in names:
                        events[dep].wait()
            for t, tid in zip(members, tids):
                try:
                    a = plan.assignments[t.name]
                    devices = topology.block_devices(a.block)
                    didx = (
                        health.indices_of(devices) if health is not None else []
                    )
                    if faults is not None and faults.crashes(
                        t.name, interval_index
                    ):
                        raise RuntimeError(
                            f"injected transient trial crash for {t.name}"
                        )
                    if abort.is_set() or (didx and health.any_lost(didx)):
                        raise PreemptedError(
                            f"task {t.name} preempted before launch "
                            f"(block [{a.block.offset}:{a.block.end}])"
                        )
                    t.select_strategy(a.apportionment)
                    if on_task_start is not None:
                        on_task_start(t.name)
                    if _stall_then_check(t.name):
                        return  # whole group abandoned during the stall
                    _set_poison(t.name, t)
                    tech = t.selected_strategy.executor
                    n = batches[t.name]
                    pbt = max(
                        getattr(t.selected_strategy, "per_batch_time", 0.0),
                        1e-9,
                    )
                    can_interleave = getattr(
                        tech, "supports_coschedule", False
                    ) and hasattr(tech, "interval_dispatches")
                    logger.info(
                        "interval: co-launching %s on block [%d:%d] for %d "
                        "batches (%s)", t.name, a.block.offset, a.block.end,
                        n, "interleaved" if can_interleave else "sequential",
                    )
                    gen = (
                        tech.interval_dispatches(
                            t, devices, tid, override_batch_count=n,
                            shared=True, **_execute_kwargs(tech, n, window_cap)
                        )
                        if can_interleave
                        else None
                    )
                    active.append({
                        "task": t, "tech": tech, "gen": gen, "tid": tid,
                        "n": n, "pbt": pbt, "didx": didx, "devices": devices,
                        "block": a.block, "per_batch": None,
                        "interleaved": can_interleave,
                    })
                except BaseException as e:
                    _record_error(t.name, e)
                    if isinstance(e, PreemptedError):
                        logger.warning("%s", e)
                    else:
                        logger.exception(
                            "task %s failed during interval", t.name
                        )

            # Phase 1: interleave dispatches across the generator members.
            pending = [m for m in active if m["gen"] is not None]
            drained: List[Dict] = []
            while pending:
                progressed = False
                for m in list(pending):
                    try:
                        tag, _ = next(m["gen"])
                    except StopIteration:
                        pending.remove(m)
                        m["gen"] = None
                        continue
                    except BaseException as e:
                        _record_error(m["task"].name, e)
                        logger.exception(
                            "task %s failed during interval", m["task"].name
                        )
                        pending.remove(m)
                        m["gen"] = None
                        continue
                    if tag == "dispatched":
                        progressed = True
                    elif tag == "drain":
                        pending.remove(m)
                        drained.append(m)
                        progressed = True
                    # "waiting": fall through to the next member — the poll
                    # retries on this member's next visit
                if not progressed and pending:
                    # every member is staging: nothing to dispatch — give the
                    # staging threads the core instead of spinning
                    _time.sleep(0.001)

            # Phase 2: blocking finalizations (loss readback, checkpoint),
            # only after ALL members' device work is enqueued.
            for m in drained:
                if _abandoned(m["task"].name):
                    continue
                try:
                    for _ in m["gen"]:
                        pass
                except BaseException as e:
                    _record_error(m["task"].name, e)
                    logger.exception(
                        "task %s failed during interval", m["task"].name
                    )
                finally:
                    m["gen"] = None

            # Sequential fallback for members without generator support.
            for m in active:
                if m["interleaved"] or m["task"].name in errors:
                    continue
                try:
                    t_solo = timeit.default_timer()
                    m["tech"].execute(
                        m["task"], m["devices"], m["tid"],
                        override_batch_count=m["n"],
                        **_execute_kwargs(m["tech"], m["n"], window_cap),
                    )
                    m["per_batch"] = (
                        timeit.default_timer() - t_solo
                    ) / max(m["n"], 1)
                except BaseException as e:
                    _record_error(m["task"].name, e)
                    logger.exception(
                        "task %s failed during interval", m["task"].name
                    )

            # Attribute the group's wall clock to the interleaved members by
            # profiled work share: member i's attributed per-batch time is
            # wall * (n_i * pbt_i / sum_j n_j * pbt_j) / n_i — the realized
            # feedback the solver's next re-solve consumes. (Sequential
            # fallback members measured their own wall time above.)
            dt_group = timeit.default_timer() - t_group0
            ok = [m for m in drained if m["task"].name not in errors]
            denom = sum(m["n"] * m["pbt"] for m in ok)
            for m in ok:
                share = (
                    m["n"] * m["pbt"] / denom if denom > 0 else 1.0 / len(ok)
                )
                m["per_batch"] = dt_group * share / max(m["n"], 1)
                note = getattr(m["task"], "note_realized_per_batch", None)
                if note is not None:
                    note(m["per_batch"])

            # Per-member post-run bookkeeping, mirroring the solo launcher.
            for m in active:
                name = m["task"].name
                if name in errors or m["per_batch"] is None:
                    continue
                try:
                    if m["didx"] and health.any_lost(m["didx"]):
                        raise PreemptedError(
                            f"task {name} lost devices mid-run (block "
                            f"[{m['block'].offset}:{m['block'].end}])"
                        )
                    m["task"].reconfigure(m["n"])
                    if m["didx"]:
                        health.note_step(m["didx"], m["per_batch"])
                    if on_task_done is not None:
                        on_task_done(name, m["n"])
                except BaseException as e:
                    _record_error(name, e)
                    if isinstance(e, PreemptedError):
                        logger.warning("%s", e)
                    else:
                        logger.exception(
                            "task %s failed during interval", name
                        )
        except BaseException as e:
            for t in members:
                # keep_first: a member that already recorded its own failure
                # above keeps it; the group-level error only fills the gaps.
                _record_error(t.name, e, keep_first=True)
            logger.exception(
                "co-schedule group %s failed", sorted(names)
            )
        finally:
            for m in active:
                if m["gen"] is not None:
                    try:
                        m["gen"].close()
                    except BaseException:
                        logger.exception(
                            "closing dispatch generator for %s failed",
                            m["task"].name,
                        )
            for t in members:
                events[t.name].set()

    def fused_launcher(members: List, tids: List[int]):
        """One launcher for a fusion group: N members, ONE stacked program.

        Unlike the co-schedule launcher — which interleaves N independent
        programs on a shared block — the whole group here is a single
        compiled step (``parallel/fused.run_fused_interval``): params and
        optimizer state stacked along a leading ``model`` axis, every member
        advancing one batch per lockstep step. Per-member outcomes come back
        in the interval report:

        - healthy members commit like the solo launcher (cursor advance,
          realized fused-lockstep feedback EWMA'd into
          ``Strategy.fused_per_batch_time``, ``on_task_done``); a member
          whose forecast budget exceeded the lockstep count gets the
          shortfall rolled back (:func:`rollback_forecast`) so the next
          re-solve prices the truth;
        - a sentinel-faulted member surfaces exactly like a solo numeric
          fault (state discarded, error recorded, guardian owns recovery);
        - a DETACHED member (mid-interval unfuse) resumes SOLO on the same
          block for its remaining budget within this interval — the stack
          already checkpointed its state at the detach boundary, so the solo
          program restores bit-identically and no step is lost or repeated.
        """
        sched_point("engine.fused_launcher")
        names = {t.name for t in members}
        from saturn_tpu.parallel import fused as _fused

        try:
            for t in members:
                for dep in plan.dependencies.get(t.name, ()):
                    if dep in running and dep not in names:
                        events[dep].wait()
            a = plan.assignments[members[0].name]
            devices = topology.block_devices(a.block)
            didx = health.indices_of(devices) if health is not None else []
            for t in members:
                if faults is not None and faults.crashes(
                    t.name, interval_index
                ):
                    raise RuntimeError(
                        f"injected transient trial crash for {t.name}"
                    )
            if abort.is_set() or (didx and health.any_lost(didx)):
                raise PreemptedError(
                    f"fused group {sorted(names)} preempted before launch "
                    f"(block [{a.block.offset}:{a.block.end}])"
                )
            for t in members:
                t.select_strategy(a.apportionment)
                if on_task_start is not None:
                    on_task_start(t.name)
                _set_poison(t.name, t)
            if _stall_then_check(members[0].name):
                return  # whole group abandoned during the stall
            counts = [batches[t.name] for t in members]
            logger.info(
                "interval: fused-launching %s on block [%d:%d] "
                "(lockstep %d batches x %d members)",
                sorted(names), a.block.offset, a.block.end,
                min(counts), len(members),
            )
            report = _fused.run_fused_interval(
                members, devices, tids[0], batch_counts=counts,
            )
            if any(_abandoned(t.name) for t in members):
                logger.warning(
                    "fused group %s finished after watchdog abandonment; "
                    "discarding the attempt", sorted(names),
                )
                return
            if didx and health.any_lost(didx):
                raise PreemptedError(
                    f"fused group {sorted(names)} lost devices mid-run "
                    f"(block [{a.block.offset}:{a.block.end}])"
                )
            detached = {t.name: s for t, s in report.detached}
            if didx and report.per_step_s > 0:
                health.note_step(didx, report.per_step_s)
            for t in members:
                name = t.name
                mr = report.members.get(name)
                if mr is None:
                    continue
                try:
                    if mr.fault is not None:
                        raise mr.fault
                    budget = batches[name]
                    steps = mr.steps
                    if name in detached:
                        remaining = max(0, budget - steps)
                        if remaining > 0:
                            tech = t.selected_strategy.executor
                            logger.info(
                                "interval: resuming unfused %s solo for %d "
                                "remaining batches", name, remaining,
                            )
                            tech.execute(
                                t, devices, tids[0],
                                override_batch_count=remaining,
                                **_execute_kwargs(tech, remaining,
                                                  window_cap),
                            )
                            # the solo restore reset the cursor to the
                            # detach point; advance only the solo portion
                            t.reconfigure(remaining)
                        else:
                            t.reconfigure(steps)
                        done = budget
                    else:
                        t.reconfigure(steps)
                        if budget > steps:
                            # lockstep ran to the SHORTEST member's budget;
                            # give this member's shortfall back
                            rollback_forecast(t, budget - steps)
                        done = steps
                    strat = t.selected_strategy
                    if report.per_step_s > 0:
                        old = strat.fused_per_batch_time
                        strat.fused_per_batch_time = (
                            report.per_step_s if old is None
                            else 0.7 * report.per_step_s + 0.3 * old
                        )
                    if on_task_done is not None:
                        on_task_done(name, done)
                except BaseException as e:
                    _record_error(name, e)
                    if isinstance(e, PreemptedError):
                        logger.warning("%s", e)
                    else:
                        logger.exception(
                            "task %s failed during interval", name
                        )
        except BaseException as e:
            for t in members:
                # keep_first: a member that already recorded its own failure
                # above keeps it; the group-level error only fills the gaps.
                _record_error(t.name, e, keep_first=True)
            if isinstance(e, PreemptedError):
                logger.warning("%s", e)
            else:
                logger.exception("fused group %s failed", sorted(names))
        finally:
            for t in members:
                events[t.name].set()

    fused_groups = _fused_groups(run_tasks, plan)
    fused_names = {t.name for g in fused_groups for t in g}
    co_groups = _coschedule_groups(
        [t for t in run_tasks if t.name not in fused_names], plan
    )
    grouped = {t.name for g in co_groups for t in g} | fused_names
    tid_of = {t.name: i for i, t in enumerate(run_tasks)}

    def _expected_s(t) -> float:
        """Profiled window work for one task this interval (seconds)."""
        a = plan.assignments.get(t.name)
        strat = t.strategies.get(a.apportionment) if a is not None else None
        pbt = max(float(getattr(strat, "per_batch_time", 0.0) or 0.0), 0.0)
        return batches.get(t.name, 0) * pbt

    # (thread, member task names, watchdog deadline in seconds). A group
    # thread's deadline covers the SUM of its members' profiled work — the
    # members run interleaved on this one thread.
    watch: List[Tuple[threading.Thread, List[str], Optional[float]]] = []
    use_watchdog = guardian is not None and guardian.watchdog_enabled
    for i, t in enumerate(run_tasks):
        if t.name in grouped:
            continue
        th = threading.Thread(
            target=launcher, args=(t, i), daemon=True, name=f"launch-{t.name}"
        )
        dl = guardian.window_deadline_s(_expected_s(t)) if use_watchdog else None
        watch.append((th, [t.name], dl))
    for g in co_groups:
        th = threading.Thread(
            target=group_launcher,
            args=(g, [tid_of[t.name] for t in g]),
            daemon=True,
            name="colaunch-" + "+".join(t.name for t in g),
        )
        dl = (
            guardian.window_deadline_s(sum(_expected_s(t) for t in g))
            if use_watchdog else None
        )
        watch.append((th, [t.name for t in g], dl))
    for g in fused_groups:
        th = threading.Thread(
            target=fused_launcher,
            args=(g, [tid_of[t.name] for t in g]),
            daemon=True,
            name="fuselaunch-" + "+".join(t.name for t in g),
        )
        # Deadline covers the members' summed profiled solo work — a loose
        # upper bound on the lockstep stack (the whole point of fusing is
        # beating it), so the watchdog only fires on a genuine wedge.
        dl = (
            guardian.window_deadline_s(sum(_expected_s(t) for t in g))
            if use_watchdog else None
        )
        watch.append((th, [t.name for t in g], dl))

    t0 = timeit.default_timer()
    for th, _, _ in watch:
        th.start()
    if use_watchdog:
        _join_with_watchdog(watch, t0, hung, hung_lock, errors, events)
    else:
        for th, _, _ in watch:
            th.join()
    for tm in timers:
        tm.cancel()
    elapsed = timeit.default_timer() - t0
    metrics.event(
        "interval",
        elapsed_s=elapsed,
        planned_s=interval,
        n_tasks=len(run_tasks),
        failed=sorted(
            n for n, e in errors.items() if not isinstance(e, PreemptedError)
        ),
        preempted=sorted(
            n for n, e in errors.items() if isinstance(e, PreemptedError)
        ),
    )
    # Interval boundary: drain the buffered metrics writer — emission is off
    # the step critical path, but an interval's telemetry must land before
    # the next interval starts (live tail_events followers, crash windows).
    metrics.flush()
    if failure_policy == "raise":
        real = {
            n: e for n, e in errors.items() if not isinstance(e, PreemptedError)
        }
        if guardian is not None:
            # Health faults belong to the guardian's recovery policy
            # (rollback + backoff), not the crash-the-batch path.
            real = {n: e for n, e in real.items() if not guardian.owns(e)}
        if real:
            name, err = next(iter(real.items()))
            raise RuntimeError(
                f"interval execution failed for task {name}"
            ) from err
    # estimate-error feedback (``executor.py:126-129``)
    if elapsed > interval:
        logger.info("interval overran: %.1fs vs planned %.1fs", elapsed, interval)
    else:
        logger.info("interval finished early: %.1fs of %.1fs", elapsed, interval)
    return errors


def _execute_multihost(
    run_tasks, batches, interval, plan, topology, failure_policy,
) -> Dict[str, BaseException]:
    """Multi-process interval: SEQUENTIAL, deterministic program order.

    Multi-controller JAX requires every pair of processes to enqueue their
    shared programs in the same order — the single-host thread gang cannot
    guarantee that, so cross-host intervals serialize tasks by planned
    (start, name). Each process executes only tasks whose block touches its
    local devices (a program over purely-remote devices has no local
    computation) but advances EVERY task's bookkeeping, keeping per-rank
    task state identical. Ordering edges are satisfied by construction: an
    overlap dependency always has an earlier planned start.
    """
    import jax

    from saturn_tpu.core import distributed

    # Co-schedule groups are ignored here on purpose: cross-host intervals
    # already serialize every task for deterministic program order, and
    # sequential execution of a group is trajectory-identical (just
    # unoverlapped). The single window-cap read per interval still applies.
    window_cap = _window_cap()
    my_proc = jax.process_index()
    errors: Dict[str, BaseException] = {}
    ordered = sorted(
        run_tasks, key=lambda t: (plan.assignments[t.name].start, t.name)
    )
    t0 = timeit.default_timer()
    for tid, task in enumerate(ordered):
        a = plan.assignments[task.name]
        task.select_strategy(a.apportionment)
        devices = topology.block_devices(a.block)
        local = any(
            getattr(d, "process_index", 0) == my_proc for d in devices
        )
        try:
            if local:
                n = batches[task.name]
                logger.info(
                    "interval[mh]: %s on block [%d:%d] for %d batches",
                    task.name, a.block.offset, a.block.end, n,
                )
                tech = task.selected_strategy.executor
                tech.execute(
                    task, devices, tid, override_batch_count=n,
                    **_execute_kwargs(tech, n, window_cap)
                )
            task.reconfigure(batches[task.name])
        except BaseException as e:
            # Fail FAST, before any barrier or further collective: healthy
            # ranks may be ahead in cross-process programs, and this rank
            # parking at a barrier while they wait in a collective is a
            # mutual hang. Raising here exits the process; the jax
            # coordination service then aborts the rest of the cluster
            # (multi-host supports failure_policy='raise' only).
            logger.exception("task %s failed during interval", task.name)
            metrics.event(
                "interval", elapsed_s=timeit.default_timer() - t0,
                planned_s=interval, n_tasks=len(run_tasks),
                failed=[task.name],
            )
            raise RuntimeError(
                f"interval execution failed for task {task.name}"
            ) from e
    # Interval-end durability point: join this rank's async checkpoint
    # writes, then barrier. Forfeits the single-host write/compute overlap,
    # but guarantees every rank sees identical shared-FS state before the
    # next interval's exists()/restore() decisions — the alternative
    # (collectives inside checkpoint reads) deadlocks for host-local tasks.
    from saturn_tpu.utils import checkpoint as _ckpt

    _ckpt.flush()
    distributed.sync("interval-end")
    elapsed = timeit.default_timer() - t0
    metrics.event(
        "interval", elapsed_s=elapsed, planned_s=interval,
        n_tasks=len(run_tasks), failed=[],
    )
    metrics.flush()
    return errors
