"""Ulysses-style sequence parallelism: all-to-all head/sequence resharding.

Complement to ring attention (``ops/ring.py``) for long-context training the
reference lacks entirely (SURVEY.md §5). Where the ring rotates k/v blocks in
S-1 neighbor hops, Ulysses (DeepSpeed-Ulysses, Jacobs et al. 2023) pays two
all-to-alls per attention: reshard from sequence-sharded (every device holds
all heads of its T/S chunk) to head-sharded (every device holds H/S heads of
the FULL sequence), run plain dense causal attention locally, reshard back.

Trade-off on the ICI torus: 2 all-to-alls of the qkv/out activations vs S-1
ppermutes of k/v — Ulysses moves less data when S is large and H >= S, but
holds full-T score blocks (O(T²/S) per device vs the ring's O(T²/S²)). Both
ship as library techniques; the trial runner measures which wins per task.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    axis_size: int,
    causal: bool = True,
) -> jax.Array:
    """Causal attention over a sequence-sharded batch via two all-to-alls.

    Must be called inside ``shard_map``. ``q``/``k``/``v`` are local chunks of
    shape (B, H, Tc, D) with Tc = T / axis_size; H must be divisible by
    axis_size. Returns the local (B, H, Tc, D) attention output.
    """
    B, H, Tc, D = q.shape
    S = axis_size
    if H % S != 0:
        raise ValueError(f"n_heads {H} not divisible by sequence axis {S}")

    def reshard_in(t):
        # (B, H, Tc, D) -> (B, H/S, T, D): split heads across devices,
        # gather the full sequence for the local head subset.
        return lax.all_to_all(t, axis_name, split_axis=1, concat_axis=2, tiled=True)

    def reshard_out(t):
        # (B, H/S, T, D) -> (B, H, Tc, D)
        return lax.all_to_all(t, axis_name, split_axis=2, concat_axis=1, tiled=True)

    if S > 1:
        q, k, v = reshard_in(q), reshard_in(k), reshard_in(v)

    T = q.shape[2]
    scores = (
        jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
        / math.sqrt(D)
    )
    if causal:
        mask = jnp.tril(jnp.ones((T, T), dtype=bool))
        scores = jnp.where(mask[None, None], scores, jnp.float32(-1e30))
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jnp.einsum(
        "bhqk,bhkd->bhqd", probs, v, preferred_element_type=jnp.float32
    ).astype(q.dtype)

    if S > 1:
        out = reshard_out(out)
    return out
