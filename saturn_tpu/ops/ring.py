"""Ring attention + sharded LM loss: sequence parallelism over an ICI ring.

Long-context support the reference lacks entirely (SURVEY.md §5
"Long-context / sequence parallelism: entirely absent"; its context was pinned
to 512 tokens, ``dataloaders.py:58``, ``GPTJ.py:507``). Delivered the way the
reference delivers every capability — as a technique behind the UDP plugin
interface (``Technique.py:24``) — but built TPU-first:

- The sequence dimension is sharded over a ``seq`` mesh axis. Each device
  holds a (B, T/S) token chunk and its q/k/v blocks.
- **Ring attention** (Liu et al. 2023): k/v blocks rotate around the ring
  with ``lax.ppermute`` (neighbor hops that ride ICI) while each device
  accumulates its queries' attention with the online-softmax (flash)
  recurrence in fp32. Peak activation memory per device drops from O(T²) to
  O(T²/S²) score blocks; compute overlaps the permute because XLA sees the
  whole loop.
- Causality is global: position offsets come from ``axis_index``, so block
  (i,j) is fully masked when j > i, lower-triangle-masked on the diagonal,
  and unmasked below — masked blocks contribute nothing thanks to the
  -inf-safe accumulator.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from saturn_tpu.ops.shmap_compat import shard_map


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    axis_size: int,
    causal: bool = True,
    overlap: bool = False,
) -> jax.Array:
    """Blockwise causal attention over a sharded sequence axis.

    Must be called inside ``shard_map``. ``q``/``k``/``v`` are the local
    chunks, shape (B, H, Tc, D) with Tc = T / axis_size; returns the local
    (B, H, Tc, D) attention output. fp32 softmax accumulation; matmuls feed
    the MXU in the input dtype with fp32 accumulation.

    ``overlap=True`` double-buffers the neighbor hop: the scan body issues
    the ``ppermute`` shipping block s+1 BEFORE folding block s, so the hop's
    DMA is in flight while the MXU chews the current block. Same values
    through the same accumulate ops in the same order — bit-identical to the
    serial schedule (asserted by tests/test_overlap.py) — only the program
    order of the hop changes, which is what the TPU scheduler keys on.
    """
    B, H, Tc, D = q.shape
    idx = lax.axis_index(axis_name)
    scale = 1.0 / math.sqrt(D)
    qpos = idx * Tc + jnp.arange(Tc)

    o0 = jnp.zeros((B, H, Tc, D), jnp.float32)
    l0 = jnp.zeros((B, H, Tc), jnp.float32)
    m0 = jnp.full((B, H, Tc), -jnp.inf, jnp.float32)
    # Rotate kv blocks one hop per step: after s steps this device holds the
    # block originally on shard (idx - s) mod S.
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def accumulate(o, l, m, kc, vc, s):
        """Fold kv block ``(idx - s) mod S`` into the flash recurrence."""
        scores = (
            jnp.einsum(
                "bhqd,bhkd->bhqk", q, kc, preferred_element_type=jnp.float32
            )
            * scale
        )
        if causal:
            kpos = ((idx - s) % axis_size) * Tc + jnp.arange(Tc)
            mask = qpos[:, None] >= kpos[None, :]
            scores = jnp.where(mask[None, None], scores, -jnp.inf)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        # A still-all-masked row has m_new == -inf; exp(x - 0) with x = -inf
        # gives exactly 0, so the safe substitute keeps every term finite.
        safe_m = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.exp(scores - safe_m[..., None])
        corr = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - safe_m))
        l_new = corr * l + p.sum(axis=-1)
        pv = jnp.einsum(
            "bhqk,bhkd->bhqd",
            p.astype(v.dtype),
            vc,
            preferred_element_type=jnp.float32,
        )
        return corr[..., None] * o + pv, l_new, m_new

    def step(carry, s):
        o, l, m, kc, vc = carry
        o, l, m = accumulate(o, l, m, kc, vc, s)
        kc, vc = lax.ppermute((kc, vc), axis_name, perm)
        return (o, l, m, kc, vc), None

    def step_overlapped(carry, s):
        # Hop first: ship block s+1 while block s is still being folded.
        # The ppermute's operands come straight from the carry, so it has no
        # data dependence on this step's accumulate.
        o, l, m, kc, vc = carry
        kc_next, vc_next = lax.ppermute((kc, vc), axis_name, perm)
        o, l, m = accumulate(o, l, m, kc, vc, s)
        return (o, l, m, kc_next, vc_next), None

    # S-1 (accumulate, rotate) steps in the scan; the final block is folded
    # outside it so no dead ppermute ships k/v nobody reads.
    o, l, m, kc, vc = o0, l0, m0, k, v
    if axis_size > 1:
        (o, l, m, kc, vc), _ = lax.scan(
            step_overlapped if overlap else step,
            (o, l, m, kc, vc),
            jnp.arange(axis_size - 1),
        )
    o, l, _ = accumulate(o, l, m, kc, vc, axis_size - 1)
    out = o / jnp.where(l == 0.0, 1.0, l)[..., None]
    return out.astype(q.dtype)


def sharded_lm_loss_terms(
    logits: jax.Array, tokens: jax.Array, *, axis_name: str, axis_size: int
) -> Tuple[jax.Array, jax.Array]:
    """Local (loss_sum, count) for shifted next-token CE over a sharded sequence.

    The label for a chunk's last position is the *next* chunk's first token,
    fetched with one ppermute; the final chunk's last position (no successor
    anywhere) is masked out. psum the two outputs over all axes and divide to
    get the same scalar ``models.loss.pretraining_loss`` computes densely.
    """
    idx = lax.axis_index(axis_name)
    # shard i receives shard (i+1)'s first token: source j sends to j-1.
    perm = [(j, (j - 1) % axis_size) for j in range(axis_size)]
    next_first = lax.ppermute(tokens[:, :1], axis_name, perm)
    labels = jnp.concatenate([tokens[:, 1:], next_first], axis=1)
    ce = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    valid = jnp.ones_like(ce).at[:, -1].set(
        jnp.where(idx == axis_size - 1, 0.0, 1.0)
    )
    return (ce * valid).sum(), valid.sum()


def ring_loss_and_grads(
    params: Any,
    tokens: jax.Array,
    *,
    mesh: Any,
    apply_fn: Callable[[Any, jax.Array], jax.Array],
    data_axis: str = "data",
    seq_axis: str = "seq",
):
    """(loss, grads) for one sequence-parallel step over a ('data','seq') mesh.

    ``apply_fn`` must be ring-aware (built with ``seq_axis`` set so its
    attention calls :func:`ring_attention`); it receives the local (Bd, Tc)
    token chunk. Params are replicated; grads psum over both axes — the
    TPU-native analog of the reference's NCCL allreduce, riding ICI.
    """
    S = mesh.shape[seq_axis]

    def local_fn(p, tokens_local):
        def loss_of(pp):
            logits = apply_fn(pp, tokens_local)
            lsum, cnt = sharded_lm_loss_terms(
                logits, tokens_local, axis_name=seq_axis, axis_size=S
            )
            lsum = lax.psum(lsum, (data_axis, seq_axis))
            cnt = lax.psum(cnt, (data_axis, seq_axis))
            return lsum / cnt

        loss, grads = jax.value_and_grad(loss_of)(p)
        grads = jax.tree.map(lambda g: lax.psum(g, (data_axis, seq_axis)), grads)
        return loss, grads

    param_specs = jax.tree.map(lambda _: P(), params)
    mapped = shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(param_specs, P(data_axis, seq_axis)),
        out_specs=(P(), param_specs),
        check_vma=False,
    )
    return mapped(params, tokens)
