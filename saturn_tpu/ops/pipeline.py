"""GPipe microbatch schedule over a ``stage`` mesh axis, as one jittable op.

TPU-native replacement for torchgpipe (reference ``Pipeline.py:24-167``,
SURVEY.md §2.2): where torchgpipe partitions an ``nn.Sequential`` across GPUs
and streams microbatches over CUDA copies, here the scanned layer stack is
*sharded* over a ``stage`` mesh axis and microbatch activations rotate between
neighbor stages with ``lax.ppermute`` — point-to-point hops that ride ICI.

The whole schedule lives inside ``shard_map`` and is differentiated with
``jax.value_and_grad`` *inside* the mapped body: ``ppermute``'s transpose is
the inverse permutation, so reverse-mode AD automatically yields the reverse
pipeline schedule (activations flow last→first stage in the backward pass),
with no hand-written backward.

Schedule shape (classic GPipe, bubble fraction (S-1)/(M+S-1)):

    t:      0    1    2    ...                    M+S-2
    stage0  mb0  mb1  mb2  ...  mbM-1  -    -
    stage1  -    mb0  mb1  ...         mbM-1 -
    stage2  -    -    mb0  ...               mbM-1

The language-model head is *not* computed inside the schedule loop (which
would redo it on every stage every tick): last-stage outputs are
``psum_scatter``-ed so each stage receives exactly its M/S chunk and computes
the head + loss for it — balancing the vocab-sized matmul across the gang at
half the wire cost of a full psum broadcast, with no (M, ...) activation
buffer materialized per stage. Embeddings are likewise computed lazily, one
microbatch per tick and only on stage 0 (``lax.cond``), instead of all M
up front on every stage (VERDICT r1 weak item 8).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from saturn_tpu.ops.shmap_compat import shard_map

#: Version tag for the *set* of pipeline schedules this module implements.
#: Folded into the profile-cache fingerprint so entries profiled before a
#: schedule was added (or after its program changes) miss instead of serving
#: stale GPipe-only timings.  v2: double-buffered (overlap=True) variants of
#: both schedules — hop latency H=2, ppermute issued before the tick's
#: compute.
SCHEDULE_SET_VERSION = "gpipe+1f1b:v2"

PIPELINE_SCHEDULES = ("gpipe", "1f1b")


def _hop_latency(overlap: bool) -> int:
    """Ticks an activation spends in flight between neighbor stages.

    Serial schedule: the hop is issued after the tick's compute and consumed
    next tick (H=1).  Double-buffered: the hop is issued at the TOP of the
    tick from the previous tick's output, so its DMA rides under this tick's
    compute and the value lands one tick later (H=2).  Every schedule
    quantity below is a function of H; H=1 reproduces the v1 programs
    exactly.
    """
    return 2 if overlap else 1


def schedule_signature() -> str:
    """Fingerprint component identifying the available schedule programs."""
    return SCHEDULE_SET_VERSION


def schedule_bubble_fraction(
    schedule: str, n_stages: int, n_microbatches: int, overlap: bool = False
) -> float:
    """Analytic idle (ramp) fraction of one pipelined step, per stage.

    GPipe runs forwards and backwards as two separate M+S-1-tick waves, so a
    stage idles for the full 2(S-1)-tick ramp of a 2(M+S-1)-tick wall:
    (S-1)/(M+S-1).  1F1B packs one forward and one backward into each steady
    tick, shrinking the wall to M+2(S-1) ticks with the same 2(S-1) ramp:
    2(S-1)/(2(M+2(S-1))) = (S-1)/(M+2(S-1)) — *smaller*, which is exactly
    why a 1F1B job leaves fewer gaps for a co-scheduled partner to fill
    (the solver's co-location term prices this, see ``solver/milp.py``).

    ``overlap=True`` (hop latency H=2) deepens the ramp H-fold in ticks —
    the price of double-buffering; what it buys (the hop leaving each tick's
    critical path) is modeled by the per-op-class overlap factor in
    ``analysis/shardflow/prior.py``, not here.
    """
    S, M = int(n_stages), int(n_microbatches)
    if S <= 1:
        return 0.0
    H = _hop_latency(overlap)
    if schedule == "1f1b":
        return H * (S - 1) / (M + 2 * H * (S - 1))
    return H * (S - 1) / (M + H * (S - 1))


def stash_depth(
    n_stages: int, n_microbatches: int, schedule: str = "1f1b",
    overlap: bool = False,
) -> int:
    """In-flight forward-activation stash depth of the staged schedule.

    A microbatch's stage input is stashed at its forward tick ``H·s + m``
    and freed at its backward tick ``m + C2 + H(S-1-s)`` (C2 = H(S-1) for
    1F1B), so at most ``C2 + H(S-1) + 1`` microbatches are live per stage —
    O(S), independent of M.  The staged-GPipe ordering flushes all M
    forwards first, so its stash is the full ``M`` — the memory cliff 1F1B
    exists to avoid.  Serial (H=1) 1F1B: ``2S-1``.
    """
    S, M = int(n_stages), int(n_microbatches)
    H = _hop_latency(overlap)
    c2 = H * (S - 1) if schedule == "1f1b" else M + H * (S - 1)
    return max(1, min(M, c2 + H * (S - 1) + 1))


def balance_stages(costs: Sequence[float], n_stages: int) -> Tuple[int, ...]:
    """Contiguous layer->stage partition minimizing the max per-stage cost.

    Returns per-stage layer counts (len ``n_stages``, sums to ``len(costs)``,
    every span >= 1). The TPU-native analog of torchgpipe's
    ``balance_by_time`` (reference ``Pipeline.py:94-103``): the reference
    timed each layer on one GPU and block-partitioned; here the costs come
    from the model's ``layer_costs`` hint (profiled or FLOP-derived) and the
    exact DP replaces the reference's heuristic — L is tens, so the
    O(S·L²) linear-partition DP is free at trace time.
    """
    L = len(costs)
    S = n_stages
    if S < 1 or S > L:
        raise ValueError(f"cannot split {L} layers into {S} stages")
    prefix = [0.0]
    for c in costs:
        prefix.append(prefix[-1] + float(c))

    def span_cost(i, j):  # layers [i, j)
        return prefix[j] - prefix[i]

    INF = (float("inf"), float("inf"))
    # best[s][j] = lexicographic (max stage cost, max span length) splitting
    # layers [0, j) into s stages. The secondary criterion breaks max-cost
    # ties toward the smallest longest span: n_max sets every stage's padded
    # param residency and scan length, so a tie spent on a longer span is
    # pure memory/schedule waste.
    best = [[INF] * (L + 1) for _ in range(S + 1)]
    cut = [[0] * (L + 1) for _ in range(S + 1)]
    best[0][0] = (0.0, 0)
    for s in range(1, S + 1):
        for j in range(s, L - (S - s) + 1):
            for i in range(s - 1, j):
                prev = best[s - 1][i]
                cand = (max(prev[0], span_cost(i, j)), max(prev[1], j - i))
                if cand < best[s][j]:
                    best[s][j] = cand
                    cut[s][j] = i
    spans = []
    j = L
    for s in range(S, 0, -1):
        i = cut[s][j]
        spans.append(j - i)
        j = i
    return tuple(reversed(spans))


def _pad_stack(blocks: Any, spans: Sequence[int], n_max: int):
    """Repack a (L, ...) stacked layer tree into (S*n_max, ...) span-major
    order, zero-padding each stage's span to ``n_max`` — the equal-shard
    layout ``shard_map`` needs. Returns (padded_tree, active_mask).

    Implemented as a gather + mask, NOT ``jnp.concatenate``: on jax 0.4.x,
    feeding a concat-built intermediate into a shard_map in_spec that shards
    only some mesh axes mis-lowers the reshard as a reduction over the
    unsharded axes — every data replica after the first silently received
    the layer stack multiplied by the replica count (d=1 meshes and eager
    execution were unaffected, which is how it went unnoticed).
    """
    bounds = [0]
    for s in spans:
        bounds.append(bounds[-1] + s)
    src = jnp.asarray(
        [bounds[i] + min(k, s - 1) for i, s in enumerate(spans) for k in range(n_max)],
        dtype=jnp.int32,
    )
    active = jnp.asarray(
        [k < s for s in spans for k in range(n_max)], dtype=jnp.bool_
    )

    def pad_leaf(a):
        taken = jnp.take(a, src, axis=0)
        m = active.reshape((-1,) + (1,) * (a.ndim - 1))
        return jnp.where(m, taken, jnp.zeros((), a.dtype))

    return jax.tree.map(pad_leaf, blocks), active


def _unpad_stack(padded: Any, spans: Sequence[int], n_max: int):
    """Inverse of :func:`_pad_stack` for the gradient tree.

    Also a gather, for the same reason ``_pad_stack`` is: the padded grad
    tree leaves ``shard_map`` sharded on the stage axis only, and a
    concat-built consumer of such an operand triggers the 0.4.x
    reshard-as-reduction mis-lowering — block grads came back multiplied
    by the data-replica count. Each global layer has exactly one active
    slot (inactive slots carry zero grad), so the gather is exact.
    """
    src = jnp.asarray(
        [i * n_max + k for i, s in enumerate(spans) for k in range(s)],
        dtype=jnp.int32,
    )
    return jax.tree.map(lambda a: jnp.take(a, src, axis=0), padded)


def _resolve_spans(params, block_key, S, stage_spans):
    """Validate/normalize ``stage_spans`` and pad the layer stack if unequal.

    Returns ``(params, spans, n_max)`` where ``spans`` is None on the
    equal-split fast path.  Shared by both schedule programs so they accept
    identical (spans, microbatches) inputs.

    The per-stage active mask is NOT returned: it must be derived from
    ``lax.axis_index`` inside the mapped body (see ``_local_active``), never
    passed as a shard_map operand — a closed-over *constant* with a sharded
    in_spec is mis-sharded under jit on multi-axis meshes (devices beyond
    the first data row receive the wrong shard), which silently corrupted
    the uneven-span schedule for every data-parallel replica but the first.
    """
    L = jax.tree.leaves(params[block_key])[0].shape[0]
    spans = tuple(stage_spans) if stage_spans is not None else None
    if spans is not None:
        if len(spans) != S or sum(spans) != L or min(spans) < 1:
            raise ValueError(
                f"stage_spans {spans} must be {S} positive counts summing "
                f"to {L} layers"
            )
        if len(set(spans)) == 1:
            spans = None  # equal spans: take the unpadded fast path
    if spans is None and L % S != 0:
        raise ValueError(
            f"{L} layers not divisible by {S} stages; pass stage_spans "
            "(see balance_stages)"
        )
    n_max = max(spans) if spans is not None else L // S
    if spans is not None:
        padded_blocks, _ = _pad_stack(params[block_key], spans, n_max)
        params = dict(params)
        params[block_key] = padded_blocks
    return params, spans, n_max


def _local_active(spans, n_max, idx):
    """This stage's active-slot mask, computed per device from its stage
    index (replicated (S,) constant + local iota — safe inside shard_map,
    unlike a stage-sharded constant operand; see ``_resolve_spans``)."""
    if spans is None:
        return None
    spans_arr = jnp.asarray(spans, jnp.int32)
    return jnp.arange(n_max, dtype=jnp.int32) < spans_arr[idx]


def _make_stage_runner(block_fn, remat):
    """Per-stage forward over the local (padded) span of scanned layers."""
    one_block = jax.checkpoint(block_fn) if remat else block_fn

    def run_stage(local_blocks, active_loc, x):
        if active_loc is None:
            def body(h, layer_params):
                return one_block(layer_params, h), None

            y, _ = lax.scan(body, x, local_blocks)
        else:
            # padded slot -> identity; lax.cond (not select) so the skipped
            # block never executes — a padded stage costs only its real span
            def body(h, xs):
                layer_params, act = xs
                h2 = lax.cond(
                    act, lambda hh: one_block(layer_params, hh),
                    lambda hh: hh, h,
                )
                return h2, None

            y, _ = lax.scan(body, x, (local_blocks, active_loc))
        return y

    return run_stage


def pipeline_loss_and_grads(
    params: Any,
    tokens: jax.Array,
    *,
    mesh: Any,
    block_key: str,
    embed_fn: Callable[[Any, jax.Array], jax.Array],
    block_fn: Callable[[Any, jax.Array], jax.Array],
    head_fn: Callable[[Any, jax.Array], jax.Array],
    loss_fn: Callable[[jax.Array, jax.Array], jax.Array],
    n_microbatches: int,
    remat: bool = False,
    data_axis: str = "data",
    stage_axis: str = "stage",
    stage_spans: Optional[Sequence[int]] = None,
):
    """(loss, grads) for one pipelined step over a ('data','stage') mesh.

    ``params`` is the full param tree; ``params[block_key]`` carries a
    leading layer axis (the model-structure contract the reference imposed
    via ``nn.Sequential`` flattening, ``GPTJ.py:502-526``). ``tokens`` is
    the global (B, T) batch; each data shard is split into
    ``n_microbatches`` microbatches.

    ``stage_spans``: per-stage layer counts for an UNEQUAL partition (from
    :func:`balance_stages`); default is the even split, which requires the
    layer count to divide by the stage count. Unequal spans are executed by
    zero-padding each stage's span to the longest one and skipping padded
    slots with ``lax.cond`` — stages still hold equal-shaped shards (the
    ``shard_map`` contract) but run only their real layers.
    """
    S = mesh.shape[stage_axis]
    M = n_microbatches
    if M % S != 0:
        raise ValueError(f"n_microbatches {M} must be a multiple of stages {S}")

    params, spans, n_max = _resolve_spans(params, block_key, S, stage_spans)
    run_stage = _make_stage_runner(block_fn, remat)

    block_specs = jax.tree.map(lambda _: P(stage_axis), params[block_key])
    param_specs = {
        k: (block_specs if k == block_key else jax.tree.map(lambda _: P(), v))
        for k, v in params.items()
    }

    def local_fn(p, local_tokens):
        """Runs on one (data shard, stage): local_tokens (Bd, T) int32."""
        idx = lax.axis_index(stage_axis)
        active_loc = _local_active(spans, n_max, idx)
        blocks = p[block_key]
        other = {k: v for k, v in p.items() if k != block_key}

        Bd, T = local_tokens.shape
        if Bd % M != 0:
            raise ValueError(f"per-shard batch {Bd} not divisible by M={M}")
        mb = Bd // M
        tokens_r = local_tokens.reshape(M, mb, T)

        def loss_of(p_local):
            blocks_, other_ = p_local
            # Activation shape/dtype without computing anything.
            act = jax.eval_shape(lambda t: embed_fn(other_, t), tokens_r[0])
            act_shape, act_dtype = act.shape, act.dtype
            outs0 = jnp.zeros((M,) + act_shape, act_dtype)
            zero = jnp.zeros(act_shape, act_dtype)

            def tick(carry, t):
                prev, outs = carry
                # Lazy, stage-0-only embedding: one microbatch per tick via
                # lax.cond, so stages 1..S-1 never pay the gather and no
                # (M, ...) embedding buffer exists anywhere (r1 embedded all
                # M microbatches on every stage).
                inp0 = lax.cond(
                    jnp.logical_and(idx == 0, t < M),
                    lambda tt: embed_fn(
                        other_,
                        lax.dynamic_index_in_dim(
                            tokens_r, jnp.minimum(tt, M - 1), keepdims=False
                        ),
                    ).astype(act_dtype),
                    lambda tt: zero,
                    t,
                )
                x_in = jnp.where(idx == 0, inp0, prev)
                y = run_stage(blocks_, active_loc, x_in)
                # Record last-stage finished microbatch t-(S-1).
                slot = jnp.clip(t - (S - 1), 0, M - 1)
                cur = lax.dynamic_index_in_dim(outs, slot, keepdims=False)
                new = jnp.where(t >= S - 1, y, cur)
                outs = lax.dynamic_update_index_in_dim(outs, new, slot, 0)
                # Rotate activations one stage forward.
                y_next = lax.ppermute(
                    y, stage_axis, [(i, (i + 1) % S) for i in range(S)]
                )
                return (y_next, outs), None

            (_, outs), _ = lax.scan(
                tick, (zero, outs0), jnp.arange(M + S - 1)
            )

            # Scatter last-stage outputs: each stage receives exactly its
            # M/S chunk (psum_scatter = half a psum's wire bytes, and the
            # full (M, ...) buffer is never broadcast), then computes the
            # vocab-sized head + loss for that chunk.
            chunk = M // S
            my_outs = lax.psum_scatter(
                jnp.where(idx == S - 1, outs, jnp.zeros_like(outs)),
                stage_axis, scatter_dimension=0, tiled=True,
            )
            my_tokens = lax.dynamic_slice_in_dim(tokens_r, idx * chunk, chunk, 0)

            def one_loss(h, t):
                return loss_fn(head_fn(other_, h), t)

            # Return the per-stage PARTIAL loss (own chunk / S) and psum
            # *outside* the differentiated function.  Differentiating through
            # a trailing psum(·)/S per-device is the check_vma=False psum
            # footgun: psum's transpose re-sums the already-replicated
            # cotangent across stages, and the later g_other psum counted the
            # stage sum a second time — every gradient came out exactly S×
            # too large (masked in training only because Adam's second-moment
            # normalization is scale-invariant).
            return jnp.mean(jax.vmap(one_loss)(my_outs, my_tokens)) / S

        loss, (g_blocks, g_other) = jax.value_and_grad(loss_of)((blocks, other))
        loss = lax.psum(loss, stage_axis)
        # Cotangent bookkeeping shard_map leaves to us: replicated params get
        # per-device partial grads — sum over stages; everything averages
        # over the data axis (the DP grad sync NCCL did for the reference).
        g_other = jax.tree.map(lambda g: lax.psum(g, stage_axis), g_other)
        grads = dict(g_other)
        grads[block_key] = g_blocks
        grads = jax.tree.map(lambda g: lax.pmean(g, data_axis), grads)
        loss = lax.pmean(loss, data_axis)
        return loss, grads

    grad_specs = dict(param_specs)
    mapped = shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(param_specs, P(data_axis)),
        out_specs=(P(), grad_specs),
        check_vma=False,
    )
    loss, grads = mapped(params, tokens)
    if spans is not None:
        grads = dict(grads)
        grads[block_key] = _unpad_stack(grads[block_key], spans, n_max)
    return loss, grads


def staged_pipeline_loss_and_grads(
    params: Any,
    tokens: jax.Array,
    *,
    mesh: Any,
    block_key: str,
    embed_fn: Callable[[Any, jax.Array], jax.Array],
    block_fn: Callable[[Any, jax.Array], jax.Array],
    head_fn: Callable[[Any, jax.Array], jax.Array],
    loss_fn: Callable[[jax.Array, jax.Array], jax.Array],
    n_microbatches: int,
    remat: bool = False,
    data_axis: str = "data",
    stage_axis: str = "stage",
    stage_spans: Optional[Sequence[int]] = None,
    schedule: str = "1f1b",
    overlap: bool = False,
):
    """(loss, grads) with an *explicitly staged* backward — 1F1B by default.

    Unlike :func:`pipeline_loss_and_grads` (which differentiates the whole
    GPipe scan with ``jax.value_and_grad`` and lets AD derive the reverse
    wave), this program stages the backward by hand: each scan tick has a
    forward phase and a backward phase, and the schedule is a pair of index
    maps over a single backward launch offset ``C``::

        forward  of microbatch m on stage s at tick  s + m
        backward of microbatch m on stage s at tick  m + C - s

        schedule="1f1b":   C = 2(S-1)      — steady state interleaves one
                                             forward and one backward per
                                             tick; wall M + 2(S-1) ticks;
                                             activation stash depth 2S-1
        schedule="gpipe":  C = M + 2(S-1)  — all forwards flush first
                                             (classic GPipe order); wall
                                             2(M+S-1) ticks; stash depth M

    The two schedules share one scan body — they differ only in the Python
    constant ``C`` and the trip count — so every per-microbatch forward,
    vjp, and gradient accumulation (increasing-m order per stage) is the
    *same jaxpr* with the same inputs in both: summed gradients come out
    bit-identical, which is what lets the trial runner pick the schedule on
    realized cost alone (``tests/test_pipeline.py`` proves it on a CPU mesh).

    ``overlap=True`` double-buffers both hops: each tick FIRST issues the
    ppermutes shipping the PREVIOUS tick's activation/cotangent (held in two
    pending carry slots), then runs its forward/backward phases — the hop's
    operands predate the tick's compute, so its DMA rides underneath it.
    Index maps generalize with hop latency H (= 2 overlapped, 1 serial)::

        forward  of microbatch m on stage s at tick  H·s + m
        backward of microbatch m on stage s at tick  m + C2 + H(S-1-s)
        C2 = H(S-1) (1f1b) | M + H(S-1) (gpipe);  wall M + C2 + H(S-1)

    Per-microbatch jaxpr and per-stage accumulation order are unchanged, so
    overlapped grads are bit-identical to serial (``tests/test_overlap.py``)
    — the schedule only stretches the ramp by H.

    The backward phase recomputes the stage forward from a stashed stage
    *input* under ``jax.vjp`` (torchgpipe-style per-microbatch
    checkpointing): residency is the depth-``stash_depth(S, M, schedule)``
    input stash plus one transient set of span residuals, instead of the AD
    path's per-tick residuals for all M+S-1 dense ticks.  Unlike the GPipe
    program there is no ``M % S`` constraint (no ``psum_scatter`` head
    chunking — the last stage runs head+loss per microbatch at its own
    tick), so microbatch counts only need to divide the per-shard batch.
    """
    S = mesh.shape[stage_axis]
    M = n_microbatches
    if schedule not in PIPELINE_SCHEDULES:
        raise ValueError(f"unknown pipeline schedule {schedule!r}")
    if M < 1:
        raise ValueError(f"n_microbatches must be >= 1, got {M}")
    H = _hop_latency(overlap)
    C2 = H * (S - 1) if schedule == "1f1b" else M + H * (S - 1)
    n_ticks = M + C2 + H * (S - 1)
    D = max(1, min(M, C2 + H * (S - 1) + 1))

    params, spans, n_max = _resolve_spans(params, block_key, S, stage_spans)
    run_stage = _make_stage_runner(block_fn, remat)

    block_specs = jax.tree.map(lambda _: P(stage_axis), params[block_key])
    param_specs = {
        k: (block_specs if k == block_key else jax.tree.map(lambda _: P(), v))
        for k, v in params.items()
    }

    def local_fn(p, local_tokens):
        idx = lax.axis_index(stage_axis)
        active_loc = _local_active(spans, n_max, idx)
        blocks = p[block_key]
        other = {k: v for k, v in p.items() if k != block_key}

        Bd, T = local_tokens.shape
        if Bd % M != 0:
            raise ValueError(f"per-shard batch {Bd} not divisible by M={M}")
        mb = Bd // M
        tokens_r = local_tokens.reshape(M, mb, T)

        act = jax.eval_shape(lambda t: embed_fn(other, t), tokens_r[0])
        act_shape, act_dtype = act.shape, act.dtype
        zero_act = jnp.zeros(act_shape, act_dtype)
        loss_sd = jax.eval_shape(
            lambda a, t: loss_fn(head_fn(other, a), t),
            jax.ShapeDtypeStruct(act_shape, act_dtype),
            tokens_r[0],
        )
        zero_loss = jnp.zeros(loss_sd.shape, loss_sd.dtype)
        one_ct = jnp.ones(loss_sd.shape, loss_sd.dtype)

        def mb_fn(blocks_, other_, x_in, tok_mb):
            # One microbatch through the local span, unified across stages:
            # stage 0 embeds (its ring input is garbage and the cond
            # transpose zeros its cotangent), the last stage runs head+loss.
            # Forward ticks and the vjp-recompute backward both trace exactly
            # this function, so the per-microbatch jaxpr is
            # schedule-independent — the bit-identity anchor.
            x0 = lax.cond(
                idx == 0,
                lambda: embed_fn(other_, tok_mb).astype(act_dtype),
                lambda: x_in,
            )
            y = run_stage(blocks_, active_loc, x0)
            loss_m = lax.cond(
                idx == S - 1,
                lambda: loss_fn(head_fn(other_, y), tok_mb).astype(loss_sd.dtype),
                lambda: zero_loss,
            )
            return y, loss_m

        fwd_perm = [(i, (i + 1) % S) for i in range(S)]
        bwd_perm = [(i, (i - 1) % S) for i in range(S)]

        def phases(t, fwd_in, bwd_ct, stash, g_blocks, g_other, loss_acc):
            """One tick's forward + backward phases (hop-free).  Returns the
            produced activation/cotangent for the schedule wrapper to ship.
            Identical jaxpr per active microbatch for both hop latencies —
            the bit-identity anchor."""
            # -- forward phase: stage idx runs microbatch t - H*idx --
            mf = t - H * idx
            act_f = jnp.logical_and(mf >= 0, mf < M)
            mf_c = jnp.clip(mf, 0, M - 1)
            tok_f = lax.dynamic_index_in_dim(tokens_r, mf_c, keepdims=False)

            def fwd_run():
                y, loss_m = mb_fn(blocks, other, fwd_in, tok_f)
                # Stash the stage INPUT (not output): the backward phase
                # recomputes this stage's forward from it under vjp.  Slot
                # m % D is free by then — a microbatch is live for
                # C2 + H(S-1) - 2Hs + 1 ticks, and D = min(M, C2 + H(S-1)+1)
                # covers the worst (stage-0) span.
                new_stash = lax.dynamic_update_index_in_dim(
                    stash, fwd_in, jnp.mod(mf_c, D), 0
                )
                return y, loss_m, new_stash

            def fwd_skip():
                return zero_act, zero_loss, stash

            y, loss_m, stash = lax.cond(act_f, fwd_run, fwd_skip)
            loss_acc = loss_acc + loss_m

            # -- backward phase: stage idx pulls mb t - C2 - H*(S-1-idx) --
            mbk = t - C2 - H * (S - 1 - idx)
            act_b = jnp.logical_and(mbk >= 0, mbk < M)
            mb_c = jnp.clip(mbk, 0, M - 1)
            tok_b = lax.dynamic_index_in_dim(tokens_r, mb_c, keepdims=False)
            x_b = lax.dynamic_index_in_dim(
                stash, jnp.mod(mb_c, D), keepdims=False
            )
            # The last stage's y feeds the ring wrap (garbage at stage 0's
            # embed cond) — its activation cotangent is identically zero;
            # the loss drives its backward through ct 1.0 instead.
            ct_y = jnp.where(idx == S - 1, jnp.zeros_like(zero_act), bwd_ct)

            def bwd_run():
                _, pull = jax.vjp(
                    lambda b, o, x: mb_fn(b, o, x, tok_b), blocks, other, x_b
                )
                d_blocks, d_other, dx = pull((ct_y, one_ct))
                return (
                    jax.tree.map(jnp.add, g_blocks, d_blocks),
                    jax.tree.map(jnp.add, g_other, d_other),
                    dx,
                )

            def bwd_skip():
                return g_blocks, g_other, zero_act

            g_blocks, g_other, gx = lax.cond(act_b, bwd_run, bwd_skip)
            return y, gx, stash, g_blocks, g_other, loss_acc

        def tick(carry, t):
            # Serial (H=1): compute, then hop — the produced activation and
            # cotangent land on the neighbor for the NEXT tick.  Collective
            # hops stay OUTSIDE the phase conds — every device executes both
            # ppermutes every tick (cond branches must not diverge on
            # collectives across the gang).
            fwd_in, bwd_ct, stash, g_blocks, g_other, loss_acc = carry
            y, gx, stash, g_blocks, g_other, loss_acc = phases(
                t, fwd_in, bwd_ct, stash, g_blocks, g_other, loss_acc
            )
            fwd_next = lax.ppermute(y, stage_axis, fwd_perm)
            bwd_next = lax.ppermute(gx, stage_axis, bwd_perm)
            return (
                fwd_next, bwd_next, stash, g_blocks, g_other, loss_acc
            ), None

        def tick_overlapped(carry, t):
            # Double-buffered (H=2): the hops shipping the PREVIOUS tick's
            # activation/cotangent are issued at the TOP of the tick, before
            # the phases — their operands predate this tick's compute, so
            # the DMA rides underneath it and the hopped values are consumed
            # on the neighbor NEXT tick (2-tick effective latency, hence the
            # H=2 index maps).
            (y_pend, fwd_in, gx_pend, bwd_ct, stash,
             g_blocks, g_other, loss_acc) = carry
            fwd_next = lax.ppermute(y_pend, stage_axis, fwd_perm)
            bwd_next = lax.ppermute(gx_pend, stage_axis, bwd_perm)
            y, gx, stash, g_blocks, g_other, loss_acc = phases(
                t, fwd_in, bwd_ct, stash, g_blocks, g_other, loss_acc
            )
            return (
                y, fwd_next, gx, bwd_next, stash,
                g_blocks, g_other, loss_acc,
            ), None

        stash0 = jnp.zeros((D,) + act_shape, act_dtype)
        g0 = (
            jax.tree.map(jnp.zeros_like, blocks),
            jax.tree.map(jnp.zeros_like, other),
        )
        if overlap:
            carry0 = (
                zero_act, zero_act, zero_act, zero_act, stash0,
                g0[0], g0[1], zero_loss,
            )
            (_, _, _, _, _, g_blocks, g_other, loss_acc), _ = lax.scan(
                tick_overlapped, carry0, jnp.arange(n_ticks)
            )
        else:
            carry0 = (zero_act, zero_act, stash0, g0[0], g0[1], zero_loss)
            (_, _, _, g_blocks, g_other, loss_acc), _ = lax.scan(
                tick, carry0, jnp.arange(n_ticks)
            )

        # loss_acc is nonzero only on the last stage; each loss_m is a
        # per-microbatch mean, so /M matches the dense/GPipe convention.
        loss = lax.psum(loss_acc, stage_axis) / M
        g_other = jax.tree.map(lambda g: lax.psum(g, stage_axis), g_other)
        grads = dict(g_other)
        grads[block_key] = g_blocks
        grads = jax.tree.map(lambda g: g / M, grads)
        grads = jax.tree.map(lambda g: lax.pmean(g, data_axis), grads)
        loss = lax.pmean(loss, data_axis)
        return loss, grads

    grad_specs = dict(param_specs)
    mapped = shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(param_specs, P(data_axis)),
        out_specs=(P(), grad_specs),
        check_vma=False,
    )
    loss, grads = mapped(params, tokens)
    if spans is not None:
        grads = dict(grads)
        grads[block_key] = _unpad_stack(grads[block_key], spans, n_max)
    return loss, grads


def pipeline_hints(spec: Any) -> Dict[str, Any]:
    """Extract and validate the model's pipeline decomposition hints."""
    h = spec.hints.get("pipeline")
    if h is None:
        raise ValueError(
            "model does not expose pipeline hints "
            "(hints['pipeline'] with embed/block/head fns)"
        )
    return h
