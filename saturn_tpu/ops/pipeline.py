"""GPipe microbatch schedule over a ``stage`` mesh axis, as one jittable op.

TPU-native replacement for torchgpipe (reference ``Pipeline.py:24-167``,
SURVEY.md §2.2): where torchgpipe partitions an ``nn.Sequential`` across GPUs
and streams microbatches over CUDA copies, here the scanned layer stack is
*sharded* over a ``stage`` mesh axis and microbatch activations rotate between
neighbor stages with ``lax.ppermute`` — point-to-point hops that ride ICI.

The whole schedule lives inside ``shard_map`` and is differentiated with
``jax.value_and_grad`` *inside* the mapped body: ``ppermute``'s transpose is
the inverse permutation, so reverse-mode AD automatically yields the reverse
pipeline schedule (activations flow last→first stage in the backward pass),
with no hand-written backward.

Schedule shape (classic GPipe, bubble fraction (S-1)/(M+S-1)):

    t:      0    1    2    ...                    M+S-2
    stage0  mb0  mb1  mb2  ...  mbM-1  -    -
    stage1  -    mb0  mb1  ...         mbM-1 -
    stage2  -    -    mb0  ...               mbM-1

The language-model head is *not* computed inside the schedule loop (which
would redo it on every stage every tick): last-stage outputs are
``psum_scatter``-ed so each stage receives exactly its M/S chunk and computes
the head + loss for it — balancing the vocab-sized matmul across the gang at
half the wire cost of a full psum broadcast, with no (M, ...) activation
buffer materialized per stage. Embeddings are likewise computed lazily, one
microbatch per tick and only on stage 0 (``lax.cond``), instead of all M
up front on every stage (VERDICT r1 weak item 8).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from saturn_tpu.ops.shmap_compat import shard_map


def balance_stages(costs: Sequence[float], n_stages: int) -> Tuple[int, ...]:
    """Contiguous layer->stage partition minimizing the max per-stage cost.

    Returns per-stage layer counts (len ``n_stages``, sums to ``len(costs)``,
    every span >= 1). The TPU-native analog of torchgpipe's
    ``balance_by_time`` (reference ``Pipeline.py:94-103``): the reference
    timed each layer on one GPU and block-partitioned; here the costs come
    from the model's ``layer_costs`` hint (profiled or FLOP-derived) and the
    exact DP replaces the reference's heuristic — L is tens, so the
    O(S·L²) linear-partition DP is free at trace time.
    """
    L = len(costs)
    S = n_stages
    if S < 1 or S > L:
        raise ValueError(f"cannot split {L} layers into {S} stages")
    prefix = [0.0]
    for c in costs:
        prefix.append(prefix[-1] + float(c))

    def span_cost(i, j):  # layers [i, j)
        return prefix[j] - prefix[i]

    INF = (float("inf"), float("inf"))
    # best[s][j] = lexicographic (max stage cost, max span length) splitting
    # layers [0, j) into s stages. The secondary criterion breaks max-cost
    # ties toward the smallest longest span: n_max sets every stage's padded
    # param residency and scan length, so a tie spent on a longer span is
    # pure memory/schedule waste.
    best = [[INF] * (L + 1) for _ in range(S + 1)]
    cut = [[0] * (L + 1) for _ in range(S + 1)]
    best[0][0] = (0.0, 0)
    for s in range(1, S + 1):
        for j in range(s, L - (S - s) + 1):
            for i in range(s - 1, j):
                prev = best[s - 1][i]
                cand = (max(prev[0], span_cost(i, j)), max(prev[1], j - i))
                if cand < best[s][j]:
                    best[s][j] = cand
                    cut[s][j] = i
    spans = []
    j = L
    for s in range(S, 0, -1):
        i = cut[s][j]
        spans.append(j - i)
        j = i
    return tuple(reversed(spans))


def _pad_stack(blocks: Any, spans: Sequence[int], n_max: int):
    """Repack a (L, ...) stacked layer tree into (S*n_max, ...) span-major
    order, zero-padding each stage's span to ``n_max`` — the equal-shard
    layout ``shard_map`` needs. Returns (padded_tree, active_mask)."""
    bounds = [0]
    for s in spans:
        bounds.append(bounds[-1] + s)

    def pad_leaf(a):
        parts = []
        for i, s in enumerate(spans):
            seg = a[bounds[i]:bounds[i + 1]]
            if s < n_max:
                pad = jnp.zeros((n_max - s,) + a.shape[1:], a.dtype)
                seg = jnp.concatenate([seg, pad], axis=0)
            parts.append(seg)
        return jnp.concatenate(parts, axis=0)

    active = jnp.asarray(
        [k < s for s in spans for k in range(n_max)], dtype=jnp.bool_
    )
    return jax.tree.map(pad_leaf, blocks), active


def _unpad_stack(padded: Any, spans: Sequence[int], n_max: int):
    """Inverse of :func:`_pad_stack` for the gradient tree."""
    def unpad_leaf(a):
        segs = [
            a[i * n_max: i * n_max + s] for i, s in enumerate(spans)
        ]
        return jnp.concatenate(segs, axis=0)

    return jax.tree.map(unpad_leaf, padded)


def pipeline_loss_and_grads(
    params: Any,
    tokens: jax.Array,
    *,
    mesh: Any,
    block_key: str,
    embed_fn: Callable[[Any, jax.Array], jax.Array],
    block_fn: Callable[[Any, jax.Array], jax.Array],
    head_fn: Callable[[Any, jax.Array], jax.Array],
    loss_fn: Callable[[jax.Array, jax.Array], jax.Array],
    n_microbatches: int,
    remat: bool = False,
    data_axis: str = "data",
    stage_axis: str = "stage",
    stage_spans: Optional[Sequence[int]] = None,
):
    """(loss, grads) for one pipelined step over a ('data','stage') mesh.

    ``params`` is the full param tree; ``params[block_key]`` carries a
    leading layer axis (the model-structure contract the reference imposed
    via ``nn.Sequential`` flattening, ``GPTJ.py:502-526``). ``tokens`` is
    the global (B, T) batch; each data shard is split into
    ``n_microbatches`` microbatches.

    ``stage_spans``: per-stage layer counts for an UNEQUAL partition (from
    :func:`balance_stages`); default is the even split, which requires the
    layer count to divide by the stage count. Unequal spans are executed by
    zero-padding each stage's span to the longest one and skipping padded
    slots with ``lax.cond`` — stages still hold equal-shaped shards (the
    ``shard_map`` contract) but run only their real layers.
    """
    S = mesh.shape[stage_axis]
    M = n_microbatches
    if M % S != 0:
        raise ValueError(f"n_microbatches {M} must be a multiple of stages {S}")

    L = jax.tree.leaves(params[block_key])[0].shape[0]
    spans = tuple(stage_spans) if stage_spans is not None else None
    if spans is not None:
        if len(spans) != S or sum(spans) != L or min(spans) < 1:
            raise ValueError(
                f"stage_spans {spans} must be {S} positive counts summing "
                f"to {L} layers"
            )
        if len(set(spans)) == 1:
            spans = None  # equal spans: take the unpadded fast path
    if spans is None and L % S != 0:
        raise ValueError(
            f"{L} layers not divisible by {S} stages; pass stage_spans "
            "(see balance_stages)"
        )
    n_max = max(spans) if spans is not None else L // S

    active = None
    if spans is not None:
        padded_blocks, active = _pad_stack(params[block_key], spans, n_max)
        params = dict(params)
        params[block_key] = padded_blocks

    one_block = jax.checkpoint(block_fn) if remat else block_fn

    def run_stage(local_blocks, active_loc, x):
        if active_loc is None:
            def body(h, layer_params):
                return one_block(layer_params, h), None

            y, _ = lax.scan(body, x, local_blocks)
        else:
            # padded slot -> identity; lax.cond (not select) so the skipped
            # block never executes — a padded stage costs only its real span
            def body(h, xs):
                layer_params, act = xs
                h2 = lax.cond(
                    act, lambda hh: one_block(layer_params, hh),
                    lambda hh: hh, h,
                )
                return h2, None

            y, _ = lax.scan(body, x, (local_blocks, active_loc))
        return y

    block_specs = jax.tree.map(lambda _: P(stage_axis), params[block_key])
    param_specs = {
        k: (block_specs if k == block_key else jax.tree.map(lambda _: P(), v))
        for k, v in params.items()
    }

    def local_fn(p, local_tokens, active_loc=None):
        """Runs on one (data shard, stage): local_tokens (Bd, T) int32."""
        idx = lax.axis_index(stage_axis)
        blocks = p[block_key]
        other = {k: v for k, v in p.items() if k != block_key}

        Bd, T = local_tokens.shape
        if Bd % M != 0:
            raise ValueError(f"per-shard batch {Bd} not divisible by M={M}")
        mb = Bd // M
        tokens_r = local_tokens.reshape(M, mb, T)

        def loss_of(p_local):
            blocks_, other_ = p_local
            # Activation shape/dtype without computing anything.
            act = jax.eval_shape(lambda t: embed_fn(other_, t), tokens_r[0])
            act_shape, act_dtype = act.shape, act.dtype
            outs0 = jnp.zeros((M,) + act_shape, act_dtype)
            zero = jnp.zeros(act_shape, act_dtype)

            def tick(carry, t):
                prev, outs = carry
                # Lazy, stage-0-only embedding: one microbatch per tick via
                # lax.cond, so stages 1..S-1 never pay the gather and no
                # (M, ...) embedding buffer exists anywhere (r1 embedded all
                # M microbatches on every stage).
                inp0 = lax.cond(
                    jnp.logical_and(idx == 0, t < M),
                    lambda tt: embed_fn(
                        other_,
                        lax.dynamic_index_in_dim(
                            tokens_r, jnp.minimum(tt, M - 1), keepdims=False
                        ),
                    ).astype(act_dtype),
                    lambda tt: zero,
                    t,
                )
                x_in = jnp.where(idx == 0, inp0, prev)
                y = run_stage(blocks_, active_loc, x_in)
                # Record last-stage finished microbatch t-(S-1).
                slot = jnp.clip(t - (S - 1), 0, M - 1)
                cur = lax.dynamic_index_in_dim(outs, slot, keepdims=False)
                new = jnp.where(t >= S - 1, y, cur)
                outs = lax.dynamic_update_index_in_dim(outs, new, slot, 0)
                # Rotate activations one stage forward.
                y_next = lax.ppermute(
                    y, stage_axis, [(i, (i + 1) % S) for i in range(S)]
                )
                return (y_next, outs), None

            (_, outs), _ = lax.scan(
                tick, (zero, outs0), jnp.arange(M + S - 1)
            )

            # Scatter last-stage outputs: each stage receives exactly its
            # M/S chunk (psum_scatter = half a psum's wire bytes, and the
            # full (M, ...) buffer is never broadcast), then computes the
            # vocab-sized head + loss for that chunk.
            chunk = M // S
            my_outs = lax.psum_scatter(
                jnp.where(idx == S - 1, outs, jnp.zeros_like(outs)),
                stage_axis, scatter_dimension=0, tiled=True,
            )
            my_tokens = lax.dynamic_slice_in_dim(tokens_r, idx * chunk, chunk, 0)

            def one_loss(h, t):
                return loss_fn(head_fn(other_, h), t)

            loss_chunk = jnp.mean(jax.vmap(one_loss)(my_outs, my_tokens))
            return lax.psum(loss_chunk, stage_axis) / S

        loss, (g_blocks, g_other) = jax.value_and_grad(loss_of)((blocks, other))
        # Cotangent bookkeeping shard_map leaves to us: replicated params get
        # per-device partial grads — sum over stages; everything averages
        # over the data axis (the DP grad sync NCCL did for the reference).
        g_other = jax.tree.map(lambda g: lax.psum(g, stage_axis), g_other)
        grads = dict(g_other)
        grads[block_key] = g_blocks
        grads = jax.tree.map(lambda g: lax.pmean(g, data_axis), grads)
        loss = lax.pmean(loss, data_axis)
        return loss, grads

    grad_specs = dict(param_specs)
    if active is not None:
        mapped = shard_map(
            local_fn,
            mesh=mesh,
            in_specs=(param_specs, P(data_axis), P(stage_axis)),
            out_specs=(P(), grad_specs),
            check_vma=False,
        )
        loss, grads = mapped(params, tokens, active)
        grads = dict(grads)
        grads[block_key] = _unpad_stack(grads[block_key], spans, n_max)
        return loss, grads
    mapped = shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(param_specs, P(data_axis)),
        out_specs=(P(), grad_specs),
        check_vma=False,
    )
    return mapped(params, tokens)


def pipeline_hints(spec: Any) -> Dict[str, Any]:
    """Extract and validate the model's pipeline decomposition hints."""
    h = spec.hints.get("pipeline")
    if h is None:
        raise ValueError(
            "model does not expose pipeline hints "
            "(hints['pipeline'] with embed/block/head fns)"
        )
    return h
