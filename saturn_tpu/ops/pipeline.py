"""GPipe microbatch schedule over a ``stage`` mesh axis, as one jittable op.

TPU-native replacement for torchgpipe (reference ``Pipeline.py:24-167``,
SURVEY.md §2.2): where torchgpipe partitions an ``nn.Sequential`` across GPUs
and streams microbatches over CUDA copies, here the scanned layer stack is
*sharded* over a ``stage`` mesh axis and microbatch activations rotate between
neighbor stages with ``lax.ppermute`` — point-to-point hops that ride ICI.

The whole schedule lives inside ``shard_map`` and is differentiated with
``jax.value_and_grad`` *inside* the mapped body: ``ppermute``'s transpose is
the inverse permutation, so reverse-mode AD automatically yields the reverse
pipeline schedule (activations flow last→first stage in the backward pass),
with no hand-written backward.

Schedule shape (classic GPipe, bubble fraction (S-1)/(M+S-1)):

    t:      0    1    2    ...                    M+S-2
    stage0  mb0  mb1  mb2  ...  mbM-1  -    -
    stage1  -    mb0  mb1  ...         mbM-1 -
    stage2  -    -    mb0  ...               mbM-1

The language-model head is *not* computed inside the schedule loop (which
would redo it on every stage every tick): last-stage outputs are
``psum_scatter``-ed so each stage receives exactly its M/S chunk and computes
the head + loss for it — balancing the vocab-sized matmul across the gang at
half the wire cost of a full psum broadcast, with no (M, ...) activation
buffer materialized per stage. Embeddings are likewise computed lazily, one
microbatch per tick and only on stage 0 (``lax.cond``), instead of all M
up front on every stage (VERDICT r1 weak item 8).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def pipeline_loss_and_grads(
    params: Any,
    tokens: jax.Array,
    *,
    mesh: Any,
    block_key: str,
    embed_fn: Callable[[Any, jax.Array], jax.Array],
    block_fn: Callable[[Any, jax.Array], jax.Array],
    head_fn: Callable[[Any, jax.Array], jax.Array],
    loss_fn: Callable[[jax.Array, jax.Array], jax.Array],
    n_microbatches: int,
    remat: bool = False,
    data_axis: str = "data",
    stage_axis: str = "stage",
):
    """(loss, grads) for one pipelined step over a ('data','stage') mesh.

    ``params`` is the full param tree; ``params[block_key]`` must carry a
    leading layer axis divisible by the stage count (the model-structure
    contract the reference imposed via ``nn.Sequential`` flattening,
    ``GPTJ.py:502-526``). ``tokens`` is the global (B, T) batch; each data
    shard is split into ``n_microbatches`` microbatches.
    """
    S = mesh.shape[stage_axis]
    M = n_microbatches
    if M % S != 0:
        raise ValueError(f"n_microbatches {M} must be a multiple of stages {S}")

    one_block = jax.checkpoint(block_fn) if remat else block_fn

    def run_stage(local_blocks, x):
        def body(h, layer_params):
            return one_block(layer_params, h), None

        y, _ = lax.scan(body, x, local_blocks)
        return y

    block_specs = jax.tree.map(lambda _: P(stage_axis), params[block_key])
    param_specs = {
        k: (block_specs if k == block_key else jax.tree.map(lambda _: P(), v))
        for k, v in params.items()
    }

    def local_fn(p, local_tokens):
        """Runs on one (data shard, stage): local_tokens (Bd, T) int32."""
        idx = lax.axis_index(stage_axis)
        blocks = p[block_key]
        other = {k: v for k, v in p.items() if k != block_key}

        Bd, T = local_tokens.shape
        if Bd % M != 0:
            raise ValueError(f"per-shard batch {Bd} not divisible by M={M}")
        mb = Bd // M
        tokens_r = local_tokens.reshape(M, mb, T)

        def loss_of(p_local):
            blocks_, other_ = p_local
            # Activation shape/dtype without computing anything.
            act = jax.eval_shape(lambda t: embed_fn(other_, t), tokens_r[0])
            act_shape, act_dtype = act.shape, act.dtype
            outs0 = jnp.zeros((M,) + act_shape, act_dtype)
            zero = jnp.zeros(act_shape, act_dtype)

            def tick(carry, t):
                prev, outs = carry
                # Lazy, stage-0-only embedding: one microbatch per tick via
                # lax.cond, so stages 1..S-1 never pay the gather and no
                # (M, ...) embedding buffer exists anywhere (r1 embedded all
                # M microbatches on every stage).
                inp0 = lax.cond(
                    jnp.logical_and(idx == 0, t < M),
                    lambda tt: embed_fn(
                        other_,
                        lax.dynamic_index_in_dim(
                            tokens_r, jnp.minimum(tt, M - 1), keepdims=False
                        ),
                    ).astype(act_dtype),
                    lambda tt: zero,
                    t,
                )
                x_in = jnp.where(idx == 0, inp0, prev)
                y = run_stage(blocks_, x_in)
                # Record last-stage finished microbatch t-(S-1).
                slot = jnp.clip(t - (S - 1), 0, M - 1)
                cur = lax.dynamic_index_in_dim(outs, slot, keepdims=False)
                new = jnp.where(t >= S - 1, y, cur)
                outs = lax.dynamic_update_index_in_dim(outs, new, slot, 0)
                # Rotate activations one stage forward.
                y_next = lax.ppermute(
                    y, stage_axis, [(i, (i + 1) % S) for i in range(S)]
                )
                return (y_next, outs), None

            (_, outs), _ = lax.scan(
                tick, (zero, outs0), jnp.arange(M + S - 1)
            )

            # Scatter last-stage outputs: each stage receives exactly its
            # M/S chunk (psum_scatter = half a psum's wire bytes, and the
            # full (M, ...) buffer is never broadcast), then computes the
            # vocab-sized head + loss for that chunk.
            chunk = M // S
            my_outs = lax.psum_scatter(
                jnp.where(idx == S - 1, outs, jnp.zeros_like(outs)),
                stage_axis, scatter_dimension=0, tiled=True,
            )
            my_tokens = lax.dynamic_slice_in_dim(tokens_r, idx * chunk, chunk, 0)

            def one_loss(h, t):
                return loss_fn(head_fn(other_, h), t)

            loss_chunk = jnp.mean(jax.vmap(one_loss)(my_outs, my_tokens))
            return lax.psum(loss_chunk, stage_axis) / S

        loss, (g_blocks, g_other) = jax.value_and_grad(loss_of)((blocks, other))
        # Cotangent bookkeeping shard_map leaves to us: replicated params get
        # per-device partial grads — sum over stages; everything averages
        # over the data axis (the DP grad sync NCCL did for the reference).
        g_other = jax.tree.map(lambda g: lax.psum(g, stage_axis), g_other)
        grads = dict(g_other)
        grads[block_key] = g_blocks
        grads = jax.tree.map(lambda g: lax.pmean(g, data_axis), grads)
        loss = lax.pmean(loss, data_axis)
        return loss, grads

    grad_specs = dict(param_specs)
    mapped = jax.shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(param_specs, P(data_axis)),
        out_specs=(P(), grad_specs),
        check_vma=False,
    )
    return mapped(params, tokens)


def pipeline_hints(spec: Any) -> Dict[str, Any]:
    """Extract and validate the model's pipeline decomposition hints."""
    h = spec.hints.get("pipeline")
    if h is None:
        raise ValueError(
            "model does not expose pipeline hints "
            "(hints['pipeline'] with embed/block/head fns)"
        )
    return h
