"""Collective matmul and ZeRO-3 prefetch: overlap-friendly sharded matmuls.

GSPMD lowers a ZeRO-sharded matmul as ``all-gather(W) -> dot_general``: every
MAC waits for the last gather hop (arxiv 2105.04663 §3.3 calls this out and
shows the fix). The collective-matmul decomposition splits the gather into S
ring hops interleaved with S partial ``dot_general``s, so hop s+1 streams
behind partial product s. The same idea applied across the scanned block
stack is ZeRO-3 prefetch: gather layer k+1's shards while layer k computes.

Both rewrites live behind config knobs on the fsdp/tp executors
(``parallel/fsdp.py``, ``parallel/tp.py``) and are profiled as grid
dimensions — realized cost picks overlapped vs serial, never faith. The
serial and prefetched ZeRO-3 programs are bit-identical (gathers are pure
data movement; the compute order never changes); the interleaved collective
matmul reassociates the contraction, so it is compared to the plain lowering
with a tolerance, never bitwise.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from saturn_tpu.ops.shmap_compat import shard_map

# Version tag for profile-cache fingerprints: bump when the overlapped
# lowering changes shape (a serial profile must never price an overlapped
# program, and vice versa).
OVERLAP_SET_VERSION = 1


def overlap_signature() -> str:
    """Content signature of the overlap machinery for cache identities."""
    return f"comm-overlap-v{OVERLAP_SET_VERSION}"


# ------------------------------------------------------------ ring gather
def ring_all_gather(
    x: jax.Array, *, axis_name: str, axis_size: int, axis: int = 0
) -> jax.Array:
    """All-gather ``x`` along ``axis`` via S-1 neighbor hops.

    Must be called inside ``shard_map``. Equivalent to
    ``lax.all_gather(..., tiled=True)`` but decomposed into ``ppermute``
    hops so the caller's scan can float each hop under unrelated compute
    (the ZeRO-3 prefetch consumer below). Chunk placement is by source
    index, so the result is the in-order concatenation — identical on every
    device and independent of hop scheduling.
    """
    S = int(axis_size)
    if S == 1:
        return x
    idx = lax.axis_index(axis_name)
    # Send my current chunk to the next device: after s hops I hold the
    # chunk that originated at (idx - s) % S.
    perm = [(j, (j + 1) % S) for j in range(S)]
    c = x.shape[axis]
    buf = jnp.zeros(
        x.shape[:axis] + (c * S,) + x.shape[axis + 1 :], dtype=x.dtype
    )

    def place(b, piece, s):
        src = (idx - s) % S
        return lax.dynamic_update_slice_in_dim(b, piece, src * c, axis)

    def step(carry, s):
        b, cur = carry
        nxt = lax.ppermute(cur, axis_name, perm)
        b = place(b, cur, s)
        return (b, nxt), None

    (buf, last), _ = lax.scan(step, (buf, x), jnp.arange(S - 1))
    return place(buf, last, S - 1)


# ------------------------------------------------------ collective matmul
def allgather_matmul(
    x: jax.Array,
    w_shard: jax.Array,
    *,
    axis_name: str,
    axis_size: int,
    overlap: bool = True,
) -> jax.Array:
    """``x @ unshard(w_shard)`` for a weight sharded on its contracting dim.

    ``w_shard`` is the local ``(K/S, N)`` row block of a ``(K, N)`` weight;
    ``x`` is ``(..., K)`` and replicated. Serial (``overlap=False``) is the
    GSPMD lowering: chain the S-1 gather hops, then one ``dot_general`` —
    the first MAC waits on the last hop. Overlapped interleaves: each hop's
    chunk feeds a partial ``dot_general`` accumulated immediately, so hop
    s+1 streams behind partial product s. The two forms reassociate the K
    contraction (chunked sum vs one reduction) — numerically close, not
    bitwise equal.
    """
    S = int(axis_size)
    if S == 1:
        return x @ w_shard
    idx = lax.axis_index(axis_name)
    perm = [(j, (j + 1) % S) for j in range(S)]
    c = w_shard.shape[0]

    def x_block(src):
        return lax.dynamic_slice_in_dim(x, src * c, c, axis=-1)

    if not overlap:
        w = ring_all_gather(
            w_shard, axis_name=axis_name, axis_size=S, axis=0
        )
        return x @ w

    def step(carry, s):
        acc, cur = carry
        nxt = lax.ppermute(cur, axis_name, perm)
        acc = acc + x_block((idx - s) % S) @ cur
        return (acc, nxt), None

    acc = jnp.zeros(x.shape[:-1] + (w_shard.shape[-1],), dtype=x.dtype)
    (acc, last), _ = lax.scan(step, (acc, w_shard), jnp.arange(S - 1))
    return acc + x_block((idx - (S - 1)) % S) @ last


# --------------------------------------------------------- ZeRO-3 program
def _block_dim(shape: Tuple[int, ...], n_shard: int, min_size: int) -> Optional[int]:
    """Shard dim for a stacked block leaf ``(L, ...)``: largest trailing dim
    divisible by the axis size (ties prefer later dims, matching
    ``sharding.fsdp_rules``); ``None`` keeps the leaf replicated."""
    if len(shape) < 2 or int(np.prod(shape)) < min_size:
        return None
    best, best_size = None, -1
    for i, s in enumerate(shape[1:], start=1):
        if s % n_shard == 0 and s >= best_size:
            best, best_size = i, s
    return best


def zero3_block_rules(block_key: str = "blocks", axis: str = "data",
                      min_size: int = 1024):
    """Sharding rules matching :func:`zero3_loss_and_grads` in_specs: block
    stack leaves shard their largest non-layer dim over ``axis``; everything
    else (embeddings, norms, head) stays replicated. Works on full state
    paths ('params/blocks/w', 'opt_state/0/mu/blocks/w', ...)."""
    seg = re.compile(rf"(^|/){re.escape(block_key)}(/|$)")

    def rules(path: str, shape: Tuple[int, ...], mesh_axes) -> P:
        if not seg.search(path):
            return P()
        d = _block_dim(tuple(shape), mesh_axes[axis], min_size)
        if d is None:
            return P()
        spec = [None] * len(shape)
        spec[d] = axis
        return P(*spec)

    return rules


def zero3_loss_and_grads(
    params: Any,
    tokens: jax.Array,
    *,
    mesh: Any,
    embed_fn: Callable[[Any, jax.Array], jax.Array],
    block_fn: Callable[[Any, jax.Array], jax.Array],
    head_fn: Callable[[Any, jax.Array], jax.Array],
    loss_fn: Callable[[jax.Array, jax.Array], jax.Array],
    block_key: str = "blocks",
    shard_axis: str = "data",
    batch_axes: Optional[Sequence[str]] = None,
    prefetch: bool = True,
    remat: bool = False,
    min_size: int = 1024,
):
    """(loss, grads) for one ZeRO-3 step with explicit, prefetchable gathers.

    The block stack enters sharded per :func:`zero3_block_rules`; the scan
    over layers gathers each layer's shards with :func:`ring_all_gather`.
    ``prefetch=True`` gathers layer k+1 inside layer k's scan step (the
    hops carry no dependence on the step's compute, so they ride under it);
    ``prefetch=False`` gathers layer k on the critical path, the GSPMD-like
    serial lowering. Both orders see identical values — bit-identical loss
    and grads, proven by tests/test_overlap.py.

    ``batch_axes``: mesh axes the batch dim shards over (default: every
    mesh axis), letting tp reuse the program as its weight-gathered
    lowering — batch over ('data','model'), shards over 'model'.
    """
    axes = tuple(batch_axes) if batch_axes is not None else tuple(mesh.axis_names)
    S = int(mesh.shape[shard_axis])
    n_members = int(np.prod([mesh.shape[a] for a in axes]))

    blocks = params[block_key]
    leaves = jax.tree_util.tree_leaves(blocks)
    if not leaves:
        raise ValueError(f"params[{block_key!r}] has no leaves")
    L = int(leaves[0].shape[0])

    # Static per-leaf shard dims (-1 = replicated; None would vanish as an
    # empty pytree): the in_specs and the in-scan gather must agree
    # leaf-for-leaf or the program reshards silently.
    dims = jax.tree.map(
        lambda a: _block_dim(tuple(a.shape), S, min_size) or -1, blocks
    )

    def _pspec(ndim: int, d: int) -> P:
        spec = [None] * ndim
        if d >= 0:
            spec[d] = shard_axis
        return P(*spec)

    in_block_specs = jax.tree.map(
        lambda a, d: _pspec(a.ndim, d), blocks, dims
    )
    param_specs = {
        k: (in_block_specs if k == block_key
            else jax.tree.map(lambda a: P(), v))
        for k, v in params.items()
    }
    batch_spec = P(axes)

    def gather_layer(lp):
        def one(a, d):
            if d < 0:
                return a
            return ring_all_gather(
                a, axis_name=shard_axis, axis_size=S, axis=d - 1
            )

        return jax.tree.map(one, lp, dims)

    def layer_shard(stack, k):
        return jax.tree.map(
            lambda a: lax.dynamic_index_in_dim(a, k, axis=0, keepdims=False),
            stack,
        )

    blk = jax.checkpoint(block_fn) if remat else block_fn

    def local_fn(p, tok):
        def loss_of(pp):
            stack = pp[block_key]
            other = {k: v for k, v in pp.items() if k != block_key}
            h = embed_fn(other, tok)
            if prefetch:
                def body(carry, k):
                    hh, cur_full = carry
                    # Issue layer k+1's gather hops before layer k's
                    # compute: no data dependence, the DMA rides under it.
                    nxt = gather_layer(
                        layer_shard(stack, jnp.minimum(k + 1, L - 1))
                    )
                    hh = blk(cur_full, hh)
                    return (hh, nxt), None

                first = gather_layer(layer_shard(stack, 0))
                (h_out, _), _ = lax.scan(body, (h, first), jnp.arange(L))
            else:
                def body(hh, k):
                    return blk(gather_layer(layer_shard(stack, k)), hh), None

                h_out, _ = lax.scan(body, h, jnp.arange(L))
            logits = head_fn(other, h_out)
            # LOCAL mean only: differentiating a psum'd scalar bakes the
            # psum transpose convention (identity vs psum — it changed
            # across jax releases) into the grad scale. Normalizing outside
            # the grad is convention-independent.
            return loss_fn(logits, tok)

        loss, grads = jax.value_and_grad(loss_of)(p)
        loss = lax.psum(loss, axes) / n_members
        # Sharded leaves already hold the total over the gather ring (every
        # remote use along ``shard_axis`` backpropagates home through the
        # reversed ring) — psum the remaining batch axes. Replicated leaves
        # hold only the local contribution and psum everything.
        rest = tuple(a for a in axes if a != shard_axis)
        out = {}
        for k, v in grads.items():
            if k == block_key:
                out[k] = jax.tree.map(
                    lambda g, d: (
                        (lax.psum(g, rest) if rest else g) if d >= 0
                        else lax.psum(g, axes)
                    ) / n_members,
                    v, dims,
                )
            else:
                out[k] = jax.tree.map(
                    lambda g: lax.psum(g, axes) / n_members, v
                )
        return loss, out

    mapped = shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(param_specs, batch_spec),
        out_specs=(P(), param_specs),
        check_vma=False,
    )
    return mapped(params, tokens)
