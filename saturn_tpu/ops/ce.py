"""Fused linear-cross-entropy for TPU: head matmul + softmax CE, Pallas.

The single largest non-matmul cost in the round-3 profiler trace of the
config-#1 step (GPT-2-small b8x512 on v5e) was the logits pipeline: XLA
materializes f32 logits (B,T,V) = 824 MiB for the loss, a bf16 stash for the
backward, a separately-fused dlogits (softmax gradient) tensor, and three
reduce/broadcast fusions over (B,T,V) — together ~10% of device time at zero
FLOPs utilization, and the allocation that OOMs b8x2048 (BASELINE.md
attention table). The reference hit the same wall differently: its 6B
example shrank batch sizes until torch's unfused CE fit
(``/root/reference/examples/wikitext103/WikiText103.py:62-71``).

This op computes ``mean CE(x @ W^T, labels)`` without ever materializing f32
logits or the softmax gradient:

- **fwd** tiles (token-block x vocab-block), runs the head matmul per tile,
  and carries the online-logsumexp recurrence (flash-attention-style, over
  the vocab axis) plus a masked gather of the label logit in VMEM scratch.
  In stash mode it also writes ONE (N, V) tensor — a bf16 logits stash for
  the backward, the same thing XLA's own CE backward keeps (round-3 trace:
  ``fusion.227``'s bf16 output); in recompute mode it writes no (N, V)
  tensor at all.
- **bwd** forms ``ds = softmax(logits) - onehot(labels)`` in registers and
  feeds it straight to the MXU — dx = ds @ W over vocab blocks, dW =
  ds^T @ x over token blocks. Two source modes (``stash`` arg): read the
  fwd's bf16 logits stash (same three matmul passes as XLA, none of the
  elementwise (N, V) fusions), or — long-context mode — recompute each
  score block from x·W^T in-kernel, which costs one extra matmul pass per
  backward kernel and needs ZERO O(N·V) memory.

Masked tokens use label -1 (the standard ignore index): they never match a
vocab column, and the wrapper zeros their loss and (via the mean's cotangent)
their gradient. The vocab axis is padded to a block multiple inside the op —
padded columns get -1e30 logits, so they vanish from the softmax and the
gradient; the pad is fused into the bf16 weight cast XLA performs anyway.

Like ``ops/flash.py``, real lowering needs the TPU backend; interpret mode
exists for CPU numerics tests (``tests/test_ce.py``). Off-TPU (or for token
counts no block divides) :func:`fused_linear_cross_entropy` itself computes
the identical objective through plain XLA ops
(:func:`dense_linear_cross_entropy`), so callers — ``models/gpt2.py``'s
``fused_loss_fn`` — can use it unconditionally.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _dot(a, b, dims):
    return jax.lax.dot_general(a, b, (dims, ((), ())),
                               preferred_element_type=jnp.float32)


def _col_ids(vb, block_n, block_v):
    """(BN, BV) int32 absolute vocab column ids for vocab block vb."""
    return vb * block_v + jax.lax.broadcasted_iota(
        jnp.int32, (block_n, block_v), 1
    )


# --------------------------------------------------------------------- fwd
def _fwd_kernel(x_ref, w_ref, lab_ref, *refs,
                block_n, block_v, n_vocab, masked, stash):
    if stash:
        logits_ref, loss_ref, lse_ref, m_scr, l_scr, lbl_scr = refs
    else:
        loss_ref, lse_ref, m_scr, l_scr, lbl_scr = refs
    vb = pl.program_id(1)
    n_v = pl.num_programs(1)

    @pl.when(vb == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        lbl_scr[:] = jnp.zeros_like(lbl_scr)

    s = _dot(x_ref[...], w_ref[...], ((1,), (1,)))        # (BN, BV) f32
    col = _col_ids(vb, block_n, block_v)
    if masked:
        # pad columns → -inf logits; the stash (or the bwd recompute, which
        # applies the same mask) carries them into the backward, where
        # exp(-1e30 - lse) = 0 kills their gradient too
        s = jnp.where(col < n_vocab, s, NEG_INF)
    if stash:
        logits_ref[...] = s.astype(logits_ref.dtype)

    lab = lab_ref[...]                                     # (BN, 1) int32
    lbl_scr[:, 0] += jnp.sum(jnp.where(col == lab, s, 0.0), axis=1)

    m_prev = m_scr[:, 0]
    m_new = jnp.maximum(m_prev, s.max(axis=-1))
    l_scr[:, 0] = (
        jnp.exp(m_prev - m_new) * l_scr[:, 0]
        + jnp.exp(s - m_new[:, None]).sum(axis=-1)
    )
    m_scr[:, 0] = m_new

    @pl.when(vb == n_v - 1)
    def _finalize():
        lse = m_scr[:, 0] + jnp.log(l_scr[:, 0])
        lse_ref[...] = lse[:, None]
        loss_ref[...] = (lse - lbl_scr[:, 0])[:, None]


# ----------------------------------------------------------- bwd: shared ds
def _ds_block(s_f32, vb, lab_ref, lse_ref, g_ref, block_n, block_v):
    """softmax(logits) - onehot(labels), scaled by the upstream cotangent."""
    p = jnp.exp(s_f32 - lse_ref[...])
    col = _col_ids(vb, block_n, block_v)
    onehot = (col == lab_ref[...]).astype(jnp.float32)
    return (p - onehot) * g_ref[...]                       # (BN, BV) f32


def _recomputed_s(x_ref, w_ref, vb, block_n, block_v, n_vocab, masked):
    s = _dot(x_ref[...], w_ref[...], ((1,), (1,)))         # (BN, BV) f32
    if masked:
        s = jnp.where(_col_ids(vb, block_n, block_v) < n_vocab, s, NEG_INF)
    return s


# ---------------------------------------------------------------- bwd: dx
def _dx_kernel(src_ref, w_ref, lab_ref, lse_ref, g_ref, dx_ref, acc_scr,
               *, block_n, block_v, n_vocab, masked, stash):
    vb = pl.program_id(1)
    n_v = pl.num_programs(1)

    @pl.when(vb == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    if stash:  # src = bf16 logits stash block (BN, BV)
        s = src_ref[...].astype(jnp.float32)
    else:      # src = x block (BN, D): recompute the score block
        s = _recomputed_s(src_ref, w_ref, vb, block_n, block_v, n_vocab,
                          masked)
    ds = _ds_block(s, vb, lab_ref, lse_ref, g_ref, block_n, block_v)
    acc_scr[:] = acc_scr[:] + _dot(
        ds.astype(w_ref.dtype), w_ref[...], ((1,), (0,))
    )

    @pl.when(vb == n_v - 1)
    def _finalize():
        dx_ref[...] = acc_scr[:].astype(dx_ref.dtype)


# ---------------------------------------------------------------- bwd: dW
def _dw_kernel(src_ref, x_ref, lab_ref, lse_ref, g_ref, dw_ref, acc_scr,
               *, block_n, block_v, n_vocab, masked, stash):
    vb, nb = pl.program_id(0), pl.program_id(1)
    n_n = pl.num_programs(1)

    @pl.when(nb == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    if stash:  # src = bf16 logits stash block (BN, BV)
        s = src_ref[...].astype(jnp.float32)
    else:      # src = W block (BV, D): recompute from this kernel's x input
        s = _recomputed_s(x_ref, src_ref, vb, block_n, block_v, n_vocab,
                          masked)
    ds = _ds_block(s, vb, lab_ref, lse_ref, g_ref, block_n, block_v)
    acc_scr[:] = acc_scr[:] + _dot(
        ds.astype(x_ref.dtype), x_ref[...], ((0,), (0,))
    )

    @pl.when(nb == n_n - 1)
    def _finalize():
        dw_ref[...] = acc_scr[:]


# ------------------------------------------------------------- vjp plumbing
# ``blocks`` is the static tuple (bn_fwd, bv_fwd, bn_dw, bv_dw): fwd/dx tile
# tokens wide and vocab narrow (the f32 score block is the VMEM hog under the
# compiler's ~16 MiB scoped-vmem limit; W re-streams once per token row),
# while dW tiles vocab wide and tokens narrow (its accumulator spans the
# vocab block; x re-streams once per vocab row).
def _run_fwd(x, w_p, lab, block_n, block_v, n_vocab, interpret, stash):
    N, D = x.shape
    Vp = w_p.shape[0]
    grid = (N // block_n, Vp // block_v)
    out_specs = [
        pl.BlockSpec((block_n, 1), lambda nb, vb: (nb, 0)),
        pl.BlockSpec((block_n, 1), lambda nb, vb: (nb, 0)),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((N, 1), jnp.float32),         # per-token loss
        jax.ShapeDtypeStruct((N, 1), jnp.float32),         # lse
    ]
    if stash:
        out_specs.insert(
            0, pl.BlockSpec((block_n, block_v), lambda nb, vb: (nb, vb))
        )
        out_shape.insert(0, jax.ShapeDtypeStruct((N, Vp), jnp.bfloat16))
    outs = pl.pallas_call(
        functools.partial(
            _fwd_kernel, block_n=block_n, block_v=block_v, n_vocab=n_vocab,
            masked=Vp != n_vocab, stash=stash,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_n, D), lambda nb, vb: (nb, 0)),
            pl.BlockSpec((block_v, D), lambda nb, vb: (vb, 0)),
            pl.BlockSpec((block_n, 1), lambda nb, vb: (nb, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_n, 1), jnp.float32),         # running max
            pltpu.VMEM((block_n, 1), jnp.float32),         # running denom
            pltpu.VMEM((block_n, 1), jnp.float32),         # label logit
        ],
        interpret=interpret,
    )(x, w_p, lab)
    if stash:
        return outs  # (logits, loss, lse)
    loss, lse = outs
    return None, loss, lse


# The compute-dtype cast and the vocab pad happen INSIDE the custom_vjp
# boundary: the primal w is f32 (the wrapper casts; a no-op for the f32
# params of every preset), so the bwd's f32 dW matches its primal exactly —
# no reliance on JAX's temporary cotangent-dtype exception — and the f32
# head gradient reaches the optimizer at full precision, the same contract
# as XLA's unfused path.
def _padded_vocab(n_vocab, blocks):
    # Pad to a common multiple of BOTH vocab block sizes: the fwd/dx grids
    # step by bv and the dW grid by bv_dw, so each must tile Vp exactly —
    # padding to only the larger block truncates the other's grid and drops
    # real vocab columns from the logsumexp (round-3 advisor finding).
    mult = int(np.lcm(blocks[1], blocks[3]))
    return ((n_vocab + mult - 1) // mult) * mult


def _prep_w(w, x_dtype, Vp):
    w_p = w.astype(x_dtype)
    if Vp != w.shape[0]:
        w_p = jnp.pad(w_p, ((0, Vp - w.shape[0]), (0, 0)))
    return w_p


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _fused_ce(x, w, lab, blocks, n_vocab, interpret, stash):
    bn, bv, _, _ = blocks
    w_p = _prep_w(w, x.dtype, _padded_vocab(n_vocab, blocks))
    _, loss, _ = _run_fwd(x, w_p, lab, bn, bv, n_vocab, interpret,
                          stash=False)
    return loss


def _fused_ce_fwd(x, w, lab, blocks, n_vocab, interpret, stash):
    bn, bv, _, _ = blocks
    w_p = _prep_w(w, x.dtype, _padded_vocab(n_vocab, blocks))
    logits, loss, lse = _run_fwd(
        x, w_p, lab, bn, bv, n_vocab, interpret, stash=stash
    )
    return loss, (x, w_p, lab, logits, lse)


def _fused_ce_bwd(blocks, n_vocab, interpret, stash, res, g):
    block_n, block_v, bn_dw, bv_dw = blocks
    x, w_p, lab, logits, lse = res
    N, D = x.shape
    Vp = w_p.shape[0]
    masked = Vp != n_vocab
    g = g.astype(jnp.float32)

    # stash mode reads the bf16 logits; recompute mode re-derives the score
    # block from x·W^T inside each kernel (one extra matmul pass per kernel,
    # zero O(N, V) memory — the long-context mode)
    dx_src = logits if stash else x
    dx_src_spec = (
        pl.BlockSpec((block_n, block_v), lambda nb, vb: (nb, vb))
        if stash else pl.BlockSpec((block_n, D), lambda nb, vb: (nb, 0))
    )
    dx = pl.pallas_call(
        functools.partial(
            _dx_kernel, block_n=block_n, block_v=block_v, n_vocab=n_vocab,
            masked=masked, stash=stash,
        ),
        grid=(N // block_n, Vp // block_v),
        in_specs=[
            dx_src_spec,
            pl.BlockSpec((block_v, D), lambda nb, vb: (vb, 0)),
            pl.BlockSpec((block_n, 1), lambda nb, vb: (nb, 0)),
            pl.BlockSpec((block_n, 1), lambda nb, vb: (nb, 0)),
            pl.BlockSpec((block_n, 1), lambda nb, vb: (nb, 0)),
        ],
        out_specs=pl.BlockSpec((block_n, D), lambda nb, vb: (nb, 0)),
        out_shape=jax.ShapeDtypeStruct((N, D), x.dtype),
        scratch_shapes=[pltpu.VMEM((block_n, D), jnp.float32)],
        interpret=interpret,
    )(dx_src, w_p, lab, lse, g)

    dw_src = logits if stash else w_p
    dw_src_spec = (
        pl.BlockSpec((bn_dw, bv_dw), lambda vb, nb: (nb, vb))
        if stash else pl.BlockSpec((bv_dw, D), lambda vb, nb: (vb, 0))
    )
    dw = pl.pallas_call(
        functools.partial(
            _dw_kernel, block_n=bn_dw, block_v=bv_dw, n_vocab=n_vocab,
            masked=masked, stash=stash,
        ),
        grid=(Vp // bv_dw, N // bn_dw),
        in_specs=[
            dw_src_spec,
            pl.BlockSpec((bn_dw, D), lambda vb, nb: (nb, 0)),
            pl.BlockSpec((bn_dw, 1), lambda vb, nb: (nb, 0)),
            pl.BlockSpec((bn_dw, 1), lambda vb, nb: (nb, 0)),
            pl.BlockSpec((bn_dw, 1), lambda vb, nb: (nb, 0)),
        ],
        out_specs=pl.BlockSpec((bv_dw, D), lambda vb, nb: (vb, 0)),
        out_shape=jax.ShapeDtypeStruct((Vp, D), jnp.float32),
        scratch_shapes=[pltpu.VMEM((bv_dw, D), jnp.float32)],
        interpret=interpret,
    )(dw_src, x, lab, lse, g)

    dlab = np.zeros(lab.shape, dtype=jax.dtypes.float0)
    return dx, dw[:n_vocab], dlab


_fused_ce.defvjp(_fused_ce_fwd, _fused_ce_bwd)


# ------------------------------------------------------------------ public
def _auto_bv_dw(d_model: int) -> int:
    """dW vocab block: (bv_dw, D) f32 accumulator ≤ 4 MiB, rounded DOWN to a
    power of two ≥ the 128-lane tile. A non-128-multiple (819 @ D=1280) both
    breaks Mosaic tiling and, pre-fix, produced a Vp the fwd grid truncated;
    a non-power-of-two 128-multiple (e.g. 640) makes lcm(bv, bv_dw) inflate
    the vocab pad by up to ~4% dead columns in every kernel."""
    cap = min(1024, (1 << 20) // max(d_model, 1024))
    return max(128, 1 << (cap.bit_length() - 1))


def _pick_block(n: int, candidates) -> Optional[int]:
    for b in candidates:
        if n % b == 0:
            return b
    return None


def _dense_per_token(x2, w, labels1):
    """Per-token CE through plain XLA ops — the one implementation behind
    both the test oracle and the production odd-shape/CPU fallback."""
    logits = _dot(x2, w.astype(x2.dtype), ((1,), (1,)))
    lse = jax.nn.logsumexp(logits, axis=-1)
    lbl = jnp.take_along_axis(
        logits, jnp.maximum(labels1, 0)[:, None], axis=-1
    )[:, 0]
    return lse - lbl


def dense_linear_cross_entropy(x, w, labels, *, ignore_index=-1):
    """Unfused reference: same math through plain XLA ops. Used as the
    CPU/odd-shape fallback and as the numerics oracle in tests."""
    *lead, D = x.shape
    N = int(np.prod(lead)) if lead else 1
    per_tok = _dense_per_token(x.reshape(N, D), w, labels.reshape(N))
    valid = labels.reshape(N) != ignore_index
    count = jnp.maximum(valid.sum(), 1)
    return jnp.where(valid, per_tok, 0.0).sum() / count


# Auto stash threshold: keep the bf16 logits stash (saves one recompute
# matmul pass in each backward kernel) while it stays a modest slice of
# HBM; above this, recompute mode drops ALL O(N·V) memory — the difference
# between b8x2048 GPT-2 fitting on a v5e chip or not.
STASH_BYTES_MAX = 512 * 1024 * 1024


def fused_linear_cross_entropy(
    x: jax.Array,
    w: jax.Array,
    labels: jax.Array,
    *,
    ignore_index: int = -1,
    block_n: Optional[int] = None,
    block_v: Optional[int] = None,
    interpret: Optional[bool] = None,
    reduction: str = "mean",
    stash: Optional[bool] = None,
) -> Any:
    """Cross-entropy of ``x @ w.T`` against ``labels``, fused.

    ``x``: (..., N, D) hidden states (any leading dims are flattened with N);
    ``w``: (V, D) head weights — the tied embedding table for the LM zoo;
    ``labels``: int32 matching x's leading dims, ``ignore_index`` masks.
    Differentiable in x and w.

    ``reduction="mean"`` (default) returns the mean over unmasked tokens;
    ``"sum_count"`` returns ``(loss_sum, valid_count)`` so a sharded caller
    (the data-parallel shard_map wrapper, ``parallel/spmd_base.py``) can
    psum both parts and divide globally — per-shard means would weight
    shards with different mask counts incorrectly.

    ``stash`` picks the backward strategy: True keeps the fwd's bf16 logits
    for the backward (fastest — XLA's own choice for the unfused path);
    False recomputes score blocks from x·W^T in each backward kernel (one
    extra matmul pass per kernel, ZERO O(N·V) memory — long-context mode).
    None (default) stashes only while the stash stays under
    ``STASH_BYTES_MAX``.

    Falls back to :func:`dense_linear_cross_entropy` math when the kernel
    cannot lower for these shapes on this backend.
    """
    if ignore_index >= 0:
        raise ValueError("ignore_index must be negative (labels are matched "
                         "against vocab columns inside the kernel)")
    if reduction not in ("mean", "sum_count"):
        raise ValueError(f"unknown reduction {reduction!r}")
    *lead, D = x.shape
    N = int(np.prod(lead)) if lead else 1
    V = w.shape[0]
    # interpret=None means production: real lowering on TPU, dense fallback
    # elsewhere. Tests pass interpret=True to exercise kernel numerics on CPU.
    interp = False if interpret is None else interpret

    def reduce(per_tok, valid):
        count = valid.sum()
        total = jnp.where(valid, per_tok, 0.0).sum()
        if reduction == "sum_count":
            return total, count
        return total / jnp.maximum(count, 1)

    def dense_fallback():
        lab1 = labels.reshape(N)
        per_tok = _dense_per_token(x.reshape(N, D), w, lab1)
        return reduce(per_tok, lab1 != ignore_index)

    # fwd/dx: wide token blocks, narrow vocab blocks; dW: the transpose.
    # Sized so every kernel's VMEM residency (score block, accumulators,
    # double-buffered streams) stays under the ~16 MiB scoped-vmem limit up
    # to d_model 4096 (gptj-6b). The round-5 chip run measured the stash-mode
    # fwd at bn=2048/bv=512/D=768 at 17.18 MiB (double-buffered x + stash
    # streams + f32 score block + exp temp) — 1.18 MiB over. One bf16 byte-
    # pair of token-block per D column (bn*D*2B <= 2 MiB) is the budget that
    # fits every kernel with ~35% headroom.
    bn_cap = max((1 << 20) // max(D, 1), 128)  # 1024 @ D<=1024, 256 @ 4096
    bn = block_n or _pick_block(
        N, tuple(b for b in (2048, 1024, 512, 256, 128, 64, 32, 16, 8)
                 if b <= bn_cap)
    )
    if (
        bn is None
        or N % bn != 0  # explicit block_n must tile N exactly
        or (not interp and _use_interpret())
    ):
        return dense_fallback()
    if block_v is not None:
        bv = bv_dw = block_v
        bn_dw = block_n or bn
    elif V >= 2048:
        bv = 512
        bv_dw = _auto_bv_dw(D)
        bn_dw = min(512, bn)
    else:
        bv = bv_dw = ((V + 127) // 128) * 128
        bn_dw = min(512, bn)
    if N % bn_dw != 0:  # possible only with an explicit non-power-of-2 bn
        bn_dw = bn

    # Real TPU lowering needs lane-aligned vocab blocks (Mosaic tiles the
    # last dim in 128-lane units); _padded_vocab's LCM padding already makes
    # every grid tile Vp exactly, so misalignment — possible only with an
    # explicit non-128-multiple block_v — is the one way left to reach the
    # kernel with a shape the chip can't lower. Route it to dense. Interpret
    # mode (CPU numerics tests) has no such constraint.
    if not interp and (bv % 128 != 0 or bv_dw % 128 != 0):
        return dense_fallback()
    Vp = _padded_vocab(V, (bn, bv, bn_dw, bv_dw))

    x2 = x.reshape(N, D)
    lab = labels.reshape(N, 1).astype(jnp.int32)

    if stash is None:
        stash = N * Vp * 2 <= STASH_BYTES_MAX

    # f32 primal: a no-op for the zoo's f32 params; the compute-dtype cast
    # and vocab pad live inside _fused_ce so dW's dtype matches its primal
    per_tok = _fused_ce(
        x2, w.astype(jnp.float32), lab, (bn, bv, bn_dw, bv_dw), V, interp,
        stash,
    )[:, 0]
    return reduce(per_tok, lab[:, 0] != ignore_index)
