"""Switch-style mixture-of-experts layer, built for the MXU.

A capability extension beyond the reference (no MoE/expert parallelism exists
anywhere in its tree — SURVEY.md §2.3 "EP ... absent"), delivered through the
same plugin interface as every other technique (``parallel/ep.py``).

TPU-first formulation (GShard/Switch): routing is expressed as dense one-hot
dispatch/combine einsums with a *static* per-expert capacity, so the whole
layer is three large batched matmuls plus elementwise — no dynamic shapes, no
scatter/gather, everything tiles onto the systolic array. Under expert
parallelism the (experts, capacity, d_model) intermediate is sharded over the
``expert`` mesh axis and XLA lowers the dispatch/combine einsums to
all-to-alls over ICI.
"""

from __future__ import annotations

import math
from typing import Tuple

import jax
import jax.numpy as jnp


def expert_capacity(n_tokens: int, n_experts: int, capacity_factor: float) -> int:
    """Static per-expert token budget (Switch Transformer's capacity)."""
    return max(1, int(math.ceil(n_tokens / n_experts * capacity_factor)))


def switch_moe(
    x: jax.Array,
    router_w: jax.Array,
    we_in: jax.Array,
    be_in: jax.Array,
    we_out: jax.Array,
    be_out: jax.Array,
    *,
    capacity_factor: float = 1.25,
) -> Tuple[jax.Array, jax.Array]:
    """Top-1 routed expert MLP.

    Shapes: ``x`` (B, T, D); ``router_w`` (D, E); ``we_in`` (E, D, F);
    ``be_in`` (E, F); ``we_out`` (E, F, D); ``be_out`` (E, D).
    Returns (output (B, T, D), load-balance aux loss scalar fp32).

    Tokens beyond an expert's capacity are dropped (contribute zero and pass
    through the residual) — the standard Switch behavior that keeps shapes
    static. Router math runs in fp32; expert matmuls in the input dtype.
    """
    B, T, D = x.shape
    E = router_w.shape[-1]
    S = B * T
    xf = x.reshape(S, D)

    logits = jnp.einsum(
        "sd,de->se", xf, router_w, preferred_element_type=jnp.float32
    )
    probs = jax.nn.softmax(logits, axis=-1)  # (S, E) fp32
    gate = probs.max(axis=-1)
    expert = probs.argmax(axis=-1)

    C = expert_capacity(S, E, capacity_factor)
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.int32)          # (S, E)
    pos = jnp.cumsum(onehot, axis=0) * onehot                    # 1-based slot
    keep = (pos > 0) & (pos <= C)
    slot = jnp.clip(pos - 1, 0, C - 1)
    dispatch = (
        jax.nn.one_hot(slot, C, dtype=x.dtype)
        * keep.astype(x.dtype)[..., None]
    )                                                            # (S, E, C)
    combine = dispatch * gate.astype(x.dtype)[:, None, None]

    xe = jnp.einsum("sec,sd->ecd", dispatch, xf)                 # (E, C, D)
    h = jnp.einsum("ecd,edf->ecf", xe, we_in) + be_in[:, None, :]
    h = jax.nn.gelu(h, approximate=True)
    ye = jnp.einsum("ecf,efd->ecd", h, we_out) + be_out[:, None, :]
    y = jnp.einsum("sec,ecd->sd", combine, ye)

    # Switch load-balance loss: E * Σ_e (token fraction) * (mean router prob).
    frac = onehot.astype(jnp.float32).mean(axis=0)
    mean_prob = probs.mean(axis=0)
    aux = E * jnp.sum(frac * mean_prob)
    return y.reshape(B, T, D), aux
