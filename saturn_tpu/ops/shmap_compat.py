"""shard_map across jax versions.

``jax.shard_map`` (new API, ``check_vma=``) only exists on recent jax;
older releases ship ``jax.experimental.shard_map.shard_map`` (same
semantics, the replication check is spelled ``check_rep=``). The ring /
ulysses / pipeline ops and the shardflow tracer all need whichever one
the interpreter can see, so the dispatch lives here once.
"""

from __future__ import annotations

from typing import Any

import jax


def shard_map(f, *, mesh: Any, in_specs: Any, out_specs: Any,
              check_vma: bool = True):
    """Version-portable ``shard_map`` (keyword-only, like the new API)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _legacy

    return _legacy(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=check_vma)
