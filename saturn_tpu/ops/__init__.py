"""TPU-native ops: explicit-schedule collectives and Pallas kernels.

The reference delegated all of this to external CUDA libraries (torchgpipe
streams, fairscale offload, NCCL — SURVEY.md §2.2). Here the hot schedules are
written against JAX primitives (``shard_map`` + ``ppermute`` + ``lax.scan``)
and Pallas where a fused kernel beats XLA's default lowering.

``stacking`` holds the pytree algebra for fused multi-model stacks
(``parallel/fused.py``): stack/unstack member trees along a leading
``model`` axis, slice one member out (checkpointing), and remove a
diverged member mid-interval (the unfuse operation).
"""
