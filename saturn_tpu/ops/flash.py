"""Pallas flash attention for TPU: fused causal attention, fwd + bwd.

The hot op of every model in the zoo. XLA already fuses the dense attention
einsums well, but it materializes the (T, T) score matrix in HBM between the
two matmuls; this kernel keeps score blocks in VMEM with the online-softmax
recurrence (Flash-Attention-2 style), so HBM traffic drops from O(T²) to
O(T·D) and both matmuls feed the MXU back-to-back.

VMEM footprint is O(block · D) per program, independent of T: the key/value
walk is a **grid dimension** (innermost, sequential on TPU), with k/v tiles
pipelined HBM→VMEM by Pallas block specs and the softmax state (m, l, acc)
carried in VMEM scratch across the kv steps — so long-context sequences
never stage a full (T, D) operand on chip.

Shapes: (B, H, T, D) with T % block == 0. The backward pass is the standard
two-kernel split — a dQ kernel gridded over (query block × kv step) and a
dK/dV kernel gridded over (kv block × query step) — recomputing
P = exp(S - lse) from the forward's saved logsumexp.

Used by the model zoo when ``GPT2Config.attention`` resolves to "flash" —
which is the DEFAULT on TPU since the round-3 chip measurements
(``benchmarks/attention_bench.py`` on v5e, GPT-2-small, fixed 4096 tokens:
1.01x at seq 512, 1.42x at 1024, 1.97x at 2048, and dense OOMs first at
b8×1024; BASELINE.md attention table). Numerics are validated against the
dense reference in interpret mode on CPU (``tests/test_flash.py``).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _use_interpret() -> bool:
    """Pallas TPU lowering needs a real TPU; interpret everywhere else."""
    return jax.default_backend() != "tpu"


def _block_mask(iq, jk, block_q, block_k):
    """(BQ, BK) causal mask for query block iq vs key block jk."""
    q_pos = iq * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    k_pos = jk * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    return q_pos >= k_pos


def _dot(a, b, dims):
    return jax.lax.dot_general(a, b, (dims, ((), ())),
                               preferred_element_type=jnp.float32)


# --------------------------------------------------------------------- fwd
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, block_q, block_k, scale, causal):
    iq, jk = pl.program_id(1), pl.program_id(2)
    n_kv = pl.num_programs(2)

    @pl.when(jk == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Blocks fully above the causal diagonal contribute nothing: skip the
    # matmuls (the k/v fetch is pipelined by the grid either way).
    needed = True
    if causal:
        needed = jk * block_k <= iq * block_q + block_q - 1

    @pl.when(needed)
    def _accumulate():
        # Matmul inputs stay in the storage dtype (bf16): the MXU computes
        # bf16×bf16→f32 natively via preferred_element_type, while f32×f32
        # needs multiple passes — upcasting before the dot costs ~2x. Scale
        # is applied to the f32 scores, softmax state stays f32.
        q = q_ref[0]                                      # (BQ, D)
        kb = k_ref[0]                                     # (BK, D)
        vb = v_ref[0]
        s = _dot(q, kb, ((1,), (1,))) * scale             # (BQ, BK) f32
        if causal:
            s = jnp.where(_block_mask(iq, jk, block_q, block_k), s, NEG_INF)
        m_prev, l_prev = m_scr[:, 0], l_scr[:, 0]
        m_new = jnp.maximum(m_prev, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m_prev - m_new)
        m_scr[:, 0] = m_new
        l_scr[:, 0] = corr * l_prev + p.sum(axis=-1)
        acc_scr[:] = corr[:, None] * acc_scr[:] + _dot(
            p.astype(vb.dtype), vb, ((1,), (0,))
        )

    @pl.when(jk == n_kv - 1)
    def _finalize():
        l = l_scr[:, 0]
        o_ref[0] = (acc_scr[:] / l[:, None]).astype(o_ref.dtype)
        # lse rides a trailing singleton dim: Mosaic requires the last two
        # block dims be (mult-of-8, mult-of-128) or equal to the array dims,
        # so a 2-D (1, block_q) lse block cannot lower; (1, block_q, 1) can.
        lse_ref[0] = (m_scr[:, 0] + jnp.log(l))[:, None]


def _kv_of(h: int, kv: int):
    """Flat (B*H) q-head program index -> flat (B*KV) k/v row.

    Grouped-query attention: ``rep = h // kv`` consecutive q heads share
    one k/v head, so the k/v BlockSpec index maps a q-head grid step to its
    group's row — the kernels never see repeated k/v and the (B, H, T, D)
    activation expansion never materializes. rep == 1 is the identity."""
    rep = h // kv

    def to_kv(bh):
        return (bh // h) * kv + (bh % h) // rep

    return to_kv


def _fwd(q, k, v, *, block_q, block_k, scale, causal, h, kv):
    BH, T, D = q.shape
    kv_of = _kv_of(h, kv)
    grid = (BH, T // block_q, T // block_k)
    o, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel, block_q=block_q, block_k=block_k, scale=scale,
            causal=causal,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_k, D),
                         lambda bh, i, j: (kv_of(bh), j, 0)),
            pl.BlockSpec((1, block_k, D),
                         lambda bh, i, j: (kv_of(bh), j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, i, j: (bh, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
            jax.ShapeDtypeStruct((BH, T, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),   # running max
            pltpu.VMEM((block_q, 1), jnp.float32),   # running denom
            pltpu.VMEM((block_q, D), jnp.float32),   # output accumulator
        ],
        interpret=_use_interpret(),
    )(q, k, v)
    return o, lse


# --------------------------------------------------------------------- bwd
def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_scr, *, block_q, block_k, scale, causal):
    iq, jk = pl.program_id(1), pl.program_id(2)
    n_kv = pl.num_programs(2)

    @pl.when(jk == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    needed = True
    if causal:
        needed = jk * block_k <= iq * block_q + block_q - 1

    @pl.when(needed)
    def _accumulate():
        # bf16 matmul inputs, f32 accumulation — see the fwd kernel note.
        q = q_ref[0]
        kb = k_ref[0]
        vb = v_ref[0]
        do = do_ref[0]
        s = _dot(q, kb, ((1,), (1,))) * scale
        if causal:
            s = jnp.where(_block_mask(iq, jk, block_q, block_k), s, NEG_INF)
        p = jnp.exp(s - lse_ref[0])          # lse block is (block_q, 1)
        dp = _dot(do, vb, ((1,), (1,)))
        ds = p * (dp - delta_ref[0])
        dq_scr[:] = dq_scr[:] + _dot(ds.astype(kb.dtype), kb, ((1,), (0,)))

    @pl.when(jk == n_kv - 1)
    def _finalize():
        dq_ref[0] = (dq_scr[:] * scale).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr, *, block_q, block_k, scale,
                causal):
    # Grid (bkv, jk, g, iq): g walks the q heads sharing this k/v head
    # (size 1 without GQA); the (bkv, jk) output block stays resident across
    # the whole inner (g, iq) sweep, so dk/dv accumulate the group sum the
    # transpose of the activation-side repeat would otherwise need.
    jk, g, iq = pl.program_id(1), pl.program_id(2), pl.program_id(3)
    n_g, n_q = pl.num_programs(2), pl.num_programs(3)

    @pl.when(jnp.logical_and(g == 0, iq == 0))
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    needed = True
    if causal:
        needed = iq * block_q + block_q - 1 >= jk * block_k

    @pl.when(needed)
    def _accumulate():
        # bf16 matmul inputs, f32 accumulation — see the fwd kernel note.
        kb = k_ref[0]
        vb = v_ref[0]
        qb = q_ref[0]
        dob = do_ref[0]
        s = _dot(qb, kb, ((1,), (1,))) * scale
        if causal:
            s = jnp.where(_block_mask(iq, jk, block_q, block_k), s, NEG_INF)
        p = jnp.exp(s - lse_ref[0])                         # (BQ, BK)
        dv_scr[:] = dv_scr[:] + _dot(p.astype(dob.dtype), dob, ((0,), (0,)))
        dp = _dot(dob, vb, ((1,), (1,)))
        ds = (p * (dp - delta_ref[0])).astype(qb.dtype)
        # ds·q is unscaled; the scale factor lands in the finalize below.
        dk_scr[:] = dk_scr[:] + _dot(ds, qb, ((0,), (0,)))

    @pl.when(jnp.logical_and(g == n_g - 1, iq == n_q - 1))
    def _finalize():
        dk_ref[0] = (dk_scr[:] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd(block_q, block_k, scale, causal, h, kv, res, do):
    q, k, v, o, lse = res
    BH, T, D = q.shape
    BKV = k.shape[0]
    rep = h // kv
    kv_of = _kv_of(h, kv)
    # (BH, T, 1) like lse — see the fwd finalize note on Mosaic block rules.
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1, keepdims=True
    )

    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, block_q=block_q, block_k=block_k, scale=scale,
            causal=causal,
        ),
        grid=(BH, T // block_q, T // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_k, D),
                         lambda bh, i, j: (kv_of(bh), j, 0)),
            pl.BlockSpec((1, block_k, D),
                         lambda bh, i, j: (kv_of(bh), j, 0)),
            pl.BlockSpec((1, block_q, D), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, i, j: (bh, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda bh, i, j: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, T, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=_use_interpret(),
    )(q, k, v, do, lse, delta)

    def qh(bkv, g):
        # flat (B*KV) k/v row + group member -> flat (B*H) q-head row
        return (bkv // kv) * h + (bkv % kv) * rep + g

    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, block_q=block_q, block_k=block_k, scale=scale,
            causal=causal,
        ),
        grid=(BKV, T // block_k, rep, T // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D),
                         lambda bkv, j, g, i: (qh(bkv, g), i, 0)),
            pl.BlockSpec((1, block_k, D), lambda bkv, j, g, i: (bkv, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda bkv, j, g, i: (bkv, j, 0)),
            pl.BlockSpec((1, block_q, D),
                         lambda bkv, j, g, i: (qh(bkv, g), i, 0)),
            pl.BlockSpec((1, block_q, 1),
                         lambda bkv, j, g, i: (qh(bkv, g), i, 0)),
            pl.BlockSpec((1, block_q, 1),
                         lambda bkv, j, g, i: (qh(bkv, g), i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda bkv, j, g, i: (bkv, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda bkv, j, g, i: (bkv, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BKV, T, D), k.dtype),
            jax.ShapeDtypeStruct((BKV, T, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        interpret=_use_interpret(),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------- public
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_bh(q, k, v, block_q, block_k, causal, h, kv):
    scale = 1.0 / math.sqrt(q.shape[-1])
    o, _ = _fwd(q, k, v, block_q=block_q, block_k=block_k, scale=scale,
                causal=causal, h=h, kv=kv)
    return o


def _flash_bh_fwd(q, k, v, block_q, block_k, causal, h, kv):
    scale = 1.0 / math.sqrt(q.shape[-1])
    o, lse = _fwd(q, k, v, block_q=block_q, block_k=block_k, scale=scale,
                  causal=causal, h=h, kv=kv)
    return o, (q, k, v, o, lse)


def _flash_bh_bwd(block_q, block_k, causal, h, kv, res, do):
    scale = 1.0 / math.sqrt(res[0].shape[-1])
    return _bwd(block_q, block_k, scale, causal, h, kv, res, do)


_flash_bh.defvjp(_flash_bh_fwd, _flash_bh_bwd)


def _default_block(T: int) -> int:
    """Largest power-of-two block ≤ 512 dividing T. 512 measured fastest on
    v5e at seq 512 (block sweep, BASELINE.md attention table): bigger blocks
    mean fewer grid programs and larger MXU matmuls; VMEM stays comfortable
    (the f32 score block at 512² is 1 MiB)."""
    for b in (512, 256, 128):
        if T % b == 0:
            return b
    return min(128, T)


def flash_supported(cfg=None) -> bool:
    """Can the Pallas kernel lower (not interpret) for this model config?

    Real lowering needs the TPU backend; interpret mode exists only for
    numerics tests. With a config, also checks the kernel's shape contract
    (seq divisible by the default block) and that attention is single-program
    (sequence-parallel configs have their own kernels). Used by the executors'
    autotune grids so the trial runner profiles flash-vs-dense per task and
    the solver selects from measurements (VERDICT r1 items 2-3).
    """
    import jax

    if jax.default_backend() != "tpu":
        return False
    if cfg is not None:
        T = getattr(cfg, "seq_len", None)
        if T is not None and T % min(128, T) != 0:
            return False
        if getattr(cfg, "seq_axis", None) is not None:
            return False
    return True


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
) -> jax.Array:
    """Fused causal attention over (B, H, T, D); differentiable.

    Grouped-query attention is native: ``k``/``v`` may carry fewer heads
    (B, KV, T, D) with KV dividing H — the kernels index each q head's
    group row directly, so the (B, H, T, D) k/v expansion (and its HBM at
    long context) never exists, and dk/dv come back at (B, KV, T, D) with
    the group sum done in-kernel.

    T must divide by the block sizes (default: the largest of 512/256/128
    dividing T, else min(128, T) — see ``_default_block``) or this raises —
    the model config validates the constraint up front
    (``GPT2Config.__post_init__``); this op stays strict.
    """
    B, H, T, D = q.shape
    KV = k.shape[1]
    if v.shape[1] != KV or KV < 1 or H % KV != 0:
        raise ValueError(
            f"k/v heads ({k.shape[1]}, {v.shape[1]}) must match and divide "
            f"q heads ({H})"
        )
    bq = block_q or _default_block(T)
    bk = block_k or _default_block(T)
    if T % bq or T % bk:
        raise ValueError(f"seq len {T} not divisible by blocks ({bq}, {bk})")
    qf = q.reshape(B * H, T, D)
    kf = k.reshape(B * KV, T, D)
    vf = v.reshape(B * KV, T, D)
    o = _flash_bh(qf, kf, vf, bq, bk, causal, H, KV)
    return o.reshape(B, H, T, D)
