"""Pallas flash attention for TPU: fused causal attention, fwd + bwd.

The hot op of every model in the zoo. XLA already fuses the dense attention
einsums well, but it materializes the (T, T) score matrix in HBM between the
two matmuls; this kernel keeps score blocks in VMEM with the online-softmax
recurrence (Flash-Attention-2 style), so HBM traffic drops from O(T²) to
O(T·D) and both matmuls feed the MXU back-to-back.

Shapes: (B, H, T, D) with T % block == 0. The backward pass is the standard
two-kernel split — a dQ kernel gridded over query blocks and a dK/dV kernel
gridded over key blocks — recomputing P = exp(S - lse) from the forward's
saved logsumexp.

Used by the model zoo when ``GPT2Config.attention == "flash"``; numerics are
validated against the dense reference in interpret mode on CPU
(``tests/test_flash.py``), and the dense path remains the default until the
kernel is faster on the target chip (``bench.py`` decides).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _use_interpret() -> bool:
    """Pallas TPU lowering needs a real TPU; interpret everywhere else."""
    return jax.default_backend() != "tpu"


# --------------------------------------------------------------------- fwd
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_q, block_k,
                scale, causal, seq_len):
    iq = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale          # (BQ, D)
    D = q.shape[-1]
    q_pos = iq * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )

    n_kv = seq_len // block_k
    if causal:
        # kv blocks strictly above the diagonal contribute nothing
        n_kv = jax.lax.div(iq * block_q + block_q + block_k - 1, block_k)

    def body(j, carry):
        m, l, acc = carry
        kb = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                              # (BQ, BK)
        if causal:
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l_new = corr * l + p.sum(axis=-1)
        acc_new = corr[:, None] * acc + jax.lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, D), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_kv, body, (m0, l0, acc0))

    o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(l)


def _fwd(q, k, v, *, block_q, block_k, scale, causal):
    BH, T, D = q.shape
    grid = (BH, T // block_q)
    kv_spec = pl.BlockSpec((1, T, D), lambda bh, i: (bh, 0, 0))
    o, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel, block_q=block_q, block_k=block_k, scale=scale,
            causal=causal, seq_len=T,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, i: (bh, i, 0)),
            kv_spec,
            kv_spec,
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, block_q), lambda bh, i: (bh, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
            jax.ShapeDtypeStruct((BH, T), jnp.float32),
        ],
        interpret=_use_interpret(),
    )(q, k, v)
    return o, lse


# --------------------------------------------------------------------- bwd
def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
               block_q, block_k, scale, causal, seq_len):
    iq = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0]
    delta = delta_ref[0]
    D = q.shape[-1]
    q_pos = iq * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )

    n_kv = seq_len // block_k
    if causal:
        n_kv = jax.lax.div(iq * block_q + block_q + block_k - 1, block_k)

    def body(j, dq):
        kb = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if causal:
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(
            do, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta[:, None])
        return dq + jax.lax.dot_general(
            ds, kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    dq = jax.lax.fori_loop(0, n_kv, body, jnp.zeros((block_q, D), jnp.float32))
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, block_q, block_k, scale, causal, seq_len):
    jk = pl.program_id(1)
    kb = k_ref[0].astype(jnp.float32)                  # (BK, D)
    vb = v_ref[0].astype(jnp.float32)
    D = kb.shape[-1]
    k_pos = jk * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )

    n_q = seq_len // block_q
    lo = 0
    if causal:
        # q blocks strictly left of this kv block see nothing of it
        lo = jax.lax.div(jk * block_k, block_q)

    def body(i, carry):
        dk, dv = carry
        qb = q_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32) * scale
        dob = do_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(i * block_q, block_q)]
        delta = delta_ref[0, pl.ds(i * block_q, block_q)]
        s = jax.lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if causal:
            q_pos = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])                  # (BQ, BK)
        dv_new = dv + jax.lax.dot_general(
            p, dob, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            dob, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta[:, None])
        dk_new = dk + jax.lax.dot_general(
            ds, qb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dk_new, dv_new

    dk0 = jnp.zeros((block_k, D), jnp.float32)
    dv0 = jnp.zeros((block_k, D), jnp.float32)
    dk, dv = jax.lax.fori_loop(lo, n_q, body, (dk0, dv0))
    # qb above already carries one factor of scale; dk needs none extra.
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd(block_q, block_k, scale, causal, res, do):
    q, k, v, o, lse = res
    BH, T, D = q.shape
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)

    kv_spec = pl.BlockSpec((1, T, D), lambda bh, i: (bh, 0, 0))
    row_spec = pl.BlockSpec((1, T), lambda bh, i: (bh, 0))
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, block_q=block_q, block_k=block_k, scale=scale,
            causal=causal, seq_len=T,
        ),
        grid=(BH, T // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, i: (bh, i, 0)),
            kv_spec,
            kv_spec,
            pl.BlockSpec((1, block_q, D), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, block_q), lambda bh, i: (bh, i)),
            pl.BlockSpec((1, block_q), lambda bh, i: (bh, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda bh, i: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, T, D), q.dtype),
        interpret=_use_interpret(),
    )(q, k, v, do, lse, delta)

    q_full = pl.BlockSpec((1, T, D), lambda bh, j: (bh, 0, 0))
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, block_q=block_q, block_k=block_k, scale=scale,
            causal=causal, seq_len=T,
        ),
        grid=(BH, T // block_k),
        in_specs=[
            q_full,
            pl.BlockSpec((1, block_k, D), lambda bh, j: (bh, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, j: (bh, j, 0)),
            q_full,
            row_spec,
            row_spec,
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda bh, j: (bh, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, j: (bh, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), k.dtype),
            jax.ShapeDtypeStruct((BH, T, D), v.dtype),
        ],
        interpret=_use_interpret(),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------- public
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_bh(q, k, v, block_q, block_k, causal):
    scale = 1.0 / math.sqrt(q.shape[-1])
    o, _ = _fwd(q, k, v, block_q=block_q, block_k=block_k, scale=scale,
                causal=causal)
    return o


def _flash_bh_fwd(q, k, v, block_q, block_k, causal):
    scale = 1.0 / math.sqrt(q.shape[-1])
    o, lse = _fwd(q, k, v, block_q=block_q, block_k=block_k, scale=scale,
                  causal=causal)
    return o, (q, k, v, o, lse)


def _flash_bh_bwd(block_q, block_k, causal, res, do):
    scale = 1.0 / math.sqrt(res[0].shape[-1])
    return _bwd(block_q, block_k, scale, causal, res, do)


_flash_bh.defvjp(_flash_bh_fwd, _flash_bh_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
) -> jax.Array:
    """Fused causal attention over (B, H, T, D); differentiable.

    Falls back silently is NOT done here: T must divide by the block sizes
    (defaults: min(128, T)) or this raises — the model layer picks dense vs
    flash, this op stays strict.
    """
    B, H, T, D = q.shape
    bq = block_q or min(128, T)
    bk = block_k or min(128, T)
    if T % bq or T % bk:
        raise ValueError(f"seq len {T} not divisible by blocks ({bq}, {bk})")
    qf = q.reshape(B * H, T, D)
    kf = k.reshape(B * H, T, D)
    vf = v.reshape(B * H, T, D)
    o = _flash_bh(qf, kf, vf, bq, bk, causal)
    return o.reshape(B, H, T, D)
