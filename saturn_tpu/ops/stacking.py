"""Pytree stacking for fused multi-model training (``parallel/fused.py``).

N sweep members that share an architecture differ only in their leaf
*values* (params, opt state, step counters) — never in tree structure or
leaf shapes. Stacking prepends a ``model`` axis to every leaf so the whole
group becomes ONE train state a single vmapped program advances; these
helpers are the (un)stacking algebra the fused technique, its unfuse path
and the per-member checkpoint slices are written against.

All functions are pure tree_map wrappers: they work on host numpy trees
(checkpoint assembly), device arrays (mid-interval unfuse slicing) and
``ShapeDtypeStruct`` trees (shape/sharding templates) alike.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class MemberShapeError(ValueError):
    """A member's array disagrees with the group's common shape/dtype.

    Raised at *staging* time with the offending member's task name, so a
    heterogeneous group fails with an attributable message instead of an
    opaque XLA shape-check deep inside the stacked program (ISSUE 16
    satellite: the prefetcher's stacked-window contract)."""

    def __init__(self, member: str, got: Any, want: Any, what: str = "batch"):
        self.member = member
        self.got = got
        self.want = want
        super().__init__(
            f"fused member {member!r}: {what} shape/dtype {got} does not "
            f"match the group's {want} — fusion requires identical "
            f"per-member shapes (same batch_size/seq_len/model config)"
        )


def stack_trees(trees: Sequence[Any]) -> Any:
    """Stack N structurally-identical trees along a new leading axis.

    Leaf k of the result has shape ``(N, *leaf_k.shape)``. Host numpy in →
    host numpy out (the checkpoint-assembly path stays off-device until the
    single sharded ``device_put``); device arrays in → device out.
    """
    if not trees:
        raise ValueError("stack_trees: empty member list")
    first = trees[0]
    for t in trees[1:]:
        if jax.tree_util.tree_structure(t) != jax.tree_util.tree_structure(first):
            raise ValueError(
                "stack_trees: member trees have different structures — "
                "fusion requires an identical ModelSpec fingerprint"
            )
    leaves = [jax.tree_util.tree_leaves(t) for t in trees]
    out_leaves = []
    for col in zip(*leaves):
        if isinstance(col[0], (np.ndarray, np.generic)) or not hasattr(
            col[0], "devices"
        ):
            out_leaves.append(np.stack([np.asarray(x) for x in col]))
        else:
            out_leaves.append(jnp.stack(col))
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(first), out_leaves
    )


def unstack_tree(tree: Any, n: int) -> List[Any]:
    """Split a stacked tree back into its N member trees (inverse of
    :func:`stack_trees`)."""
    return [member_slice(tree, i) for i in range(int(n))]


def member_slice(tree: Any, i: int) -> Any:
    """Member ``i``'s tree: every leaf's ``[i]`` slice (leading-axis drop).

    This is the per-member checkpoint view — the slice is what the sharded
    manifest writer persists for one member, identical in shape/dtype to the
    state the member's solo program would have produced.
    """
    return jax.tree_util.tree_map(lambda x: x[int(i)], tree)


def remove_member(tree: Any, i: int) -> Any:
    """A stacked tree with member ``i`` sliced OUT — the unfuse operation.

    Every leaf ``(N, ...)`` becomes ``(N-1, ...)``; member order of the
    survivors is preserved, so surviving index ``j`` maps to old index
    ``j if j < i else j + 1``.
    """
    i = int(i)

    def drop(x):
        n = x.shape[0]
        if not 0 <= i < n:
            raise IndexError(f"remove_member: index {i} out of range for N={n}")
        if isinstance(x, (np.ndarray, np.generic)):
            return np.delete(x, i, axis=0)
        return jnp.concatenate([x[:i], x[i + 1:]], axis=0)

    return jax.tree_util.tree_map(drop, tree)


def stacked_shapes(member_shapes: Any, n: int) -> Any:
    """ShapeDtypeStruct tree for an N-stack of a member-shaped tree."""
    return jax.tree_util.tree_map(
        lambda s: jax.ShapeDtypeStruct((int(n), *s.shape), s.dtype),
        member_shapes,
    )


def stack_member_batches(
    batches: Sequence[Any],
    member_names: Optional[Sequence[str]] = None,
    expect: Optional[Tuple[int, ...]] = None,
) -> np.ndarray:
    """One ``(N, batch, seq)`` staging stack from N members' host batches.

    The shape contract of the fused data path: every member's batch must
    share shape AND dtype. A mismatch raises :class:`MemberShapeError`
    naming the offending member's task id — the attributable error the
    prefetcher's contract promises instead of an XLA stack failure.

    ``expect``: the per-member ``(batch, seq)`` shape the compiled program
    was staged for; when given, member 0 is validated against it too (a
    group whose FIRST member drifted would otherwise pass self-consistency).
    """
    arrs = [np.asarray(b) for b in batches]
    if not arrs:
        raise ValueError("stack_member_batches: empty member list")
    names = list(member_names) if member_names is not None else [
        f"member[{i}]" for i in range(len(arrs))
    ]
    want = tuple(expect) if expect is not None else arrs[0].shape
    want_dtype = arrs[0].dtype
    for name, a in zip(names, arrs):
        if tuple(a.shape) != tuple(want) or a.dtype != want_dtype:
            raise MemberShapeError(
                name, (tuple(a.shape), str(a.dtype)),
                (tuple(want), str(want_dtype)),
            )
    return np.stack(arrs)


def stacked_hparam_array(
    values: Sequence[float], dtype: Any = np.float32
) -> jnp.ndarray:
    """Per-member hyperparameters as a stacked ``(N,)`` vector.

    Passed into the vmapped step alongside the state stack, so each member's
    optimizer update closes over ITS value as a traced scalar — bit-identical
    to the solo program's concrete-float closure (verified by
    ``tests/test_fused.py``'s trajectory-equivalence cases).
    """
    return jnp.asarray(np.asarray(list(values), dtype=dtype))


def tree_equal(a: Any, b: Any) -> bool:
    """Bitwise equality of two host trees (test/bench helper)."""
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    if len(la) != len(lb):
        return False
    return all(
        np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb)
    )
