"""ctypes binding for the native SPASE scheduler (``native/spase.cpp``).

Drop-in producer of the same ``Plan`` the MILP emits. The caller picks when
to use it (large batches; MILP timeout fallback); plans are validated here —
device-overlap or misalignment rejects the native result, so a native bug can
never produce an unsound schedule, only a fallback to the Python greedy.
"""

from __future__ import annotations

import ctypes
import logging
from typing import Dict, List, Optional, Tuple

import numpy as np

from saturn_tpu import native
from saturn_tpu.core.mesh import Block, SliceTopology

log = logging.getLogger("saturn_tpu")

_FN = None


def _fn():
    """Resolve and type the ``spase_solve_v2`` symbol once (None if
    the library is stale/missing — graceful fallback, never a crash)."""
    global _FN
    if _FN is None:
        lib = native.load("spase")
        if lib is None:
            _FN = False
        else:
            f = getattr(lib, "spase_solve_v2", None)
            if f is None:  # stale prebuilt .so from an older ABI
                _FN = False
                return None
            ip = ctypes.POINTER(ctypes.c_int)
            dp = ctypes.POINTER(ctypes.c_double)
            f.argtypes = [
                ctypes.c_int, ip, ip, ip, dp,
                ctypes.c_int, ctypes.c_double, ctypes.c_double,
                ctypes.c_uint64, ip, ip, dp, dp,
            ]
            f.restype = ctypes.c_int
            _FN = f
    return _FN or None


def available() -> bool:
    return _fn() is not None


def solve_native(
    task_list: List,
    topology: SliceTopology,
    time_limit: float = 1.0,
    ordering_slack: float = 1.0,
    seed: int = 0,
    warm=None,
):
    """Schedule via libspase; returns a ``Plan`` or None if unavailable.

    Builds the identical option set the MILP enumerates (feasible strategies
    × aligned blocks, ``milp.solve``), calls the C++ core, validates, decodes.
    ``warm`` (a previous ``Plan``) seeds the native search with each task's
    previous (size, block) choice — the analog of the reference's Gurobi
    ``warmStart`` (``milp.py:323``).
    """
    from saturn_tpu.solver.milp import Assignment, Plan

    fn = _fn()
    if fn is None:
        return None

    counts, offs, sizes, rts = [], [], [], []
    per_task: List[List[Tuple[int, Block, float]]] = []
    for t in task_list:
        opts = []
        for size, strat in sorted(t.feasible_strategies().items()):
            if size > topology.capacity:
                continue
            for blk in topology.blocks(size):
                opts.append((size, blk, strat.runtime))
        if not opts:
            return None  # same contract as milp.solve's ValueError path
        per_task.append(opts)
        counts.append(len(opts))
        for s, b, rt in opts:
            offs.append(b.offset)
            sizes.append(s)
            rts.append(rt)

    n = len(task_list)
    c_counts = (ctypes.c_int * n)(*counts)
    c_offs = (ctypes.c_int * len(offs))(*offs)
    c_sizes = (ctypes.c_int * len(sizes))(*sizes)
    c_rts = (ctypes.c_double * len(rts))(*rts)
    c_chosen = (ctypes.c_int * n)()
    c_starts = (ctypes.c_double * n)()
    c_mk = ctypes.c_double()

    c_warm = None
    if warm is not None:
        widx = [-1] * n
        for i, t in enumerate(task_list):
            a = warm.assignments.get(t.name)
            if a is None:
                continue
            for oi, (s, b, _) in enumerate(per_task[i]):
                if s == a.apportionment and b.offset == a.block.offset:
                    widx[i] = oi
                    break
        if any(w >= 0 for w in widx):
            c_warm = (ctypes.c_int * n)(*widx)

    rc = fn(
        n, c_counts, c_offs, c_sizes, c_rts, topology.capacity,
        float(time_limit), float(ordering_slack), seed,
        c_warm, c_chosen, c_starts, ctypes.byref(c_mk),
    )
    if rc != 0:
        log.warning("libspase returned %d — falling back", rc)
        return None

    assignments: Dict[str, Assignment] = {}
    for i, t in enumerate(task_list):
        size, blk, rt = per_task[i][c_chosen[i]]
        assignments[t.name] = Assignment(
            apportionment=size, block=blk, start=float(c_starts[i]), runtime=rt
        )
    plan = Plan(assignments=assignments, makespan=float(c_mk.value))
    if not _valid(plan, topology, ordering_slack):
        log.warning("libspase plan failed validation — falling back")
        return None
    plan.compute_dependencies()
    return plan


def _valid(plan, topology: SliceTopology, slack: float) -> bool:
    """Tasks sharing any device must be separated by >= slack (the same
    separation the MILP's ordering constraints enforce)."""
    items = list(plan.assignments.values())
    for i, a in enumerate(items):
        if a.start < -1e-9 or a.block.end > topology.capacity:
            return False
        for b in items[i + 1 :]:
            if not a.block.overlaps(b.block):
                continue
            if (a.start + a.runtime + slack <= b.start + 1e-6) or (
                b.start + b.runtime + slack <= a.start + 1e-6
            ):
                continue
            return False
    return True
