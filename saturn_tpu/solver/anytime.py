"""Anytime tiered solver: deadline-bounded re-solves that scale to 10k jobs.

The SPASE MILP (``solver/milp.py``) assumes the batch fits inside the
execution interval; once the gateway admits thousands of jobs the full
re-solve blows the interval budget (ROADMAP item 1). This front-end
*always* returns a plan inside a caller-supplied deadline by racing down a
quality ladder, cheapest-sufficient tier first:

- **tier 0 — incremental**: warm-started delta re-placement. Survivors keep
  last interval's (size, block) choice; only the delta since the last
  adopted plan (arrivals, evictions, strategy changes) is inserted, each at
  a probe-capped min-finish slot. Extends ``warm_schedule`` below
  ``_INCR_BACKFILL_N`` tasks (backfill quality); above it a frontier
  timeline keeps placement O(block size).
- **tier 1 — hierarchical decomposition**: partition jobs by slice affinity
  (previous block) and preferred size class, solve each partition's MILP
  independently under a per-partition time slice, stitch with a
  conflict-resolving merge (partition start order, min-finish block choice
  on the partition-chosen size). A single-partition instance degenerates to
  the exact MILP — small batches lose nothing.
- **tier 2 — LP relaxation + randomized rounding**: the apportionment LP
  over the Amdahl cost model (per-task fractional size choice + the area
  bound), built directly on scipy arrays (the ``solver/lp`` Expr layer is
  O(terms²) at this scale), then seeded rounding rounds list-scheduled on
  the frontier. Round 0 is the plain greedy, so tier 2 is never worse than
  the floor; the LP optimum doubles as a quality lower bound.
- **tier 3 — greedy floor**: ``milp.greedy_plan`` (backfill) at small N,
  frontier greedy at large N. Never fails; adopted only when every richer
  tier was deadline-starved.

Every produced plan is a plain :class:`~saturn_tpu.solver.milp.Plan` that
passes the ``analysis/plan_verifier`` gate; large plans carry sparse
per-device *chain* dependencies (consecutive occupants of each device)
instead of the O(N²) all-overlapping-pairs edge set — same race-freedom
guarantee (any two tasks sharing a device are connected through that
device's chain), linear size.

``anytime_resolve`` mirrors ``milp.resolve``'s compare-and-swap contract
and is what the orchestrator, the service loop, and the elastic replanner
call; it emits one ``solver_tier`` metrics event per re-solve (tier chosen,
wall time, deadline, job count, quality estimate) — surfaced by
``python -m saturn_tpu.analysis solver``.

Operator knobs (environment):

- ``SATURN_TPU_SOLVE_DEADLINE``: global per-re-solve deadline override in
  seconds (wins over the interval-derived budget at every wired site).
- ``SATURN_TPU_PARTITION_MAX``: max jobs per tier-1 partition (default 10;
  also the size below which an instance is solved exactly).
"""

from __future__ import annotations

import logging
import os
import random
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from saturn_tpu.core.mesh import Block, SliceTopology
from saturn_tpu.solver import milp
from saturn_tpu.solver.milp import Assignment, Plan
from saturn_tpu.utils import metrics

log = logging.getLogger("saturn_tpu")

DEADLINE_ENV = "SATURN_TPU_SOLVE_DEADLINE"
PARTITION_MAX_ENV = "SATURN_TPU_PARTITION_MAX"

TIER_NAMES = {0: "incremental", 1: "partition", 2: "lp_round", 3: "greedy"}

# --- ladder applicability thresholds (calibrated on the bench host; every
# estimate errs high so a tier that starts is expected to finish in budget).
_INCR_BACKFILL_N = 160    # below: tier 0 reuses warm_schedule's backfill rule
_CHAIN_DEP_N = 256        # above: plans carry sparse chain dependencies
_INSERTION_PROBE_CAP = 32  # tier-0 per-newcomer (strategy, block) probe cap
_MIN_PART_SLICE = 0.25    # tier 1 needs at least this much budget/partition
_MAX_PARTS = 48           # beyond this many partitions, stitch overhead wins
_DEFAULT_DEADLINE = 5.0   # only when a site passes neither deadline nor env


def partition_max() -> int:
    try:
        return max(2, int(os.environ.get(PARTITION_MAX_ENV, "10")))
    except ValueError:
        return 10


@dataclass
class AnytimeReport:
    """What the ladder did for one re-solve (attached to the returned plan
    as ``plan.anytime`` and emitted as the ``solver_tier`` metrics event)."""

    tier: int                 # tier that produced the adopted plan
    wall_s: float             # total front-end wall time
    deadline_s: float         # the budget this re-solve was given
    n_tasks: int
    n_loose: int              # delta size seen by tier 0 (0 = full warm)
    makespan: float
    lower_bound: float        # cheap/LP makespan lower bound (0 if unknown)
    quality: Optional[float]  # makespan / lower_bound (>= 1.0; None if no lb)
    tiers_tried: List[int] = field(default_factory=list)
    outcome: str = "fresh"    # "fresh" or "slid" (compare-and-swap kept old)
    # How many of the adopted plan's assignments rest on shardflow
    # cold-start priors (``Strategy.static_prior``) rather than trials —
    # the "this plan is partly an educated guess" signal in solver_tier.
    n_static_prior: int = 0

    @property
    def tier_name(self) -> str:
        return TIER_NAMES.get(self.tier, str(self.tier))

    @property
    def deadline_missed(self) -> bool:
        """True when the front-end overran the budget it was given (the
        ladder's contract is that this never happens; campaign harnesses
        count these as hard failures)."""
        return self.wall_s > self.deadline_s


# ---------------------------------------------------------------------------
# frontier timeline: O(block size) placement for 10k-task plans
# ---------------------------------------------------------------------------

class FrontierTimeline:
    """Per-device next-free-time frontier.

    Unlike :class:`~saturn_tpu.solver.milp.DeviceTimeline` there is no
    backfill — a task starts at the max frontier of its block — which trades
    a little packing quality for O(block size) placement instead of
    O(N log N) per call. The large-N tiers live on this.
    """

    __slots__ = ("free",)

    def __init__(self, capacity: int):
        self.free = [0.0] * capacity

    def earliest_free(self, blk: Block) -> float:
        free = self.free
        return max(free[d] for d in range(blk.offset, blk.end))

    def place(self, blk: Block, runtime: float, slack: float) -> float:
        free = self.free
        st = max(free[d] for d in range(blk.offset, blk.end))
        end = st + runtime + slack
        for d in range(blk.offset, blk.end):
            free[d] = end
        return st


def chain_dependencies(assignments: Dict[str, Assignment],
                       coschedule: Optional[List[List[str]]] = None,
                       fused: Optional[List[List[str]]] = None,
                       ) -> Dict[str, List[str]]:
    """Sparse per-device chain edges: on every device, each occupant depends
    on the previous occupant (start order). Any two tasks whose blocks
    overlap share at least one device, so they are connected through that
    device's chain — the same race-freedom property the O(N²)
    ``Plan.compute_dependencies`` edge set guarantees, at O(total occupancy)
    size. Members of one co-schedule group are exempt, as in the dense form;
    so are members of one FUSION group (they are one stacked program holding
    identical assignments by construction).
    """
    group_of: Dict[str, int] = {}
    for gi, grp in enumerate(coschedule or []):
        for n in grp:
            group_of[n] = gi
    fgroup_of: Dict[str, int] = {}
    for gi, grp in enumerate(fused or []):
        for n in grp:
            fgroup_of[n] = gi
    per_device: Dict[int, List[Tuple[float, str]]] = {}
    for name, a in assignments.items():
        for d in range(a.block.offset, a.block.end):
            per_device.setdefault(d, []).append((a.start, name))
    deps: Dict[str, set] = {name: set() for name in assignments}
    for occ in per_device.values():
        occ.sort()
        for (_, n1), (_, n2) in zip(occ, occ[1:]):
            g1, g2 = group_of.get(n1), group_of.get(n2)
            if g1 is not None and g1 == g2:
                continue
            f1, f2 = fgroup_of.get(n1), fgroup_of.get(n2)
            if f1 is not None and f1 == f2:
                continue
            deps[n2].add(n1)
    return {name: sorted(d) for name, d in deps.items()}


def _finish_plan(assignments: Dict[str, Assignment],
                 coschedule: Optional[List[List[str]]] = None,
                 fused: Optional[List[List[str]]] = None) -> Plan:
    """Wrap assignments in a Plan with scale-appropriate dependencies."""
    makespan = max((a.start + a.runtime for a in assignments.values()),
                   default=0.0)
    plan = Plan(assignments=assignments, makespan=makespan,
                coschedule=list(coschedule or []), fused=list(fused or []))
    if len(assignments) > _CHAIN_DEP_N:
        plan.dependencies = chain_dependencies(assignments, plan.coschedule,
                                               plan.fused)
    else:
        plan.compute_dependencies()
    return plan


def _options_of(task, capacity: int) -> List[Tuple[int, Block, float]]:
    opts = []
    for size, strat in sorted(task.feasible_strategies().items()):
        if size > capacity:
            continue
        for blk in _blocks_cached(size, capacity):
            opts.append((size, blk, strat.runtime))
    return opts


_BLOCK_CACHE: Dict[Tuple[int, int], List[Block]] = {}


def _blocks_cached(size: int, capacity: int) -> List[Block]:
    key = (size, capacity)
    blks = _BLOCK_CACHE.get(key)
    if blks is None:
        blks = [Block(off, size) for off in range(0, capacity, size)
                ] if 0 < size <= capacity else []
        _BLOCK_CACHE[key] = blks
    return blks


def _validate(task_list: Sequence, topology: SliceTopology) -> None:
    for t in task_list:
        feas = t.feasible_strategies()
        if not feas:
            raise ValueError(
                f"task {t.name} has no feasible strategy; run search first")
        if all(size > topology.capacity for size in feas):
            raise ValueError(
                f"task {t.name}: no strategy fits topology capacity "
                f"{topology.capacity}")


def cheap_lower_bound(task_list: Sequence, topology: SliceTopology) -> float:
    """O(N) valid makespan lower bound: longest single task's fastest
    option, and the work-area bound (best-case area / capacity). Loose by
    construction — 'quality vs bound' overstates the true gap."""
    cap = topology.capacity
    longest = 0.0
    area = 0.0
    for t in task_list:
        best_rt = None
        best_area = None
        for size, strat in t.feasible_strategies().items():
            if size > cap:
                continue
            if best_rt is None or strat.runtime < best_rt:
                best_rt = strat.runtime
            a = size * strat.runtime
            if best_area is None or a < best_area:
                best_area = a
        if best_rt is None:
            continue
        longest = max(longest, best_rt)
        area += best_area or 0.0
    return max(longest, area / max(cap, 1))


# ---------------------------------------------------------------------------
# tier 3 — greedy floor
# ---------------------------------------------------------------------------

def fast_greedy_plan(task_list: Sequence, topology: SliceTopology,
                     ordering_slack: float = 1.0,
                     weights: Optional[Dict[str, float]] = None) -> Plan:
    """Frontier list-scheduling floor: priority-then-LPT order, min-finish
    (size, block) choice. Same decision rule as ``milp.greedy_plan`` minus
    backfill — O(N · capacity) total, ~10k tasks in well under a second."""
    cap = topology.capacity
    w = weights or {}
    order = sorted(
        task_list,
        key=lambda t: (
            -w.get(t.name, 0.0),
            -min(s.runtime for s in t.feasible_strategies().values()),
        ),
    )
    timeline = FrontierTimeline(cap)
    free = timeline.free
    assignments: Dict[str, Assignment] = {}
    for t in order:
        best = None  # (finish, start, size, blk, rt)
        for size, strat in sorted(t.feasible_strategies().items()):
            if size > cap:
                continue
            rt = strat.runtime
            for blk in _blocks_cached(size, cap):
                st = max(free[d] for d in range(blk.offset, blk.end))
                fin = st + rt
                if best is None or fin < best[0]:
                    best = (fin, st, size, blk, rt)
        if best is None:
            raise ValueError(
                f"task {t.name}: no strategy fits topology capacity {cap}")
        fin, st, size, blk, rt = best
        end = fin + ordering_slack
        for d in range(blk.offset, blk.end):
            free[d] = end
        assignments[t.name] = Assignment(size, blk, st, rt)
    return _finish_plan(assignments)


def _greedy_floor(task_list, topology, ordering_slack, weights) -> Plan:
    if len(task_list) <= _CHAIN_DEP_N:
        return milp.greedy_plan(task_list, topology, ordering_slack,
                                weights=weights)
    return fast_greedy_plan(task_list, topology, ordering_slack, weights)


# ---------------------------------------------------------------------------
# tier 0 — warm-started incremental delta re-placement
# ---------------------------------------------------------------------------

def split_delta(task_list: Sequence, topology: SliceTopology,
                previous: Optional[Plan]) -> Tuple[List, List]:
    """(pinned, loose): tasks whose previous (size, block) choice is still
    valid vs the delta the incremental tier must re-place."""
    if previous is None:
        return [], list(task_list)
    pinned, loose = [], []
    for t in task_list:
        a = previous.assignments.get(t.name)
        strat = (t.feasible_strategies().get(a.apportionment)
                 if a is not None else None)
        if a is None or strat is None or a.block.end > topology.capacity:
            loose.append(t)
        else:
            pinned.append(t)
    return pinned, loose


def incremental_plan(task_list: Sequence, topology: SliceTopology,
                     previous: Plan, ordering_slack: float = 1.0,
                     weights: Optional[Dict[str, float]] = None,
                     probe_cap: int = _INSERTION_PROBE_CAP,
                     ) -> Optional[Plan]:
    """Tier 0: survivors keep their previous (size, block) in previous start
    order; the delta is inserted at probe-capped min-finish slots. Below
    ``_INCR_BACKFILL_N`` this IS ``warm_schedule(insert_missing=True)``
    (backfill quality); above it, the frontier rule keeps the whole pass
    O(N · block size)."""
    if len(task_list) <= _INCR_BACKFILL_N:
        return milp.warm_schedule(
            task_list, topology, previous, ordering_slack,
            insert_missing=True, weights=weights,
            insertion_probe_cap=probe_cap,
        )

    cap = topology.capacity
    pinned_t, loose = split_delta(task_list, topology, previous)
    pinned: List[Tuple[Any, int, Block, float]] = []
    for t in pinned_t:
        a = previous.assignments[t.name]
        rt = t.feasible_strategies()[a.apportionment].runtime
        pinned.append((t, a.apportionment, a.block, rt))
    pinned.sort(key=lambda p: previous.assignments[p[0].name].start)

    timeline = FrontierTimeline(cap)
    free = timeline.free
    assignments: Dict[str, Assignment] = {}
    for t, size, blk, rt in pinned:
        st = max(free[d] for d in range(blk.offset, blk.end))
        end = st + rt + ordering_slack
        for d in range(blk.offset, blk.end):
            free[d] = end
        assignments[t.name] = Assignment(size, blk, st, rt)

    w = weights or {}
    loose.sort(
        key=lambda t: (
            -w.get(t.name, 0.0),
            -min(s.runtime for s in t.feasible_strategies().values()),
        ),
    )
    for t in loose:
        best = None
        probes = 0
        for size, strat in sorted(t.feasible_strategies().items()):
            if size > cap:
                continue
            rt = strat.runtime
            for blk in _blocks_cached(size, cap):
                if probes >= probe_cap and best is not None:
                    break
                probes += 1
                st = max(free[d] for d in range(blk.offset, blk.end))
                fin = st + rt
                if best is None or fin < best[0]:
                    best = (fin, st, size, blk, rt)
            if probes >= probe_cap and best is not None:
                break
        if best is None:
            return None
        fin, st, size, blk, rt = best
        end = fin + ordering_slack
        for d in range(blk.offset, blk.end):
            free[d] = end
        assignments[t.name] = Assignment(size, blk, st, rt)
    return _finish_plan(assignments)


# ---------------------------------------------------------------------------
# tier 1 — hierarchical decomposition (partition / solve / stitch)
# ---------------------------------------------------------------------------

def _partitions(task_list: Sequence, previous: Optional[Plan],
                max_size: int) -> List[List]:
    """Group by (preferred size class, previous-block slice affinity), then
    chunk each group to ``max_size``. Tasks that shared a block region last
    interval land in one partition, so the per-partition MILP sees the
    ordering conflicts that actually matter."""
    groups: Dict[Tuple[int, int], List] = {}
    for t in task_list:
        feas = t.feasible_strategies()
        pref = min(feas.items(), key=lambda kv: kv[1].runtime)[0]
        a = previous.assignments.get(t.name) if previous is not None else None
        affinity = a.block.offset // max(a.block.size, 1) if a is not None else -1
        groups.setdefault((pref, affinity), []).append(t)
    parts: List[List] = []
    for key in sorted(groups, key=lambda k: (k[0], k[1])):
        grp = groups[key]
        for i in range(0, len(grp), max_size):
            parts.append(grp[i:i + max_size])
    return parts


def partition_plan(task_list: Sequence, topology: SliceTopology,
                   budget: float, ordering_slack: float = 1.0,
                   weights: Optional[Dict[str, float]] = None,
                   previous: Optional[Plan] = None,
                   coschedule_exclude=None,
                   fusion: Optional[List[List[str]]] = None,
                   fusion_exclude=None, fusion_fits=None) -> Optional[Plan]:
    """Tier 1: solve each partition's MILP under its time slice, then stitch.

    The merge keeps each task's partition-chosen apportionment (the
    MILP-optimized size) and its partition-internal start for ordering, then
    re-places every task on the frontier in global start order, choosing the
    min-finish block of the chosen size — always feasible, conflict-free by
    construction. A single partition returns the exact plan untouched
    (co-schedule AND fusion groups included); multi-partition stitches are
    conservatively serial, so co-location and fusion proposals only appear
    at exact scale (the merge's re-placement cannot honor a group's shared
    assignment).
    """
    t0 = time.perf_counter()
    parts = _partitions(task_list, previous, partition_max())
    if len(parts) == 1:
        return milp.solve(task_list, topology,
                          time_limit=max(0.05, budget * 0.9),
                          ordering_slack=ordering_slack, weights=weights,
                          warm=previous, coschedule_exclude=coschedule_exclude,
                          fusion=fusion, fusion_exclude=fusion_exclude,
                          fusion_fits=fusion_fits)

    slice_budget = max(_MIN_PART_SLICE, (budget * 0.8) / len(parts))
    placed: List[Tuple[float, int, Any, int, float]] = []  # (start, pi, task, size, rt)
    for pi, part in enumerate(parts):
        remaining = budget - (time.perf_counter() - t0)
        if remaining > slice_budget * 0.5:
            # A huge min_gain keeps the co-location term out: merge
            # re-placement cannot honor a group's tied starts.
            sub = milp.solve(part, topology,
                             time_limit=min(slice_budget, remaining),
                             ordering_slack=ordering_slack, weights=weights,
                             warm=previous, coschedule_min_gain=1e9)
        else:
            # budget exhausted mid-ladder: the leftovers get the greedy rule
            sub = milp.greedy_plan(part, topology, ordering_slack,
                                   weights=weights)
        for t in part:
            a = sub.assignments[t.name]
            placed.append((a.start, pi, t, a.apportionment, a.runtime))

    # Conflict-resolving merge: zipper all partitions by internal start.
    placed.sort(key=lambda p: (p[0], p[1]))
    cap = topology.capacity
    timeline = FrontierTimeline(cap)
    free = timeline.free
    assignments: Dict[str, Assignment] = {}
    for _, _, t, size, rt in placed:
        best = None  # (finish, start, blk)
        for blk in _blocks_cached(size, cap):
            st = max(free[d] for d in range(blk.offset, blk.end))
            if best is None or st + rt < best[0]:
                best = (st + rt, st, blk)
        if best is None:
            return None
        fin, st, blk = best
        end = fin + ordering_slack
        for d in range(blk.offset, blk.end):
            free[d] = end
        assignments[t.name] = Assignment(size, blk, st, rt)
    return _finish_plan(assignments)


# ---------------------------------------------------------------------------
# tier 2 — LP relaxation + seeded randomized rounding
# ---------------------------------------------------------------------------

def lp_round_plan(task_list: Sequence, topology: SliceTopology,
                  ordering_slack: float = 1.0,
                  weights: Optional[Dict[str, float]] = None,
                  seed: int = 0, rounds: int = 3,
                  time_limit: float = 5.0,
                  ) -> Tuple[Optional[Plan], float]:
    """Tier 2: apportionment LP over the Amdahl cost model, then rounding.

    Blocks of one size are symmetric, so the LP only chooses *sizes*:
    minimize mk s.t. per-task option mix sums to 1, mk >= each task's mixed
    runtime, mk >= selected work area / capacity. Built directly on scipy
    arrays — the ``solver/lp`` Expr layer re-copies coefficient dicts per
    term and is quadratic at 10k x 4 options. Rounding: round 0 is plain
    greedy (floor quality guaranteed); later rounds sample each task's size
    from its LP mix with a seeded RNG and list-schedule min-finish on the
    frontier. Returns ``(best plan, LP lower bound)`` — bound 0.0 when the
    LP failed to prove optimality (a time-limited primal is not a bound).
    """
    try:
        import numpy as np
        from scipy import sparse
        from scipy.optimize import linprog
    except Exception:  # pragma: no cover - scipy is in-image; belt and braces
        return None, 0.0

    cap = topology.capacity
    names: List[str] = []
    per_task: List[List[Tuple[int, float]]] = []
    for t in task_list:
        opts = [(size, strat.runtime)
                for size, strat in sorted(t.feasible_strategies().items())
                if size <= cap]
        if not opts:
            return None, 0.0
        names.append(t.name)
        per_task.append(opts)

    n = len(per_task)
    offsets = [0] * n
    total = 0
    for i, opts in enumerate(per_task):
        offsets[i] = total
        total += len(opts)
    nvar = 1 + total  # [mk, x...]

    c = np.zeros(nvar)
    c[0] = 1.0
    eq_r, eq_c, eq_v = [], [], []
    ub_r, ub_c, ub_v = [], [], []
    for i, opts in enumerate(per_task):
        for k, (size, rt) in enumerate(opts):
            j = 1 + offsets[i] + k
            eq_r.append(i); eq_c.append(j); eq_v.append(1.0)
            ub_r.append(i); ub_c.append(j); ub_v.append(rt)       # mixed rt
            ub_r.append(n); ub_c.append(j); ub_v.append(size * rt / cap)
        ub_r.append(i); ub_c.append(0); ub_v.append(-1.0)         # ... <= mk
    ub_r.append(n); ub_c.append(0); ub_v.append(-1.0)
    A_eq = sparse.coo_matrix((eq_v, (eq_r, eq_c)), shape=(n, nvar)).tocsr()
    A_ub = sparse.coo_matrix((ub_v, (ub_r, ub_c)), shape=(n + 1, nvar)).tocsr()
    bounds = [(0.0, None)] + [(0.0, 1.0)] * total
    try:
        res = linprog(c, A_ub=A_ub, b_ub=np.zeros(n + 1), A_eq=A_eq,
                      b_eq=np.ones(n), bounds=bounds, method="highs",
                      options={"time_limit": max(0.05, time_limit)})
    except (ValueError, TypeError):
        return None, 0.0
    lp_bound = 0.0
    frac: Optional[List[List[float]]] = None
    if res.status == 0 and res.x is not None:
        lp_bound = float(res.fun)
        frac = [
            [max(0.0, float(res.x[1 + offsets[i] + k]))
             for k in range(len(per_task[i]))]
            for i in range(n)
        ]

    # Rounding rounds. Order is priority-then-LPT, shared across rounds.
    w = weights or {}
    order = sorted(
        range(n),
        key=lambda i: (
            -w.get(names[i], 0.0),
            -min(rt for _, rt in per_task[i]),
        ),
    )
    by_name = {t.name: t for t in task_list}
    best_plan: Optional[Plan] = None
    for r in range(max(1, rounds)):
        rng = random.Random((seed << 8) ^ r) if r > 0 else None
        timeline = FrontierTimeline(cap)
        free = timeline.free
        assignments: Dict[str, Assignment] = {}
        for i in order:
            opts = per_task[i]
            if rng is not None and frac is not None and len(opts) > 1:
                u, acc, pick = rng.random(), 0.0, len(opts) - 1
                for k, f in enumerate(frac[i]):
                    acc += f
                    if u <= acc:
                        pick = k
                        break
                cand = [opts[pick]]
            else:
                cand = opts  # round 0 (or no LP mix): greedy over all sizes
            best = None  # (finish, start, size, blk, rt)
            for size, rt in cand:
                for blk in _blocks_cached(size, cap):
                    st = max(free[d] for d in range(blk.offset, blk.end))
                    fin = st + rt
                    if best is None or fin < best[0]:
                        best = (fin, st, size, blk, rt)
            if best is None:
                return None, lp_bound
            fin, st, size, blk, rt = best
            end = fin + ordering_slack
            for d in range(blk.offset, blk.end):
                free[d] = end
            assignments[names[i]] = Assignment(size, blk, st, rt)
        plan = _finish_plan(assignments)
        if best_plan is None or plan.makespan < best_plan.makespan:
            best_plan = plan
    return best_plan, lp_bound


# ---------------------------------------------------------------------------
# the ladder front-end
# ---------------------------------------------------------------------------

def _est_floor(n: int) -> float:
    return 0.005 + 2e-5 * n


def _est_incremental(n: int, n_loose: int) -> float:
    return 0.01 + 1.5e-5 * n + 4e-6 * n_loose * _INSERTION_PROBE_CAP


def _est_lp(n: int) -> float:
    return 0.06 + 2.5e-4 * n


def anytime_solve(task_list: Sequence, topology: SliceTopology,
                  deadline: float, previous: Optional[Plan] = None,
                  ordering_slack: float = 1.0,
                  weights: Optional[Dict[str, float]] = None,
                  coschedule_exclude=None, seed: int = 0,
                  fusion: Optional[List[List[str]]] = None,
                  fusion_exclude=None, fusion_fits=None,
                  ) -> Tuple[Plan, AnytimeReport]:
    """Race down the tier ladder; always returns a plan within ~``deadline``.

    Applicability is cost-model driven: a tier only starts when its
    (conservative) estimate fits the remaining budget after reserving the
    greedy floor, so the floor can always still run. The best-makespan plan
    among the tiers that ran is adopted, and the report records which tier
    produced it.
    """
    t0 = time.perf_counter()
    _validate(task_list, topology)
    n = len(task_list)
    deadline = max(float(deadline), 1e-3)
    floor_est = _est_floor(n)

    def remaining() -> float:
        return deadline - (time.perf_counter() - t0)

    best: Optional[Plan] = None
    best_tier = 3
    tried: List[int] = []
    lp_bound = 0.0

    pinned, loose = split_delta(task_list, topology, previous)
    n_loose = len(loose)

    # tier 0 — incremental (needs a mostly-covering previous plan)
    if (previous is not None and n > 0
            and n_loose <= max(8, n // 4)
            and _est_incremental(n, n_loose) <= remaining() - floor_est):
        tried.append(0)
        p0 = incremental_plan(task_list, topology, previous, ordering_slack,
                              weights, probe_cap=_INSERTION_PROBE_CAP)
        if p0 is not None:
            best, best_tier = p0, 0

    # tier 1 — hierarchical decomposition (budget permitting)
    if n > 0:
        n_parts = max(1, -(-n // partition_max()))
        budget = remaining() - floor_est
        tier1_ok = (n_parts <= _MAX_PARTS
                    and budget >= n_parts * _MIN_PART_SLICE)
        if tier1_ok:
            tried.append(1)
            p1 = partition_plan(task_list, topology, budget, ordering_slack,
                                weights, previous=previous,
                                coschedule_exclude=coschedule_exclude,
                                fusion=fusion, fusion_exclude=fusion_exclude,
                                fusion_fits=fusion_fits)
            if p1 is not None and (best is None or p1.makespan < best.makespan):
                best, best_tier = p1, 1
        elif best is None and remaining() - floor_est >= _est_lp(n):
            # tier 2 — LP + rounding (the mid-scale workhorse)
            tried.append(2)
            p2, lp_bound = lp_round_plan(
                task_list, topology, ordering_slack, weights, seed=seed,
                time_limit=max(0.05, (remaining() - floor_est) * 0.5),
            )
            if p2 is not None and (best is None or p2.makespan < best.makespan):
                best, best_tier = p2, 2

    # tier 3 — the never-fail floor
    if best is None:
        tried.append(3)
        best = _greedy_floor(task_list, topology, ordering_slack, weights)
        best_tier = 3

    lb = max(cheap_lower_bound(task_list, topology), lp_bound) if n else 0.0
    wall = time.perf_counter() - t0
    by_name = {getattr(t, "name", None): t for t in task_list}
    n_static = sum(
        1 for name, a in best.assignments.items()
        if getattr(
            getattr(by_name.get(name), "strategies", {}).get(a.apportionment),
            "static_prior", False,
        )
    )
    report = AnytimeReport(
        tier=best_tier, wall_s=wall, deadline_s=deadline, n_tasks=n,
        n_loose=n_loose, makespan=best.makespan, lower_bound=lb,
        quality=(best.makespan / lb) if lb > 1e-9 else None,
        tiers_tried=tried,
        n_static_prior=n_static,
    )
    best.anytime = report
    return best, report


def resolve_deadline(deadline: Optional[float],
                     interval: Optional[float] = None) -> float:
    """The wired sites' deadline derivation: the explicit env override wins,
    then the caller's budget (the orchestrator/service ``tlimit``, which
    already defaults to interval/2), then half the interval, then a
    conservative default."""
    env = os.environ.get(DEADLINE_ENV)
    if env:
        try:
            return max(1e-3, float(env))
        except ValueError:
            log.warning("ignoring unparsable %s=%r", DEADLINE_ENV, env)
    if deadline is not None:
        return max(1e-3, float(deadline))
    if interval is not None and interval > 0:
        return max(1e-3, interval / 2)
    return _DEFAULT_DEADLINE


def _emit_tier_event(report: AnytimeReport, source: str) -> None:
    metrics.event(
        "solver_tier",
        source=source,
        tier=report.tier,
        tier_name=report.tier_name,
        wall_s=round(report.wall_s, 6),
        deadline_s=round(report.deadline_s, 6),
        n_tasks=report.n_tasks,
        n_loose=report.n_loose,
        makespan_s=round(report.makespan, 6),
        quality=(round(report.quality, 4) if report.quality is not None
                 else None),
        tiers_tried=list(report.tiers_tried),
        outcome=report.outcome,
        n_static_prior=report.n_static_prior,
    )


def anytime_resolve(task_list: Sequence, topology: SliceTopology,
                    previous: Optional[Plan], interval: float,
                    threshold: float = 0.0,
                    deadline: Optional[float] = None,
                    weights: Optional[Dict[str, float]] = None,
                    coschedule_exclude=None,
                    warm: Optional[Plan] = None,
                    ordering_slack: float = 1.0,
                    source: str = "resolve", seed: int = 0,
                    fusion: Optional[List[List[str]]] = None,
                    fusion_exclude=None, fusion_fits=None) -> Plan:
    """Deadline-bounded drop-in for ``milp.resolve``: tier-ladder fresh
    solve + the introspective compare-and-swap, one ``solver_tier`` metrics
    event per call.

    ``previous`` plays its two ``milp.resolve`` roles (warm seed + CAS
    incumbent); pass ``warm`` alone (with ``previous=None``) to seed the
    ladder without the compare-and-swap — the replanner's shape, where the
    old plan may reference dead devices and must never be kept.
    """
    dl = resolve_deadline(deadline, interval)
    warm_seed = warm if warm is not None else previous
    fresh, report = anytime_solve(
        task_list, topology, dl, previous=warm_seed,
        ordering_slack=ordering_slack, weights=weights,
        coschedule_exclude=coschedule_exclude, seed=seed,
        fusion=fusion, fusion_exclude=fusion_exclude,
        fusion_fits=fusion_fits,
    )
    if previous is None:
        _emit_tier_event(report, source)
        return fresh

    prev_names = set(previous.assignments)
    cur_names = {t.name for t in task_list}
    adopt_fresh = bool(cur_names - prev_names) or len(cur_names) < len(prev_names)
    slid: Optional[Plan] = None
    if not adopt_fresh:
        slid = Plan(
            assignments={
                n: Assignment(a.apportionment, a.block,
                              max(0.0, a.start - interval), a.runtime)
                for n, a in previous.assignments.items() if n in cur_names
            },
            makespan=max(0.0, previous.makespan - interval),
            coschedule=[
                kept for grp in previous.coschedule
                if len(kept := [n for n in grp if n in cur_names]) >= 2
            ],
            # surviving fusion groups slide like co-schedule groups; a stack
            # shrunk below 2 members stops being a stack
            fused=[
                kept for grp in previous.fused
                if len(kept := [n for n in grp if n in cur_names]) >= 2
            ],
        )
        if coschedule_exclude:
            excl = set(coschedule_exclude)
            if any(excl & set(grp) for grp in slid.coschedule):
                adopt_fresh = True  # a detached member sits in a slid group
        if fusion_exclude:
            excl = set(fusion_exclude)
            if any(excl & set(grp) for grp in slid.fused):
                adopt_fresh = True  # a quarantined member sits in a slid stack
        if not adopt_fresh:
            if len(slid.assignments) > _CHAIN_DEP_N:
                slid.dependencies = chain_dependencies(slid.assignments,
                                                       slid.coschedule,
                                                       slid.fused)
            else:
                slid.compute_dependencies()
            adopt_fresh = fresh.makespan < slid.makespan - threshold

    if adopt_fresh or slid is None:
        _emit_tier_event(report, source)
        return fresh
    report.outcome = "slid"
    _emit_tier_event(report, source)
    slid.anytime = report
    return slid
