"""Planning layer: MILP joint allocation/scheduling + greedy fallback.

Public entry point: :func:`solve` — produce a :class:`~saturn_tpu.solver.milp.Plan`
for a task list over a :class:`~saturn_tpu.core.mesh.SliceTopology`.
"""

from saturn_tpu.solver.milp import solve

__all__ = ["solve"]
