"""The SPASE MILP: jointly select strategy, allocate a sub-mesh, and schedule.

Reference: ``saturn/solver/milp.py:23-445``. Same decision structure —
one strategy per task (``bss``, ``milp.py:96-111``), one placement per task
(``bna`` node choice, ``:117-137``), start times (``sta``, ``:139-149``),
pairwise ordering (``boa``, ``:263-270``), makespan objective (``:90,321``) —
re-shaped for a TPU pod slice:

- Placement ranges over **contiguous, size-aligned blocks** of the device ring
  (buddy allocation; see ``core/mesh.py``) instead of (node × GPU-subset).
  The reference's "a job never spans nodes" constraint (``milp.py:134-137``)
  becomes "a job occupies exactly one contiguous block" — which also
  guarantees its collectives ride ICI.
- Strategy and placement merge into one joint binary ``x[t][(size, block)]``
  per task: exactly-one per task covers both ``bss`` and ``bna``.
- Big-M is the total runtime bound, not 1e10 (``milp.py:163`` used 1e10 and
  leaned on Gurobi's IntFeasTol; HiGHS is happier with tight Ms).
- Solved with HiGHS via ``saturn_tpu.solver.lp`` (no Gurobi/PuLP in-image).

The introspection compare-and-swap (``milp.py:354-444``) lives in
``resolve()``: re-solve each interval, adopt the new plan only if it beats the
old one by more than interval+threshold, else slide the old plan down.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from saturn_tpu.core.mesh import Block, SliceTopology
from saturn_tpu.solver.lp import Expr, Model

log = logging.getLogger("saturn_tpu")


@dataclass
class Assignment:
    """One task's slot in the plan."""

    apportionment: int      # sub-mesh size (chips)
    block: Block            # which aligned block of the ring
    start: float            # start time, seconds from interval origin
    runtime: float          # estimated remaining runtime under this strategy


@dataclass
class Plan:
    """Decoded schedule (reference ``convert_into_comprehensible``,
    ``milp.py:448-513``)."""

    assignments: Dict[str, Assignment]          # task name -> slot
    makespan: float
    dependencies: Dict[str, List[str]] = field(default_factory=dict)
    # Co-schedule groups: lists of task names whose windows the engine may
    # INTERLEAVE on a shared device block instead of serializing them — the
    # explicit co-location edge ``_check_disjoint`` honors. Produced by the
    # MILP's co-location term when the measured host fractions predict that
    # one job's host phases can hide under the other's device windows; empty
    # everywhere else (warm/greedy/native plans are conservatively serial).
    coschedule: List[List[str]] = field(default_factory=list)
    # Fusion groups: lists of task names the engine trains as ONE stacked
    # SPMD program (``parallel/fused.py``) — N identical-architecture sweep
    # members advancing in lockstep under a single compiled step. Members of
    # a group hold IDENTICAL assignments (same block, same start, runtime =
    # the fused lockstep runtime) by construction; like co-schedule groups
    # their mutual overlap is the point, not a race. Produced only by the
    # fusion pricing pre-pass in :func:`solve` when every member carries a
    # measured ``fused_per_batch_time`` and the fused runtime beats both the
    # serial and the co-scheduled alternative.
    fused: List[List[str]] = field(default_factory=list)

    def coschedule_group_of(self) -> Dict[str, int]:
        """task name -> index of its co-schedule group (absent = solo)."""
        out: Dict[str, int] = {}
        for gi, grp in enumerate(self.coschedule):
            for n in grp:
                out[n] = gi
        return out

    def fused_group_of(self) -> Dict[str, int]:
        """task name -> index of its fusion group (absent = not fused)."""
        out: Dict[str, int] = {}
        for gi, grp in enumerate(self.fused):
            for n in grp:
                out[n] = gi
        return out

    def compute_dependencies(self) -> None:
        """Edges between tasks whose blocks overlap: later start depends on
        earlier (reference builds deps from GPU-overlap ∩ boa,
        ``milp.py:489-511``). Members of one co-schedule group are exempt:
        their overlap is the point — the engine interleaves them on a shared
        launcher rather than ordering them. Members of one FUSION group are
        exempt for the stronger reason: they are one program, and their
        assignments are identical by construction."""
        group_of = self.coschedule_group_of()
        fgroup_of = self.fused_group_of()
        deps: Dict[str, List[str]] = {name: [] for name in self.assignments}
        items = list(self.assignments.items())
        for i, (n1, a1) in enumerate(items):
            for n2, a2 in items[i + 1 :]:
                g1, g2 = group_of.get(n1), group_of.get(n2)
                if g1 is not None and g1 == g2:
                    continue
                f1, f2 = fgroup_of.get(n1), fgroup_of.get(n2)
                if f1 is not None and f1 == f2:
                    continue
                if a1.block.overlaps(a2.block):
                    if a1.start <= a2.start:
                        deps[n2].append(n1)
                    else:
                        deps[n1].append(n2)
        self.dependencies = deps

    def migrations_from(self, previous: "Plan") -> Dict[str, dict]:
        """Per-task placement diff against ``previous`` — the elastic
        replanner's migration report (``resilience/replan.py``). A task
        "moved" when its sub-mesh size or block changed: its next interval
        must restore state onto a different mesh (cross-mesh checkpoint
        migration, ``utils/checkpoint.py::restore_sharded``) instead of
        reusing live device buffers."""
        out: Dict[str, dict] = {}
        for name, a in self.assignments.items():
            p = previous.assignments.get(name)
            if p is None:
                out[name] = {"moved": True, "from": None,
                             "to": [a.apportionment, a.block.offset]}
                continue
            moved = (
                a.apportionment != p.apportionment
                or a.block.offset != p.block.offset
                or a.block.size != p.block.size
            )
            out[name] = {
                "moved": moved,
                "from": [p.apportionment, p.block.offset],
                "to": [a.apportionment, a.block.offset],
            }
        return out

    # Wire format for the multi-host control plane: the coordinator solves,
    # every rank executes the SAME decoded plan (core/distributed.py
    # broadcast_json) — a time-limited HiGHS run is not deterministic
    # across processes.
    def to_json(self) -> dict:
        return {
            "makespan": self.makespan,
            "assignments": {
                n: [a.apportionment, a.block.offset, a.block.size, a.start,
                    a.runtime]
                for n, a in self.assignments.items()
            },
            "dependencies": self.dependencies,
            "coschedule": [list(g) for g in self.coschedule],
            "fused": [list(g) for g in self.fused],
        }

    @staticmethod
    def from_json(d: dict) -> "Plan":
        return Plan(
            assignments={
                n: Assignment(int(app), Block(int(off), int(size)), float(st),
                              float(rt))
                for n, (app, off, size, st, rt) in d["assignments"].items()
            },
            makespan=float(d["makespan"]),
            dependencies={k: list(v) for k, v in d["dependencies"].items()},
            # absent in plans journaled before the co-schedule term existed
            coschedule=[list(g) for g in d.get("coschedule", [])],
            # absent in plans journaled before fused stacking existed
            fused=[list(g) for g in d.get("fused", [])],
        )


class DeviceTimeline:
    """Per-device busy intervals with the earliest-free-slot rule.

    The single Python implementation of the list-scheduling primitive that
    ``warm_schedule`` and ``greedy_plan`` share and that ``evaluate`` in
    ``native/spase.cpp:47-90`` mirrors in C++ — occupied windows are padded by
    the caller's ordering slack, finish times exclude the pad, and a task
    starts at the earliest t where [t, t+duration) is free on every device of
    its block. Property-tested for exact equivalence against the native
    constructor (``tests/test_native.py``); the warm plan's "never worse"
    guarantee rests on all three agreeing.
    """

    def __init__(self, capacity: int):
        self._events: Dict[int, List[Tuple[float, float]]] = {
            d: [] for d in range(capacity)
        }

    def earliest_free(self, blk: Block, duration: float) -> float:
        """Earliest t such that [t, t+duration) is free on all devices of blk."""
        busy = sorted(
            iv for d in range(blk.offset, blk.end) for iv in self._events[d]
        )
        t0 = 0.0
        for s, e in busy:
            if t0 + duration <= s:
                break
            t0 = max(t0, e)
        return t0

    def occupy(self, blk: Block, start: float, end: float) -> None:
        for d in range(blk.offset, blk.end):
            self._events[d].append((start, end))

    def place(self, blk: Block, runtime: float, slack: float) -> float:
        """Book the earliest slack-padded slot for ``runtime`` on ``blk``;
        returns the start time."""
        st = self.earliest_free(blk, runtime + slack)
        self.occupy(blk, st, st + runtime + slack)
        return st


def warm_schedule(
    task_list: List,
    topology: SliceTopology,
    previous: Plan,
    ordering_slack: float = 1.0,
    insert_missing: bool = False,
    weights: Optional[Dict[str, float]] = None,
    insertion_probe_cap: Optional[int] = None,
) -> Optional[Plan]:
    """Fix-and-optimize warm start: keep each task's previous (size, block)
    choice, list-schedule starts under CURRENT runtimes in previous start
    order. O(N² log N), always feasible — the analog of the reference seeding
    Gurobi with last interval's solution (``milp.py:103-104,151-155,323``).

    Returns None if any task lacks a previous assignment or its previous
    choice no longer exists (strategy became infeasible / capacity changed) —
    unless ``insert_missing`` is set, in which case such tasks are appended
    AFTER the pinned incumbent structure, each at its min-finish
    (strategy, block) slot, in descending ``weights`` order (priority-first;
    ties broken longest-first). This is the online service's incremental
    warm start: one arrival or departure perturbs the live plan instead of
    invalidating it.

    ``insertion_probe_cap`` bounds the (strategy, block) slots probed per
    inserted task: probes run in the deterministic sorted option order and
    stop at the cap once at least one feasible slot was found (the cap never
    leaves a schedulable task unplaced — it only stops the search for a
    *better* slot). The anytime solver's tier-0 budget depends on this: one
    newcomer with a rich option set on a big mesh must cost O(cap) probes,
    not O(sizes x blocks).
    """
    pinned: List[Tuple[object, int, Block, float]] = []  # (task, size, blk, rt)
    loose: List = []
    for t in task_list:
        a = previous.assignments.get(t.name)
        strat = (
            t.feasible_strategies().get(a.apportionment) if a is not None else None
        )
        if a is None or strat is None or a.block.end > topology.capacity:
            if not insert_missing:
                return None
            loose.append(t)
            continue
        pinned.append((t, a.apportionment, a.block, strat.runtime))

    # Previous start order preserves the incumbent schedule's structure.
    pinned.sort(key=lambda p: previous.assignments[p[0].name].start)

    timeline = DeviceTimeline(topology.capacity)
    assignments: Dict[str, Assignment] = {}
    for t, size, blk, rt in pinned:
        st = timeline.place(blk, rt, ordering_slack)
        assignments[t.name] = Assignment(size, blk, st, rt)

    w = weights or {}
    loose.sort(
        key=lambda t: (
            -w.get(t.name, 0.0),
            -min(s.runtime for s in t.feasible_strategies().values()),
        )
    )
    for t in loose:
        best = None  # (finish, start, size, blk, rt)
        probes = 0
        for size, strat in sorted(t.feasible_strategies().items()):
            if size > topology.capacity:
                continue
            for blk in topology.blocks(size):
                if (insertion_probe_cap is not None
                        and probes >= insertion_probe_cap
                        and best is not None):
                    break  # deterministic cutoff: keep the best slot so far
                probes += 1
                st = timeline.earliest_free(blk, strat.runtime + ordering_slack)
                fin = st + strat.runtime
                if best is None or fin < best[0]:
                    best = (fin, st, size, blk, strat.runtime)
            if (insertion_probe_cap is not None
                    and probes >= insertion_probe_cap
                    and best is not None):
                break
        if best is None:
            return None  # a loose task fits no block: no warm plan exists
        fin, st, size, blk, rt = best
        timeline.occupy(blk, st, fin + ordering_slack)
        assignments[t.name] = Assignment(size, blk, st, rt)

    makespan = max((a.start + a.runtime for a in assignments.values()), default=0.0)
    plan = Plan(assignments=assignments, makespan=makespan)
    plan.compute_dependencies()
    return plan


def _host_fraction_of(task, size: int) -> float:
    """Measured host fraction of a task's strategy at ``size``, clamped to
    [0, 1]. 0.0 when unmeasured (pre-existing cache entries, dummy
    strategies) — which makes the predicted interleave gain 1.0x and keeps
    the pair out of the co-location term entirely."""
    strat = getattr(task, "strategies", {}).get(size)
    if strat is None:
        return 0.0
    hf = float(getattr(strat, "host_fraction", 0.0) or 0.0)
    return min(max(hf, 0.0), 1.0)


def _fillable_fraction_of(task, size: int) -> float:
    """Fraction of a steady-state batch during which the job's DEVICES are
    idle and a co-scheduled partner could run: measured host-side staging
    (``host_fraction``) plus the analytic schedule bubble
    (``bubble_fraction`` — pipeline warmup/cooldown ticks). Clamped to
    [0, 1]. A GPipe job donates its (S-1)/(M+S-1) bubble to a partner; the
    same job under 1F1B donates only (S-1)/(M+2(S-1)), so switching
    schedules shrinks the predicted interleave win — exactly the trade the
    co-location term must see."""
    strat = getattr(task, "strategies", {}).get(size)
    if strat is None:
        return 0.0
    hf = float(getattr(strat, "host_fraction", 0.0) or 0.0)
    bubble = float(getattr(strat, "bubble_fraction", 0.0) or 0.0)
    return min(max(hf, 0.0) + max(bubble, 0.0), 1.0)


def coschedule_candidates(
    task_list: List,
    choices: Dict[str, List[Tuple[int, "Block", float]]],
    min_gain: float,
) -> List[Tuple[str, str, List[Tuple[int, int, float]]]]:
    """Task pairs whose measured host fractions predict an interleave win.

    For each pair and each (size, block) option BOTH tasks could take, the
    interleaved pair occupies the block for
    ``comb = max(rt1, rt2, dev1 + dev2)`` where ``dev = (1 - fillable) *
    rt`` and ``fillable = host_fraction + bubble_fraction`` — device phases
    serialize on the shared block; host staging AND schedule bubbles
    (pipeline warmup/cooldown) hide under the partner's device windows. The
    pair is a candidate only when the best common option predicts
    ``(rt1 + rt2) / comb >= min_gain``: two compute-bound bubble-free jobs
    give ``comb = rt1 + rt2`` (gain 1.0x) and never qualify, which is
    exactly the "choose co-location only when the profile predicts a win"
    contract — and a job whose solver-picked schedule is 1F1B offers a
    smaller bubble than the same job under GPipe, so pairs that only
    cleared ``min_gain`` on the fatter GPipe bubble drop out. Returns
    ``(n1, n2, [(i1, i2, comb), ...])`` with option indices into each
    task's choice list.
    """
    by_name = {t.name: t for t in task_list}
    names = [t.name for t in task_list]
    out: List[Tuple[str, str, List[Tuple[int, int, float]]]] = []
    for i, n1 in enumerate(names):
        for n2 in names[i + 1 :]:
            opt2 = {
                (s, b.offset, b.size): (j, rt)
                for j, (s, b, rt) in enumerate(choices[n2])
            }
            common: List[Tuple[int, int, float]] = []
            best_gain = 0.0
            for i1, (s, b, rt1) in enumerate(choices[n1]):
                hit = opt2.get((s, b.offset, b.size))
                if hit is None:
                    continue
                i2, rt2 = hit
                f1 = _fillable_fraction_of(by_name[n1], s)
                f2 = _fillable_fraction_of(by_name[n2], s)
                comb = max(rt1, rt2, (1.0 - f1) * rt1 + (1.0 - f2) * rt2)
                common.append((i1, i2, comb))
                if comb > 1e-9:
                    best_gain = max(best_gain, (rt1 + rt2) / comb)
            if common and best_gain >= min_gain:
                out.append((n1, n2, common))
    return out


class _FusedPseudoTask:
    """Stand-in the MILP schedules in place of a whole fusion group.

    Carries ONLY the fused option set (the sizes the group was actually
    priced at), so no solver path — exact, native, warm, greedy — can place
    the group at a size its fused program was never profiled for. Its
    strategies report zero host/bubble fractions, which keeps it out of the
    co-location candidate generator (a fused stack is already the denser
    packing; interleaving it with a third job is the engine's problem, not
    the solver's).
    """

    def __init__(self, name: str, strategies: Dict[int, Any]):
        self.name = name
        self.strategies = strategies

    def feasible_strategies(self) -> Dict[int, Any]:
        return self.strategies


def _remaining_batches(strat) -> Optional[float]:
    """Remaining batch count implied by a strategy's (runtime, per-batch)
    estimates; None when per-batch time was never measured — fusion pricing
    refuses to guess."""
    pbt = float(getattr(strat, "per_batch_time", 0.0) or 0.0)
    if pbt <= 0.0:
        return None
    return max(0.0, float(strat.runtime) / pbt)


def fusion_priced_groups(
    task_list: List,
    proposed: List[List[str]],
    topology: SliceTopology,
    fusion_exclude=None,
    fusion_fits=None,
) -> List[Tuple[List[str], int, float, float]]:
    """Price each proposed fusion group on MEASURED cost; keep the winners.

    For each candidate group (same ModelSpec fingerprint, from
    ``parallel/fused.fusion_candidates``) and each sub-mesh size at which
    EVERY member holds a feasible strategy with a measured
    ``fused_per_batch_time``, the fused stack occupies the block for

        ``fused_rt = max_m(remaining_batches_m) * max_m(fused_per_batch_time_m)``

    — lockstep: the stack runs until its longest member finishes (shorter
    members detach early, but the block is booked for the stack). The group
    fuses only when that beats BOTH alternatives the solver could otherwise
    pick on the same size:

    - serial: ``sum_m(runtime_m)`` — members run back-to-back;
    - co-scheduled pairs: members paired longest-with-longest, each pair
      priced at the interleaved combined occupancy from
      :func:`coschedule_candidates`'s formula, pairs serialized.

    ``fusion_exclude`` drops individual members (the health guardian's
    quarantined repeat offenders) — the rest of the group can still fuse if
    >= 2 members remain. ``fusion_fits`` is the memlens residency gate:
    ``(member_tasks, size, n_members) -> Optional[bool]``; an explicit False
    (the x N stacked HBM residency exceeds capacity) vetoes the size, None
    (unknown) does not prune — exactly the analyzer's zero-compile
    feasibility-prior contract.

    Returns ``[(member_names, size, fused_runtime, fused_per_batch_time)]``
    with each group priced at its best (smallest fused runtime) size.
    """
    by_name = {t.name: t for t in task_list}
    excl = set(fusion_exclude or ())
    out: List[Tuple[List[str], int, float, float]] = []
    claimed: set = set()
    for group in proposed:
        names = [n for n in group if n in by_name and n not in excl
                 and n not in claimed]
        if len(names) < 2:
            continue
        members = [by_name[n] for n in names]
        common = None
        for m in members:
            sizes = {
                s for s, strat in m.feasible_strategies().items()
                if s <= topology.capacity
                and getattr(strat, "fused_per_batch_time", None) is not None
            }
            common = sizes if common is None else (common & sizes)
        best: Optional[Tuple[float, int, float]] = None  # (fused_rt, size, fpbt)
        for size in sorted(common or ()):
            strats = [m.feasible_strategies()[size] for m in members]
            batches = [_remaining_batches(s) for s in strats]
            if any(b is None for b in batches):
                continue  # a member's per-batch time was never measured
            fpbt = max(float(s.fused_per_batch_time) for s in strats)
            fused_rt = max(batches) * fpbt
            serial = sum(float(s.runtime) for s in strats)
            # Co-scheduled alternative: longest-with-longest pairs, each at
            # the interleaved combined occupancy, pairs serialized on the
            # block (the engine runs one shared launcher at a time).
            ordered = sorted(
                zip(members, strats), key=lambda p: -float(p[1].runtime)
            )
            cosched = 0.0
            i = 0
            while i < len(ordered):
                if i + 1 < len(ordered):
                    (t1, s1), (t2, s2) = ordered[i], ordered[i + 1]
                    f1 = _fillable_fraction_of(t1, size)
                    f2 = _fillable_fraction_of(t2, size)
                    rt1, rt2 = float(s1.runtime), float(s2.runtime)
                    cosched += max(
                        rt1, rt2, (1.0 - f1) * rt1 + (1.0 - f2) * rt2
                    )
                    i += 2
                else:
                    cosched += float(ordered[i][1].runtime)
                    i += 1
            if fused_rt >= min(serial, cosched):
                continue  # measured cost does not favor fusion at this size
            if fusion_fits is not None and fusion_fits(
                members, size, len(members)
            ) is False:
                continue  # memlens: stacked residency would not fit
            if best is None or fused_rt < best[0]:
                best = (fused_rt, size, fpbt)
        if best is not None:
            fused_rt, size, fpbt = best
            out.append((names, size, fused_rt, fpbt))
            claimed.update(names)
    return out


def solve(
    task_list: List,
    topology: SliceTopology,
    time_limit: Optional[float] = None,
    ordering_slack: float = 1.0,
    milp_task_limit: int = 12,
    warm: Optional[Plan] = None,
    weights: Optional[Dict[str, float]] = None,
    coschedule_min_gain: float = 1.15,
    coschedule_exclude=None,
    fusion: Optional[List[List[str]]] = None,
    fusion_exclude=None,
    fusion_fits=None,
) -> Plan:
    """Build and solve the joint strategy/placement/schedule MILP.

    Each task contributes its *feasible* strategies (``params is not None`` —
    the reference's dummy-strategy exclusion, ``PerformanceEvaluator.py:96-110``).
    Tasks with no feasible strategy raise — better than silently dropping.

    Above ``milp_task_limit`` tasks, the exact MILP's pairwise big-M
    constraints explode (O(N²·devices) rows); the native C++ scheduler
    (``native/spase.cpp``) takes over — same option set, validated plan.

    ``warm`` (the previous interval's plan) warm-starts both paths, parity
    with the reference's ``warmStart=True`` (``milp.py:323``): the exact MILP
    gets the fix-and-optimize makespan as an upper-bound cut (scipy's HiGHS
    wrapper cannot inject an incumbent) and returns the warm plan instead of
    greedy when the time limit strikes out; the native search is seeded with
    the previous (size, block) choices. Tasks absent from ``warm`` (online
    arrivals) are inserted into the fix-and-optimize incumbent rather than
    discarding it (``warm_schedule(insert_missing=True)``).

    ``weights`` (task name -> nonnegative urgency, from the service's
    admission controller) adds a priority term to the objective: among
    makespan-equal schedules, higher-weight tasks start earlier. The term is
    scaled to at most ~0.5% of the horizon so it can only reorder, never
    trade away meaningful makespan — minimizing batch makespan stays the
    primary objective (the paper's SPASE formulation).

    ``coschedule_min_gain``: minimum predicted pair speedup (sequential
    runtime sum over interleaved combined occupancy, from the trial runner's
    measured host fractions) for a pair to enter the co-location term — see
    :func:`coschedule_candidates`. Only the exact MILP proposes co-schedule
    groups; the native/greedy/warm paths stay conservatively serial.

    ``coschedule_exclude``: task names barred from co-location (the health
    guardian's detached repeat offenders). Exclusion happens at the
    CANDIDATE level — pairs touching an excluded name never get a ``co``
    binary — because group members hold overlapping assignments: stripping
    a member from an already-solved group would be a device race.

    ``fusion``: proposed fusion groups (lists of task names sharing a
    ModelSpec fingerprint, from ``parallel/fused.fusion_candidates``). Each
    group is priced on measured cost by :func:`fusion_priced_groups`; the
    winners are collapsed to one :class:`_FusedPseudoTask` each, the reduced
    batch is solved normally (every path — exact MILP, native, warm, greedy
    — sees the pseudo-task), and the decoded plan is expanded so every
    member holds the representative's assignment and ``Plan.fused`` records
    the groups. ``fusion_exclude`` bars individual members (quarantined
    repeat offenders); ``fusion_fits`` is the memlens stacked-residency gate
    — see :func:`fusion_priced_groups`.
    """
    for t in task_list:
        if not t.feasible_strategies():
            raise ValueError(f"task {t.name} has no feasible strategy; run search first")
        if all(size > topology.capacity for size in t.feasible_strategies()):
            raise ValueError(
                f"task {t.name}: no strategy fits topology capacity {topology.capacity}"
            )

    if fusion:
        winners = fusion_priced_groups(
            task_list, fusion, topology,
            fusion_exclude=fusion_exclude, fusion_fits=fusion_fits,
        )
        if winners:
            from saturn_tpu.core.strategy import Strategy as _Strategy

            by_name = {t.name: t for t in task_list}
            fused_member: Dict[str, int] = {}  # member name -> winner index
            reduced: List = []
            red_weights = dict(weights) if weights else {}
            for wi, (names, _, _, _) in enumerate(winners):
                for n in names:
                    fused_member[n] = wi
            for wi, (names, _, _, _) in enumerate(winners):
                rep = names[0]
                # Pseudo-option set: every size the group was priced at
                # (fusion_priced_groups returns only the best size, so
                # re-derive the full priced set to keep the solver's choice).
                strategies: Dict[int, Any] = {}
                common = None
                for n in names:
                    sizes = {
                        s for s, st in by_name[n].feasible_strategies().items()
                        if s <= topology.capacity
                        and getattr(st, "fused_per_batch_time", None) is not None
                        and _remaining_batches(st) is not None
                    }
                    common = sizes if common is None else (common & sizes)
                for size in sorted(common or ()):
                    strats = [
                        by_name[n].feasible_strategies()[size] for n in names
                    ]
                    fpbt = max(
                        float(s.fused_per_batch_time) for s in strats
                    )
                    fused_rt = (
                        max(_remaining_batches(s) for s in strats) * fpbt
                    )
                    if fusion_fits is not None and fusion_fits(
                        [by_name[n] for n in names], size, len(names)
                    ) is False:
                        continue
                    strategies[size] = _Strategy(
                        executor=strats[0].executor,
                        apportionment=size,
                        params=dict(strats[0].params or {}),
                        runtime=fused_rt,
                        per_batch_time=fpbt,
                    )
                reduced.append(_FusedPseudoTask(rep, strategies))
                if weights:
                    red_weights[rep] = max(
                        (weights.get(n, 0.0) for n in names), default=0.0
                    )
            reduced.extend(t for t in task_list if t.name not in fused_member)
            inner = solve(
                reduced, topology, time_limit=time_limit,
                ordering_slack=ordering_slack,
                milp_task_limit=milp_task_limit, warm=warm,
                weights=red_weights or None,
                coschedule_min_gain=coschedule_min_gain,
                coschedule_exclude=coschedule_exclude,
            )
            assignments = dict(inner.assignments)
            for names, _, _, _ in winners:
                rep_a = assignments[names[0]]
                for n in names[1:]:
                    assignments[n] = Assignment(
                        rep_a.apportionment, rep_a.block, rep_a.start,
                        rep_a.runtime,
                    )
            plan = Plan(
                assignments=assignments, makespan=inner.makespan,
                coschedule=inner.coschedule,
                fused=[list(names) for names, _, _, _ in winners],
            )
            plan.compute_dependencies()
            log.info(
                "fusion pre-pass: %d group(s) priced in favor of stacking "
                "(%s)", len(winners),
                "; ".join(
                    f"{len(names)}@{size} fused={rt:.1f}s"
                    for names, size, rt, _ in winners
                ),
            )
            return plan

    wplan = (
        warm_schedule(task_list, topology, warm, ordering_slack,
                      insert_missing=True, weights=weights)
        if warm is not None
        else None
    )

    if len(task_list) > milp_task_limit:
        from saturn_tpu.solver import native_sched

        plan = native_sched.solve_native(
            task_list, topology,
            # honor an explicit caller budget (e.g. orchestrate's interval/2);
            # 5s is only the default when none was given.
            time_limit=time_limit if time_limit is not None else 5.0,
            ordering_slack=ordering_slack,
            warm=warm,
        )
        if plan is not None:
            log.info("large batch (%d tasks): native scheduler makespan %.1fs",
                     len(task_list), plan.makespan)
            if wplan is not None and wplan.makespan < plan.makespan:
                return wplan
            return plan
        if wplan is not None:
            return wplan
        return greedy_plan(task_list, topology, ordering_slack, weights=weights)

    # Cheap native pass first (~0.1-0.2s at these sizes): its plan is a
    # guaranteed-feasible incumbent that (a) upper-bounds the MILP via a cut
    # and (b) floors the result quality if HiGHS strikes out. Measured
    # (benchmarks/solver_quality.py): at >= 8 tasks with rich option sets the
    # exact solver rarely proves optimality inside a 30s budget and the
    # native search often leads — combining them is never worse than either.
    # Its cost (incl. a possible first-call g++ build) is deducted from the
    # caller's budget below so solve() never overruns time_limit.
    import time as _time

    from saturn_tpu.solver import native_sched

    t_pre = _time.perf_counter()
    nplan = native_sched.solve_native(
        task_list, topology, time_limit=min(1.0, time_limit or 1.0),
        ordering_slack=ordering_slack, warm=warm,
    )
    if time_limit is not None:
        time_limit = max(0.1, time_limit - (_time.perf_counter() - t_pre))
    incumbent = nplan
    if wplan is not None and (incumbent is None or wplan.makespan < incumbent.makespan):
        incumbent = wplan

    m = Model("spase")
    # Joint (strategy,block) choice per task.
    choices: Dict[str, List[Tuple[int, Block, float]]] = {}
    x: Dict[str, List] = {}
    for t in task_list:
        opts = []
        for size, strat in sorted(t.feasible_strategies().items()):
            if size > topology.capacity:
                continue
            for blk in topology.blocks(size):
                opts.append((size, blk, strat.runtime))
        choices[t.name] = opts
        x[t.name] = [m.binary(f"x_{t.name}_{s}_{b.offset}") for s, b, _ in opts]
        m.add(sum(x[t.name][1:], Expr.of(x[t.name][0])) == 1)

    # Horizon T: serial-sum of worst-case runtimes plus per-pair ordering
    # slack — no valid schedule needs starts beyond it. The big-M must relax
    # ``sta_i >= sta_j + rt_j + slack - M`` even at sta_j = T, so M ≈ 2T
    # (the reference sidestepped this with M=1e10 and solver IntFeasTol,
    # ``milp.py:163``; HiGHS prefers tight-but-sufficient).
    T = sum(max(s.runtime for s in t.feasible_strategies().values()) for t in task_list)
    T += max(0, len(task_list) - 1) * ordering_slack
    T = max(T, 1.0) * 1.05
    M = 2.0 * T + 1.0

    sta = {t.name: m.continuous(f"sta_{t.name}", lb=0.0, ub=T) for t in task_list}
    makespan = m.continuous("makespan", lb=0.0, ub=T)

    def runtime_expr(name: str) -> Expr:
        e = Expr()
        for xi, (_, _, rt) in zip(x[name], choices[name]):
            e = e + xi * rt
        return e

    def occ_expr(name: str, dev: int) -> Expr:
        """Linear expression: does task occupy device ``dev``? (analog of the
        reference's tga occupancy vars, ``milp.py:184-195`` — here derived,
        not free variables)."""
        e = Expr()
        for xi, (_, blk, _) in zip(x[name], choices[name]):
            if blk.offset <= dev < blk.end:
                e = e + xi
        return e

    names = [t.name for t in task_list]

    # ------------------------------------------------------- co-location term
    # For pairs whose measured host fractions predict an interleave win, a
    # binary ``co`` lets the solver pack both jobs onto the SAME (size,
    # block) option at the SAME start: their windows then interleave on one
    # launcher (engine CoScheduleGroup) instead of serializing. When co=1:
    # both tasks are pinned to a common option (identical choice), starts
    # are tied, the pair's own ordering-exclusion rows relax away, and each
    # member's EFFECTIVE runtime — what third parties on the block and the
    # makespan see — rises to the pair's combined occupancy ``comb``
    # (device phases serialize; host phases hide). Tasks without a measured
    # host fraction produce no candidates, no binaries, no new rows.
    co_pairs = coschedule_candidates(task_list, choices, coschedule_min_gain)
    if coschedule_exclude:
        excl = set(coschedule_exclude)
        co_pairs = [
            (n1, n2, c) for n1, n2, c in co_pairs
            if n1 not in excl and n2 not in excl
        ]
    co_of: Dict[Tuple[str, str], Any] = {}
    eff: Dict[str, Expr] = {n: runtime_expr(n) for n in names}
    per_task_cos: Dict[str, List] = {}
    for n1, n2, common in co_pairs:
        co = m.binary(f"co_{n1}_{n2}")
        co_of[(n1, n2)] = co
        per_task_cos.setdefault(n1, []).append(co)
        per_task_cos.setdefault(n2, []).append(co)
        common1 = {i1 for i1, _, _ in common}
        common2 = {i2 for _, i2, _ in common}
        # co=1 restricts both tasks to their COMMON options...
        for j, xi in enumerate(x[n1]):
            if j not in common1:
                m.add(Expr.of(xi) <= Expr.of(1.0) - co)
        for j, xi in enumerate(x[n2]):
            if j not in common2:
                m.add(Expr.of(xi) <= Expr.of(1.0) - co)
        # ...forces the identical choice, and ties the starts.
        for i1, i2, _ in common:
            m.link_when(co, x[n1][i1], x[n2][i2], 1.0)
        m.link_when(co, sta[n1], sta[n2], M)
    if per_task_cos:
        for n, cos in per_task_cos.items():
            # One co-partner per task: groups stay pairs, and the engine's
            # shared launcher never has to merge transitively-linked chains.
            if len(cos) > 1:
                m.add(sum(cos[1:], Expr.of(cos[0])) <= 1)
            ert = m.continuous(f"ert_{n}", lb=0.0, ub=M)
            m.add(Expr.of(ert) >= runtime_expr(n))
            eff[n] = Expr.of(ert)
        for n1, n2, common in co_pairs:
            co = co_of[(n1, n2)]
            comb_expr = Expr()
            for i1, _, comb in common:
                comb_expr = comb_expr + x[n1][i1] * comb
            # co=1 (choice pinned to a common option, sum of common x's = 1)
            # makes comb_expr the selected option's combined occupancy.
            m.add(eff[n1] >= comb_expr - (Expr.of(1.0) - co) * M)
            m.add(eff[n2] >= comb_expr - (Expr.of(1.0) - co) * M)

    # makespan >= start + effective runtime of the selected option
    # (``milp.py:170-177``; eff == runtime for every non-co-scheduled task)
    for t in task_list:
        m.add(makespan >= sta[t.name] + eff[t.name])

    # Worker exclusion: tasks sharing any device must be fully ordered with no
    # overlap in time (``milp.py:277-319``) — unless their co-schedule binary
    # is set, which relaxes BOTH rows (the pair overlaps by design, and a
    # third task on the block is still excluded from the whole interleaved
    # span via the pair members' effective runtimes).
    for i, n1 in enumerate(names):
        for n2 in names[i + 1 :]:
            # skip pairs that can never overlap (disjoint choice sets)
            may_overlap = any(
                b1.overlaps(b2)
                for _, b1, _ in choices[n1]
                for _, b2, _ in choices[n2]
            )
            if not may_overlap:
                continue
            boa = m.binary(f"boa_{n1}_{n2}")  # 1 => n1 before n2
            co = co_of.get((n1, n2))
            co_relax = Expr.of(co) * M if co is not None else Expr.of(0.0)
            for dev in range(topology.capacity):
                o1, o2 = occ_expr(n1, dev), occ_expr(n2, dev)
                # if both occupy dev and boa=1: sta2 >= sta1 + rt1
                m.add(
                    sta[n2]
                    >= sta[n1]
                    + eff[n1]
                    + ordering_slack
                    - M * (1 - Expr.of(boa))
                    - M * (2 - o1 - o2)
                    - co_relax
                )
                m.add(
                    sta[n1]
                    >= sta[n2]
                    + eff[n2]
                    + ordering_slack
                    - M * Expr.of(boa)
                    - M * (2 - o1 - o2)
                    - co_relax
                )

    # Valid inequality (area cut): the selected options' total work area
    # cannot exceed makespan × capacity. Redundant for integer solutions but
    # tightens the LP relaxation — the big-M ordering rows relax to nothing,
    # so without it HiGHS's dual bound starts near max-single-runtime.
    # A co-scheduled pair's host phases consume no device area — the pair
    # occupies ``comb * size``, not ``(rt1 + rt2) * size`` — so each pair
    # gets a savings variable, active only when its co binary is (sav <= 0
    # otherwise), bounded by the SELECTED common option's area saving.
    area = Expr()
    for t in task_list:
        for xi, (size, _, rt) in zip(x[t.name], choices[t.name]):
            area = area + xi * (size * rt)
    for n1, n2, common in co_pairs:
        co = co_of[(n1, n2)]
        sav = m.continuous(f"sav_{n1}_{n2}", lb=0.0, ub=M * topology.capacity)
        savings_expr = Expr()
        for i1, i2, comb in common:
            size, _, rt1 = choices[n1][i1]
            _, _, rt2 = choices[n2][i2]
            savings_expr = savings_expr + x[n1][i1] * (
                max(0.0, rt1 + rt2 - comb) * size
            )
        m.add(Expr.of(sav) <= savings_expr)
        m.add(Expr.of(sav) <= Expr.of(co) * (M * topology.capacity))
        area = area - Expr.of(sav)
    m.add(makespan >= area * (1.0 / topology.capacity))

    # Tiny pressure AGAINST co-location: among makespan-equal schedules
    # (e.g. the pair also fits side-by-side on disjoint blocks) prefer the
    # plain plan — interleaving should only engage when it buys wall-clock.
    # Scaled to ~0.01% of the horizon per pair so it can never trade a real
    # makespan win away.
    co_term = sum((Expr.of(c) for c in co_of.values()), Expr()) * (1e-4 * T)

    if weights:
        # Priority pressure: weighted start times, normalized so the whole
        # term is <= 0.5% of the horizon — a tie-breaker among makespan-equal
        # schedules (high-weight tasks start first), never a makespan trade.
        wsum = sum(max(weights.get(n, 0.0), 0.0) for n in names) or 1.0
        wterm = Expr()
        for n in names:
            wn = max(weights.get(n, 0.0), 0.0)
            if wn > 0.0:
                wterm = wterm + sta[n] * (wn / wsum)
        m.minimize(makespan + wterm * 5e-3 + co_term)
    else:
        # Tiny pressure toward early starts (keeps solutions canonical).
        m.minimize(
            makespan
            + sum((sta[n] for n in names), Expr()) * (1e-6 / max(len(names), 1))
            + co_term
        )

    if incumbent is not None:
        # Incumbent cut (native and/or warm fix-and-optimize plan): feasible,
        # so its makespan upper-bounds the optimum — prunes every
        # branch-and-bound node whose relaxation exceeds it.
        m.add(makespan <= incumbent.makespan + 1e-6 * max(incumbent.makespan, 1.0))

    res = m.solve(time_limit=time_limit)
    if not res.ok:
        if incumbent is not None:
            # Timed out without beating the cut: the incumbent IS the answer
            # (never worse than the native/previous-interval plan).
            log.info("MILP timeout — keeping native/warm incumbent plan")
            return incumbent
        log.warning("MILP infeasible/error — falling back to greedy")
        return greedy_plan(task_list, topology, ordering_slack, weights=weights)

    assignments: Dict[str, Assignment] = {}
    for t in task_list:
        vals = [res.value(xi) for xi in x[t.name]]
        k = max(range(len(vals)), key=lambda i: vals[i])  # argmax like ``milp.py:471-486``
        size, blk, rt = choices[t.name][k]
        assignments[t.name] = Assignment(
            apportionment=size,
            block=blk,
            start=max(0.0, res.value(sta[t.name])),
            runtime=rt,
        )
    groups = [
        [n1, n2] for (n1, n2), co in co_of.items() if res.value(co) > 0.5
    ]
    plan = Plan(
        assignments=assignments, makespan=res.value(makespan),
        coschedule=groups,
    )
    plan.compute_dependencies()
    return plan


def makespan_lower_bound(
    task_list: List, topology: SliceTopology, time_limit: float = 10.0
) -> float:
    """Valid lower bound on the optimal makespan (VERDICT r2 item 5).

    The reference proved optimality outright by solving its full batch exactly
    (``milp.py:322-327``); above ``milp_task_limit`` this system runs the
    native local search instead, so quality must be certified against a bound.
    Three bounds, max taken:

    - longest single task: every task needs at least its fastest option's
      runtime somewhere;
    - whole-ring serialization: tasks whose every option occupies the full
      ring pairwise overlap and must run serially;
    - assignment LP: per-task fractional option choice with ordering dropped
      and capacity kept as the area inequality (makespan ≥ selected work area
      / capacity, and ≥ each task's own mixed runtime). This dominates the
      pure area bound and stays an LP — solved in milliseconds at 64 tasks.

    The bound is loose by construction (it assumes perfectly efficient
    packing), so 'gap vs LB' *over*states the true optimality gap.
    """
    cap = topology.capacity
    per_task: List[List[Tuple[int, float]]] = []
    for t in task_list:
        opts = [
            (size, strat.runtime)
            for size, strat in sorted(t.feasible_strategies().items())
            if size <= cap
        ]
        if not opts:
            raise ValueError(f"task {t.name}: no option fits capacity {cap}")
        per_task.append(opts)

    longest = max(min(rt for _, rt in opts) for opts in per_task)
    serial = sum(
        min(rt for _, rt in opts)
        for opts in per_task
        if all(size == cap for size, _ in opts)
    )

    m = Model("spase_lb")
    mk = m.continuous("mk", lb=0.0)
    area = Expr()
    for i, opts in enumerate(per_task):
        xs = [m.continuous(f"x_{i}_{k}", lb=0.0, ub=1.0) for k in range(len(opts))]
        m.add(sum(xs[1:], Expr.of(xs[0])) == 1)
        rt_expr = Expr()
        for xi, (size, rt) in zip(xs, opts):
            rt_expr = rt_expr + xi * rt
            area = area + xi * (size * rt)
        m.add(mk >= rt_expr)
    m.add(mk >= area * (1.0 / cap))
    m.minimize(mk)
    res = m.solve(time_limit=time_limit, relax=True)
    # Only a PROVEN LP optimum is a valid bound — a time-limited feasible
    # primal of a minimization LP upper-bounds the LP optimum and could
    # exceed the true MILP optimum, silently breaking the certificate.
    lp_bound = res.objective if res.status == "optimal" else 0.0
    return max(longest, serial, lp_bound)


def greedy_plan(
    task_list: List, topology: SliceTopology, ordering_slack: float = 0.0,
    weights: Optional[Dict[str, float]] = None,
) -> Plan:
    """List-scheduling fallback: longest task first, earliest feasible
    (block, time) slot, choosing the strategy that minimizes finish time.
    Used when the MILP times out dry — the reference had no fallback and
    would just fail. With ``ordering_slack`` this is exactly the native
    constructor (``spase.cpp`` LPT order + min-finish choice), via the shared
    ``DeviceTimeline`` slot rule. ``weights`` prepends a priority key to the
    LPT order so the fallback respects the service's admission weights."""
    timeline = DeviceTimeline(topology.capacity)
    w = weights or {}
    order = sorted(
        task_list,
        key=lambda t: (
            -w.get(t.name, 0.0),
            -min(s.runtime for s in t.feasible_strategies().values()),
        ),
    )
    assignments: Dict[str, Assignment] = {}
    for t in order:
        best = None  # (finish, start, size, blk, rt)
        for size, strat in sorted(t.feasible_strategies().items()):
            if size > topology.capacity:
                continue
            for blk in topology.blocks(size):
                st = timeline.earliest_free(blk, strat.runtime + ordering_slack)
                fin = st + strat.runtime
                if best is None or fin < best[0]:
                    best = (fin, st, size, blk, strat.runtime)
        if best is None:
            raise ValueError(
                f"task {t.name}: no strategy fits topology capacity {topology.capacity}"
            )
        fin, st, size, blk, rt = best
        timeline.occupy(blk, st, fin + ordering_slack)
        assignments[t.name] = Assignment(size, blk, st, rt)

    makespan = max((a.start + a.runtime for a in assignments.values()), default=0.0)
    plan = Plan(assignments=assignments, makespan=makespan)
    plan.compute_dependencies()
    return plan


def resolve(
    task_list: List,
    topology: SliceTopology,
    previous: Optional[Plan],
    interval: float,
    threshold: float = 0.0,
    time_limit: Optional[float] = None,
    warm_budget_frac: float = 0.25,
    weights: Optional[Dict[str, float]] = None,
    coschedule_exclude=None,
    fusion: Optional[List[List[str]]] = None,
    fusion_exclude=None,
    fusion_fits=None,
) -> Plan:
    """Introspective re-solve with compare-and-swap (``milp.py:354-444``).

    Adopt the fresh plan iff (a) there was no previous plan, (b) the task set
    shrank (``milp.py:376-379``), or (c) the fresh makespan beats the slid-down
    old plan by more than ``threshold`` (``milp.py:394-427``). Otherwise keep
    the old plan with all start times slid down by ``interval``
    (``milp.py:429-442``). The previous plan also warm-starts the re-solve
    (reference ``warmStart=True``, ``milp.py:323``) — and because the warm
    fix-and-optimize plan is a guaranteed-feasible incumbent no worse than
    last interval's schedule, the re-solve only gets ``warm_budget_frac`` of
    the caller's time budget: a long proof phase buys nothing when any
    timeout falls back to the warm plan. This is where the reference's Gurobi
    warm start saved its time too (incumbent reuse, ``milp.py:323``); interval
    re-solves are cheap, only the cold initial solve pays the full budget.
    """
    tl = time_limit
    if previous is not None and time_limit is not None:
        # Reduce the budget only when the warm incumbent actually exists —
        # if the task set changed (new task, choice now infeasible) the
        # fix-and-optimize floor is unavailable and the re-solve must get
        # the full budget like a cold solve. (Arrivals/departures get the
        # insertion-extended incumbent inside solve(), but its quality for a
        # changed set is unproven — full budget is the safe default there.)
        if warm_schedule(task_list, topology, previous) is not None:
            tl = max(1.0, time_limit * warm_budget_frac)
    fresh = solve(task_list, topology, time_limit=tl, warm=previous,
                  weights=weights, coschedule_exclude=coschedule_exclude,
                  fusion=fusion, fusion_exclude=fusion_exclude,
                  fusion_fits=fusion_fits)
    if previous is None:
        return fresh

    prev_names = set(previous.assignments)
    cur_names = {t.name for t in task_list}
    if cur_names - prev_names:
        return fresh  # new tasks appeared: old plan can't cover them
    if len(cur_names) < len(prev_names):
        return fresh  # reference adopts on shrink (``milp.py:376-379``)

    slid = Plan(
        assignments={
            n: Assignment(
                a.apportionment,
                a.block,
                max(0.0, a.start - interval),
                a.runtime,
            )
            for n, a in previous.assignments.items()
            if n in cur_names
        },
        makespan=max(0.0, previous.makespan - interval),
        # surviving co-schedule groups slide with the plan; a group whose
        # partner finished degenerates below 2 members and is dropped
        coschedule=[
            kept
            for grp in previous.coschedule
            if len(kept := [n for n in grp if n in cur_names]) >= 2
        ],
        # surviving fusion groups slide too: a stack whose member finished
        # (or was unfused) shrinks; below 2 members it stops being a stack
        fused=[
            kept
            for grp in previous.fused
            if len(kept := [n for n in grp if n in cur_names]) >= 2
        ],
    )
    if coschedule_exclude:
        # A freshly detached member may still sit in the slid plan's groups
        # (members hold OVERLAPPING assignments, so the group can't just be
        # stripped) — in that case the fresh plan, solved without the
        # excluded pairs, is the only valid choice.
        excl = set(coschedule_exclude)
        if any(excl & set(grp) for grp in slid.coschedule):
            return fresh
    if fusion_exclude:
        # Same rule for a freshly quarantined fusion member: its groupmates
        # hold the stack's shared assignment, so the slid plan cannot simply
        # strip it — only the fresh solve (priced without it) is valid.
        excl = set(fusion_exclude)
        if any(excl & set(grp) for grp in slid.fused):
            return fresh
    slid.compute_dependencies()
    if fresh.makespan < slid.makespan - threshold:
        return fresh
    return slid
