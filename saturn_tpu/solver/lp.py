"""Tiny MILP modeling layer over scipy's HiGHS backend.

The reference modeled its MILP with PuLP and solved with Gurobi/CBC
subprocesses (``milp.py:322-327``). This environment ships neither; scipy's
``scipy.optimize.milp`` (HiGHS, native C++) is the in-tree equivalent — so
this module is a ~150-line PuLP replacement: named variables, linear
expressions, constraints, warm-start-free solve with a time limit.

Only what the SPASE MILP needs is implemented: binary/integer/continuous
variables, <= / >= / == constraints, minimize.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple, Union

import numpy as np
from scipy import sparse
from scipy.optimize import Bounds, LinearConstraint, milp


class Expr:
    """Sparse linear expression: sum(coef * var) + const."""

    __slots__ = ("terms", "const")

    def __init__(self, terms: Optional[Dict[int, float]] = None, const: float = 0.0):
        self.terms = dict(terms or {})
        self.const = float(const)

    @staticmethod
    def of(x: Union["Expr", "Var", float, int]) -> "Expr":
        if isinstance(x, Expr):
            return x
        if isinstance(x, Var):
            return Expr({x.idx: 1.0})
        return Expr({}, float(x))

    def __add__(self, other):
        o = Expr.of(other)
        t = dict(self.terms)
        for k, v in o.terms.items():
            t[k] = t.get(k, 0.0) + v
        return Expr(t, self.const + o.const)

    __radd__ = __add__

    def __sub__(self, other):
        return self + (Expr.of(other) * -1.0)

    def __rsub__(self, other):
        return Expr.of(other) + (self * -1.0)

    def __mul__(self, c):
        c = float(c)
        return Expr({k: v * c for k, v in self.terms.items()}, self.const * c)

    __rmul__ = __mul__

    def __neg__(self):
        return self * -1.0

    # comparisons build constraints
    def __le__(self, other):
        return Constraint(self - Expr.of(other), "<=")

    def __ge__(self, other):
        return Constraint(self - Expr.of(other), ">=")

    def __eq__(self, other):  # type: ignore[override]
        return Constraint(self - Expr.of(other), "==")


class Var(Expr):
    """A decision variable; behaves as an Expr with one term."""

    __slots__ = ("idx", "name")

    def __init__(self, idx: int, name: str):
        super().__init__({idx: 1.0})
        self.idx = idx
        self.name = name

    def __hash__(self):
        return self.idx

    def __repr__(self):  # pragma: no cover
        return f"Var({self.name})"


@dataclass
class Constraint:
    expr: Expr  # expr (op) 0
    op: str     # '<=', '>=', '=='


@dataclass
class SolveResult:
    status: str                      # 'optimal' | 'feasible' | 'infeasible' | 'error'
    objective: float
    values: np.ndarray

    def value(self, v: Union[Var, Expr]) -> float:
        e = Expr.of(v)
        return float(
            sum(c * self.values[i] for i, c in e.terms.items()) + e.const
        )

    @property
    def ok(self) -> bool:
        return self.status in ("optimal", "feasible")


class Model:
    """An LP/MILP under construction."""

    def __init__(self, name: str = "model"):
        self.name = name
        self._lb: List[float] = []
        self._ub: List[float] = []
        self._int: List[bool] = []
        self._names: List[str] = []
        self.constraints: List[Constraint] = []
        self._objective: Optional[Expr] = None

    # ------------------------------------------------------------- variables
    def _add_var(self, name, lb, ub, integer) -> Var:
        idx = len(self._lb)
        self._lb.append(lb)
        self._ub.append(ub)
        self._int.append(integer)
        self._names.append(name)
        return Var(idx, name)

    def binary(self, name: str) -> Var:
        return self._add_var(name, 0.0, 1.0, True)

    def integer(self, name: str, lb=0.0, ub=np.inf) -> Var:
        return self._add_var(name, lb, ub, True)

    def continuous(self, name: str, lb=0.0, ub=np.inf) -> Var:
        return self._add_var(name, lb, ub, False)

    # ----------------------------------------------------------- constraints
    def add(self, c: Constraint) -> None:
        if not isinstance(c, Constraint):
            raise TypeError(f"expected Constraint, got {type(c)}")
        self.constraints.append(c)

    def link_when(self, gate: Union[Var, Expr], a, b, big_m: float) -> None:
        """Force ``a == b`` (up to tolerance) when the binary ``gate`` is 1.

        Adds the big-M pair ``a - b <= M(1-gate)`` / ``b - a <= M(1-gate)``;
        with gate=0 both rows relax away. The SPASE co-location term uses
        this to pin a co-scheduled pair onto the identical (size, block)
        option and an identical start time — the standard indicator-linking
        idiom, kept here so the MILP builder stays declarative.
        """
        g = Expr.of(gate)
        ea, eb = Expr.of(a), Expr.of(b)
        slack = (Expr.of(1.0) - g) * float(big_m)
        self.add(ea - eb <= slack)
        self.add(eb - ea <= slack)

    def minimize(self, e: Expr) -> None:
        self._objective = Expr.of(e)

    # ----------------------------------------------------------------- solve
    def solve(
        self,
        time_limit: Optional[float] = None,
        gap: float = 1e-4,
        relax: bool = False,
    ) -> SolveResult:
        """``relax=True`` drops all integrality (the LP relaxation): the
        optimum is then a valid lower bound on the MILP optimum — used by
        ``milp.makespan_lower_bound`` for optimality-gap reporting."""
        n = len(self._lb)
        if self._objective is None:
            raise ValueError("no objective set")
        c = np.zeros(n)
        for i, v in self._objective.terms.items():
            c[i] = v

        rows, cols, vals = [], [], []
        lo, hi = [], []
        for r, con in enumerate(self.constraints):
            rhs = -con.expr.const
            for i, v in con.expr.terms.items():
                rows.append(r)
                cols.append(i)
                vals.append(v)
            if con.op == "<=":
                lo.append(-np.inf)
                hi.append(rhs)
            elif con.op == ">=":
                lo.append(rhs)
                hi.append(np.inf)
            else:
                lo.append(rhs)
                hi.append(rhs)

        A = sparse.csr_matrix(
            (vals, (rows, cols)), shape=(len(self.constraints), n)
        )
        lc = LinearConstraint(A, np.asarray(lo), np.asarray(hi))
        bounds = Bounds(np.asarray(self._lb), np.asarray(self._ub))
        integrality = (
            np.zeros(n, dtype=np.uint8)
            if relax
            else np.asarray(self._int, dtype=np.uint8)
        )
        options: Dict[str, float] = {"mip_rel_gap": gap}
        if time_limit is not None:
            options["time_limit"] = float(time_limit)
        res = milp(
            c,
            constraints=[lc],
            bounds=bounds,
            integrality=integrality,
            options=options,
        )
        if res.x is None:
            return SolveResult("infeasible", np.inf, np.zeros(n))
        status = "optimal" if res.status == 0 else "feasible"
        return SolveResult(status, float(res.fun) + self._objective.const, res.x)
