"""saturn_tpu: a TPU-native multi-model training orchestrator.

A brand-new JAX/XLA/pjit framework with the capabilities of knagrecha/saturn
(the SPASE multi-query optimizer: Select Parallelism, Apportion resources,
SchedulE). Public API mirrors the reference's four calls (SURVEY.md §0):

1. ``saturn_tpu.library.register(name, technique_cls)``
2. ``saturn_tpu.search(tasks)``           — profile (task × sub-mesh × technique)
3. ``saturn_tpu.orchestrate(task_list)``  — solve + gang-execute to completion
4. ``Task`` / ``HParams`` / ``Strategy``  — job description dataclasses
"""

from saturn_tpu.core.strategy import Strategy, Techniques
from saturn_tpu.core.task import HParams, Task
from saturn_tpu.core.technique import BaseTechnique
from saturn_tpu.core.modelspec import ModelSpec
from saturn_tpu import library

__version__ = "0.1.0"

__all__ = [
    "Task",
    "HParams",
    "Strategy",
    "Techniques",
    "BaseTechnique",
    "ModelSpec",
    "library",
    "search",
    "orchestrate",
]


def search(tasks, technique_names=None, log=False, topology=None, **kw):
    """Profile every (task × sub-mesh size × technique) combination.

    Reference: ``saturn/trial_runner/PerformanceEvaluator.py:33``.
    """
    from saturn_tpu.trial_runner.evaluator import search as _search

    return _search(
        tasks, technique_names=technique_names, log=log, topology=topology, **kw
    )


def orchestrate(task_list, log=False, interval=1000, topology=None, **kw):
    """Solve the SPASE problem and run the batch to completion.

    Reference: ``saturn/orchestrator.py:32``.
    """
    from saturn_tpu.executor.orchestrator import orchestrate as _orch

    return _orch(task_list, log=log, interval=interval, topology=topology, **kw)
