"""saturn_tpu: a TPU-native multi-model training orchestrator.

A brand-new JAX/XLA/pjit framework with the capabilities of knagrecha/saturn
(the SPASE multi-query optimizer: Select Parallelism, Apportion resources,
SchedulE). Public API mirrors the reference's four calls (SURVEY.md §0):

1. ``saturn_tpu.library.register(name, technique_cls)``
2. ``saturn_tpu.search(tasks)``           — profile (task × sub-mesh × technique)
3. ``saturn_tpu.orchestrate(task_list)``  — solve + gang-execute to completion
4. ``Task`` / ``HParams`` / ``Strategy``  — job description dataclasses
"""

from saturn_tpu.core.strategy import Strategy, Techniques
from saturn_tpu.core.task import HParams, Task
from saturn_tpu.core.technique import BaseTechnique
from saturn_tpu.core.modelspec import ModelSpec
from saturn_tpu import library

__version__ = "0.1.0"

__all__ = [
    "Task",
    "HParams",
    "Strategy",
    "Techniques",
    "BaseTechnique",
    "ModelSpec",
    "library",
    "search",
    "orchestrate",
    "serve",
]


def search(tasks, technique_names=None, log=False, topology=None, **kw):
    """Profile every (task × sub-mesh size × technique) combination.

    Reference: ``saturn/trial_runner/PerformanceEvaluator.py:33``.
    """
    from saturn_tpu.trial_runner.evaluator import search as _search

    return _search(
        tasks, technique_names=technique_names, log=log, topology=topology, **kw
    )


def orchestrate(
    task_list,
    log=False,
    interval=1000.0,
    topology=None,
    threshold=0.0,
    solver_time_limit=None,
    failure_policy="raise",
    max_task_retries=1,
    metrics_path=None,
    trace_dir=None,
    fault_injector=None,
    health_monitor=None,
    recovery_policy="pause-resolve-resume",
    replan_degrade_factor=2.0,
    resume_dir=None,
    health_guardian=None,
    crash_barrier=None,
):
    """Solve the SPASE problem and run the batch to completion.

    Reference: ``saturn/orchestrator.py:32``. Mirrors
    ``executor.orchestrator.orchestrate`` exactly (parameter names, order
    and defaults — a signature-parity test enforces it) so callers get
    introspectable keywords instead of an opaque ``**kw`` passthrough.
    """
    from saturn_tpu.executor.orchestrator import orchestrate as _orch

    return _orch(
        task_list,
        log=log,
        interval=interval,
        topology=topology,
        threshold=threshold,
        solver_time_limit=solver_time_limit,
        failure_policy=failure_policy,
        max_task_retries=max_task_retries,
        metrics_path=metrics_path,
        trace_dir=trace_dir,
        fault_injector=fault_injector,
        health_monitor=health_monitor,
        recovery_policy=recovery_policy,
        replan_degrade_factor=replan_degrade_factor,
        resume_dir=resume_dir,
        health_guardian=health_guardian,
        crash_barrier=crash_barrier,
    )


def serve(topology=None, **kw):
    """Start an online job service (``saturn_tpu.service.SaturnService``)
    and return (service, client): the always-on counterpart to the batch
    ``orchestrate`` — jobs submit over time, admission profiles them through
    the profile cache, and each interval boundary re-solves incrementally.
    """
    from saturn_tpu.service import SaturnService, ServiceClient

    svc = SaturnService(topology=topology, **kw).start()
    return svc, ServiceClient(svc)
