"""Pass 1 — static plan verifier.

Checks any :class:`~saturn_tpu.solver.milp.Plan` — fresh solve, warm
re-solve, journal replay, or migration plan — BEFORE it reaches chips:

- **Launch invariants** (the engine's historical dynamic guard, lifted
  here verbatim so there is exactly one implementation): device-block
  overlap races, dependency cycles over the condensed co-schedule graph,
  and intra-group dependency edges.  ``executor.engine._check_disjoint``
  is now a thin call into :func:`check_launch_invariants`.
- **Structure**: dangling names in ``dependencies``/``coschedule``,
  undersized or overlapping groups.
- **Feasibility** (when a :class:`SliceTopology` and/or task list is
  supplied): blocks inside the buddy capacity, apportionment == block
  size, a feasible strategy at the assigned size, co-schedule
  host-fraction preconditions.
- **Timeline**: non-negative starts/runtimes, start order consistent
  with dependency edges, makespan and deadline arithmetic.

Everything here is pure Python over plan/topology data — no JAX, no
solver import — so it runs on any CPU in microseconds and is safe to
call from every plan-adoption site.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from saturn_tpu.analysis.diagnostics import (
    AnalysisReport,
    Diagnostic,
    PlanVerificationError,
    make,
)


# ---------------------------------------------------------------------------
# condensed co-schedule graph (shared with the engine)
# ---------------------------------------------------------------------------

def coschedule_find(names: Iterable[str], plan: Any) -> Callable[[str], str]:
    """Union-find root function over the plan's co-schedule AND fusion
    groups, restricted to ``names``.  Members of one group are one condensed
    node: co-schedule members run interleaved on one shared launcher and
    fusion members run as ONE stacked program, so ordering and race
    properties are checked between groups, never inside one.  Groups that
    share a member merge (one launcher must own a task).

    This is THE implementation — ``engine._coschedule_find`` delegates
    here so the dynamic guard and the static verifier cannot drift.
    """
    running = set(names)
    parent: Dict[str, str] = {n: n for n in running}

    def find(n: str) -> str:
        while parent[n] != n:
            parent[n] = parent[parent[n]]  # path halving
            n = parent[n]
        return n

    for attr in ("coschedule", "fused"):
        for grp in getattr(plan, attr, None) or []:
            members = [n for n in grp if n in running]
            for a, b in zip(members, members[1:]):
                ra, rb = find(a), find(b)
                if ra != rb:
                    parent[ra] = rb
    return find


#: Above this many gang members the O(N²)-pairs + transitive-closure exact
#: check hands off to the per-device sweep (same guarantees for every
#: solver-produced plan; see :func:`_launch_diagnostics_sweep`).
SWEEP_THRESHOLD = int(os.environ.get("SATURN_TPU_VERIFY_SWEEP_THRESHOLD",
                                     "256"))


def launch_diagnostics(names: Sequence[str], plan: Any, *,
                       force_exact: bool = False,
                       force_sweep: bool = False) -> List[Diagnostic]:
    """The engine's gang-launch invariants as structured diagnostics, in
    the exact order the dynamic guard historically checked (and raised)
    them: intra-group edges, then cycles, then pairwise races.

    The MILP's plans satisfy all three by construction; a hand-built or
    corrupted plan that violates them would either run two XLA programs on
    the same chips concurrently (silent corruption, not a crash) or park
    launcher threads on events that never fire (silent hang).

    Above :data:`SWEEP_THRESHOLD` tasks the exact pairwise check (O(N²)
    pairs plus a transitive closure) is replaced by a per-device sweep that
    is linear in total device occupancy — the anytime solver's 5k-10k-job
    plans verify in milliseconds instead of minutes. The sweep is *sound*
    (it never accepts a plan with a device race the exact check would
    reject) but stricter: it demands a DIRECT ordering edge between
    consecutive occupants of each device, which every solver-produced
    dependency shape provides (all-overlapping-pairs edges and per-device
    chain edges alike). ``force_exact``/``force_sweep`` pin the mode for
    tests and offline audits.
    """
    if force_sweep or (not force_exact and len(set(names)) > SWEEP_THRESHOLD):
        return _launch_diagnostics_sweep(names, plan)
    out: List[Diagnostic] = []
    running = set(names)
    order = list(dict.fromkeys(names))  # stable de-duped iteration order
    find = coschedule_find(running, plan)

    cdeps: Dict[str, set] = {find(n): set() for n in order}
    for n in order:
        rn = find(n)
        for d in plan.dependencies.get(n, ()):
            if d not in running:
                continue
            rd = find(d)
            if rd == rn:
                if d != n:
                    out.append(make(
                        "SAT-P003", "error",
                        f"plan makes co-scheduled task {n!r} depend on its "
                        f"groupmate {d!r}: group members run interleaved on "
                        "one launcher, so an intra-group completion wait "
                        "would deadlock the group",
                        counterexample={"task": n, "groupmate": d},
                        category="launch",
                    ))
                continue
            cdeps[rn].add(rd)

    # Reachability over the condensed dependency DAG; cycle check rides
    # the same DFS (a node reaching itself).
    reach: Dict[str, set] = {}

    def reachable(r: str) -> set:
        if r in reach:
            return reach[r]
        reach[r] = set()  # placeholder breaks self-recursion on cycles
        out_set = set()
        for d in cdeps[r]:
            out_set.add(d)
            out_set |= reachable(d)
        reach[r] = out_set
        return out_set

    for r in cdeps:
        if r in reachable(r):
            out.append(make(
                "SAT-P002", "error",
                f"plan dependency cycle through task {r!r}: the gang "
                "launch would deadlock (every thread in the cycle waits "
                "on another's completion event)",
                counterexample={"cycle_witness": r,
                                "cycle_nodes": sorted(
                                    n for n in cdeps if r in reachable(n)
                                    and n in reachable(r) or n == r)},
                category="launch",
            ))
            break  # one witness is the minimal counterexample

    items = [(n, plan.assignments.get(n)) for n in order]
    for i, (n1, a1) in enumerate(items):
        if a1 is None:
            continue
        for n2, a2 in items[i + 1:]:
            if a2 is None or not a1.block.overlaps(a2.block):
                continue
            r1, r2 = find(n1), find(n2)
            if r1 == r2:
                continue  # co-scheduled: the shared block is the point
            if r1 not in reachable(r2) and r2 not in reachable(r1):
                out.append(make(
                    "SAT-P001", "error",
                    f"plan races tasks {n1!r} and {n2!r}: blocks "
                    f"[{a1.block.offset}:{a1.block.end}] and "
                    f"[{a2.block.offset}:{a2.block.end}] overlap with no "
                    "ordering path or co-schedule edge between them",
                    counterexample={
                        "tasks": [n1, n2],
                        "blocks": [[a1.block.offset, a1.block.end],
                                   [a2.block.offset, a2.block.end]],
                    },
                    category="launch",
                ))
    return out


def _launch_diagnostics_sweep(names: Sequence[str],
                              plan: Any) -> List[Diagnostic]:
    """Large-N launch check: per-device start-order sweep, O(occupancy log).

    Invariants checked (same codes as the exact path):

    - SAT-P003: intra-group dependency edges (identical logic, O(E));
    - SAT-P002: cycles via Kahn's toposort over the condensed graph
      (O(V + E), no transitive closure);
    - SAT-P001: on every device, consecutive occupants in start order must
      be directly ordered by a condensed dependency edge (either direction)
      or share a co-schedule group. A direct edge between every
      same-device-adjacent pair chains into a path between EVERY pair of
      tasks sharing that device, so acceptance implies the exact path's
      race-freedom. Solver-produced plans always carry such edges (the
      dense form links every overlapping pair; the sparse form links
      exactly these neighbors); a hand-built plan relying on a longer
      transitive detour is rejected here — quarantine-safe, and such plans
      only reach this path above SWEEP_THRESHOLD tasks.
    """
    out: List[Diagnostic] = []
    running = set(names)
    order = list(dict.fromkeys(names))
    find = coschedule_find(running, plan)

    cdeps: Dict[str, set] = {find(n): set() for n in order}
    for n in order:
        rn = find(n)
        for d in plan.dependencies.get(n, ()):
            if d not in running:
                continue
            rd = find(d)
            if rd == rn:
                if d != n:
                    out.append(make(
                        "SAT-P003", "error",
                        f"plan makes co-scheduled task {n!r} depend on its "
                        f"groupmate {d!r}: group members run interleaved on "
                        "one launcher, so an intra-group completion wait "
                        "would deadlock the group",
                        counterexample={"task": n, "groupmate": d},
                        category="launch",
                    ))
                continue
            cdeps[rn].add(rd)

    # Kahn's toposort for cycle detection (linear, closure-free).
    indeg: Dict[str, int] = {r: 0 for r in cdeps}
    for r, ds in cdeps.items():
        for d in ds:
            if d in indeg:
                indeg[d] += 1
    queue = [r for r, k in indeg.items() if k == 0]
    seen = 0
    while queue:
        u = queue.pop()
        seen += 1
        for d in cdeps[u]:
            if d in indeg:
                indeg[d] -= 1
                if indeg[d] == 0:
                    queue.append(d)
    if seen != len(cdeps):
        stuck = sorted(r for r, k in indeg.items() if k > 0)
        out.append(make(
            "SAT-P002", "error",
            f"plan dependency cycle through task {stuck[0]!r}: the gang "
            "launch would deadlock (every thread in the cycle waits "
            "on another's completion event)",
            counterexample={"cycle_witness": stuck[0],
                            "cycle_nodes": stuck},
            category="launch",
        ))

    # Per-device sweep: adjacent occupants must be directly ordered.
    per_device: Dict[int, List[Tuple[float, str]]] = {}
    for n in order:
        a = plan.assignments.get(n)
        if a is None:
            continue
        for d in range(a.block.offset, a.block.end):
            per_device.setdefault(d, []).append((a.start, n))
    flagged: set = set()
    for occ in per_device.values():
        occ.sort()
        for (_, n1), (_, n2) in zip(occ, occ[1:]):
            r1, r2 = find(n1), find(n2)
            if r1 == r2:
                continue  # co-scheduled: the shared block is the point
            if r1 in cdeps.get(r2, ()) or r2 in cdeps.get(r1, ()):
                continue
            key = (n1, n2) if n1 <= n2 else (n2, n1)
            if key in flagged:
                continue
            flagged.add(key)
            a1, a2 = plan.assignments[n1], plan.assignments[n2]
            out.append(make(
                "SAT-P001", "error",
                f"plan races tasks {n1!r} and {n2!r}: blocks "
                f"[{a1.block.offset}:{a1.block.end}] and "
                f"[{a2.block.offset}:{a2.block.end}] overlap with no "
                "ordering path or co-schedule edge between them",
                counterexample={
                    "tasks": [n1, n2],
                    "blocks": [[a1.block.offset, a1.block.end],
                               [a2.block.offset, a2.block.end]],
                },
                category="launch",
            ))
    return out


def check_launch_invariants(names: Sequence[str], plan: Any) -> None:
    """Raise ``RuntimeError`` on the FIRST launch-invariant violation, with
    the dynamic guard's historical message — the engine's refusal path.
    """
    for diag in launch_diagnostics(names, plan):
        raise RuntimeError(diag.message)


# ---------------------------------------------------------------------------
# full static verification
# ---------------------------------------------------------------------------

def _structure_diagnostics(plan: Any) -> List[Diagnostic]:
    out: List[Diagnostic] = []
    known = set(plan.assignments)
    for n, deps in (plan.dependencies or {}).items():
        for d in deps:
            if d not in known:
                out.append(make(
                    "SAT-P010", "warning",
                    f"dependency of {n!r} names unknown task {d!r} "
                    "(no assignment in the plan)",
                    counterexample={"task": n, "unknown": d},
                    category="structure",
                ))
    seen_members: Dict[str, int] = {}
    for gi, grp in enumerate(getattr(plan, "coschedule", None) or []):
        for m in grp:
            if m not in known:
                out.append(make(
                    "SAT-P011", "warning",
                    f"co-schedule group {gi} names unknown task {m!r}",
                    counterexample={"group": gi, "unknown": m},
                    category="structure",
                ))
            if m in seen_members and seen_members[m] != gi:
                out.append(make(
                    "SAT-P013", "warning",
                    f"task {m!r} appears in co-schedule groups "
                    f"{seen_members[m]} and {gi} — the engine merges them "
                    "into one launcher",
                    counterexample={"task": m,
                                    "groups": [seen_members[m], gi]},
                    category="structure",
                ))
            seen_members.setdefault(m, gi)
        if len([m for m in grp if m in known]) < 2:
            out.append(make(
                "SAT-P012", "warning",
                f"co-schedule group {gi} has fewer than two assigned "
                "members — nothing to interleave",
                counterexample={"group": gi, "members": list(grp)},
                category="structure",
            ))
    seen_fused: Dict[str, int] = {}
    for gi, grp in enumerate(getattr(plan, "fused", None) or []):
        for m in grp:
            if m not in known:
                out.append(make(
                    "SAT-P014", "warning",
                    f"fusion group {gi} names unknown task {m!r}",
                    counterexample={"group": gi, "unknown": m},
                    category="structure",
                ))
            if m in seen_fused and seen_fused[m] != gi:
                out.append(make(
                    "SAT-P016", "warning",
                    f"task {m!r} appears in fusion groups {seen_fused[m]} "
                    f"and {gi} — one task can belong to only one stacked "
                    "program",
                    counterexample={"task": m,
                                    "groups": [seen_fused[m], gi]},
                    category="structure",
                ))
            seen_fused.setdefault(m, gi)
        if len([m for m in grp if m in known]) < 2:
            out.append(make(
                "SAT-P015", "warning",
                f"fusion group {gi} has fewer than two assigned members — "
                "nothing to stack",
                counterexample={"group": gi, "members": list(grp)},
                category="structure",
            ))
    return out


def _feasibility_diagnostics(plan: Any, topology: Any,
                             tasks: Optional[Sequence[Any]]) -> List[Diagnostic]:
    out: List[Diagnostic] = []
    capacity = getattr(topology, "capacity", None)
    by_name = {getattr(t, "name", None): t for t in (tasks or [])}
    for n, a in plan.assignments.items():
        if capacity is not None and a.block.end > capacity:
            out.append(make(
                "SAT-P020", "error",
                f"assignment for {n!r} occupies devices "
                f"[{a.block.offset}:{a.block.end}] but the topology's buddy "
                f"capacity is {capacity}",
                counterexample={"task": n,
                                "block": [a.block.offset, a.block.end],
                                "capacity": capacity},
                category="feasibility",
            ))
        if a.apportionment != a.block.size:
            out.append(make(
                "SAT-P021", "error",
                f"assignment for {n!r} apportions {a.apportionment} chips "
                f"but its block holds {a.block.size}: the profiled strategy "
                "would run on a mesh it was never measured for",
                counterexample={"task": n, "apportionment": a.apportionment,
                                "block_size": a.block.size},
                category="feasibility",
            ))
        t = by_name.get(n)
        if t is not None:
            strat = getattr(t, "strategies", {}).get(a.apportionment)
            if strat is None or not getattr(strat, "feasible", True):
                out.append(make(
                    "SAT-P022", "error",
                    f"task {n!r} has no feasible strategy at apportionment "
                    f"{a.apportionment} — the plan schedules a configuration "
                    "the sweep rejected or never measured",
                    counterexample={"task": n,
                                    "apportionment": a.apportionment,
                                    "known_sizes": sorted(
                                        getattr(t, "strategies", {}))},
                    category="feasibility",
                ))
    for gi, grp in enumerate(getattr(plan, "coschedule", None) or []):
        assigned = [(m, plan.assignments[m]) for m in grp
                    if m in plan.assignments]
        blocks = {(a.block.offset, a.block.size) for _, a in assigned}
        if len(blocks) > 1:
            out.append(make(
                "SAT-P023", "warning",
                f"co-schedule group {gi} members do not share one device "
                "block — interleaving only hides bubbles when the group is "
                "co-located",
                counterexample={"group": gi,
                                "blocks": sorted(blocks)},
                category="feasibility",
            ))
        for m, a in assigned:
            t = by_name.get(m)
            if t is None:
                continue
            strat = getattr(t, "strategies", {}).get(a.apportionment)
            hf = getattr(strat, "host_fraction", 0.0) if strat else 0.0
            # A pipeline job with a measured-zero host fraction can still be
            # a legitimate co-schedule member: its analytic schedule bubble
            # (GPipe/1F1B warmup-cooldown) is the gap the partner fills.
            bubble = getattr(strat, "bubble_fraction", 0.0) if strat else 0.0
            if (not hf or hf <= 0.0) and (not bubble or bubble <= 0.0):
                out.append(make(
                    "SAT-P024", "warning",
                    f"co-scheduled task {m!r} has no measured host fraction "
                    "or schedule bubble at its apportionment — the "
                    "co-location term had no idle window to fill",
                    counterexample={"task": m, "group": gi,
                                    "apportionment": a.apportionment},
                    category="feasibility",
                ))
    for gi, grp in enumerate(getattr(plan, "fused", None) or []):
        assigned = [(m, plan.assignments[m]) for m in grp
                    if m in plan.assignments]
        slots = {(a.apportionment, a.block.offset, a.block.size, a.start)
                 for _, a in assigned}
        if len(slots) > 1:
            out.append(make(
                "SAT-P025", "error",
                f"fusion group {gi} members do not hold IDENTICAL "
                "(size, block, start) assignments — a stacked program is "
                "one compiled step on one sub-mesh; divergent slots would "
                "dispatch the same stack twice",
                counterexample={"group": gi, "slots": sorted(slots)},
                category="feasibility",
            ))
        for m, a in assigned:
            t = by_name.get(m)
            if t is None:
                continue
            strat = getattr(t, "strategies", {}).get(a.apportionment)
            fpbt = getattr(strat, "fused_per_batch_time", None) if strat else None
            if fpbt is None:
                out.append(make(
                    "SAT-P026", "warning",
                    f"fused task {m!r} has no measured fused_per_batch_time "
                    "at its apportionment — the fusion pre-pass prices "
                    "strictly on measured lockstep cost, so this group was "
                    "fused on guesswork",
                    counterexample={"task": m, "group": gi,
                                    "apportionment": a.apportionment},
                    category="feasibility",
                ))
    return out


def _timeline_diagnostics(plan: Any,
                          tasks: Optional[Sequence[Any]]) -> List[Diagnostic]:
    out: List[Diagnostic] = []
    by_name = {getattr(t, "name", None): t for t in (tasks or [])}
    last_end = 0.0
    for n, a in plan.assignments.items():
        if a.start < 0 or a.runtime < 0:
            out.append(make(
                "SAT-P030", "error",
                f"assignment for {n!r} has negative timing "
                f"(start={a.start}, runtime={a.runtime})",
                counterexample={"task": n, "start": a.start,
                                "runtime": a.runtime},
                category="timeline",
            ))
        last_end = max(last_end, a.start + max(a.runtime, 0.0))
        for d in plan.dependencies.get(n, ()):
            da = plan.assignments.get(d)
            if da is not None and a.start < da.start:
                out.append(make(
                    "SAT-P031", "error",
                    f"task {n!r} starts at {a.start:.1f}s but depends on "
                    f"{d!r} which starts later ({da.start:.1f}s) — the "
                    "schedule contradicts its own ordering edges",
                    counterexample={"task": n, "start": a.start,
                                    "dep": d, "dep_start": da.start},
                    category="timeline",
                ))
        t = by_name.get(n)
        deadline = getattr(t, "deadline", None) if t is not None else None
        if deadline is None and t is not None:
            hints = getattr(t, "hints", None) or {}
            deadline = hints.get("deadline") if isinstance(hints, dict) else None
        if isinstance(deadline, (int, float)) and deadline > 0:
            if a.start + a.runtime > float(deadline):
                out.append(make(
                    "SAT-P033", "warning",
                    f"task {n!r} is scheduled to finish at "
                    f"{a.start + a.runtime:.1f}s, past its deadline "
                    f"{float(deadline):.1f}s",
                    counterexample={"task": n,
                                    "finish": a.start + a.runtime,
                                    "deadline": float(deadline)},
                    category="timeline",
                ))
    makespan = getattr(plan, "makespan", None)
    if isinstance(makespan, (int, float)) and last_end > makespan + 1e-6:
        out.append(make(
            "SAT-P032", "warning",
            f"recorded makespan {makespan:.1f}s is below the last "
            f"assignment's end {last_end:.1f}s — stale after a slide or "
            "hand edit",
            counterexample={"makespan": makespan, "last_end": last_end},
            category="timeline",
        ))
    return out


def verify_plan(plan: Any, topology: Any = None,
                tasks: Optional[Sequence[Any]] = None,
                names: Optional[Sequence[str]] = None,
                subject: str = "plan") -> AnalysisReport:
    """Full static verification of one plan.

    ``topology``/``tasks`` unlock the feasibility checks; without them only
    launch, structure and timeline invariants run (exactly what a journal
    audit can check offline).  ``names`` restricts the launch invariants to
    a subset (the engine passes this interval's gang); default is every
    assigned task.
    """
    report = AnalysisReport(subject=subject)
    launch_names = list(names) if names is not None else list(plan.assignments)
    report.extend(launch_diagnostics(launch_names, plan))
    report.extend(_structure_diagnostics(plan))
    if topology is not None or tasks is not None:
        report.extend(_feasibility_diagnostics(plan, topology, tasks))
    report.extend(_timeline_diagnostics(plan, tasks))
    return report


def verify_or_raise(plan: Any, topology: Any = None,
                    tasks: Optional[Sequence[Any]] = None,
                    names: Optional[Sequence[str]] = None,
                    source: str = "plan") -> AnalysisReport:
    """The mandatory adoption gate: verify, raise
    :class:`PlanVerificationError` on any error-severity diagnostic,
    return the report (warnings and all) otherwise.
    """
    report = verify_plan(plan, topology=topology, tasks=tasks, names=names,
                         subject=source)
    if not report.ok:
        raise PlanVerificationError(report, source=source)
    return report


# ---------------------------------------------------------------------------
# journal audit
# ---------------------------------------------------------------------------

def audit_journal(root: str, topology: Any = None,
                  tasks: Optional[Sequence[Any]] = None) -> AnalysisReport:
    """Audit every ``plan_commit`` record in a durability journal.

    Used by durability recovery (quarantine gate) and the CLI's ``journal``
    subcommand: a crash must never resurrect a plan the verifier rejects.
    """
    report = AnalysisReport(subject=f"journal:{root}")
    try:
        from saturn_tpu.durability import journal as _journal
        records = _journal.replay(root)
    except Exception as e:  # unreadable tree, corrupt segment past quarantine
        report.add(make(
            "SAT-J002", "error",
            f"journal at {root!r} unreadable: {type(e).__name__}: {e}",
            category="journal",
        ))
        return report
    from saturn_tpu.solver import milp
    n_plans = 0
    for rec in records:
        if rec.get("kind") != "plan_commit":
            continue
        n_plans += 1
        seq = rec.get("seq")
        payload = (rec.get("data") or {}).get("plan")
        try:
            plan = milp.Plan.from_json(payload)
        except Exception as e:
            report.add(make(
                "SAT-J002", "error",
                f"plan_commit seq={seq} undecodable: "
                f"{type(e).__name__}: {e}",
                counterexample={"seq": seq},
                category="journal",
            ))
            continue
        sub = verify_plan(plan, topology=topology, tasks=tasks,
                          subject=f"plan_commit seq={seq}")
        if not sub.ok:
            report.add(make(
                "SAT-J001", "error",
                f"plan_commit seq={seq} fails static verification "
                f"({[d.code for d in sub.errors]}) — quarantine on replay",
                counterexample={"seq": seq,
                                "codes": [d.code for d in sub.errors]},
                category="journal",
            ))
        report.extend(sub.diagnostics)
    if n_plans == 0:
        report.add(make(
            "SAT-J000", "info",
            f"journal at {root!r} holds no plan_commit records",
            category="journal",
        ))
    return report
