"""Entry point: ``python -m saturn_tpu.analysis``."""

import sys

from saturn_tpu.analysis.cli import main

sys.exit(main())
