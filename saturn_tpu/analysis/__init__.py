"""saturn-lint: static plan verifier + JAX hot-path analyzer.

Two passes, one gate:

- :mod:`saturn_tpu.analysis.plan_verifier` — Pass 1: verify any
  :class:`~saturn_tpu.solver.milp.Plan` (fresh solve, warm re-solve,
  journal replay, migration) before it reaches chips.  The engine's
  dynamic race guard delegates here; the orchestrator, service, and
  durability recovery call :func:`verify_or_raise` /
  :func:`audit_journal` as a mandatory adoption gate.
- :mod:`saturn_tpu.analysis.jax_lint` — Pass 2: retrace-risk registry,
  hot-loop host-sync lint, donation lint, and PartitionSpec/mesh
  sharding lint with ``file:line`` diagnostics, all on CPU.

``python -m saturn_tpu.analysis`` lints a plan JSON, audits a journal
directory, or lints a registered technique (:mod:`.cli`).

This package is deliberately import-light (stdlib + diagnostics only at
import time) so every layer — including ``utils`` fingerprinting — can
depend on it without cycles.
"""

from __future__ import annotations

from saturn_tpu.analysis.diagnostics import (  # noqa: F401
    SCHEMA_VERSION,
    AnalysisReport,
    Diagnostic,
    PlanVerificationError,
)


def verify_plan(plan, topology=None, tasks=None, names=None,
                subject="plan") -> AnalysisReport:
    """See :func:`saturn_tpu.analysis.plan_verifier.verify_plan`."""
    from saturn_tpu.analysis import plan_verifier

    return plan_verifier.verify_plan(plan, topology=topology, tasks=tasks,
                                     names=names, subject=subject)


def verify_or_raise(plan, topology=None, tasks=None, names=None,
                    source="plan") -> AnalysisReport:
    """See :func:`saturn_tpu.analysis.plan_verifier.verify_or_raise`."""
    from saturn_tpu.analysis import plan_verifier

    return plan_verifier.verify_or_raise(plan, topology=topology, tasks=tasks,
                                         names=names, source=source)


def audit_journal(root, topology=None, tasks=None) -> AnalysisReport:
    """See :func:`saturn_tpu.analysis.plan_verifier.audit_journal`."""
    from saturn_tpu.analysis import plan_verifier

    return plan_verifier.audit_journal(root, topology=topology, tasks=tasks)


__all__ = [
    "SCHEMA_VERSION",
    "AnalysisReport",
    "Diagnostic",
    "PlanVerificationError",
    "audit_journal",
    "verify_or_raise",
    "verify_plan",
]
