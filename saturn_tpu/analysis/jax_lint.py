"""Pass 2 — JAX program lint: hot-loop and sharding hazards, on CPU.

Four analyzers over technique code and bundle metadata, none of which
needs a chip:

- **Retrace risk** (:class:`SignatureRegistry`): an abstract-signature
  registry per ``(bundle, K)`` dispatch key.  A novel shape/dtype
  signature for an already-seen key means the next dispatch recompiles
  (AOT-cache miss) — flagged *before* the compile burns chip time.
- **Host-sync lint** (:func:`lint_host_syncs`): AST scan for implicit
  device→host readbacks (``block_until_ready``, ``float(...)``,
  ``.item()``, ``np.asarray``, ``device_get``, ``host_array``) inside a
  loop body.  The interval hot loop is allowed exactly the syncs marked
  ``# lint: sanctioned-host-sync`` (the warmup fence); the one real
  loss drain sits after the loop and is out of scope by construction.
- **Donation lint** (:func:`lint_donation`): donated window stacks /
  state referenced after the donating dispatch.  A statement that
  rebinds the name is treated as a kill — the rebind-from-donor idiom
  (``state, loss = fused_fn(state, window)``) dominates real code.
- **Sharding lint** (:func:`check_pspec` / :func:`lint_rules`): every
  ``PartitionSpec`` a rule function emits is validated against the mesh
  axis names and dimension divisibility before anything is lowered, so
  GSPMD errors surface as ``file:line`` diagnostics on CPU instead of
  compile failures on a v5e.

Only :func:`abstract_signature` touches JAX (lazily); everything else is
pure ``ast``/``inspect`` so the linter itself can never trigger the
hazards it hunts.
"""

from __future__ import annotations

import ast
import inspect
import textwrap
import threading
from typing import (
    Any, Callable, Dict, Iterable, List, Mapping, Optional, Sequence, Tuple,
)

from saturn_tpu.analysis.diagnostics import AnalysisReport, Diagnostic, make

SANCTION_MARKER = "lint: sanctioned-host-sync"

#: attribute / function names whose call forces a device->host sync
_SYNC_ATTRS = {"block_until_ready", "item", "device_get", "host_array",
               "asarray"}
_SYNC_NAMES = {"float"}


class ShardingLintError(ValueError):
    """A rule function emitted a PartitionSpec the mesh cannot satisfy.

    Raised at bundle-build time (before lowering) with the rule source
    location; ``ValueError`` so the trial runner's infeasibility handling
    treats it like any other rejected configuration.
    """

    def __init__(self, diagnostics: List[Diagnostic]) -> None:
        self.diagnostics = diagnostics
        first = diagnostics[0]
        loc = f" [{first.location}]" if first.location else ""
        super().__init__(f"{first.code}{loc}: {first.message}")


# ---------------------------------------------------------------------------
# retrace risk
# ---------------------------------------------------------------------------

def abstract_signature(tree: Any) -> Tuple[Tuple[str, Tuple[int, ...], str], ...]:
    """Canonical (path, shape, dtype) tuple for a pytree of arrays /
    ShapeDtypeStructs — the identity JAX traces against."""
    import jax

    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    out = []
    for path, leaf in leaves:
        shape = tuple(getattr(leaf, "shape", ()))
        dtype = str(getattr(leaf, "dtype", type(leaf).__name__))
        out.append((jax.tree_util.keystr(path), shape, dtype))
    return tuple(out)


class SignatureRegistry:
    """Abstract-signature registry per ``(bundle_key, K)`` dispatch key.

    ``note`` returns a SAT-L001 diagnostic when an already-compiled key is
    about to trace a NOVEL signature — the static predictor of an AOT-cache
    miss.  Thread-safe (bundle builds run on trial threads); bounded so a
    pathological sweep cannot grow it without limit.
    """

    def __init__(self, cap: int = 4096) -> None:
        self._seen: Dict[Tuple[Any, Any], set] = {}
        self._lock = threading.Lock()
        self._cap = cap
        self.flagged: List[Diagnostic] = []

    def note(self, bundle_key: Any, k: Any,
             signature: Tuple) -> Optional[Diagnostic]:
        key = (bundle_key, k)
        with self._lock:
            sigs = self._seen.get(key)
            if sigs is None:
                if len(self._seen) >= self._cap:
                    self._seen.clear()  # epoch reset beats unbounded growth
                self._seen[key] = {signature}
                return None
            if signature in sigs:
                return None
            sigs.add(signature)
            diag = make(
                "SAT-L001", "warning",
                f"retrace risk: dispatch key {bundle_key!r} (K={k!r}) has "
                f"already compiled {len(sigs) - 1} signature(s) and is now "
                "tracing a novel shape/dtype set — the AOT cache will miss "
                "and the next dispatch recompiles",
                counterexample={"k": k, "n_signatures": len(sigs)},
                category="jax",
            )
            self.flagged.append(diag)
            if len(self.flagged) > 256:
                del self.flagged[:128]
            return diag

    def drain(self) -> List[Diagnostic]:
        with self._lock:
            out, self.flagged = self.flagged, []
            return out


#: process-wide registry the technique layer notes into
retrace_registry = SignatureRegistry()


# ---------------------------------------------------------------------------
# source helpers
# ---------------------------------------------------------------------------

def _source_of(fn: Callable) -> Tuple[Optional[str], int, str]:
    """(abs file or None, first line number, dedented source) of ``fn``."""
    fn = inspect.unwrap(fn)
    fn = getattr(fn, "__func__", fn)
    try:
        path = inspect.getsourcefile(fn)
        lines, first = inspect.getsourcelines(fn)
    except (OSError, TypeError):
        return None, 1, ""
    return path, first, textwrap.dedent("".join(lines))


def source_location(fn: Callable) -> Optional[str]:
    """``file:line`` of a callable, or None for builtins/C functions."""
    path, first, src = _source_of(fn)
    if path is None:
        return None
    return f"{path}:{first}"


def _loc(path: Optional[str], first: int, node: ast.AST) -> Optional[str]:
    if path is None:
        return None
    return f"{path}:{first + node.lineno - 1}"


def _call_name(node: ast.Call) -> Optional[str]:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


# ---------------------------------------------------------------------------
# host-sync lint
# ---------------------------------------------------------------------------

def lint_host_syncs(fn: Callable,
                    marker: str = SANCTION_MARKER) -> List[Diagnostic]:
    """Flag device->host syncs inside loop bodies of ``fn``.

    A sync on a line carrying ``marker`` — or directly below a line that
    carries it — is sanctioned.  Only ``for``/``while`` bodies count as the
    hot loop: a single drain after the loop is the sanctioned pattern by
    construction.
    """
    path, first, src = _source_of(fn)
    if not src:
        return []
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return []
    src_lines = src.splitlines()

    def sanctioned(node: ast.AST) -> bool:
        for ln in (node.lineno, node.lineno - 1):
            if 1 <= ln <= len(src_lines) and marker in src_lines[ln - 1]:
                return True
        return False

    out: List[Diagnostic] = []

    class V(ast.NodeVisitor):
        def __init__(self) -> None:
            self.loop_depth = 0

        def visit_For(self, node: ast.For) -> None:
            self._loop(node)

        def visit_While(self, node: ast.While) -> None:
            self._loop(node)

        def _loop(self, node: ast.AST) -> None:
            self.loop_depth += 1
            self.generic_visit(node)
            self.loop_depth -= 1

        def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
            # nested defs run whenever called, not per loop iteration here
            saved, self.loop_depth = self.loop_depth, 0
            self.generic_visit(node)
            self.loop_depth = saved

        visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

        def visit_Call(self, node: ast.Call) -> None:
            name = _call_name(node)
            is_sync = (
                (isinstance(node.func, ast.Attribute) and name in _SYNC_ATTRS)
                or (isinstance(node.func, ast.Name) and name in _SYNC_NAMES)
            )
            if is_sync and self.loop_depth > 0 and not sanctioned(node):
                out.append(make(
                    "SAT-L002", "error",
                    f"implicit host sync {name!r} inside the hot loop — a "
                    "device->host readback per iteration serializes the "
                    "dispatch pipeline; drain once after the loop or mark "
                    f"the line '# {SANCTION_MARKER}'",
                    counterexample={"call": name},
                    location=_loc(path, first, node),
                    category="jax",
                ))
            self.generic_visit(node)

    V().visit(tree)
    return out


# ---------------------------------------------------------------------------
# donation lint
# ---------------------------------------------------------------------------

def _stmt_kills(stmt: ast.stmt, name: str) -> bool:
    """True when the statement rebinds ``name`` (treated as a kill even if
    its RHS reads the donated value: the rebind-from-donor idiom)."""
    targets: List[ast.expr] = []
    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    elif isinstance(stmt, ast.For):
        targets = [stmt.target]
    for t in targets:
        for n in ast.walk(t):
            if isinstance(n, ast.Name) and n.id == name:
                return True
    return False


def _expr_load(node: ast.AST, name: str) -> Optional[ast.Name]:
    """First Load of ``name`` in an expression subtree."""
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and n.id == name and isinstance(n.ctx, ast.Load):
            return n
    return None


def _scan_stmt(stmt: ast.stmt, name: str) -> Tuple[str, Optional[ast.Name]]:
    """('flag', load) | ('kill', None) | ('alive', None) for one statement,
    respecting inner statement order — a branch that rebinds the name
    before reading it kills the taint, it doesn't trip the lint."""
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
        return ("alive", None)  # separate scope; not executed here
    if isinstance(stmt, ast.If):
        load = _expr_load(stmt.test, name)
        if load is not None:
            return ("flag", load)
        rb = _scan_stmts(stmt.body, name)
        ro = _scan_stmts(stmt.orelse, name)
        for r in (rb, ro):
            if r[0] == "flag":
                return r
        if rb[0] == "kill" and ro[0] == "kill" and stmt.orelse:
            return ("kill", None)
        return ("alive", None)
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        load = _expr_load(stmt.iter, name)
        if load is not None:
            return ("flag", load)
        for part in (stmt.body, stmt.orelse):
            r = _scan_stmts(part, name)
            if r[0] == "flag":
                return r
        return ("alive", None)  # zero-iteration path keeps the taint alive
    if isinstance(stmt, ast.While):
        load = _expr_load(stmt.test, name)
        if load is not None:
            return ("flag", load)
        for part in (stmt.body, stmt.orelse):
            r = _scan_stmts(part, name)
            if r[0] == "flag":
                return r
        return ("alive", None)
    if isinstance(stmt, ast.Try):
        rb = _scan_stmts(stmt.body, name)
        if rb[0] == "flag":
            return rb
        for h in stmt.handlers:
            r = _scan_stmts(h.body, name)
            if r[0] == "flag":
                return r
        rf = _scan_stmts(stmt.finalbody, name)
        if rf[0] == "flag":
            return rf
        if rb[0] == "kill" or rf[0] == "kill":
            return ("kill", None)
        return ("alive", None)
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            load = _expr_load(item, name)
            if load is not None:
                return ("flag", load)
        return _scan_stmts(stmt.body, name)
    if _stmt_kills(stmt, name):
        return ("kill", None)
    load = _expr_load(stmt, name)
    if load is not None:
        return ("flag", load)
    return ("alive", None)


def _scan_stmts(stmts: Sequence[ast.stmt],
                name: str) -> Tuple[str, Optional[ast.Name]]:
    for s in stmts:
        r = _scan_stmt(s, name)
        if r[0] != "alive":
            return r
    return ("alive", None)


def lint_donation(fn: Callable,
                  donating: Mapping[str, Sequence[int]]) -> List[Diagnostic]:
    """Flag reads of donated buffers after the donating dispatch.

    ``donating`` maps callee names (``fused_fn`` / attribute name) to the
    positional argument indices XLA donates.  The scan follows forward
    control flow per statement list (if/else branches don't see each
    other) plus the loop back edge; a statement that rebinds the donated
    name kills the taint.
    """
    path, first, src = _source_of(fn)
    if not src:
        return []
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return []
    out: List[Diagnostic] = []

    def scan_forward(name: str, stmts: Iterable[ast.stmt],
                     call_node: ast.Call, callee: str) -> bool:
        """Flag the first post-donation load of ``name``; True = resolved
        (killed or flagged), False = taint survives this list."""
        kind, load = _scan_stmts(list(stmts), name)
        if kind == "flag" and load is not None:
            out.append(make(
                "SAT-L003", "error",
                f"donated buffer {name!r} (argument of {callee!r}) is "
                "read after dispatch — XLA has already reused its "
                "memory; stage a fresh buffer instead",
                counterexample={"name": name, "callee": callee,
                                "donated_at": call_node.lineno + first - 1},
                location=_loc(path, first, load),
                category="jax",
            ))
            return True
        return kind == "kill"

    def own_nodes(stmt: ast.stmt) -> Iterable[ast.AST]:
        """The statement's own expressions — headers only for compound
        statements, whose bodies are handled at their own nesting level
        (where the rebind-kill applies to the right statement list)."""
        if isinstance(stmt, (ast.If, ast.While)):
            heads: List[ast.AST] = [stmt.test]
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            heads = [stmt.target, stmt.iter]
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            heads = list(stmt.items)
        elif isinstance(stmt, (ast.Try, ast.FunctionDef,
                               ast.AsyncFunctionDef, ast.ClassDef)):
            heads = []
        else:
            heads = [stmt]
        for h in heads:
            yield from ast.walk(h)

    def donations_in(stmt: ast.stmt) -> List[Tuple[ast.Call, str, List[str]]]:
        found = []
        for n in own_nodes(stmt):
            if isinstance(n, ast.Call):
                callee = _call_name(n)
                if callee in donating:
                    names = [a.id for i, a in enumerate(n.args)
                             if i in tuple(donating[callee])
                             and isinstance(a, ast.Name)]
                    if names:
                        found.append((n, callee, names))
        return found

    def handle(body: List[ast.stmt],
               suffixes: List[List[ast.stmt]],
               back_edge: Optional[List[ast.stmt]]) -> None:
        for i, stmt in enumerate(body):
            rest = body[i + 1:]
            for call_node, callee, names in donations_in(stmt):
                for name in names:
                    if _stmt_kills(stmt, name):
                        continue  # rebind-from-donor: taint dies at the call
                    resolved = scan_forward(name, rest, call_node, callee)
                    for suf in suffixes:
                        if resolved:
                            break
                        resolved = scan_forward(name, suf, call_node, callee)
                    if not resolved and back_edge is not None:
                        scan_forward(name, back_edge, call_node, callee)
            child_suffixes = [rest] + suffixes
            if isinstance(stmt, ast.If):
                handle(stmt.body, child_suffixes, back_edge)
                handle(stmt.orelse, child_suffixes, back_edge)
            elif isinstance(stmt, (ast.For, ast.While)):
                handle(stmt.body, child_suffixes, stmt.body)
                handle(stmt.orelse, child_suffixes, back_edge)
            elif isinstance(stmt, ast.Try):
                handle(stmt.body, [stmt.finalbody] + child_suffixes, back_edge)
                for h in stmt.handlers:
                    handle(h.body, [stmt.finalbody] + child_suffixes, back_edge)
                handle(stmt.finalbody, child_suffixes, back_edge)
            elif isinstance(stmt, ast.With):
                handle(stmt.body, child_suffixes, back_edge)

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            handle(node.body, [], None)
            break
    return out


# ---------------------------------------------------------------------------
# sharding lint
# ---------------------------------------------------------------------------

def _spec_entries(spec: Any) -> List[Any]:
    try:
        return list(tuple(spec))
    except TypeError:
        return []


def check_pspec(spec: Any, shape: Sequence[int], mesh_axes: Mapping[str, int],
                *, path: str = "", strict: bool = False,
                location: Optional[str] = None) -> List[Diagnostic]:
    """Validate one PartitionSpec against mesh axis names + divisibility.

    ``strict`` promotes divisibility findings to errors (GSPMD pads uneven
    shards, which is at best silent waste and at worst an op that doesn't
    support padding — strict mode refuses).
    """
    out: List[Diagnostic] = []
    entries = _spec_entries(spec)
    where = f" for {path!r}" if path else ""
    if len(entries) > len(shape):
        out.append(make(
            "SAT-L012", "error",
            f"PartitionSpec {tuple(entries)!r}{where} has rank "
            f"{len(entries)} but the tensor has rank {len(shape)}",
            counterexample={"path": path, "spec": [str(e) for e in entries],
                            "shape": list(shape)},
            location=location, category="sharding",
        ))
        return out
    for dim, entry in enumerate(entries):
        if entry is None:
            continue
        axes = entry if isinstance(entry, (tuple, list)) else (entry,)
        factor = 1
        for axis in axes:
            if axis not in mesh_axes:
                out.append(make(
                    "SAT-L010", "error",
                    f"PartitionSpec{where} names mesh axis {axis!r} on dim "
                    f"{dim} but the mesh only has axes "
                    f"{sorted(mesh_axes)} — GSPMD would reject this at "
                    "compile time",
                    counterexample={"path": path, "dim": dim, "axis": axis,
                                    "mesh_axes": dict(mesh_axes)},
                    location=location, category="sharding",
                ))
                continue
            factor *= int(mesh_axes[axis])
        if factor > 1 and shape[dim] % factor != 0:
            out.append(make(
                "SAT-L011", "error" if strict else "warning",
                f"dim {dim} of shape {tuple(shape)}{where} is sharded "
                f"{factor}-way by {axes!r} but {shape[dim]} is not "
                f"divisible by {factor} — GSPMD pads every shard",
                counterexample={"path": path, "dim": dim,
                                "size": shape[dim], "factor": factor},
                location=location, category="sharding",
            ))
    return out


def lint_rules(rules: Callable, params_shapes: Mapping[str, Sequence[int]],
               mesh_axes: Mapping[str, int], *, strict: bool = False,
               subject: str = "sharding-rules") -> AnalysisReport:
    """Run a rule function over a {path: shape} map and validate every
    emitted PartitionSpec.  Diagnostics carry the rule function's
    ``file:line`` so a bad rule is a one-click fix."""
    report = AnalysisReport(subject=subject)
    location = source_location(rules)
    for path, shape in params_shapes.items():
        try:
            spec = rules(path, tuple(shape), dict(mesh_axes))
        except Exception as e:
            report.add(make(
                "SAT-L013", "error",
                f"rule function raised for {path!r} {tuple(shape)!r}: "
                f"{type(e).__name__}: {e}",
                counterexample={"path": path, "shape": list(shape)},
                location=location, category="sharding",
            ))
            continue
        report.extend(check_pspec(spec, tuple(shape), mesh_axes, path=path,
                                  strict=strict, location=location))
    return report


def enforce_pspec(spec: Any, shape: Sequence[int],
                  mesh_axes: Mapping[str, int], *, path: str = "",
                  rules: Optional[Callable] = None) -> None:
    """Bundle-build gate: raise :class:`ShardingLintError` on any
    error-severity sharding finding (unknown axis, rank overflow) for the
    spec a rule just emitted.  Divisibility stays a warning here — the
    in-tree rules guard it themselves and GSPMD tolerates padding."""
    location = source_location(rules) if rules is not None else None
    diags = check_pspec(spec, shape, mesh_axes, path=path, strict=False,
                        location=location)
    errors = [d for d in diags if d.severity == "error"]
    if errors:
        raise ShardingLintError(errors)


def lint_technique(tech: Any, size: int = 8,
                   params_shapes: Optional[Mapping[str, Sequence[int]]] = None,
                   ) -> AnalysisReport:
    """Best-effort static lint of a registered technique's sharding rules
    plus its hot-loop source — the CLI's ``technique`` subcommand.

    Uses the technique's own ``mesh_spec``/``param_rules`` hooks with an
    empty config; techniques whose hooks require a real task degrade to an
    informational diagnostic rather than failing the lint run.
    """
    name = getattr(tech, "name", type(tech).__name__)
    report = AnalysisReport(subject=f"technique:{name}")
    shapes = dict(params_shapes or {
        # GPT-2-small-ish probe tree: embed, qkv, mlp, bias, vocab
        "embed/kernel": (50257, 768),
        "attn/qkv/kernel": (768, 2304),
        "mlp/fc/kernel": (768, 3072),
        "mlp/fc/bias": (3072,),
        "ln/scale": (768,),
    })
    try:
        axis_names, axis_sizes = tech.mesh_spec(size, None, {})
        mesh_axes = dict(zip(axis_names, axis_sizes))
        rules = tech.param_rules(None, {})
    except Exception as e:
        report.add(make(
            "SAT-L020", "info",
            f"technique {name!r} needs a concrete task to lint its rules "
            f"({type(e).__name__}: {e}) — sharding lint skipped",
            category="sharding",
        ))
    else:
        report.extend(
            lint_rules(rules, shapes, mesh_axes,
                       subject=report.subject).diagnostics
        )
    hot = getattr(tech, "interval_dispatches", None)
    if hot is not None:
        report.extend(lint_host_syncs(hot))
    return report
