"""``python -m saturn_tpu.analysis`` — lint before you burn chip time.

Subcommands:

- ``plan PLAN.json``: verify one plan (the ``to_json`` form committed to
  journals / emitted by the solver).  ``--topology N`` adds the
  capacity-feasibility checks for an N-device slice.
- ``journal DIR``: audit every ``plan_commit`` record in a durability
  journal — what recovery would replay after a crash.
- ``technique NAME``: lint a registered technique's sharding rules and
  hot-loop source (``--size`` sets the probe sub-mesh size).
- ``health DIR``: replay a durability journal's ``health_*`` records into
  the per-task quarantine / detach / fault ledger the next incarnation
  would restore.  ``--unquarantine TASK[:i,j,k]`` appends a durable
  ``health_unquarantine`` record (all indices when no list is given) —
  the operator-facing undo for a batch range the guardian skip-listed.
- ``gateway DIR``: operator view of a durability journal's gateway records
  — the durable dedup table (idempotent submission keys -> job ids), retry
  collapses, the shed ledger by reason, and drain markers (a missing or
  dirty marker means the last incarnation died instead of handing off).
- ``tenancy DIR``: operator view of a durability journal's multi-tenant
  records — per-tenant admission verdicts, gateway sheds, chip-second
  burn vs budget, the gateway lease/epoch history (replica failovers),
  and the compile-ahead hit/miss ledger.  Exit 1 on lease fencing
  violations (an epoch issued twice, or to two owners).
- ``concurrency [PATH ...]``: saturn-tsan's static pass over the thread
  mesh — lock-order inversions, unguarded shared state, blocking calls
  under a lock, condition-wait-without-loop (SAT-C001..C004).  With no
  paths it audits the five thread-bearing packages (executor, service,
  durability, data, health) plus utils/metrics.py.
- ``solver METRICS.jsonl``: summarize the anytime tier ladder's
  ``solver_tier`` events from a metrics stream — per-tier adoption counts,
  wall-time p50/p99 vs deadline, deadline misses (must be zero in a
  healthy run), fallback (greedy) frequency, and mean quality ratio.
- ``fusion METRICS.jsonl``: summarize fused-stack events from a metrics
  stream — per-group membership and lockstep throughput
  (``fused_interval``), unfuse events with the interval step each member
  left at (``fused_unfuse``), and fused-trial pricing (``trial_fused``).
- ``mfu PATH``: operator view of achieved TFLOP/s and MFU — p50/p99 per
  task and per technique, from ``task_interval`` events in a metrics
  JSONL file or a directory of them.
- ``shardflow``: saturn-shardflow's jaxpr-level sharding-propagation pass
  over every in-tree technique — traces each step function on virtual CPU
  devices (no chip), propagates PartitionSpecs through every equation, and
  reports the communication ledger plus SAT-X001..X005 findings, with the
  source scan (SAT-X002) over ``parallel/``, ``ops/`` and
  ``utils/checkpoint.py``.  ``--size`` sets the probe sub-mesh size,
  ``--ledger`` prints per-technique collective byte totals.
- ``ckpt DIR``: inspect a checkpoint directory — per-manifest shard/leaf
  counts, on-disk bytes, PartitionSpec fingerprint, quarantined
  ``.corrupt`` sidecars and orphan shard files no manifest references.
  Exit 1 when any checkpoint fails verification.
- ``twin DIR``: operator view of a saturn-twin campaign directory —
  makespan, solver tier shares, admission verdict mix, gateway/pressure
  shed and eviction counts, and (against ``--trace``, optionally
  ``--real-metrics``) the fidelity deltas vs a journaled real run.
  ``--run synth|storm|replay|whatif`` executes a fresh deterministic
  campaign into DIR first (``storm`` = seeded preemption/crash/straggler
  chaos; ``replay`` re-drives a real journal through the twin; ``whatif``
  = capacity planning: base vs +1 slice vs 2x deadlines).  Exit 1 on
  solver deadline misses, a non-``ok`` status, or out-of-band fidelity.

Exit code 0 = no error-severity diagnostics; 1 = at least one error;
2 = usage/IO failure.  ``--json`` prints the machine-readable report.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional, Sequence

from saturn_tpu.analysis.diagnostics import AnalysisReport


def _emit(report: AnalysisReport, as_json: bool) -> int:
    if as_json:
        print(json.dumps(report.to_json(), sort_keys=True, default=str))
    else:
        print(report.render())
    return 0 if report.ok else 1


def _cmd_plan(args: argparse.Namespace) -> int:
    from saturn_tpu.analysis import plan_verifier
    from saturn_tpu.solver import milp

    try:
        with open(args.path) as f:
            payload = json.load(f)
        plan = milp.Plan.from_json(payload)
    except (OSError, ValueError, KeyError, TypeError) as e:
        print(f"cannot load plan from {args.path!r}: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return 2
    topology = None
    if args.topology:
        from saturn_tpu.core.mesh import SliceTopology

        topology = SliceTopology(devices=list(range(args.topology)))
    report = plan_verifier.verify_plan(plan, topology=topology,
                                       subject=args.path)
    return _emit(report, args.json)


def _cmd_journal(args: argparse.Namespace) -> int:
    from saturn_tpu.analysis import plan_verifier

    report = plan_verifier.audit_journal(args.path)
    return _emit(report, args.json)


def _cmd_technique(args: argparse.Namespace) -> int:
    from saturn_tpu.analysis import jax_lint

    try:
        from saturn_tpu import library

        try:
            tech = library.retrieve(args.name)
        except KeyError:
            library.register_default_library()
            tech = library.retrieve(args.name)
    except (KeyError, ImportError) as e:
        print(f"cannot retrieve technique {args.name!r}: {e}",
              file=sys.stderr)
        return 2
    if isinstance(tech, type):
        tech = tech()
    report = jax_lint.lint_technique(tech, size=args.size)
    return _emit(report, args.json)


def _cmd_health(args: argparse.Namespace) -> int:
    from saturn_tpu.durability import journal as jmod
    from saturn_tpu.durability import recovery as rmod
    from saturn_tpu.health.guardian import HEALTH_EVENT_CODES

    if args.unquarantine:
        task, _, idx_s = args.unquarantine.partition(":")
        indices = None
        if idx_s:
            try:
                indices = [int(x) for x in idx_s.split(",") if x]
            except ValueError:
                print(f"bad index list in {args.unquarantine!r} "
                      "(want TASK or TASK:i,j,k)", file=sys.stderr)
                return 2
        try:
            jnl = jmod.Journal(args.path)
        except OSError as e:
            print(f"cannot open journal at {args.path!r}: {e}",
                  file=sys.stderr)
            return 2
        try:
            jnl.log("health_unquarantine", task=task, indices=indices,
                    operator=True)
        finally:
            jnl.close()

    quarantined: dict = {}
    detached: list = []
    faults: dict = {}
    try:
        records = list(jmod.replay(args.path))
    except OSError as e:
        print(f"cannot replay journal at {args.path!r}: {e}",
              file=sys.stderr)
        return 2
    for rec in records:
        kind, d = rec["kind"], rec.get("data", {})
        if kind == "health_fault":
            per = faults.setdefault(d.get("task", ""), {})
            cause = d.get("cause", "unknown")
            per[cause] = per.get(cause, 0) + 1
        else:
            rmod.fold_health_record(kind, d, quarantined, detached)
    payload = {
        "quarantined": quarantined,
        "detached": sorted(detached),
        "faults": faults,
        "event_codes": HEALTH_EVENT_CODES,
    }
    if args.json:
        print(json.dumps(payload, sort_keys=True))
        return 0
    if not (quarantined or detached or faults):
        print(f"{args.path}: no health records in the durable journal")
        return 0
    for task in sorted(set(quarantined) | set(detached) | set(faults)):
        bits = []
        if task in faults:
            bits.append("faults " + ", ".join(
                f"{c}x{n}" for c, n in sorted(faults[task].items())))
        if quarantined.get(task):
            bits.append(f"quarantined batches {quarantined[task]}")
        if task in detached:
            bits.append("detached from co-schedule groups")
        print(f"{task}: " + "; ".join(bits))
    return 0


def _cmd_gateway(args: argparse.Namespace) -> int:
    from saturn_tpu.durability import journal as jmod

    try:
        records = list(jmod.replay(args.path))
    except OSError as e:
        print(f"cannot replay journal at {args.path!r}: {e}",
              file=sys.stderr)
        return 2
    submitted = 0
    dedup: dict = {}          # key -> job id (the durable idempotency table)
    hits: dict = {}           # key -> retry-collapse count
    sheds: dict = {}          # reason -> count
    drains: list = []
    for rec in records:
        kind, d = rec["kind"], rec.get("data", {})
        if kind == "job_submitted":
            submitted += 1
            if d.get("dedup_key") is not None:
                dedup[d["dedup_key"]] = d.get("job")
        elif kind == "gateway_dedup_hit":
            hits[d.get("key")] = hits.get(d.get("key"), 0) + 1
        elif kind == "gateway_shed":
            reason = d.get("reason", "unknown")
            sheds[reason] = sheds.get(reason, 0) + 1
        elif kind == "gateway_drain":
            drains.append({
                "reason": d.get("reason"),
                "clean": d.get("clean"),
                "sessions": d.get("sessions"),
                "dedup_entries": d.get("dedup_entries"),
                "dedup_hits": d.get("dedup_hits"),
                "sheds": d.get("sheds"),
            })
    payload = {
        "submitted": submitted,
        "dedup_entries": len(dedup),
        "dedup_hits": sum(hits.values()),
        "dedup_hit_keys": {k: n for k, n in sorted(hits.items())},
        "sheds": sheds,
        "shed_total": sum(sheds.values()),
        "drains": drains,
        "last_drain_clean": drains[-1]["clean"] if drains else None,
    }
    if args.json:
        print(json.dumps(payload, sort_keys=True))
        return 0
    if not (submitted or sheds or drains or hits):
        print(f"{args.path}: no gateway records in the durable journal")
        return 0
    print(f"{args.path}: {submitted} job(s) submitted, "
          f"{len(dedup)} with a dedup key")
    if hits:
        print(f"idempotent retries collapsed: {sum(hits.values())} "
              f"across {len(hits)} key(s)")
        for key, n in sorted(hits.items()):
            print(f"  {key} -> {dedup.get(key, '?')} (x{n})")
    if sheds:
        print("sheds: " + ", ".join(
            f"{r}x{n}" for r, n in sorted(sheds.items())))
    for dr in drains:
        state = "clean" if dr["clean"] else "DIRTY"
        print(f"drain ({dr['reason']}): {state}, "
              f"{dr['sessions']} session(s), "
              f"{dr['dedup_entries']} dedup entry(s), "
              f"{dr['dedup_hits']} hit(s)")
    if not drains:
        print("no drain marker: the last gateway incarnation did not "
              "hand off cleanly (crashed or still running)")
    return 0


def _cmd_tenancy(args: argparse.Namespace) -> int:
    from saturn_tpu.durability import journal as jmod

    try:
        records = list(jmod.replay(args.path))
    except OSError as e:
        print(f"cannot replay journal at {args.path!r}: {e}",
              file=sys.stderr)
        return 2

    tenants: dict = {}   # tenant -> {admit/defer/reject, sheds, charged}

    def row(tenant) -> dict:
        t = tenant if tenant else "default"
        return tenants.setdefault(t, {
            "submitted": 0, "admit": 0, "defer": 0, "reject": 0,
            "sheds": {}, "charged_chip_s": 0.0,
        })

    leases: list = []    # (epoch, owner, prev_owner) in journal order
    compile_counts: dict = {}
    for rec in records:
        kind, d = rec["kind"], rec.get("data", {})
        if kind == "job_submitted":
            row(d.get("tenant"))["submitted"] += 1
        elif kind == "job_admission":
            r = row(d.get("tenant"))
            dec = d.get("decision", "?")
            if dec in r:
                r[dec] += 1
        elif kind == "gateway_shed":
            r = row(d.get("tenant"))
            reason = d.get("reason", "unknown")
            r["sheds"][reason] = r["sheds"].get(reason, 0) + 1
        elif kind == "tenant_charge":
            row(d.get("tenant"))["charged_chip_s"] += float(
                d.get("chip_s", 0.0))
        elif kind == "gateway_lease":
            leases.append((int(d.get("epoch", 0)), d.get("owner"),
                           d.get("prev_owner")))
        elif kind == "compile_ahead":
            status = d.get("status", "?")
            compile_counts[status] = compile_counts.get(status, 0) + 1

    # Fencing audit: every epoch is minted exactly once, under the lease
    # lock, so a value appearing in two records (or bound to two owners)
    # means a deposed replica kept acting on a fenced epoch. Record
    # *order* is not audited — lease records are journaled outside the
    # lease lock and may legitimately land out of order.
    violations: list = []
    seen: dict = {}
    for epoch, owner, _prev in leases:
        if epoch in seen:
            violations.append(
                f"epoch {epoch} issued twice "
                f"(to {seen[epoch]!r} and {owner!r})"
            )
        else:
            seen[epoch] = owner
    current_epoch = max(seen) if seen else 0

    hits = compile_counts.get("hit", 0)
    misses = compile_counts.get("miss", 0)
    hit_rate = (round(hits / (hits + misses), 6)
                if (hits + misses) > 0 else None)
    for r in tenants.values():
        r["charged_chip_s"] = round(r["charged_chip_s"], 6)
    backlog = _fold_grow_records(records)["backlog"]
    payload = {
        "tenants": {t: tenants[t] for t in sorted(tenants)},
        "backlog": backlog,
        "lease": {
            "records": len(leases),
            "current_epoch": current_epoch,
            "current_owner": seen.get(current_epoch),
            "history": [
                {"epoch": e, "owner": o, "prev_owner": p}
                for e, o, p in sorted(leases)
            ],
        },
        "compile_ahead": dict(sorted(compile_counts.items())),
        "compile_ahead_hit_rate": hit_rate,
        "fencing_violations": violations,
    }
    if args.json:
        print(json.dumps(payload, sort_keys=True))
        return 1 if violations else 0
    if not (tenants or leases or compile_counts):
        print(f"{args.path}: no tenancy records in the durable journal")
        return 0
    for t in sorted(tenants):
        r = tenants[t]
        bits = [f"{r['submitted']} submitted",
                f"admit {r['admit']} / defer {r['defer']} / "
                f"reject {r['reject']}"]
        if r["sheds"]:
            bits.append("sheds " + ", ".join(
                f"{k}x{n}" for k, n in sorted(r["sheds"].items())))
        if r["charged_chip_s"]:
            bits.append(f"burned {r['charged_chip_s']:g} chip-s")
        if t in backlog:
            b = backlog[t]
            bits.append(f"backlog {len(b['jobs'])} job(s), oldest "
                        f"{b['oldest_age_s']:g}s")
        print(f"{t}: " + "; ".join(bits))
    if leases:
        print(f"lease: epoch {current_epoch} held by "
              f"{seen.get(current_epoch)!r} "
              f"({len(leases)} transition(s))")
        for e, o, p in sorted(leases):
            print(f"  epoch {e}: {p!r} -> {o!r}")
    if compile_counts:
        rate = f"{100 * hit_rate:.1f}%" if hit_rate is not None else "n/a"
        print("compile-ahead: " + ", ".join(
            f"{k}x{n}" for k, n in sorted(compile_counts.items()))
            + f"; first-dispatch hit rate {rate}")
    if violations:
        print("LEASE FENCING VIOLATIONS:")
        for v in violations:
            print(f"  {v}")
        return 1
    return 0


def _fold_grow_records(records) -> dict:
    """Fold journaled elastic scale-up records into the ``grow`` payload.

    Shared by ``analysis grow`` (full view) and ``analysis tenancy``
    (per-tenant backlog summary). Exit-status-relevant field:
    ``unresolved_intents`` — migration intents with neither a ``done`` nor
    a ``rollback``, i.e. moves a crash left open that recovery never
    closed.
    """
    from saturn_tpu.service.admission import DEFER

    grow_events: list = []
    drains: list = []
    waves: list = []
    intents: dict = {}       # (wave, task) -> intent data
    migrations = {"done": 0, "rolled_back": 0, "recovered_done": 0,
                  "recovered_rollback": 0}
    deferred: dict = {}      # job -> live backlog entry
    drained_jobs = 0
    last_ts = 0.0
    for rec in records:
        kind, d = rec["kind"], rec.get("data", {})
        last_ts = max(last_ts, float(rec.get("ts", 0.0)))
        if kind == "grow_event":
            grow_events.append({
                "interval": d.get("interval"),
                "gained": d.get("gained", []),
                "cause": d.get("cause", ""),
                "n_deferred": d.get("n_deferred", 0),
                "n_parked": d.get("n_parked", 0),
                "unbenched": d.get("unbenched", []),
            })
        elif kind == "backlog_drain":
            jobs = list(d.get("jobs", []))
            drained_jobs += len(jobs)
            drains.append({"interval": d.get("interval"), "jobs": jobs,
                           "trigger": d.get("trigger", "")})
        elif kind == "defrag_wave":
            waves.append({
                "wave": d.get("wave"), "interval": d.get("interval"),
                "moves": d.get("moves", []),
                "rolled_back": d.get("rolled_back", []),
                "admitted": sorted(d.get("admitted", {})),
                "still_blocked": d.get("still_blocked", []),
            })
        elif kind == "migration_intent":
            intents[(d.get("wave"), d.get("task"))] = {
                "wave": d.get("wave"), "task": d.get("task"),
                "interval": d.get("interval"),
                "from": d.get("from"), "to": d.get("to"),
            }
        elif kind == "migration_done":
            intents.pop((d.get("wave"), d.get("task")), None)
            migrations["done"] += 1
            if d.get("recovered"):
                migrations["recovered_done"] += 1
        elif kind == "migration_rollback":
            intents.pop((d.get("wave"), d.get("task")), None)
            migrations["rolled_back"] += 1
            if d.get("recovered"):
                migrations["recovered_rollback"] += 1
        elif kind == "job_deferred":
            deferred[d.get("job")] = {
                "task": d.get("task"), "tenant": d.get("tenant"),
                "reason": d.get("reason", ""),
                "revisit_on": d.get("revisit_on", ""),
                "at": float(d.get("at", rec.get("ts", 0.0)) or 0.0),
            }
        elif kind == "job_admission":
            if d.get("decision") != DEFER:
                deferred.pop(d.get("job"), None)

    backlog: dict = {}       # tenant -> summary of still-deferred jobs
    for job, e in deferred.items():
        t = e["tenant"] or "default"
        row = backlog.setdefault(t, {
            "jobs": [], "oldest_age_s": 0.0, "revisit_on": {}})
        row["jobs"].append(job)
        age = max(0.0, last_ts - e["at"]) if e["at"] else 0.0
        row["oldest_age_s"] = round(max(row["oldest_age_s"], age), 6)
        r = e["revisit_on"] or "?"
        row["revisit_on"][r] = row["revisit_on"].get(r, 0) + 1
    for row in backlog.values():
        row["jobs"].sort()
    return {
        "grow_events": grow_events,
        "backlog_drains": drains,
        "drained_jobs": drained_jobs,
        "defrag_waves": waves,
        "migrations": migrations,
        "unresolved_intents": [
            intents[k] for k in sorted(intents, key=lambda k: (
                str(k[0]), str(k[1])))
        ],
        "backlog": {t: backlog[t] for t in sorted(backlog)},
    }


def _cmd_grow(args: argparse.Namespace) -> int:
    from saturn_tpu.durability import journal as jmod

    try:
        records = list(jmod.replay(args.path))
    except OSError as e:
        print(f"cannot replay journal at {args.path!r}: {e}",
              file=sys.stderr)
        return 2
    payload = _fold_grow_records(records)
    unresolved = payload["unresolved_intents"]
    if args.json:
        print(json.dumps(payload, sort_keys=True))
        return 1 if unresolved else 0
    if not (payload["grow_events"] or payload["backlog_drains"]
            or payload["defrag_waves"] or payload["backlog"] or unresolved):
        print(f"{args.path}: no elastic scale-up records in the journal")
        return 0
    for g in payload["grow_events"]:
        bits = [f"gained {g['gained']}"]
        if g["cause"]:
            bits.append(g["cause"])
        if g["n_deferred"]:
            bits.append(f"{g['n_deferred']} deferred at the time")
        if g["n_parked"]:
            bits.append(f"{g['n_parked']} parked re-admitted")
        if g["unbenched"]:
            bits.append("unbenched " + ", ".join(g["unbenched"]))
        print(f"grow @ interval {g['interval']}: " + "; ".join(bits))
    for dr in payload["backlog_drains"]:
        print(f"drain @ interval {dr['interval']} ({dr['trigger']}): "
              + ", ".join(dr["jobs"]))
    for w in payload["defrag_waves"]:
        print(f"defrag {w['wave']} @ interval {w['interval']}: "
              f"{len(w['moves'])} move(s), "
              f"unblocked {w['admitted']}"
              + (f", rolled back {w['rolled_back']}"
                 if w["rolled_back"] else "")
              + (f", still blocked {w['still_blocked']}"
                 if w["still_blocked"] else ""))
    m = payload["migrations"]
    if m["done"] or m["rolled_back"]:
        print(f"migrations: {m['done']} done "
              f"({m['recovered_done']} via recovery), "
              f"{m['rolled_back']} rolled back "
              f"({m['recovered_rollback']} via recovery)")
    for t, row in payload["backlog"].items():
        mix = ", ".join(f"{k}x{n}" for k, n in sorted(
            row["revisit_on"].items()))
        print(f"backlog[{t}]: {len(row['jobs'])} job(s), oldest "
              f"{row['oldest_age_s']:g}s ({mix}): "
              + ", ".join(row["jobs"]))
    if unresolved:
        print("UNRESOLVED MIGRATION INTENTS (recovery never closed):")
        for it in unresolved:
            print(f"  {it['wave']}/{it['task']} "
                  f"@ interval {it['interval']}")
        return 1
    return 0


def _cmd_concurrency(args: argparse.Namespace) -> int:
    from saturn_tpu.analysis.concurrency import static_pass

    paths = list(args.paths) or static_pass.default_paths()
    if not paths:
        print("no paths given and no default audit paths found under cwd "
              "(run from the repo root, or pass files/directories)",
              file=sys.stderr)
        return 2
    try:
        result = static_pass.run(paths)
    except (OSError, SyntaxError) as e:
        print(f"cannot analyze {paths!r}: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2
    report = result.report
    if args.json:
        payload = report.to_json()
        # per-code counts + the derived lock-order graph, for tooling
        counts: dict = {}
        for d in report.diagnostics:
            per = counts.setdefault(d.code, {"error": 0, "warning": 0,
                                             "info": 0})
            per[d.severity] += 1
        payload["by_code"] = counts
        payload["order_edges"] = [
            {"from": a, "to": b, "where": w}
            for (a, b), w in sorted(result.edges.items())
        ]
        print(json.dumps(payload, sort_keys=True, default=str))
        return 0 if report.ok else 1
    return _emit(report, False)


def _cmd_shardflow(args: argparse.Namespace) -> int:
    import os

    # The audit traces techniques at a probe sub-mesh size on virtual CPU
    # devices — no chip, no compile. Outside the test harness this process
    # sees one CPU device, so the device-count flag must land before jax
    # initializes; once jax is imported the platform is frozen.
    if "jax" not in sys.modules:
        want = args.size * 2
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={want}"
            ).strip()
        os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from saturn_tpu.analysis.shardflow import passes as sf_passes

    try:
        report, ledgers = sf_passes.audit_intree(size=args.size)
    except (OSError, ImportError, RuntimeError) as e:
        print(f"shardflow audit failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2
    if args.json:
        payload = report.to_json()
        payload["ledgers"] = {
            name: led.to_json() for name, led in sorted(ledgers.items())
        }
        print(json.dumps(payload, sort_keys=True, default=str))
        return 0 if report.ok else 1
    rc = _emit(report, False)
    if args.ledger:
        for name, led in sorted(ledgers.items()):
            ops = ", ".join(
                f"{op} x{row['count']} ({row['bytes']}B)"
                for op, row in sorted(led.by_op().items())
            ) or "no collectives"
            print(f"  {name}: {ops}; flops {led.flops:.3g}")
    return rc


def _cmd_memlens(args: argparse.Namespace) -> int:
    import os

    # Same virtual-device dance as shardflow: the liveness audit traces
    # techniques at a probe sub-mesh size on virtual CPU devices, and the
    # device-count flag must land before jax initializes.
    if "jax" not in sys.modules:
        want = args.size * 2
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={want}"
            ).strip()
        os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from saturn_tpu.analysis.memlens import passes as ml_passes

    try:
        report, profiles = ml_passes.audit_intree(
            size=args.size, capacity_bytes=args.capacity,
            window=args.window,
        )
    except (OSError, ImportError, RuntimeError) as e:
        print(f"memlens audit failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2
    if args.json:
        payload = report.to_json()
        payload["profiles"] = {
            name: prof.to_json() for name, prof in sorted(profiles.items())
        }
        print(json.dumps(payload, sort_keys=True, default=str))
        return 0 if report.ok else 1
    rc = _emit(report, False)
    if args.profile:
        for name, prof in sorted(profiles.items()):
            print(
                f"  {name}: peak {prof.peak_bytes}B "
                f"(persistent {prof.persistent_bytes}B + transient "
                f"{prof.transient_peak_bytes}B; scratch "
                f"{prof.collective_scratch_peak}B; host {prof.host_bytes}B); "
                f"largest temp {prof.largest_temp_bytes}B "
                f"@ {prof.largest_temp_where or '?'}"
            )
    return rc


def _cmd_ckpt(args: argparse.Namespace) -> int:
    import os

    from saturn_tpu.utils import checkpoint as ckpt_mod

    if not os.path.isdir(args.path):
        print(f"{args.path!r} is not a directory", file=sys.stderr)
        return 2
    try:
        summary = ckpt_mod.summarize_dir(args.path)
    except OSError as e:
        print(f"cannot inspect {args.path!r}: {e}", file=sys.stderr)
        return 2
    bad = [c for c in summary["checkpoints"] if not c.get("ok")]
    if args.json:
        print(json.dumps(summary, sort_keys=True))
        return 1 if bad else 0
    print(f"{summary['dir']}: {len(summary['checkpoints'])} checkpoint(s), "
          f"{summary['total_bytes']} bytes on disk")
    for c in summary["checkpoints"]:
        name = os.path.basename(c["path"])
        if c["format"] == "sharded-manifest":
            print(f"  {name}: sharded manifest gen {c['generation']} — "
                  f"{c['leaves']} leaves in {c['shards']} shard(s) across "
                  f"{c['shard_files']} file(s), {c['bytes']} bytes, "
                  f"pspec {c['pspec_fingerprint']}, "
                  f"{'ok' if c['ok'] else 'CORRUPT/PARTIAL'}")
        else:
            print(f"  {name}: legacy single-file npz — {c['leaves']} "
                  f"arrays, {c['bytes']} bytes, "
                  f"{'ok' if c['ok'] else 'CORRUPT'}")
    if summary["corrupt_sidecars"]:
        print(f"  quarantined sidecars: "
              + ", ".join(summary["corrupt_sidecars"]))
    if summary["orphan_shards"]:
        print(f"  orphan shard files (no manifest references them): "
              + ", ".join(summary["orphan_shards"]))
    return 1 if bad else 0


def _percentile(values, q: float) -> float:
    xs = sorted(values)
    if not xs:
        return 0.0
    idx = min(len(xs) - 1, max(0, int(round(q * (len(xs) - 1)))))
    return xs[idx]


def _cmd_solver(args: argparse.Namespace) -> int:
    from saturn_tpu.solver.anytime import TIER_NAMES
    from saturn_tpu.utils import metrics

    try:
        events = metrics.read_events(args.path, kind="solver_tier")
    except OSError as e:
        print(f"cannot read metrics at {args.path!r}: {e}", file=sys.stderr)
        return 2

    per_tier: dict = {}
    misses = []
    qualities = []
    outcomes = {"fresh": 0, "slid": 0}
    sources: dict = {}
    for ev in events:
        tier = ev.get("tier")
        per_tier.setdefault(tier, []).append(float(ev.get("wall_s", 0.0)))
        if float(ev.get("wall_s", 0.0)) > float(ev.get("deadline_s", 0.0)):
            misses.append(ev)
        if ev.get("quality") is not None:
            qualities.append(float(ev["quality"]))
        outcomes[ev.get("outcome", "fresh")] = (
            outcomes.get(ev.get("outcome", "fresh"), 0) + 1)
        src = ev.get("source", "?")
        sources[src] = sources.get(src, 0) + 1

    n = len(events)
    tiers_payload = {}
    for tier in sorted(per_tier, key=lambda t: (t is None, t)):
        walls = per_tier[tier]
        tiers_payload[str(tier)] = {
            "name": TIER_NAMES.get(tier, str(tier)),
            "count": len(walls),
            "share": round(len(walls) / n, 4) if n else 0.0,
            "wall_p50_s": round(_percentile(walls, 0.50), 6),
            "wall_p99_s": round(_percentile(walls, 0.99), 6),
        }
    payload = {
        "resolves": n,
        "tiers": tiers_payload,
        "deadline_misses": len(misses),
        "greedy_fallbacks": len(per_tier.get(3, [])),
        "mean_quality": (round(sum(qualities) / len(qualities), 4)
                         if qualities else None),
        "outcomes": outcomes,
        "sources": sources,
    }
    if args.json:
        print(json.dumps(payload, sort_keys=True))
        return 1 if misses else 0
    if not n:
        print(f"{args.path}: no solver_tier events")
        return 0
    print(f"{args.path}: {n} anytime re-solve(s) "
          f"({outcomes.get('fresh', 0)} fresh, {outcomes.get('slid', 0)} slid)")
    for tier, row in tiers_payload.items():
        print(f"  tier {tier} ({row['name']}): {row['count']} "
              f"({100 * row['share']:.1f}%), wall p50 {row['wall_p50_s']:.4f}s "
              f"p99 {row['wall_p99_s']:.4f}s")
    if payload["mean_quality"] is not None:
        print(f"mean quality (makespan / lower bound): "
              f"{payload['mean_quality']:.4f}")
    print("sources: " + ", ".join(
        f"{s}x{c}" for s, c in sorted(sources.items())))
    if misses:
        print(f"DEADLINE MISSES: {len(misses)} re-solve(s) ran past their "
              "budget — the ladder's cost model is miscalibrated for this "
              "host")
        for ev in misses[:5]:
            print(f"  tier {ev.get('tier')} wall {ev.get('wall_s')}s "
                  f"> deadline {ev.get('deadline_s')}s "
                  f"(n_tasks={ev.get('n_tasks')}, source={ev.get('source')})")
        return 1
    print("deadline misses: 0")
    return 0


def _cmd_mfu(args: argparse.Namespace) -> int:
    import glob
    import os

    from saturn_tpu.utils import metrics

    if os.path.isdir(args.path):
        paths = sorted(glob.glob(
            os.path.join(args.path, "**", "*.jsonl"), recursive=True
        ))
        if not paths:
            print(f"no *.jsonl metrics files under {args.path!r}",
                  file=sys.stderr)
            return 2
    else:
        paths = [args.path]

    events = []
    for p in paths:
        try:
            events.extend(metrics.read_events(p, kind="task_interval"))
        except OSError as e:
            print(f"cannot read metrics at {p!r}: {e}", file=sys.stderr)
            return 2

    # tflops/mfu are additive fields: intervals recorded with metrics off
    # mid-run, or whose step couldn't be shardflow-traced, simply lack them.
    perf = [ev for ev in events
            if isinstance(ev.get("tflops"), (int, float))
            and isinstance(ev.get("mfu"), (int, float))]

    def summarize(group_key):
        groups: dict = {}
        for ev in perf:
            groups.setdefault(str(ev.get(group_key, "?")), []).append(ev)
        out = {}
        for name, evs in sorted(groups.items()):
            tf = [float(e["tflops"]) for e in evs]
            mf = [float(e["mfu"]) for e in evs]
            out[name] = {
                "intervals": len(evs),
                "tflops_p50": round(_percentile(tf, 0.50), 4),
                "tflops_p99": round(_percentile(tf, 0.99), 4),
                "mfu_p50": round(_percentile(mf, 0.50), 6),
                "mfu_p99": round(_percentile(mf, 0.99), 6),
            }
        return out

    payload = {
        "intervals": len(events),
        "with_perf": len(perf),
        "tasks": summarize("task"),
        "techniques": summarize("technique"),
    }
    if args.json:
        print(json.dumps(payload, sort_keys=True))
        return 0
    if not events:
        print(f"{args.path}: no task_interval events")
        return 0
    print(f"{args.path}: {len(events)} interval(s), "
          f"{len(perf)} with achieved-perf fields")
    for title, rows in (("task", payload["tasks"]),
                        ("technique", payload["techniques"])):
        for name, row in rows.items():
            print(f"  {title} {name}: {row['intervals']} interval(s), "
                  f"TFLOP/s p50 {row['tflops_p50']:.3f} "
                  f"p99 {row['tflops_p99']:.3f}, "
                  f"MFU p50 {100 * row['mfu_p50']:.2f}% "
                  f"p99 {100 * row['mfu_p99']:.2f}%")
    return 0


def _cmd_fusion(args: argparse.Namespace) -> int:
    from saturn_tpu.utils import metrics

    try:
        intervals = metrics.read_events(args.path, kind="fused_interval")
        unfuses = metrics.read_events(args.path, kind="fused_unfuse")
        trials = metrics.read_events(args.path, kind="trial_fused")
    except OSError as e:
        print(f"cannot read metrics at {args.path!r}: {e}", file=sys.stderr)
        return 2

    groups: dict = {}
    for ev in intervals:
        key = tuple(ev.get("members") or [])
        row = groups.setdefault(key, {
            "members": list(key), "intervals": 0, "batches": 0,
            "per_step_s": [], "samples_per_sec": [],
            "detached": [], "faulted": [],
        })
        row["intervals"] += 1
        row["batches"] += int(ev.get("batches", 0))
        row["per_step_s"].append(float(ev.get("per_step_s", 0.0)))
        row["samples_per_sec"].append(float(ev.get("samples_per_sec", 0.0)))
        row["detached"].extend(ev.get("detached") or [])
        row["faulted"].extend(ev.get("faulted") or [])
    unfuse_rows = [
        {"task": ev.get("task"), "group": ev.get("group"),
         "step": ev.get("step"), "n_remaining": ev.get("n_remaining")}
        for ev in unfuses
    ]
    trial_rows = [
        {"tasks": ev.get("tasks"), "size": ev.get("size"),
         "feasible": ev.get("feasible"),
         "per_step_s": ev.get("per_step_s")}
        for ev in trials
    ]

    payload = {
        "groups": [
            {
                "members": row["members"],
                "intervals": row["intervals"],
                "lockstep_batches": row["batches"],
                "per_step_p50_s": round(
                    _percentile(row["per_step_s"], 0.50), 6),
                "samples_per_sec_last": (
                    row["samples_per_sec"][-1]
                    if row["samples_per_sec"] else 0.0),
                "detached": row["detached"],
                "faulted": sorted(set(row["faulted"])),
            }
            for row in groups.values()
        ],
        "unfuse_events": unfuse_rows,
        "fused_trials": trial_rows,
    }
    if args.json:
        print(json.dumps(payload, sort_keys=True))
        return 0
    if not (groups or unfuse_rows or trial_rows):
        print(f"{args.path}: no fusion events "
              "(fused_interval / fused_unfuse / trial_fused)")
        return 0
    for row in payload["groups"]:
        print(f"group {'+'.join(row['members'])}: "
              f"{row['intervals']} interval(s), "
              f"{row['lockstep_batches']} lockstep batch(es), "
              f"per-step p50 {row['per_step_p50_s']:.4f}s, "
              f"last {row['samples_per_sec_last']:.1f} samples/s")
        if row["detached"]:
            print(f"  detached: {', '.join(row['detached'])}")
        if row["faulted"]:
            print(f"  faulted: {', '.join(row['faulted'])}")
    for ev in unfuse_rows:
        print(f"unfuse: {ev['task']} left {ev['group']} at interval step "
              f"{ev['step']} ({ev['n_remaining']} member(s) remained)")
    for ev in trial_rows:
        verdict = (f"{ev['per_step_s']:.4f}s/lockstep step"
                   if ev.get("feasible") and ev.get("per_step_s") is not None
                   else "infeasible")
        print(f"trial: {'+'.join(ev['tasks'] or [])} @ size {ev['size']}: "
              f"{verdict}")
    return 0


def _twin_shares(counts: dict) -> dict:
    total = sum(counts.values())
    if total == 0:
        return {}
    return {k: round(v / total, 6) for k, v in sorted(counts.items())}


def _twin_journal_makespan(trace_dir: str, fallback: float) -> float:
    """First submission -> last terminal ``job_state`` record, in journal
    time — the duration the journaled run actually witnessed.  Falls back
    to the submission span when no terminal record exists."""
    from saturn_tpu.durability import journal as jmod

    first: Optional[float] = None
    last: Optional[float] = None
    for rec in jmod.replay_reconciled(trace_dir):
        kind = rec.get("kind")
        ts = float(rec.get("ts", 0.0))
        if kind == "job_submitted" and first is None:
            first = ts
        elif (kind == "job_state"
              and rec.get("data", {}).get("state")
              in ("DONE", "FAILED", "EVICTED")):
            last = ts
    if first is None or last is None or last <= first:
        return fallback
    return last - first


def _twin_fidelity(summary: dict, trace_dir: str,
                   real_metrics: Optional[str]) -> dict:
    """Fidelity deltas of a campaign summary vs a journaled real run.

    Without ``--real-metrics`` the real side has no ``solver_tier`` stream,
    so the tier comparison is skipped (both sides empty) rather than
    spuriously failed.  The real makespan reference is the journal's own
    witnessed duration (first submit -> last terminal state).
    """
    from saturn_tpu.twin.trace import fidelity_compare, load_trace, tier_shares

    trace = load_trace(trace_dir)
    real = {
        "tier_shares": tier_shares(real_metrics) if real_metrics else {},
        "verdict_shares": trace.verdict_shares,
        "makespan_s": _twin_journal_makespan(trace_dir, trace.span_s),
    }
    twin = {
        "tier_shares": (summary.get("tier_shares")
                        or _twin_shares(summary.get("tier_counts", {})))
        if real_metrics else {},
        "verdict_shares": (summary.get("verdict_shares")
                           or _twin_shares(summary.get("admission", {}))),
        "makespan_s": float(summary.get("makespan_s", 0.0)),
    }
    out = fidelity_compare(twin, real)
    out["reference"] = {
        "trace_dir": trace_dir,
        "real_metrics": real_metrics,
        "real_makespan_s": round(real["makespan_s"], 6),
    }
    return out


def _twin_report_whatif(path: str, verdict: dict, as_json: bool) -> int:
    comparison = verdict.get("comparison", {})
    misses = sum(int(row.get("deadline_misses", 0))
                 for row in comparison.values())
    if as_json:
        print(json.dumps({"whatif": comparison, "deadline_misses": misses},
                         sort_keys=True))
        return 1 if misses else 0
    print(f"{path}: capacity what-if ({len(comparison)} scenario(s))")
    for name in ("base", "add-slice", "relax-deadlines"):
        row = comparison.get(name)
        if row is None:
            continue
        print(f"  {name}: completed {row['completed']}, "
              f"failed {row['failed']}, evicted {row['evicted']}, "
              f"shed {row['shed_total']}, "
              f"pressure sheds {row['pressure_sheds']}, "
              f"misses {row['deadline_misses']}, "
              f"makespan {row['makespan_s']:.3f} sim s")
    if misses:
        print(f"DEADLINE MISSES: {misses} across scenarios")
        return 1
    return 0


def _cmd_twin(args: argparse.Namespace) -> int:
    import os

    path = args.path
    if args.run is not None:
        from saturn_tpu.twin.runner import (
            CampaignConfig,
            run_campaign,
            run_what_if,
        )

        if args.run == "replay" and not args.trace:
            print("--run replay requires --trace DIR (a durability journal "
                  "from a real run)", file=sys.stderr)
            return 2
        cfg = CampaignConfig(
            n_jobs=args.jobs, n_slices=args.slices,
            chips_per_slice=args.chips, interval_s=args.interval,
            solve_deadline_s=args.solve_deadline, deadline_s=args.deadline,
            max_inflight=args.max_inflight, seed=args.seed,
            storm=(args.run == "storm"),
            trace_dir=(args.trace if args.run == "replay" else None),
        )
        if args.run == "whatif":
            verdict = run_what_if(cfg, path)
            return _twin_report_whatif(path, verdict, args.json)
        run_campaign(cfg, path)

    whatif_path = os.path.join(path, "whatif.json")
    summary_path = os.path.join(path, "summary.json")
    ledger_path = os.path.join(path, "ledger.json")
    try:
        if not os.path.exists(summary_path) and os.path.exists(whatif_path):
            with open(whatif_path) as fh:
                return _twin_report_whatif(path, json.load(fh), args.json)
        source = summary_path if os.path.exists(summary_path) else ledger_path
        with open(source) as fh:
            summary = json.load(fh)
    except (OSError, ValueError) as e:
        print(f"cannot read twin campaign at {path!r}: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return 2

    fidelity = None
    if args.trace:
        try:
            fidelity = _twin_fidelity(summary, args.trace, args.real_metrics)
        except (OSError, ValueError, KeyError) as e:
            print(f"cannot compute fidelity vs {args.trace!r}: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            return 2

    tier_counts = summary.get("tier_counts", {})
    tier_sh = summary.get("tier_shares") or _twin_shares(tier_counts)
    admission = summary.get("admission", {})
    verdict_sh = summary.get("verdict_shares") or _twin_shares(admission)
    misses = int(summary.get("deadline_misses", 0))
    status = summary.get("status", "?")
    payload = {
        "status": status,
        "intervals": summary.get("intervals"),
        "makespan_sim_s": summary.get("makespan_s"),
        "submitted": summary.get("submitted"),
        "duplicates": summary.get("duplicates"),
        "completed": summary.get("completed"),
        "failed": summary.get("failed"),
        "evicted": summary.get("evicted"),
        "admission": admission,
        "verdict_shares": verdict_sh,
        "solves": summary.get("solves"),
        "tier_counts": tier_counts,
        "tier_shares": tier_sh,
        "deadline_misses": misses,
        "gateway_sheds": summary.get("gateway_sheds", {}),
        "shed_total": summary.get("shed_total"),
        "pressure_sheds": summary.get("pressure_sheds"),
        "preemption_requeues": summary.get("preemption_requeues"),
        "retries": summary.get("retries"),
        "crashes": summary.get("crashes"),
        "topology_changes": summary.get("topology_changes"),
    }
    if fidelity is not None:
        payload["fidelity"] = fidelity
    bad = (status != "ok" or misses > 0
           or (fidelity is not None and not fidelity["within_band"]))
    if args.json:
        print(json.dumps(payload, sort_keys=True))
        return 1 if bad else 0

    print(f"{path}: twin campaign {status} — "
          f"{summary.get('intervals', 0)} interval(s), makespan "
          f"{float(summary.get('makespan_s', 0.0)):.3f} sim s")
    print(f"  jobs: {summary.get('submitted', 0)} submitted "
          f"(+{summary.get('duplicates', 0)} dedup hit(s)), "
          f"{summary.get('completed', 0)} completed, "
          f"{summary.get('failed', 0)} failed, "
          f"{summary.get('evicted', 0)} evicted")
    if admission:
        print("  admission: " + ", ".join(
            f"{k} x{v} ({100 * verdict_sh.get(k, 0.0):.1f}%)"
            for k, v in sorted(admission.items())))
    if tier_counts:
        from saturn_tpu.solver.anytime import TIER_NAMES

        print(f"  solver: {summary.get('solves', 0)} re-solve(s); " +
              ", ".join(
                  f"tier {t} ({TIER_NAMES.get(int(t), t)}) x{n} "
                  f"({100 * tier_sh.get(t, 0.0):.1f}%)"
                  for t, n in sorted(tier_counts.items())))
    sheds = summary.get("gateway_sheds", {})
    print(f"  sheds: gateway {summary.get('shed_total', 0)}"
          + (" [" + ", ".join(f"{k} x{v}" for k, v in sorted(sheds.items()))
             + "]" if sheds else "")
          + f", pressure {summary.get('pressure_sheds', 0)}")
    if summary.get("topology_changes") or summary.get("crashes"):
        print(f"  chaos: {summary.get('topology_changes', 0)} topology "
              f"change(s), {summary.get('crashes', 0)} crash(es), "
              f"{summary.get('preemption_requeues', 0)} preemption "
              f"requeue(s), {summary.get('retries', 0)} retry(ies)")
    if fidelity is not None:
        t_max = max(fidelity["tier_share_deltas"].values(), default=0.0)
        v_max = max(fidelity["verdict_share_deltas"].values(), default=0.0)
        tag = "within band" if fidelity["within_band"] else "OUT OF BAND"
        print(f"  fidelity vs {args.trace}: {tag} "
              f"(tier dmax {t_max:.4f}, verdict dmax {v_max:.4f}, "
              f"makespan ratio {fidelity['makespan_ratio']:.4f})")
    if misses:
        print(f"DEADLINE MISSES: {misses} re-solve(s) ran past the budget")
    return 1 if bad else 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m saturn_tpu.analysis",
        description="saturn-lint + saturn-tsan: static plan verifier, JAX "
                    "hot-path analyzer, and thread-mesh concurrency checks",
    )
    parser.add_argument("--json", action="store_true",
                        help="machine-readable report on stdout")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("plan", help="verify a plan JSON file")
    p.add_argument("path")
    p.add_argument("--topology", type=int, default=0, metavar="N",
                   help="device count for capacity-feasibility checks")
    p.set_defaults(fn=_cmd_plan)

    j = sub.add_parser("journal", help="audit a durability journal dir")
    j.add_argument("path")
    j.set_defaults(fn=_cmd_journal)

    t = sub.add_parser("technique", help="lint a registered technique")
    t.add_argument("name")
    t.add_argument("--size", type=int, default=8,
                   help="probe sub-mesh size (default 8)")
    t.set_defaults(fn=_cmd_technique)

    h = sub.add_parser(
        "health", help="inspect (or undo) journaled training-health state"
    )
    h.add_argument("path")
    h.add_argument("--unquarantine", metavar="TASK[:i,j,k]", default=None,
                   help="append a durable un-quarantine record for TASK "
                        "(all its indices, or just i,j,k)")
    h.set_defaults(fn=_cmd_health)

    g = sub.add_parser(
        "gateway",
        help="summarize journaled gateway records: dedup table, idempotent "
             "retry hits, shed ledger, drain markers",
    )
    g.add_argument("path")
    g.set_defaults(fn=_cmd_gateway)

    tn = sub.add_parser(
        "tenancy",
        help="summarize journaled multi-tenant records: per-tenant "
             "admit/shed/burn ledger, lease/epoch history, compile-ahead "
             "hit rate (exit 1 on lease fencing violations)",
    )
    tn.add_argument("path")
    tn.set_defaults(fn=_cmd_tenancy)

    gr = sub.add_parser(
        "grow",
        help="summarize journaled elastic scale-up records: grow events, "
             "backlog drains, defrag waves, migration intent/done pairing, "
             "per-tenant DEFER backlog age (exit 1 on unresolved intents)",
    )
    gr.add_argument("path")
    gr.set_defaults(fn=_cmd_grow)

    c = sub.add_parser(
        "concurrency",
        help="saturn-tsan static pass: lock order, shared state, "
             "blocking-under-lock (SAT-C codes)",
    )
    c.add_argument("paths", nargs="*", metavar="PATH",
                   help="files/directories to analyze (default: the "
                        "audited thread-mesh packages)")
    c.set_defaults(fn=_cmd_concurrency)

    s = sub.add_parser(
        "solver",
        help="summarize anytime tier-ladder solver_tier events from a "
             "metrics JSONL (tier shares, wall p50/p99, deadline misses)",
    )
    s.add_argument("path")
    s.set_defaults(fn=_cmd_solver)

    f = sub.add_parser(
        "fusion",
        help="summarize fused-stack events from a metrics JSONL: group "
             "membership, lockstep throughput, unfuse events, fused trials",
    )
    f.add_argument("path")
    f.set_defaults(fn=_cmd_fusion)

    u = sub.add_parser(
        "mfu",
        help="operator view of achieved TFLOP/s + MFU per task and per "
             "technique from task_interval events (a metrics JSONL, or a "
             "directory of them)",
    )
    u.add_argument("path")
    u.set_defaults(fn=_cmd_mfu)

    x = sub.add_parser(
        "shardflow",
        help="saturn-shardflow: trace every in-tree technique's step "
             "function, propagate its PartitionSpecs, and report the "
             "communication ledger + SAT-X findings",
    )
    x.add_argument("--size", type=int, default=4,
                   help="probe sub-mesh size (default 4)")
    x.add_argument("--ledger", action="store_true",
                   help="also print per-technique collective byte totals")
    x.set_defaults(fn=_cmd_shardflow)

    m = sub.add_parser(
        "memlens",
        help="saturn-memlens: static per-device HBM peak-liveness audit "
             "over every in-tree technique (SAT-M findings; zero compiles)",
    )
    m.add_argument("--size", type=int, default=4,
                   help="probe sub-mesh size (default 4)")
    m.add_argument("--capacity", type=int, default=None,
                   help="per-device HBM capacity in bytes (default: "
                        "SATURN_TPU_HBM_BYTES, then the device's own "
                        "report; unknown capacity skips SAT-M001/M004)")
    m.add_argument("--window", type=int, default=1,
                   help="fused dispatch window K to model (default 1)")
    m.add_argument("--profile", action="store_true",
                   help="also print per-technique peak/persistent/"
                        "transient byte splits")
    m.set_defaults(fn=_cmd_memlens)

    w = sub.add_parser(
        "twin",
        help="inspect (or --run) a saturn-twin campaign dir: makespan, "
             "tier shares, admission mix, shed/evict counts, fidelity "
             "deltas vs a journaled real run",
    )
    w.add_argument("path", metavar="DIR",
                   help="campaign directory (summary.json / ledger.json / "
                        "whatif.json)")
    w.add_argument("--run", choices=("synth", "storm", "replay", "whatif"),
                   default=None,
                   help="execute a fresh campaign into DIR first")
    w.add_argument("--trace", metavar="DIR", default=None,
                   help="durability journal of a real run: the arrival "
                        "source for --run replay, the fidelity reference "
                        "otherwise")
    w.add_argument("--real-metrics", metavar="PATH", default=None,
                   dest="real_metrics",
                   help="the real run's metrics JSONL (enables the "
                        "solver-tier-share fidelity check)")
    w.add_argument("--jobs", type=int, default=200,
                   help="synthesized jobs (default 200)")
    w.add_argument("--slices", type=int, default=4,
                   help="virtual slices (default 4)")
    w.add_argument("--chips", type=int, default=8,
                   help="chips per slice (default 8)")
    w.add_argument("--interval", type=float, default=60.0,
                   help="simulated seconds per interval (default 60)")
    w.add_argument("--solve-deadline", type=float, default=2.0,
                   dest="solve_deadline",
                   help="REAL seconds of solver budget (default 2.0)")
    w.add_argument("--deadline", type=float, default=None,
                   help="per-job deadline in simulated seconds")
    w.add_argument("--max-inflight", type=int, default=64,
                   dest="max_inflight",
                   help="gateway inflight window (default 64)")
    w.add_argument("--seed", type=int, default=7,
                   help="campaign seed (default 7)")
    w.set_defaults(fn=_cmd_twin)

    k = sub.add_parser(
        "ckpt",
        help="inspect a checkpoint directory: per-manifest shard counts, "
             "bytes, pspec fingerprint, corrupt sidecars and orphan shards",
    )
    k.add_argument("path", metavar="DIR")
    k.set_defaults(fn=_cmd_ckpt)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
