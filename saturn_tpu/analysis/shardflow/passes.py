"""Shardflow pass 2: SAT-X diagnostics over ledgers and source.

Two complementary detectors feed one :class:`AnalysisReport`:

- **ledger diagnostics** over an interpreted step trace
  (:func:`analyze_traced`): SAT-X001 implicit reshard on the fused hot
  loop, SAT-X003 fully-replicated intermediate above the size threshold,
  SAT-X004 cross-slice collective inside a ``scan`` body;
- **source diagnostics** (:func:`scan_sources`): SAT-X002
  gather-to-replicated / single-writer patterns — ``process_allgather``
  calls and ``device_put`` to a literal replicated ``NamedSharding`` —
  found by AST walk, the ``utils/checkpoint.py`` wall ROADMAP item 6
  names.

A ``# sanctioned-shardflow: <reason>`` comment on the finding line or in
the contiguous comment block above it downgrades the finding to ``info``
— audited cases stay visible but never gate (the saturn-tsan marker
convention, never silence).

SAT-X005 (static-estimate vs profiled-runtime disagreement) lives in
:mod:`saturn_tpu.analysis.shardflow.prior` next to the estimate it
audits.
"""

from __future__ import annotations

import ast
import logging
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

from saturn_tpu.analysis.diagnostics import AnalysisReport, make

from saturn_tpu.analysis.shardflow.interp import CommLedger, interpret

log = logging.getLogger("saturn_tpu")

SANCTION_MARKER = "sanctioned-shardflow:"

#: SAT-X003 default byte floor for flagging a fully-replicated intermediate.
REPLICATED_THRESHOLD = 1 << 26


def _sanction_in_lines(lines: Sequence[str], line: int) -> Optional[str]:
    """Marker text on ``line`` (1-indexed) or in the contiguous comment
    block immediately above it — the saturn-tsan lookup, re-implemented
    over a plain line list so source and AST findings share it."""
    if 1 <= line <= len(lines):
        text = lines[line - 1]
        if SANCTION_MARKER in text:
            return text.split(SANCTION_MARKER, 1)[1].strip() or "audited"
    ln = line - 1
    while 1 <= ln <= len(lines):
        text = lines[ln - 1]
        if not text.strip().startswith("#"):
            break
        if SANCTION_MARKER in text:
            return text.split(SANCTION_MARKER, 1)[1].strip() or "audited"
        ln -= 1
    return None


def _sanction_at(provenance: str) -> Optional[str]:
    """Resolve a ``file:line`` provenance against its source file's
    sanction markers; eqn#-style provenance can never be sanctioned."""
    path, _, line_s = provenance.rpartition(":")
    try:
        line = int(line_s)
    except ValueError:
        return None
    if not path or not os.path.isfile(path):
        return None
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError:
        return None
    return _sanction_in_lines(lines, line)


def crossing_axes(mesh_axes: Dict[str, int],
                  slice_size: Optional[int]) -> frozenset:
    """Mesh axes whose collectives ride DCN rather than ICI.

    Devices are slice-major (``core/mesh.py``): an aligned block of at most
    one slice never crosses a boundary, and when a block spans slices it is
    the *leading* mesh axis that crosses. So: no axis crosses when the
    total device count fits one slice; otherwise the leading axis does.
    """
    if not slice_size:
        return frozenset()
    total = 1
    for n in mesh_axes.values():
        total *= int(n)
    if total <= slice_size:
        return frozenset()
    leading = next(iter(mesh_axes), None)
    return frozenset({leading} if leading else ())


# --------------------------------------------------------------- ledger pass
def analyze_traced(
    traced: Dict[str, Any],
    report: Optional[AnalysisReport] = None,
    slice_size: Optional[int] = None,
    replicated_threshold: int = REPLICATED_THRESHOLD,
) -> Tuple[AnalysisReport, CommLedger]:
    """SAT-X001/X003/X004 over one ``trace_step`` result."""
    subject = f"shardflow:{traced.get('technique')}@{traced.get('size')}"
    if report is None:
        report = AnalysisReport(subject=subject)
    ledger = interpret(traced, replicated_threshold=replicated_threshold)
    cross = crossing_axes(traced.get("mesh_axes", {}), slice_size)

    for rec in ledger.resharded:
        sanction = _sanction_at(rec.provenance)
        report.add(make(
            "SAT-X001", "info" if sanction else "error",
            f"implicit reshard on the fused hot loop: {rec.primitive} "
            f"mixes shardings over axes {list(rec.axes)} "
            f"({rec.bytes} bytes x{rec.count})"
            + (f" [sanctioned: {sanction}]" if sanction else ""),
            counterexample=rec.to_json(),
            location=rec.provenance, category="shardflow",
        ))

    for nbytes, provenance in ledger.replicated_intermediates:
        sanction = _sanction_at(provenance)
        report.add(make(
            "SAT-X003", "info" if sanction else "warning",
            f"fully-replicated intermediate of {nbytes} bytes "
            f"(>= {replicated_threshold}) — every chip holds a full copy"
            + (f" [sanctioned: {sanction}]" if sanction else ""),
            counterexample={"bytes": nbytes},
            location=provenance, category="shardflow",
        ))

    if cross:
        for rec in ledger.records:
            if rec.scan_depth >= 1 and set(rec.axes) & cross:
                sanction = _sanction_at(rec.provenance)
                report.add(make(
                    "SAT-X004", "info" if sanction else "error",
                    f"cross-slice collective inside a scan body: "
                    f"{rec.op} over {list(rec.axes)} repeats x{rec.count} "
                    f"per step over DCN"
                    + (f" [sanctioned: {sanction}]" if sanction else ""),
                    counterexample=rec.to_json(),
                    location=rec.provenance, category="shardflow",
                ))
    return report, ledger


# --------------------------------------------------------------- source pass
def _is_replicated_namedsharding(node: ast.AST) -> bool:
    """``NamedSharding(mesh, PartitionSpec())`` — a literal everything-to-
    every-chip target."""
    if not (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "NamedSharding"
            and len(node.args) >= 2):
        return False
    spec = node.args[1]
    return (isinstance(spec, ast.Call)
            and isinstance(spec.func, ast.Name)
            and spec.func.id in ("PartitionSpec", "P")
            and not spec.args and not spec.keywords)


def scan_sources(paths: Sequence[str],
                 report: Optional[AnalysisReport] = None) -> AnalysisReport:
    """SAT-X002 over source files: gather-to-replicated / single-writer
    sites (``process_allgather``, ``device_put`` to a replicated
    ``NamedSharding``)."""
    if report is None:
        report = AnalysisReport(subject="shardflow:sources")
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _, names in os.walk(p):
                files += [os.path.join(root, n) for n in sorted(names)
                          if n.endswith(".py")]
        elif p.endswith(".py"):
            files.append(p)
    for path in files:
        try:
            with open(path) as f:
                src = f.read()
            tree = ast.parse(src, filename=path)
        except (OSError, SyntaxError) as e:
            report.add(make(
                "SAT-X000", "error",
                f"source file failed to parse: {type(e).__name__}: {e}",
                location=path, category="shardflow",
            ))
            continue
        lines = src.splitlines()
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None)
            hit = None
            if name == "process_allgather":
                hit = ("process_allgather gathers every shard to every "
                       "process — a single-writer wall at billion scale")
            elif name == "device_put" and any(
                _is_replicated_namedsharding(a) for a in node.args
            ):
                hit = ("device_put to a replicated NamedSharding gathers "
                       "the full value onto every chip")
            if hit is None:
                continue
            sanction = _sanction_in_lines(lines, node.lineno)
            loc = f"{os.path.relpath(path)}:{node.lineno}"
            report.add(make(
                "SAT-X002", "info" if sanction else "error",
                f"gather-to-replicated/single-writer: {hit}"
                + (f" [sanctioned: {sanction}]" if sanction else ""),
                location=loc, category="shardflow",
            ))
    return report


def default_source_paths(repo_root: Optional[str] = None) -> List[str]:
    """The audited packages: the technique hot paths plus the known
    checkpoint gather wall."""
    root = repo_root or os.getcwd()
    candidates = [
        os.path.join(root, "saturn_tpu", "parallel"),
        os.path.join(root, "saturn_tpu", "ops"),
        os.path.join(root, "saturn_tpu", "utils", "checkpoint.py"),
    ]
    return [p for p in candidates if os.path.exists(p)]


# ------------------------------------------------------------ in-tree audit
def _probe_tasks(tmpdir: str):
    """Tiny probe tasks covering the in-tree technique families: a dense
    causal GPT-2 (dp/fsdp/tp/ring/ulysses) and a MoE variant (ep)."""
    from saturn_tpu.core.task import HParams, Task
    from saturn_tpu.data.lm_dataset import make_lm_dataset
    from saturn_tpu.models.gpt2 import build_gpt2
    from saturn_tpu.models.loss import pretraining_loss

    def mk(preset: str) -> Task:
        return Task(
            get_model=lambda **kw: build_gpt2(preset, **kw),
            get_dataloader=lambda: make_lm_dataset(
                context_length=64, batch_size=8, vocab_size=256,
                n_tokens=64 * 8 * 2,
            ),
            loss_fn=pretraining_loss,
            hparams=HParams(lr=1e-3, batch_count=4),
            save_dir=os.path.join(tmpdir, "ckpts"),
        )

    return {"dense": mk("test-tiny"), "moe": mk("moe-test-tiny")}


def analyze_technique(
    tech: Any, task: Any, devices: Sequence[Any],
    config: Optional[Dict[str, Any]] = None,
    report: Optional[AnalysisReport] = None,
    slice_size: Optional[int] = None,
    replicated_threshold: int = REPLICATED_THRESHOLD,
) -> Tuple[AnalysisReport, Optional[CommLedger]]:
    """Trace + interpret + diagnose one (technique, task, size, config)."""
    if config is None:
        grid = tech.candidate_configs(task, len(devices))
        if not grid:
            return report or AnalysisReport(
                subject=f"shardflow:{tech.name}"), None
        config = grid[0]
    traced = tech.trace_step(task, devices, config)
    return analyze_traced(traced, report=report, slice_size=slice_size,
                          replicated_threshold=replicated_threshold)


def audit_intree(
    size: int = 4,
    devices: Optional[Sequence[Any]] = None,
    repo_root: Optional[str] = None,
    slice_size: Optional[int] = None,
) -> Tuple[AnalysisReport, Dict[str, CommLedger]]:
    """The CLI/gate entry point: SAT-X over every registered in-tree
    technique's traced step at a probe size, plus the SAT-X002 source scan
    over the audited packages. Techniques a probe task cannot exercise
    (no candidate configs, missing model hints) are skipped, not failed —
    the gate is about the code that *would* run."""
    import tempfile

    import jax

    from saturn_tpu.parallel import BUILTIN_TECHNIQUES

    report = AnalysisReport(subject="shardflow")
    scan_sources(default_source_paths(repo_root), report=report)

    devs = list(devices) if devices is not None else list(jax.devices())
    probe = min(size, len(devs))
    ledgers: Dict[str, CommLedger] = {}
    with tempfile.TemporaryDirectory() as tmpdir:
        tasks = _probe_tasks(tmpdir)
        for name, cls in sorted(BUILTIN_TECHNIQUES.items()):
            tech = cls() if isinstance(cls, type) else cls
            if not hasattr(tech, "trace_step"):
                continue  # non-SPMD executor (pipeline): out of scope
            task = tasks["moe" if name == "ep" else "dense"]
            try:
                _, ledger = analyze_technique(
                    tech, task, devs[:probe], report=report,
                    slice_size=slice_size,
                )
            except Exception as e:
                report.add(make(
                    "SAT-X000", "warning",
                    f"technique {name!r} could not be traced at size "
                    f"{probe}: {type(e).__name__}: {e}",
                    category="shardflow",
                ))
                continue
            if ledger is not None:
                ledgers[name] = ledger
    return report, ledgers
