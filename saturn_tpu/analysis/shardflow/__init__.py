"""saturn-shardflow: jaxpr-level sharding propagation + comm-cost analysis.

Three passes over a technique's traced step function (abstract values
only — CPU, no chip):

- :mod:`.interp` — propagate the technique's PartitionSpecs through every
  jaxpr equation into a per-collective communication ledger (op, mesh
  axes, bytes = elements x dtype x axis factor);
- :mod:`.passes` — SAT-X diagnostics with file:line-ish jaxpr provenance
  (SAT-X001 implicit reshard, SAT-X002 gather-to-replicated source scan,
  SAT-X003 oversized replicated intermediate, SAT-X004 cross-slice
  collective inside a scan), sanctionable via
  ``# sanctioned-shardflow: reason`` markers (downgrade to info, never
  silence);
- :mod:`.prior` — the cold-start solver prior: the byte ledger priced by
  a roofline hardware model into ``static_prior=True`` strategies that
  make ADMIT/DEFER and first plans sharding-aware before the trial
  runner has run, with SAT-X005 auditing the estimate once real
  measurements supersede it.

Import-light at package level (the CLI must be able to set XLA device
flags before jax loads); everything heavier is imported inside functions.
"""

from __future__ import annotations

#: Version of the shardflow rule set (propagation rules, ledger schema,
#: prior cost model). Folded into the profile-cache fingerprint and the
#: AOT-cache runtime identity so profiles and executables recorded under
#: one rule set miss cleanly under another.
PASS_VERSION = 1

__all__ = ["PASS_VERSION"]
