"""Shardflow pass 1: abstract interpretation of a traced step jaxpr.

Walks every equation of a technique's traced ``train_step`` (abstract
values only — CPU, no chip) carrying a per-variable sharding spec, and
records every communication event GSPMD would have to materialize into a
:class:`CommLedger`: explicit collectives (``psum`` / ``all_gather`` /
``all_to_all`` / ``ppermute`` from shard_map techniques) are counted
directly, while for pjit/GSPMD techniques the collectives are *predicted*
from the propagation rules (GSPMD, arxiv 2105.04663):

- a dot_general contracting a dimension sharded the same way on both
  operands produces partial sums -> **all-reduce** of the output;
- a dot_general operand sharded on an axis the output cannot carry (the
  ZeRO-3 parameter pattern) is **all-gathered** first;
- a reduction over a sharded dimension -> **all-reduce**;
- a gather from an operand sharded on its indexed dimension (the
  vocab-sharded embedding) -> masked local gather + **all-reduce**;
- two genuinely conflicting shardings meeting in one elementwise op ->
  an **implicit reshard** (SAT-X001 material — never intended).

Known approximation (documented, tolerance-checked by the differential
test): the ZeRO gradient reduce-scatter is modelled as an all-reduce —
the byte totals differ by the well-known 2x ring factor, and XLA's
all-reduce combiner merges per-parameter collectives, so the ledger's
*per-class byte totals* are the comparable quantity, not raw op counts.

Wire bytes use the standard ring-algorithm cost factors over the axis
group size ``n``: all-reduce ``2(n-1)/n``, all-gather / reduce-scatter /
all-to-all ``(n-1)/n``, ppermute ``1``.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

log = logging.getLogger("saturn_tpu")

#: One sharding spec: per-dimension tuple of mesh axis names (empty tuple =
#: replicated along that dimension).
Spec = Tuple[Tuple[str, ...], ...]

_ELEMENTWISE = frozenset({
    "add", "sub", "mul", "div", "max", "min", "pow", "rem", "atan2",
    "exp", "exp2", "log", "log1p", "expm1", "tanh", "logistic", "erf",
    "erfc", "erf_inv", "rsqrt", "sqrt", "cbrt", "neg", "abs", "sign",
    "floor", "ceil", "round", "sin", "cos", "tan", "asin", "acos", "atan",
    "sinh", "cosh", "convert_element_type", "integer_pow", "not", "and",
    "or", "xor", "shift_left", "shift_right_logical",
    "shift_right_arithmetic", "eq", "ne", "lt", "le", "gt", "ge",
    "select_n", "clamp", "nextafter", "is_finite", "stop_gradient",
    "copy", "real", "imag", "square", "logistic", "rng_uniform",
})

_REDUCERS = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod", "reduce_and",
    "reduce_or", "reduce_xor", "argmax", "argmin",
})

#: Wire-cost factor per collective class for an axis group of size n.
_WIRE_FACTOR = {
    "all_reduce": lambda n: 2.0 * (n - 1) / n,
    "all_gather": lambda n: (n - 1) / n,
    "reduce_scatter": lambda n: (n - 1) / n,
    "all_to_all": lambda n: (n - 1) / n,
    "ppermute": lambda n: 1.0,
    "reshard": lambda n: (n - 1) / n,
}


@dataclass
class CollectiveRecord:
    """One (possibly scan-repeated) communication event in the ledger."""

    op: str                    # all_reduce | all_gather | all_to_all |
    #                            ppermute | reduce_scatter | reshard
    axes: Tuple[str, ...]      # mesh axes the transfer spans
    bytes: int                 # logical payload bytes per occurrence
    wire_bytes: float          # ring-cost bytes per occurrence
    count: int                 # occurrences per step (scan trip counts folded)
    primitive: str             # jaxpr primitive that produced it
    provenance: str            # file:line-ish origin (source_info or eqn#)
    scan_depth: int = 0        # 0 = top level, >=1 = inside a scan body
    explicit: bool = False     # present in the jaxpr vs predicted by GSPMD

    def to_json(self) -> Dict[str, Any]:
        return {
            "op": self.op,
            "axes": list(self.axes),
            "bytes": self.bytes,
            "wire_bytes": round(self.wire_bytes, 1),
            "count": self.count,
            "primitive": self.primitive,
            "provenance": self.provenance,
            "scan_depth": self.scan_depth,
            "explicit": self.explicit,
        }


@dataclass
class CommLedger:
    """Per-collective communication ledger for one traced step."""

    records: List[CollectiveRecord] = field(default_factory=list)
    flops: float = 0.0         # dense dot_general flops per step (global)
    resharded: List[CollectiveRecord] = field(default_factory=list)
    replicated_intermediates: List[Tuple[int, str]] = field(
        default_factory=list
    )  # (bytes, provenance) of large fully-replicated eqn outputs

    def add(self, rec: CollectiveRecord) -> None:
        self.records.append(rec)
        if rec.op == "reshard":
            self.resharded.append(rec)

    def total_bytes(self) -> int:
        return sum(r.bytes * r.count for r in self.records)

    def total_wire_bytes(self) -> float:
        return sum(r.wire_bytes * r.count for r in self.records)

    def by_op(self) -> Dict[str, Dict[str, float]]:
        out: Dict[str, Dict[str, float]] = {}
        for r in self.records:
            agg = out.setdefault(
                r.op, {"count": 0, "bytes": 0, "wire_bytes": 0.0}
            )
            agg["count"] += r.count
            agg["bytes"] += r.bytes * r.count
            agg["wire_bytes"] += r.wire_bytes * r.count
        return out

    def to_json(self) -> Dict[str, Any]:
        return {
            "flops": self.flops,
            "total_bytes": self.total_bytes(),
            "total_wire_bytes": round(self.total_wire_bytes(), 1),
            "by_op": self.by_op(),
            "records": [r.to_json() for r in self.records],
        }


def _itemsize(aval: Any) -> int:
    try:
        return int(aval.dtype.itemsize)
    except Exception:
        return 4


def _nbytes(aval: Any) -> int:
    try:
        n = 1
        for d in aval.shape:
            n *= int(d)
        return n * _itemsize(aval)
    except Exception:
        return 0


def _provenance(eqn: Any, index: int) -> str:
    """file:line-ish origin of one equation — the user frame from jax's
    source_info when available, else a stable eqn# handle."""
    try:
        from jax._src import source_info_util

        frame = source_info_util.user_frame(eqn.source_info)
        if frame is not None:
            return f"{frame.file_name}:{frame.start_line}"
    except Exception:
        pass
    return f"eqn#{index}:{eqn.primitive.name}"


def _replicated(aval: Any) -> Spec:
    return tuple(() for _ in getattr(aval, "shape", ()))


def _axis_group_size(axes: Sequence[str], mesh_axes: Dict[str, int]) -> int:
    n = 1
    for a in axes:
        n *= int(mesh_axes.get(a, 1))
    return max(n, 1)


def _from_pspec(pspec: Any, rank: int) -> Spec:
    """Normalize a PartitionSpec (or None) to the interpreter's Spec form."""
    entries = tuple(pspec) if pspec is not None else ()
    out: List[Tuple[str, ...]] = []
    for d in range(rank):
        e = entries[d] if d < len(entries) else None
        if e is None:
            out.append(())
        elif isinstance(e, str):
            out.append((e,))
        else:
            out.append(tuple(e))
    return tuple(out)


class Interpreter:
    """One pass over one closed jaxpr, collecting a :class:`CommLedger`.

    ``mesh_axes`` maps axis name -> size. ``replicated_threshold`` is the
    SAT-X003 byte floor for flagging fully-replicated intermediates.
    """

    def __init__(
        self,
        mesh_axes: Dict[str, int],
        replicated_threshold: int = 1 << 26,
    ) -> None:
        self.mesh_axes = dict(mesh_axes)
        self.replicated_threshold = int(replicated_threshold)
        self.ledger = CommLedger()
        # > 0 while interpreting a shard_map body: avals there are
        # per-shard and sharding is manual, so the implicit GSPMD rules
        # (dot resharding, reduce-over-sharded-dim, SAT-X003) must not
        # fire — only the body's explicit collectives count.
        self._shmap_depth = 0

    # ------------------------------------------------------------- plumbing
    def run(self, closed: Any, in_specs: Sequence[Spec]) -> List[Spec]:
        jaxpr = getattr(closed, "jaxpr", closed)
        consts = getattr(closed, "consts", ())
        env: Dict[Any, Spec] = {}
        for cv, c in zip(jaxpr.constvars, consts):
            env[cv] = _replicated(cv.aval)
        for cv in jaxpr.constvars:
            env.setdefault(cv, _replicated(cv.aval))
        invars = list(jaxpr.invars)
        specs = list(in_specs)
        if len(specs) < len(invars):
            # leading invars without a declared spec (captured consts in
            # some call primitives): treat as replicated, align at the end
            pad = len(invars) - len(specs)
            specs = [_replicated(v.aval) for v in invars[:pad]] + specs
        for v, s in zip(invars, specs):
            env[v] = self._fit(s, v.aval)
        self._interpret(jaxpr, env, multiplier=1, scan_depth=0)
        return [self._read(env, v) for v in jaxpr.outvars]

    def _fit(self, spec: Any, aval: Any) -> Spec:
        rank = len(getattr(aval, "shape", ()))
        if spec is None:
            return tuple(() for _ in range(rank))
        spec = tuple(spec)
        if len(spec) < rank:
            spec = spec + tuple(() for _ in range(rank - len(spec)))
        return tuple(tuple(e) if not isinstance(e, str) else (e,)
                     for e in spec[:rank])

    def _read(self, env: Dict[Any, Spec], atom: Any) -> Spec:
        if hasattr(atom, "val"):          # Literal
            return _replicated(atom.aval)
        return env.get(atom, _replicated(atom.aval))

    def _record(self, op: str, axes: Sequence[str], payload: int,
                eqn: Any, index: int, multiplier: int, scan_depth: int,
                explicit: bool = False) -> None:
        axes = tuple(a for a in axes if a in self.mesh_axes)
        n = _axis_group_size(axes, self.mesh_axes)
        if n <= 1:
            return  # a 1-wide axis moves no bytes
        self.ledger.add(CollectiveRecord(
            op=op, axes=axes, bytes=int(payload),
            wire_bytes=_WIRE_FACTOR[op](n) * payload,
            count=max(int(multiplier), 1),
            primitive=eqn.primitive.name,
            provenance=_provenance(eqn, index),
            scan_depth=scan_depth, explicit=explicit,
        ))

    # ---------------------------------------------------------- interpreter
    def _interpret(self, jaxpr: Any, env: Dict[Any, Spec],
                   multiplier: int, scan_depth: int) -> None:
        for index, eqn in enumerate(jaxpr.eqns):
            name = eqn.primitive.name
            in_specs = [self._read(env, v) for v in eqn.invars]
            handler = getattr(self, f"_h_{name}", None)
            if handler is None:
                if name in _ELEMENTWISE:
                    outs = self._elementwise(eqn, in_specs, index,
                                             multiplier, scan_depth)
                elif name in _REDUCERS:
                    outs = self._reduce(eqn, in_specs, index,
                                        multiplier, scan_depth)
                else:
                    outs = [_replicated(v.aval) for v in eqn.outvars]
            else:
                outs = handler(eqn, in_specs, index, multiplier, scan_depth)
            for v, s in zip(eqn.outvars, outs):
                if not hasattr(v, "aval"):
                    continue
                fitted = self._fit(s, v.aval)
                env[v] = fitted
                nb = _nbytes(v.aval)
                if (
                    nb >= self.replicated_threshold
                    and self._shmap_depth == 0
                    and all(not e for e in fitted)
                    and len(fitted) > 0
                ):
                    self.ledger.replicated_intermediates.append(
                        (nb, _provenance(eqn, index))
                    )

    # elementwise: unify; conflicting non-trivial shardings -> reshard
    def _elementwise(self, eqn, in_specs, index, multiplier, scan_depth):
        out_aval = eqn.outvars[0].aval
        rank = len(getattr(out_aval, "shape", ()))
        unified: List[Tuple[str, ...]] = [() for _ in range(rank)]
        for spec, invar in zip(in_specs, eqn.invars):
            if len(spec) != rank:
                continue
            for d in range(rank):
                if not spec[d]:
                    continue
                if not unified[d]:
                    unified[d] = spec[d]
                elif unified[d] != spec[d] and self._shmap_depth == 0:
                    # genuine conflict: GSPMD inserts a resharding transfer
                    self._record(
                        "reshard", set(unified[d]) | set(spec[d]),
                        _nbytes(invar.aval), eqn, index, multiplier,
                        scan_depth,
                    )
        return [tuple(unified) for _ in eqn.outvars]

    def _reduce(self, eqn, in_specs, index, multiplier, scan_depth):
        axes_param = eqn.params.get("axes", ())
        spec = in_specs[0] if in_specs else ()
        reduced_mesh_axes: List[str] = []
        out_spec: List[Tuple[str, ...]] = []
        for d, e in enumerate(spec):
            if d in axes_param:
                reduced_mesh_axes.extend(e)
            else:
                out_spec.append(e)
        if reduced_mesh_axes and self._shmap_depth == 0:
            self._record("all_reduce", reduced_mesh_axes,
                         _nbytes(eqn.outvars[0].aval), eqn, index,
                         multiplier, scan_depth)
        return [tuple(out_spec) for _ in eqn.outvars]

    # ---------------------------------------------------------- dot_general
    def _h_dot_general(self, eqn, in_specs, index, multiplier, scan_depth):
        (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
        lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
        ls, rs = in_specs[0], in_specs[1]

        # flops: 2 * |out| * |contraction|
        out_elems = 1
        for d in getattr(eqn.outvars[0].aval, "shape", ()):
            out_elems *= int(d)
        contract = 1
        for d in lc:
            contract *= int(lhs.shape[d])
        flops = 2.0 * out_elems * contract * max(multiplier, 1)
        if self._shmap_depth > 0:
            # body avals are per-shard; scale to the global total so both
            # trace styles report the same workload flops
            for n in self.mesh_axes.values():
                flops *= max(int(n), 1)
        self.ledger.flops += flops

        # output sharding skeleton: batch dims, then lhs free, then rhs free
        l_free = [d for d in range(len(lhs.shape)) if d not in lc and d not in lb]
        r_free = [d for d in range(len(rhs.shape)) if d not in rc and d not in rb]
        out_spec: List[Tuple[str, ...]] = []
        used_axes: set = set()
        for d in lb:
            out_spec.append(ls[d] if d < len(ls) else ())
            used_axes.update(out_spec[-1])
        for d in l_free:
            out_spec.append(ls[d] if d < len(ls) else ())
            used_axes.update(out_spec[-1])

        # rhs free dims: an axis already claimed by the lhs side cannot
        # shard the output a second way — GSPMD all-gathers the rhs (the
        # ZeRO-3 parameter pattern: W sharded on 'data' meets a
        # 'data'-sharded batch).
        implicit = self._shmap_depth == 0
        rhs_gathered = False
        for d in r_free:
            e = rs[d] if d < len(rs) else ()
            if e and set(e) & used_axes:
                if not rhs_gathered and implicit:
                    self._record("all_gather", e, _nbytes(rhs), eqn, index,
                                 multiplier, scan_depth)
                    rhs_gathered = True
                out_spec.append(())
            else:
                out_spec.append(e)
                used_axes.update(e)

        # contracting dims: same axis on both sides -> partial sums ->
        # all-reduce of the output. Sharded on exactly one side -> that
        # operand must be gathered along the contraction.
        reduce_axes: List[str] = []
        for dl, dr in zip(lc, rc):
            el = set(ls[dl]) if dl < len(ls) else set()
            er = set(rs[dr]) if dr < len(rs) else set()
            both = el & er
            reduce_axes.extend(sorted(both))
            only_l = el - er
            only_r = er - el
            if only_l and implicit:
                self._record("all_gather", sorted(only_l), _nbytes(lhs),
                             eqn, index, multiplier, scan_depth)
            if only_r and not rhs_gathered and implicit:
                self._record("all_gather", sorted(only_r), _nbytes(rhs),
                             eqn, index, multiplier, scan_depth)
        if reduce_axes and implicit:
            self._record("all_reduce", reduce_axes,
                         _nbytes(eqn.outvars[0].aval), eqn, index,
                         multiplier, scan_depth)
        return [tuple(out_spec)]

    # ------------------------------------------------------- shape plumbing
    def _h_broadcast_in_dim(self, eqn, in_specs, index, multiplier, scan_depth):
        bd = eqn.params["broadcast_dimensions"]
        out_rank = len(eqn.outvars[0].aval.shape)
        spec = in_specs[0] if in_specs else ()
        out = [() for _ in range(out_rank)]
        in_shape = getattr(eqn.invars[0].aval, "shape", ())
        for i, d in enumerate(bd):
            if i < len(spec) and i < len(in_shape) and int(in_shape[i]) > 1:
                out[d] = spec[i]
        return [tuple(out)]

    def _h_transpose(self, eqn, in_specs, index, multiplier, scan_depth):
        perm = eqn.params["permutation"]
        spec = in_specs[0]
        return [tuple(spec[p] if p < len(spec) else () for p in perm)]

    def _h_reshape(self, eqn, in_specs, index, multiplier, scan_depth):
        in_shape = tuple(int(d) for d in eqn.invars[0].aval.shape)
        out_shape = tuple(int(d) for d in eqn.outvars[0].aval.shape)
        spec = in_specs[0]
        out: List[Tuple[str, ...]] = [() for _ in out_shape]
        # conservative: carry specs only across a dimension-preserving
        # prefix/suffix; split or merged dims silently drop to replicated
        # (a dropped spec can only *miss* communication, never invent it)
        i = 0
        while (i < len(in_shape) and i < len(out_shape)
               and in_shape[i] == out_shape[i]):
            if i < len(spec):
                out[i] = spec[i]
            i += 1
        j = 0
        while (j < len(in_shape) - i and j < len(out_shape) - i
               and in_shape[-1 - j] == out_shape[-1 - j]):
            k = len(spec) - 1 - j
            if 0 <= k:
                out[len(out_shape) - 1 - j] = spec[k]
            j += 1
        return [tuple(out)]

    def _h_squeeze(self, eqn, in_specs, index, multiplier, scan_depth):
        dims = set(eqn.params["dimensions"])
        spec = in_specs[0]
        return [tuple(e for d, e in enumerate(spec) if d not in dims)]

    def _h_expand_dims(self, eqn, in_specs, index, multiplier, scan_depth):
        dims = set(eqn.params["dimensions"])
        out_rank = len(eqn.outvars[0].aval.shape)
        spec = list(in_specs[0])
        out: List[Tuple[str, ...]] = []
        src = 0
        for d in range(out_rank):
            if d in dims:
                out.append(())
            else:
                out.append(spec[src] if src < len(spec) else ())
                src += 1
        return [tuple(out)]

    def _h_concatenate(self, eqn, in_specs, index, multiplier, scan_depth):
        dim = eqn.params["dimension"]
        rank = len(eqn.outvars[0].aval.shape)
        out = [() for _ in range(rank)]
        for spec in in_specs:
            for d in range(min(rank, len(spec))):
                if d != dim and spec[d] and not out[d]:
                    out[d] = spec[d]
        return [tuple(out)]

    def _h_slice(self, eqn, in_specs, index, multiplier, scan_depth):
        return [in_specs[0]]

    def _h_dynamic_slice(self, eqn, in_specs, index, multiplier, scan_depth):
        return [in_specs[0]]

    def _h_dynamic_update_slice(self, eqn, in_specs, index, multiplier,
                                scan_depth):
        return [in_specs[0]]

    def _h_pad(self, eqn, in_specs, index, multiplier, scan_depth):
        return [in_specs[0]]

    def _h_gather(self, eqn, in_specs, index, multiplier, scan_depth):
        """take/embedding-lookup pattern: a sharded table (vocab-sharded
        wte) forces a masked local gather + all-reduce of the result."""
        operand_spec = in_specs[0]
        idx_spec = in_specs[1] if len(in_specs) > 1 else ()
        out_rank = len(eqn.outvars[0].aval.shape)
        table_axes = sorted({a for e in operand_spec for a in e})
        if table_axes and self._shmap_depth == 0:
            self._record("all_reduce", table_axes,
                         _nbytes(eqn.outvars[0].aval), eqn, index,
                         multiplier, scan_depth)
        out = [() for _ in range(out_rank)]
        for d in range(min(out_rank, len(idx_spec))):
            out[d] = idx_spec[d]
        return [tuple(out)]

    # -------------------------------------------------- explicit collectives
    def _named_axes(self, eqn) -> Tuple[str, ...]:
        p = eqn.params
        axes = p.get("axes", p.get("axis_name", ()))
        if isinstance(axes, str):
            axes = (axes,)
        return tuple(a for a in axes if isinstance(a, str))

    def _h_psum(self, eqn, in_specs, index, multiplier, scan_depth):
        axes = self._named_axes(eqn)
        for v in eqn.outvars:
            self._record("all_reduce", axes, _nbytes(v.aval), eqn, index,
                         multiplier, scan_depth, explicit=True)
        return list(in_specs[: len(eqn.outvars)]) or [
            _replicated(v.aval) for v in eqn.outvars
        ]

    # psum inside a shard_map body traces as the ``psum2`` primitive on
    # the jax versions this repo supports — same wire traffic as psum.
    def _h_psum2(self, eqn, in_specs, index, multiplier, scan_depth):
        return self._h_psum(eqn, in_specs, index, multiplier, scan_depth)

    # shard_map's replication-tracking bookkeeping: no bytes move.
    def _h_pbroadcast(self, eqn, in_specs, index, multiplier, scan_depth):
        return list(in_specs[: len(eqn.outvars)]) or [
            _replicated(v.aval) for v in eqn.outvars
        ]

    def _h_pmax(self, eqn, in_specs, index, multiplier, scan_depth):
        return self._h_psum(eqn, in_specs, index, multiplier, scan_depth)

    def _h_pmin(self, eqn, in_specs, index, multiplier, scan_depth):
        return self._h_psum(eqn, in_specs, index, multiplier, scan_depth)

    def _h_all_gather(self, eqn, in_specs, index, multiplier, scan_depth):
        axes = self._named_axes(eqn)
        self._record("all_gather", axes, _nbytes(eqn.outvars[0].aval),
                     eqn, index, multiplier, scan_depth, explicit=True)
        return [_replicated(v.aval) for v in eqn.outvars]

    def _h_all_to_all(self, eqn, in_specs, index, multiplier, scan_depth):
        axes = self._named_axes(eqn)
        self._record("all_to_all", axes, _nbytes(eqn.outvars[0].aval),
                     eqn, index, multiplier, scan_depth, explicit=True)
        return [in_specs[0]]

    def _h_ppermute(self, eqn, in_specs, index, multiplier, scan_depth):
        axes = self._named_axes(eqn)
        self._record("ppermute", axes, _nbytes(eqn.outvars[0].aval),
                     eqn, index, multiplier, scan_depth, explicit=True)
        return list(in_specs[: len(eqn.outvars)]) or [
            _replicated(v.aval) for v in eqn.outvars
        ]

    def _h_psum_scatter(self, eqn, in_specs, index, multiplier, scan_depth):
        axes = self._named_axes(eqn)
        self._record("reduce_scatter", axes,
                     _nbytes(eqn.invars[0].aval), eqn, index, multiplier,
                     scan_depth, explicit=True)
        return [in_specs[0]]

    def _h_axis_index(self, eqn, in_specs, index, multiplier, scan_depth):
        return [_replicated(v.aval) for v in eqn.outvars]

    # --------------------------------------------------- structured control
    def _recurse(self, inner: Any, in_specs: Sequence[Spec],
                 multiplier: int, scan_depth: int) -> List[Spec]:
        jaxpr = getattr(inner, "jaxpr", inner)
        env: Dict[Any, Spec] = {}
        for cv in getattr(jaxpr, "constvars", ()):
            env[cv] = _replicated(cv.aval)
        invars = list(jaxpr.invars)
        specs = list(in_specs)
        if len(specs) < len(invars):
            pad = len(invars) - len(specs)
            specs = [_replicated(v.aval) for v in invars[:pad]] + specs
        for v, s in zip(invars, specs):
            env[v] = self._fit(s, v.aval)
        self._interpret(jaxpr, env, multiplier, scan_depth)
        return [self._read(env, v) for v in jaxpr.outvars]

    def _h_pjit(self, eqn, in_specs, index, multiplier, scan_depth):
        return self._recurse(eqn.params["jaxpr"], in_specs, multiplier,
                             scan_depth)

    def _h_closed_call(self, eqn, in_specs, index, multiplier, scan_depth):
        return self._recurse(eqn.params["call_jaxpr"], in_specs, multiplier,
                             scan_depth)

    def _h_core_call(self, eqn, in_specs, index, multiplier, scan_depth):
        return self._recurse(eqn.params["call_jaxpr"], in_specs, multiplier,
                             scan_depth)

    def _h_remat2(self, eqn, in_specs, index, multiplier, scan_depth):
        return self._recurse(eqn.params["jaxpr"], in_specs, multiplier,
                             scan_depth)

    def _h_remat(self, eqn, in_specs, index, multiplier, scan_depth):
        return self._recurse(eqn.params["jaxpr"], in_specs, multiplier,
                             scan_depth)

    def _h_checkpoint(self, eqn, in_specs, index, multiplier, scan_depth):
        return self._recurse(eqn.params["jaxpr"], in_specs, multiplier,
                             scan_depth)

    def _h_custom_jvp_call(self, eqn, in_specs, index, multiplier, scan_depth):
        return self._recurse(eqn.params["call_jaxpr"], in_specs, multiplier,
                             scan_depth)

    def _h_custom_vjp_call(self, eqn, in_specs, index, multiplier, scan_depth):
        return self._recurse(eqn.params["call_jaxpr"], in_specs, multiplier,
                             scan_depth)

    def _h_custom_vjp_call_jaxpr(self, eqn, in_specs, index, multiplier,
                                 scan_depth):
        return self._recurse(eqn.params["fun_jaxpr"], in_specs, multiplier,
                             scan_depth)

    def _h_scan(self, eqn, in_specs, index, multiplier, scan_depth):
        p = eqn.params
        length = int(p.get("length", 1))
        n_consts = int(p.get("num_consts", 0))
        n_carry = int(p.get("num_carry", 0))
        inner = p["jaxpr"]
        body_in: List[Spec] = []
        for i, spec in enumerate(in_specs):
            if i < n_consts + n_carry:
                body_in.append(spec)
            else:
                body_in.append(tuple(spec[1:]))  # xs lose the scan dim
        body_out = self._recurse(inner, body_in,
                                 multiplier * max(length, 1),
                                 scan_depth + 1)
        outs: List[Spec] = []
        for i, v in enumerate(eqn.outvars):
            s = body_out[i] if i < len(body_out) else _replicated(v.aval)
            if i < n_carry:
                outs.append(s)
            else:
                outs.append(((),) + tuple(s))  # ys gain the scan dim
        return outs

    def _h_while(self, eqn, in_specs, index, multiplier, scan_depth):
        p = eqn.params
        n_cc = int(p.get("cond_nconsts", 0))
        n_bc = int(p.get("body_nconsts", 0))
        carry = in_specs[n_cc + n_bc:]
        body_in = list(in_specs[n_cc: n_cc + n_bc]) + list(carry)
        return self._recurse(p["body_jaxpr"], body_in, multiplier,
                             scan_depth)

    def _h_cond(self, eqn, in_specs, index, multiplier, scan_depth):
        branches = eqn.params["branches"]
        # one representative branch for the ledger; specs from the first
        return self._recurse(branches[0], in_specs[1:], multiplier,
                             scan_depth)

    def _h_shard_map(self, eqn, in_specs, index, multiplier, scan_depth):
        """shard_map body: avals inside are already per-shard; explicit
        collectives in the body are counted directly."""
        p = eqn.params
        inner = p.get("jaxpr")
        in_names = p.get("in_names", ())
        body_in: List[Spec] = []
        jaxpr = getattr(inner, "jaxpr", inner)
        for i, v in enumerate(jaxpr.invars):
            rank = len(getattr(v.aval, "shape", ()))
            names = in_names[i] if i < len(in_names) else {}
            spec = [tuple(names.get(d, ())) for d in range(rank)]
            body_in.append(tuple(spec))
        self._shmap_depth += 1
        try:
            self._recurse(inner, body_in, multiplier, scan_depth)
        finally:
            self._shmap_depth -= 1
        out_names = p.get("out_names", ())
        outs: List[Spec] = []
        for i, v in enumerate(eqn.outvars):
            rank = len(getattr(v.aval, "shape", ()))
            names = out_names[i] if i < len(out_names) else {}
            outs.append(tuple(tuple(names.get(d, ()))
                              for d in range(rank)))
        return outs


def interpret(traced: Dict[str, Any],
              replicated_threshold: int = 1 << 26) -> CommLedger:
    """Run the interpreter over one ``SPMDTechnique.trace_step`` result."""
    import jax
    from jax.sharding import PartitionSpec

    closed = traced["jaxpr"]
    mesh_axes = traced["mesh_axes"]
    state_leaves = jax.tree_util.tree_leaves(traced["state_shapes"])
    spec_leaves = jax.tree_util.tree_leaves(
        traced["state_specs"],
        is_leaf=lambda x: x is None or isinstance(x, PartitionSpec),
    )
    in_specs: List[Spec] = []
    for leaf, pspec in zip(state_leaves, spec_leaves):
        in_specs.append(_from_pspec(pspec, len(leaf.shape)))
    in_specs.append(
        _from_pspec(traced["batch_spec"], len(traced["batch_sds"].shape))
    )
    interp = Interpreter(mesh_axes, replicated_threshold=replicated_threshold)
    interp.run(closed, in_specs)
    return interp.ledger
