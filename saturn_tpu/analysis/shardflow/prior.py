"""Shardflow pass 3: the cold-start solver prior, and its SAT-X005 audit.

Before the trial runner has spent any chip time on a (task, technique,
size) grid point, the only cost signal available used to be the dummy
``DUMMY_RUNTIME`` sentinel — ADMIT/DEFER and the first plan were blind to
sharding. This module turns the shardflow communication ledger into a
**static per-batch-time prior** (Piper's programmable-cost-model framing,
arxiv 2606.11169):

    t_step  =  flops / (chips x peak x MFU)  +  wire_bytes / bandwidth

— roofline compute plus un-overlapped communication (pessimistic on
purpose: a prior that flatters communication-heavy layouts would admit
jobs the mesh cannot actually serve).

Strategies synthesized here are marked ``static_prior=True`` and are
superseded the moment real evidence lands: a trial profile overwrites
them wholesale, and ``Task.apply_realized_feedback`` clears the flag on
the first realized interval. :func:`audit_task` then closes the loop —
SAT-X005 flags any grid point whose static estimate disagreed with the
eventually-measured runtime by more than ``AUDIT_TOLERANCE``, which is
how a drifting cost model gets caught instead of silently steering
admission.

The hardware constants are env-overridable deployment knobs, not
measurements — the prior's job is *relative ordering* across techniques
and sizes, and SAT-X005 polices its absolute error.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Dict, List, Optional, Sequence

from saturn_tpu.analysis.diagnostics import Diagnostic, make

from saturn_tpu.analysis.shardflow.interp import CommLedger, interpret

log = logging.getLogger("saturn_tpu")

#: |static - profiled| / profiled above which SAT-X005 fires.
AUDIT_TOLERANCE = 0.35

_ENV_PEAK = "SATURN_TPU_PRIOR_PEAK_FLOPS"
_ENV_ICI = "SATURN_TPU_PRIOR_ICI_BYTES_S"
_ENV_DCN = "SATURN_TPU_PRIOR_DCN_BYTES_S"
_ENV_MFU = "SATURN_TPU_PRIOR_MFU"
_ENV_OVERLAP_PREFIX = "SATURN_TPU_PRIOR_OVERLAP_"

#: Per-op-class fraction of wire time the overlapped lowering hides under
#: compute (``{"overlap": True}`` grid points: double-buffered ppermute
#: hops in ring/pipeline, collective-matmul / ZeRO-3 prefetch gathers).
#: Static seeds, deliberately conservative; :func:`calibrate_overlap_factors`
#: moves them from the SAT-X005 audit stream and
#: ``SATURN_TPU_PRIOR_OVERLAP_<OP>`` pins them per deployment. Serial grid
#: points keep the fully-pessimistic un-overlapped pricing.
DEFAULT_OVERLAP_FACTORS: Dict[str, float] = {
    "ppermute": 0.7,        # neighbor hop rides under the chunk's compute
    "all_gather": 0.6,      # layer-ahead prefetch / chunked partial products
    "reduce_scatter": 0.3,  # grad scatter partially hides behind backward
    "all_reduce": 0.0,      # grad psum gates the optimizer: critical path
    "all_to_all": 0.0,      # MoE dispatch has no overlapped lowering yet
}

# Calibrated deltas layered over the defaults (process-local; the factor
# set is stamped into the profile-cache fingerprint, so recalibration
# invalidates stale entries instead of silently repricing them).
_calibrated_factors: Dict[str, float] = {}


def _envf(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def overlap_factors() -> Dict[str, float]:
    """Active per-op-class overlap factor set: defaults, then calibration,
    then env pins — each clamped to [0, 1]."""
    out = dict(DEFAULT_OVERLAP_FACTORS)
    out.update(_calibrated_factors)
    for op in out:
        out[op] = min(
            max(_envf(_ENV_OVERLAP_PREFIX + op.upper(), out[op]), 0.0), 1.0
        )
    return out


def overlap_factor_signature() -> str:
    """Stable signature of the active factor set for cache fingerprints."""
    f = overlap_factors()
    return ",".join(f"{k}={f[k]:.4f}" for k in sorted(f))


def hardware_model() -> Dict[str, float]:
    """Roofline constants for the prior (per chip / per link)."""
    return {
        "peak_flops": _envf(_ENV_PEAK, 100e12),   # bf16-class chip
        "ici_bytes_s": _envf(_ENV_ICI, 4.5e10),   # per-link ICI
        "dcn_bytes_s": _envf(_ENV_DCN, 2.5e9),    # per-host DCN
        "mfu": _envf(_ENV_MFU, 0.45),             # the repo's MFU target
    }


def estimate_step_seconds(
    ledger: CommLedger, size: int,
    crossing: Optional[frozenset] = None,
    hw: Optional[Dict[str, float]] = None,
    overlap: bool = False,
    factors: Optional[Dict[str, float]] = None,
) -> float:
    """Static per-batch seconds from one ledger: roofline compute + wire
    time, DCN-priced for axes in ``crossing``. Serial (default) prices every
    collective un-overlapped; ``overlap=True`` discounts each op class by
    the active :func:`overlap_factors` — the pricing for ``overlap`` grid
    points, never for the serial lowering."""
    hw = hw or hardware_model()
    compute = ledger.flops / max(size, 1) / (hw["peak_flops"] * hw["mfu"])
    f = (factors if factors is not None else overlap_factors()) if overlap \
        else {}
    comm = 0.0
    cross = crossing or frozenset()
    for rec in ledger.records:
        bw = hw["dcn_bytes_s"] if set(rec.axes) & cross else hw["ici_bytes_s"]
        comm += (rec.wire_bytes * rec.count / bw) * (
            1.0 - f.get(rec.op, 0.0)
        )
    return max(compute + comm, 1e-9)


def comm_seconds_by_op(
    ledger: CommLedger, crossing: Optional[frozenset] = None,
    hw: Optional[Dict[str, float]] = None,
) -> Dict[str, float]:
    """Un-overlapped wire seconds per op class — the breakdown the
    calibrator needs to attribute a measured overlap win to op classes."""
    hw = hw or hardware_model()
    cross = crossing or frozenset()
    out: Dict[str, float] = {}
    for rec in ledger.records:
        bw = hw["dcn_bytes_s"] if set(rec.axes) & cross else hw["ici_bytes_s"]
        out[rec.op] = out.get(rec.op, 0.0) + rec.wire_bytes * rec.count / bw
    return out


def _resolve_techniques(technique_names: Optional[List[str]]) -> Dict[str, Any]:
    from saturn_tpu import library as lib

    if not lib.registered_names():
        lib.register_default_library()
    names = (technique_names if technique_names is not None
             else lib.registered_names())
    out: Dict[str, Any] = {}
    for n in names:
        cls = lib.retrieve(n)
        tech = cls() if isinstance(cls, type) else cls
        out[getattr(tech, "name", str(n))] = tech
    return out


def synthesize_strategies(
    task: Any,
    topology: Any,
    technique_names: Optional[List[str]] = None,
    sizes: Optional[Sequence[int]] = None,
    max_configs: int = 3,
    slice_size: Optional[int] = None,
) -> List[int]:
    """Fill ``task.strategies`` with ``static_prior=True`` entries for every
    never-profiled size a technique can trace — zero trials, zero compiles.

    For each (technique, size) the prior picks the candidate config with
    the best static estimate (up to ``max_configs`` traced per point), and
    across techniques the fastest estimate wins the grid point — the same
    per-size argmin the trial runner's ``install`` applies to measured
    trials. Returns the sizes synthesized. Existing feasible strategies
    (measured, cached or already-synthesized) are never overwritten.
    """
    from saturn_tpu.analysis.shardflow.passes import crossing_axes
    from saturn_tpu.core.strategy import Strategy
    from saturn_tpu.utils import profile_cache as pcache

    try:
        techs = _resolve_techniques(technique_names)
    except Exception as e:
        log.warning("shardflow prior: technique resolution failed: %r", e)
        return []
    task_sig = pcache.task_signature(task)
    topo_sig = pcache.topology_signature(topology)
    ss = slice_size if slice_size is not None else getattr(
        topology, "slice_size", None)

    chip_range = getattr(task, "chip_range", None)
    grid_sizes = [
        g for g in (sizes if sizes is not None else topology.valid_sizes())
        if chip_range is None or g in chip_range
    ]
    added: List[int] = []
    for g in grid_sizes:
        if g in task.feasible_strategies():
            continue
        try:
            devices = topology.block_devices(topology.blocks(g)[0])
        except Exception:
            continue
        best: Optional[Strategy] = None
        best_t = float("inf")
        for name, tech in sorted(techs.items()):
            if not hasattr(tech, "trace_step"):
                continue
            try:
                grid = tech.candidate_configs(task, g)
            except Exception:
                continue
            for config in grid[:max_configs]:
                try:
                    traced = tech.trace_step(task, devices, config)
                    ledger = interpret(traced)
                except Exception as e:
                    log.debug(
                        "shardflow prior: %s@%d %r untraceable: %r",
                        name, g, config, e,
                    )
                    continue
                cross = crossing_axes(traced["mesh_axes"], ss)
                overlapped = bool(config.get("overlap", False))
                t = estimate_step_seconds(
                    ledger, g, crossing=cross, overlap=overlapped
                )
                if t < best_t:
                    best_t = t
                    # Analytic schedule bubble (pipeline GPipe/1F1B
                    # warmup-cooldown): like the runtime prior itself it
                    # needs no trial, so cold-started strategies price
                    # co-location the same way measured ones do.
                    bubble = 0.0
                    bf = getattr(tech, "config_bubble_fraction", None)
                    if callable(bf):
                        try:
                            bubble = min(max(float(bf(config)), 0.0), 1.0)
                        except Exception:
                            bubble = 0.0
                    best = Strategy(
                        executor=tech,
                        apportionment=g,
                        params=dict(config),
                        runtime=t * max(task.total_batches, 0),
                        per_batch_time=t,
                        static_prior=True,
                        cache_key=pcache.fingerprint(
                            task_sig, name, g, topo_sig
                        ),
                        bubble_fraction=bubble,
                    )
                    best._static_overlap = overlapped
                    best._static_compute_s = estimate_step_seconds(
                        ledger, g, crossing=cross,
                        factors={}, overlap=False,
                    ) - sum(comm_seconds_by_op(ledger, crossing=cross).values())
                    best._static_comm_by_op = comm_seconds_by_op(
                        ledger, crossing=cross
                    )
        if best is not None:
            best._static_prior_estimate = best_t
            task.strategies[g] = best
            added.append(g)
    if added:
        log.info(
            "shardflow prior: synthesized %d static strategy(s) for %s "
            "at sizes %s", len(added), getattr(task, "name", "?"), added,
        )
    return added


# ------------------------------------------------------------ SAT-X005 audit
def audit_point(
    static_s: float, profiled_s: float, technique: str, size: int,
    tolerance: float = AUDIT_TOLERANCE,
) -> Optional[Diagnostic]:
    """SAT-X005 for one grid point, when a profile exists."""
    if profiled_s <= 0.0 or static_s <= 0.0:
        return None
    err = abs(static_s - profiled_s) / profiled_s
    if err <= tolerance:
        return None
    return make(
        "SAT-X005", "warning",
        f"static estimate disagrees with the profiled runtime by "
        f"{100 * err:.0f}% (> {100 * tolerance:.0f}%) for {technique}@"
        f"{size}: static {static_s:.6f}s vs profiled {profiled_s:.6f}s — "
        "the cost prior is miscalibrated for this workload",
        counterexample={
            "technique": technique, "size": size,
            "static_s": round(static_s, 9),
            "profiled_s": round(profiled_s, 9),
            "relative_error": round(err, 4),
        },
        category="shardflow",
    )


def audit_task(task: Any,
               tolerance: float = AUDIT_TOLERANCE) -> List[Diagnostic]:
    """SAT-X005 over every strategy whose static prior has since been
    superseded by real evidence (trial profile or realized feedback)."""
    diags: List[Diagnostic] = []
    for g, strat in getattr(task, "strategies", {}).items():
        static_s = getattr(strat, "_static_prior_estimate", None)
        if static_s is None or getattr(strat, "static_prior", False):
            continue  # never had a prior, or the prior is still live
        tech = getattr(strat.executor, "name", str(strat.executor))
        d = audit_point(float(static_s), float(strat.per_batch_time),
                        tech, g, tolerance=tolerance)
        if d is not None:
            diags.append(d)
    return diags


# ------------------------------------------------ overlap factor calibration
def calibrate_overlap_factors(
    tasks: Sequence[Any], blend: float = 0.25,
) -> Dict[str, float]:
    """Move :func:`overlap_factors` from static seeds toward measured truth.

    Consumes the same stream SAT-X005 audits: strategies synthesized with an
    ``overlap`` config whose static prior has since been superseded by a
    realized measurement (``static_prior`` flipped off in place, so the
    stashed ``_static_*`` decomposition survives). For each such point the
    measured step time implies how much wire time the overlapped lowering
    actually hid::

        hidden = (compute_s + comm_total - measured) / comm_total

    clamped to [0, 1]. One scalar cannot separate op classes, so the update
    is attributed to each class by its share of the static wire time and
    EWMA-blended (weight ``blend`` x share) into the process-local
    calibrated set. The blended factors flow through
    :func:`overlap_factors` into every later :func:`estimate_step_seconds`
    call — cold-start priors, admission, and the anytime solver all re-price
    — and through :func:`overlap_factor_signature` into the profile-cache
    fingerprint, so entries priced under the old factor set miss.

    Returns the active factor set after calibration. Env pins still win.
    """
    n_points = 0
    for task in tasks:
        for strat in getattr(task, "strategies", {}).values():
            if not getattr(strat, "_static_overlap", False):
                continue
            if getattr(strat, "static_prior", False):
                continue  # prior still live: no measurement yet
            comm_by_op = getattr(strat, "_static_comm_by_op", None) or {}
            compute_s = getattr(strat, "_static_compute_s", None)
            measured = float(getattr(strat, "per_batch_time", 0.0) or 0.0)
            comm_total = sum(comm_by_op.values())
            if compute_s is None or comm_total <= 0.0 or measured <= 0.0:
                continue
            hidden = min(
                max((compute_s + comm_total - measured) / comm_total, 0.0),
                1.0,
            )
            active = overlap_factors()
            for op, s in comm_by_op.items():
                w = min(max(blend, 0.0), 1.0) * (s / comm_total)
                base = active.get(op, 0.0)
                _calibrated_factors[op] = min(
                    max((1.0 - w) * base + w * hidden, 0.0), 1.0
                )
            n_points += 1
    if n_points:
        log.info(
            "shardflow prior: calibrated overlap factors from %d measured "
            "point(s): %s", n_points, overlap_factor_signature(),
        )
    return overlap_factors()


def reset_overlap_calibration() -> None:
    """Drop calibrated deltas (tests; factor set reverts to defaults+env)."""
    _calibrated_factors.clear()
