"""saturn-tsan: concurrency analysis for the saturn_tpu thread mesh.

Three cooperating pieces, mirroring the saturn-lint layering of PR 7:

- ``static_pass`` — an AST walk over the package that builds a
  lock-acquisition graph (``with self._lock:`` / ``.acquire()`` patterns,
  thread roots from ``Thread(target=...)``) and reports SAT-Cxxx
  diagnostics: lock-order inversions with minimal cycle counterexamples,
  shared mutable attributes with inconsistent guarding, blocking calls
  held under a lock, and condition-wait-without-loop.
- ``sanitizer`` — an opt-in instrumented lock/queue layer
  (``SATURN_TPU_TSAN=1``) recording real acquisition orders so runtime
  behaviour can be validated against the static graph.
- ``interleave`` — a seeded deterministic interleaving scheduler for
  tests: named preemption points in engine/service/journal hot paths
  (the crash-harness kill-point pattern) so races reproduce
  bit-identically by seed.

This module is deliberately import-light (stdlib only at import time):
product modules on hot paths import the sanitizer factories from here,
so nothing in this package may import JAX or the wider saturn_tpu tree.
"""

from __future__ import annotations

from typing import Any

__all__ = [
    "analyze_paths",
    "lock",
    "rlock",
    "condition",
    "make_queue",
    "sched_point",
]


def analyze_paths(paths: Any, *, package_root: Any = None) -> Any:
    """Run the static concurrency pass over files/directories (lazy import)."""
    from saturn_tpu.analysis.concurrency import static_pass

    return static_pass.analyze_paths(paths, package_root=package_root)


# Re-export the sanitizer factories directly: they are stdlib-only and
# product modules call them at import time (module-level locks).
from saturn_tpu.analysis.concurrency.sanitizer import (  # noqa: E402
    condition,
    lock,
    make_queue,
    rlock,
)
from saturn_tpu.analysis.concurrency.interleave import sched_point  # noqa: E402
