"""saturn-tsan static pass: lock-acquisition graph + SAT-C diagnostics.

Walks package ASTs to build a model of the thread mesh — lock objects
(``threading.Lock/RLock/Condition`` and the sanitizer factories
``tsan.lock/rlock/condition/make_queue``), thread entry points
(``threading.Thread(target=...)``), and per-function lock-held contexts
(``with self._lock:`` nesting plus ``.acquire()``/``.release()`` pairs)
— then reports:

========== ========= =====================================================
code       severity  meaning
========== ========= =====================================================
SAT-C001   error     lock-order inversion: the static acquisition graph
                     has a cycle (potential deadlock); counterexample is
                     the minimal cycle with one witness site per edge
SAT-C002   error     shared mutable state with no common guard: a class
                     attribute / closure variable / module global is
                     mutated under a lock on one path and without it on
                     another (or mutated lock-free from ≥2 thread roots)
SAT-C003   error     blocking call (fsync, sleep, Thread.join, blocking
                     queue get/put, Event.wait) executed while holding a
                     lock
SAT-C004   error     Condition.wait() outside a retest loop (lost-wakeup
                     / spurious-wakeup hazard)
========== ========= =====================================================

Suppression: a ``# sanctioned-unlocked: <reason>`` comment on the finding
line (or the line above) downgrades it to ``info`` — the audited case
stays visible in reports but does not gate.  Placed on a ``def`` line (or
the line above it), the marker sanctions the whole function: blocking
calls inside it are audited, and call sites to it stop propagating its
may-block set (the journal's group-commit ``fsync`` is the canonical
case — holding the lock across the fsync IS the durability contract).

Heuristics and honest limits (``docs/analysis.md`` has the full policy):

- Interprocedural reasoning follows *resolvable* calls only: methods on
  ``self``, same-module (or alias-imported analyzed-module) functions,
  nested siblings, and attributes with a constructor-typed assignment
  (``self.journal = jmod.Journal(...)``).  Dynamic callables — e.g. the
  queue's ``observer`` hook — are invisible here; the runtime sanitizer
  (``SATURN_TPU_TSAN=1``) covers exactly that gap by recording real
  acquisition orders and validating them against this graph.
- A method whose every in-tree call site holds lock L is treated as
  executing under L ("lock-context"); call sites inside ``__init__``
  count as pre-publication and don't constrain the context.
- ``with X:`` over an unresolvable name counts as *a* guard when the
  name looks lock-like (contains ``lock``/``mutex``/``cond``/``_mu``) —
  such opaque guards satisfy guarding rules but never join the order
  graph.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterable, List, Optional, Sequence, Set, Tuple

from saturn_tpu.analysis.diagnostics import AnalysisReport, make
from saturn_tpu.analysis.concurrency.sanitizer import find_cycles

SANCTION_MARKER = "sanctioned-unlocked:"

#: threading constructors → lock kind
_THREADING_LOCKS = {"Lock": "lock", "RLock": "rlock"}
#: constructors whose instances are internally synchronized / single-writer
_SAFE_CTORS = {
    "Event", "Queue", "LifoQueue", "PriorityQueue", "SimpleQueue",
    "Semaphore", "BoundedSemaphore", "Barrier", "local",
}
#: attribute calls that mutate their receiver
_MUTATING_METHODS = {
    "append", "appendleft", "add", "extend", "insert", "remove", "discard",
    "pop", "popitem", "popleft", "clear", "update", "setdefault",
}
_LOCKISH_HINTS = ("lock", "mutex", "cond", "_mu")


def _lockish(name: str) -> bool:
    low = name.lower()
    return any(h in low for h in _LOCKISH_HINTS) or low == "mu"


# --------------------------------------------------------------------------
# model
# --------------------------------------------------------------------------


@dataclass
class LockDef:
    lock_id: str
    kind: str                      # "lock" | "rlock"
    where: str


#: lock-context lattice top: "only ever called pre-publication".
_TOP = frozenset({"<prepub>"})


@dataclass
class Site:
    """One read/write of a tracked shared variable."""

    fn: "FuncUnit"
    line: int
    guards: FrozenSet[str]         # known + opaque lock ids held at the site
    access: str                    # "write" | "read"


@dataclass
class CallRecord:
    callee: "FuncUnit"
    held: FrozenSet[str]
    line: int


@dataclass
class BlockRecord:
    op: str                        # "fsync" | "sleep" | "join" | ...
    held: FrozenSet[str]           # known/opaque locks held at the site
    line: int


@dataclass
class FuncUnit:
    qual: str
    node: ast.AST                  # FunctionDef | AsyncFunctionDef
    module: "ModuleInfo"
    cls: Optional["ClassInfo"]
    parent: Optional["FuncUnit"]
    is_init: bool = False
    sanction: Optional[str] = None            # function-level marker reason
    local_locks: Dict[str, LockDef] = field(default_factory=dict)
    local_threads: Set[str] = field(default_factory=set)
    local_queues: Set[str] = field(default_factory=set)
    local_containers: Set[str] = field(default_factory=set)
    local_bound: Set[str] = field(default_factory=set)
    global_decls: Set[str] = field(default_factory=set)
    nested: Dict[str, "FuncUnit"] = field(default_factory=dict)
    # populated by the walk:
    acquires: List[Tuple[str, FrozenSet[str], int]] = field(default_factory=list)
    calls: List[CallRecord] = field(default_factory=list)
    blocking: List[BlockRecord] = field(default_factory=list)
    condwaits: List[Tuple[str, int, bool]] = field(default_factory=list)
    closure_sites: List[Tuple["FuncUnit", str, Site]] = field(default_factory=list)
    is_thread_root: bool = False
    # fixed-point results:
    may_acquire: Set[str] = field(default_factory=set)
    may_block: Set[str] = field(default_factory=set)
    ctx_guards: FrozenSet[str] = _TOP

    def effective(self, held: FrozenSet[str]) -> FrozenSet[str]:
        ctx = frozenset() if self.ctx_guards == _TOP else self.ctx_guards
        return held | ctx


@dataclass
class ClassInfo:
    name: str
    module: "ModuleInfo"
    locks: Dict[str, LockDef] = field(default_factory=dict)     # attr -> def
    cond_of: Dict[str, str] = field(default_factory=dict)       # cond attr -> lock attr
    safe_attrs: Set[str] = field(default_factory=set)
    thread_attrs: Set[str] = field(default_factory=set)
    attr_types: Dict[str, str] = field(default_factory=dict)    # attr -> class name
    methods: Dict[str, FuncUnit] = field(default_factory=dict)
    mutations: Dict[str, List[Site]] = field(default_factory=dict)


@dataclass
class ModuleInfo:
    path: str
    name: str
    src_lines: List[str]
    alias: Dict[str, str] = field(default_factory=dict)          # threading/queue/os/time/tsan
    mod_alias: Dict[str, str] = field(default_factory=dict)      # local name -> analyzed module short name
    from_names: Dict[str, str] = field(default_factory=dict)     # bare name -> "threading.Lock" style
    locks: Dict[str, LockDef] = field(default_factory=dict)      # module-level lock vars
    global_candidates: Set[str] = field(default_factory=set)
    global_sites: Dict[str, List[Site]] = field(default_factory=dict)
    functions: Dict[str, FuncUnit] = field(default_factory=dict)
    classes: Dict[str, ClassInfo] = field(default_factory=dict)
    closure_vars: Dict[Tuple[str, str], List[Site]] = field(default_factory=dict)

    def sanction_at(self, line: int) -> Optional[str]:
        """Marker text on ``line`` (1-indexed) or in the contiguous comment
        block immediately above it."""
        if 1 <= line <= len(self.src_lines):
            text = self.src_lines[line - 1]
            if SANCTION_MARKER in text:
                return text.split(SANCTION_MARKER, 1)[1].strip() or "audited"
        ln = line - 1
        while 1 <= ln <= len(self.src_lines):
            text = self.src_lines[ln - 1]
            if not text.strip().startswith("#"):
                break
            if SANCTION_MARKER in text:
                return text.split(SANCTION_MARKER, 1)[1].strip() or "audited"
            ln -= 1
        return None


@dataclass
class ConcurrencyResult:
    """Everything the pass derived: the report plus the order graph."""

    report: AnalysisReport
    edges: Dict[Tuple[str, str], str]          # (held, acquired) -> witness
    locks: Dict[str, LockDef]

    def order_pairs(self) -> Set[Tuple[str, str]]:
        return set(self.edges)


# --------------------------------------------------------------------------
# per-module collection
# --------------------------------------------------------------------------

_TSAN_MODULES = {"concurrency", "sanitizer", "tsan"}


class _Collector:
    """Phase 1+2 over one module; defers cross-module work to the linker."""

    def __init__(self, path: str, source: str) -> None:
        self.mod = ModuleInfo(
            path=path,
            name=os.path.splitext(os.path.basename(path))[0],
            src_lines=source.splitlines(),
        )
        self.tree = ast.parse(source, filename=path)

    # -------------------------------------------------------------- helpers
    def _loc(self, node: ast.AST) -> str:
        return f"{self.mod.path}:{getattr(node, 'lineno', 0)}"

    def _call_root(self, call: ast.Call) -> Optional[Tuple[str, str]]:
        """(root, attr) for ``root.attr(...)`` or ("", name) for ``name(...)``."""
        f = call.func
        if isinstance(f, ast.Name):
            return ("", f.id)
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            return (f.value.id, f.attr)
        return None

    def _classify_ctor(
        self, node: ast.AST
    ) -> Optional[Tuple[str, Optional[str], Optional[ast.expr]]]:
        """(kind, literal-name, underlying-expr) for synchronization ctors.

        kind ∈ lock | rlock | condition | safe | thread | instance:<Class>.
        """
        if not isinstance(node, ast.Call):
            return None
        root_attr = self._call_root(node)
        if root_attr is None:
            return None
        root, name = root_attr
        target = None
        if root == "" and name in self.mod.from_names:
            target = self.mod.from_names[name]          # "threading.Lock"
        elif root and self.mod.alias.get(root) in ("threading", "queue"):
            target = f"{self.mod.alias[root]}.{name}"
        elif root and self.mod.alias.get(root) == "tsan":
            lit: Optional[str] = None
            args = node.args
            if name in ("lock", "rlock"):
                if args and isinstance(args[0], ast.Constant):
                    lit = str(args[0].value)
                return (name, lit, None)
            if name == "condition":
                under = args[0] if args else None
                for a in args[1:]:
                    if isinstance(a, ast.Constant):
                        lit = str(a.value)
                for kw in node.keywords:
                    if kw.arg == "name" and isinstance(kw.value, ast.Constant):
                        lit = str(kw.value.value)
                return ("condition", lit, under)
            if name == "make_queue":
                return ("safe", None, None)
            return None
        if target:
            mod, _, ctor = target.partition(".")
            if mod == "threading" and ctor in _THREADING_LOCKS:
                return (_THREADING_LOCKS[ctor], None, None)
            if mod == "threading" and ctor == "Condition":
                under = node.args[0] if node.args else None
                return ("condition", None, under)
            if mod == "threading" and ctor == "Thread":
                return ("thread", None, None)
            if ctor in _SAFE_CTORS:
                return ("safe", None, None)
            return None
        # plain ClassName(...) / modalias.ClassName(...): instance typing
        if root == "" and name[:1].isupper():
            return (f"instance:{name}", None, None)
        if root and root in self.mod.mod_alias and name[:1].isupper():
            return (f"instance:{name}", None, None)
        return None

    # -------------------------------------------------------------- phase 1
    def collect(self) -> ModuleInfo:
        for stmt in self.tree.body:
            if isinstance(stmt, (ast.Import, ast.ImportFrom)):
                self._collect_import(stmt)
        for stmt in self.tree.body:
            if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                self._collect_module_assign(stmt)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._collect_function(stmt, cls=None, parent=None)
            elif isinstance(stmt, ast.ClassDef):
                self._collect_class(stmt)
        return self.mod

    def _collect_import(self, stmt: ast.AST) -> None:
        if isinstance(stmt, ast.Import):
            for a in stmt.names:
                asname = a.asname or a.name.split(".")[0]
                head = a.name.split(".")[0]
                if head in ("threading", "queue", "os", "time"):
                    self.mod.alias[asname] = head
        elif isinstance(stmt, ast.ImportFrom):
            src = stmt.module or ""
            for a in stmt.names:
                asname = a.asname or a.name
                if src == "threading":
                    self.mod.from_names[asname] = f"threading.{a.name}"
                elif src == "queue":
                    self.mod.from_names[asname] = f"queue.{a.name}"
                elif src == "os" and a.name == "fsync":
                    self.mod.from_names[asname] = "os.fsync"
                elif src == "time" and a.name == "sleep":
                    self.mod.from_names[asname] = "time.sleep"
                elif a.name in _TSAN_MODULES and "analysis" in src:
                    self.mod.alias[asname] = "tsan"
                elif src.startswith("saturn_tpu"):
                    self.mod.mod_alias[asname] = a.name

    def _lock_id(self, scope: str, name: str, lit: Optional[str]) -> str:
        return lit if lit else f"{self.mod.name}.{scope}{name}"

    def _collect_module_assign(self, stmt: ast.AST) -> None:
        target: Optional[ast.expr] = None
        value: Optional[ast.expr] = None
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            target, value = stmt.targets[0], stmt.value
        elif isinstance(stmt, ast.AnnAssign):
            target, value = stmt.target, stmt.value
        if not isinstance(target, ast.Name) or value is None:
            return
        ctor = self._classify_ctor(value)
        if ctor and ctor[0] in ("lock", "rlock"):
            lid = self._lock_id("", target.id, ctor[1])
            self.mod.locks[target.id] = LockDef(lid, ctor[0], self._loc(stmt))
        elif ctor and ctor[0] == "safe":
            pass
        else:
            self.mod.global_candidates.add(target.id)

    def _collect_class(self, cdef: ast.ClassDef) -> None:
        cls = ClassInfo(name=cdef.name, module=self.mod)
        self.mod.classes[cdef.name] = cls
        # scan every method for self-attr constructor assignments first so
        # the walk phase knows attribute types regardless of def order
        for m in cdef.body:
            if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for sub in ast.walk(m):
                    if not (isinstance(sub, ast.Assign) and len(sub.targets) == 1):
                        continue
                    t = sub.targets[0]
                    if not (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        continue
                    ctor = self._classify_ctor(sub.value)
                    if ctor is None:
                        continue
                    kind, lit, under = ctor
                    if kind in ("lock", "rlock"):
                        lid = self._lock_id(f"{cdef.name}.", t.attr, lit)
                        cls.locks[t.attr] = LockDef(lid, kind, self._loc(sub))
                    elif kind == "condition":
                        if (
                            isinstance(under, ast.Attribute)
                            and isinstance(under.value, ast.Name)
                            and under.value.id == "self"
                        ):
                            cls.cond_of[t.attr] = under.attr
                        else:
                            lid = self._lock_id(f"{cdef.name}.", t.attr, lit)
                            cls.locks[t.attr] = LockDef(lid, "rlock", self._loc(sub))
                    elif kind == "safe":
                        cls.safe_attrs.add(t.attr)
                    elif kind == "thread":
                        cls.thread_attrs.add(t.attr)
                    elif kind.startswith("instance:"):
                        cls.attr_types[t.attr] = kind.split(":", 1)[1]
        for m in cdef.body:
            if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fu = self._collect_function(m, cls=cls, parent=None)
                cls.methods[m.name] = fu

    def _collect_function(
        self,
        fdef: ast.AST,
        cls: Optional[ClassInfo],
        parent: Optional[FuncUnit],
    ) -> FuncUnit:
        assert isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef))
        bits = [self.mod.name]
        if cls is not None:
            bits.append(cls.name)
        if parent is not None:
            bits.append(parent.qual.split(".", 1)[1])
        bits.append(fdef.name)
        fu = FuncUnit(
            qual=".".join(bits),
            node=fdef,
            module=self.mod,
            cls=cls if cls is not None else (parent.cls if parent else None),
            parent=parent,
            is_init=(fdef.name == "__init__" and cls is not None),
            sanction=self.mod.sanction_at(fdef.lineno),
        )
        args = fdef.args
        for a in (
            list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
            + ([args.vararg] if args.vararg else [])
            + ([args.kwarg] if args.kwarg else [])
        ):
            fu.local_bound.add(a.arg)
        for sub in ast.walk(fdef):
            if isinstance(sub, ast.Global):
                fu.global_decls.update(sub.names)
        self._prescan_locals(fu)
        if parent is None and cls is None:
            self.mod.functions[fdef.name] = fu
        if parent is not None:
            parent.nested[fdef.name] = fu
        for stmt in fdef.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._collect_function(stmt, cls=None, parent=fu)
        return fu

    def _prescan_locals(self, fu: FuncUnit) -> None:
        """Local bindings: locks, Thread vars, containers (closure-shared)."""
        assert isinstance(fu.node, (ast.FunctionDef, ast.AsyncFunctionDef))
        for stmt in fu.node.body:
            self._prescan_stmt(fu, stmt)

    def _prescan_stmt(self, fu: FuncUnit, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return  # nested scopes handled separately
        if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            targets = (
                stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
            )
            value = stmt.value
            for t in targets:
                if isinstance(t, ast.Name):
                    fu.local_bound.add(t.id)
                    if value is None:
                        continue
                    ctor = self._classify_ctor(value)
                    if ctor and ctor[0] in ("lock", "rlock"):
                        scope = fu.qual.split(".", 1)[1] + "."
                        lid = self._lock_id(scope, t.id, ctor[1])
                        fu.local_locks[t.id] = LockDef(
                            lid, ctor[0], self._loc(stmt)
                        )
                    elif ctor and ctor[0] == "thread":
                        fu.local_threads.add(t.id)
                    elif isinstance(
                        value,
                        (ast.Dict, ast.List, ast.Set, ast.DictComp,
                         ast.SetComp, ast.ListComp),
                    ) or (
                        isinstance(value, ast.Call)
                        and isinstance(value.func, ast.Name)
                        and value.func.id in ("dict", "list", "set")
                    ):
                        fu.local_containers.add(t.id)
                elif isinstance(t, (ast.Tuple, ast.List)):
                    for el in t.elts:
                        if isinstance(el, ast.Name):
                            fu.local_bound.add(el.id)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            for el in ast.walk(stmt.target):
                if isinstance(el, ast.Name):
                    fu.local_bound.add(el.id)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                if item.optional_vars is not None:
                    for el in ast.walk(item.optional_vars):
                        if isinstance(el, ast.Name):
                            fu.local_bound.add(el.id)
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.stmt):
                self._prescan_stmt(fu, child)
        # comprehension variables, except-as names
        if isinstance(stmt, ast.Try):
            for h in stmt.handlers:
                if h.name:
                    fu.local_bound.add(h.name)


# --------------------------------------------------------------------------
# the walk: held-lock tracking per function
# --------------------------------------------------------------------------


class _Walker:
    """Phase 2: per-function statement walk with a held-lock context."""

    def __init__(self, mod: ModuleInfo, registry: "_Registry") -> None:
        self.mod = mod
        self.reg = registry

    def walk_module(self) -> None:
        for fu in _all_funcs(self.mod):
            assert isinstance(fu.node, (ast.FunctionDef, ast.AsyncFunctionDef))
            self._walk_body(fu, fu.node.body, tuple(), loop_depth=0)

    # ------------------------------------------------------------- resolve
    def _resolve_lock(
        self, fu: FuncUnit, expr: ast.expr
    ) -> Optional[Tuple[str, str]]:
        """(lock_id, kind); kind "opaque" for lock-ish unresolvable names."""
        if isinstance(expr, ast.Name):
            scope: Optional[FuncUnit] = fu
            while scope is not None:
                if expr.id in scope.local_locks:
                    d = scope.local_locks[expr.id]
                    return (d.lock_id, d.kind)
                if expr.id in scope.local_bound:
                    break  # shadowed by a non-lock local
                scope = scope.parent
            if expr.id in self.mod.locks:
                d = self.mod.locks[expr.id]
                return (d.lock_id, d.kind)
            if _lockish(expr.id):
                return (f"~opaque:{self.mod.name}.{expr.id}", "opaque")
            return None
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and fu.cls is not None
        ):
            cls = fu.cls
            if expr.attr in cls.locks:
                d = cls.locks[expr.attr]
                return (d.lock_id, d.kind)
            if expr.attr in cls.cond_of and cls.cond_of[expr.attr] in cls.locks:
                d = cls.locks[cls.cond_of[expr.attr]]
                return (d.lock_id, d.kind)
            if _lockish(expr.attr):
                return (f"~opaque:{self.mod.name}.{cls.name}.{expr.attr}",
                        "opaque")
        return None

    def _resolve_callee(
        self, fu: FuncUnit, call: ast.Call
    ) -> Optional[FuncUnit]:
        f = call.func
        if isinstance(f, ast.Name):
            scope: Optional[FuncUnit] = fu
            while scope is not None:
                if f.id in scope.nested:
                    return scope.nested[f.id]
                scope = scope.parent
            return self.mod.functions.get(f.id)
        if not isinstance(f, ast.Attribute):
            return None
        recv = f.value
        if isinstance(recv, ast.Name):
            if recv.id == "self" and fu.cls is not None:
                return fu.cls.methods.get(f.attr)
            if recv.id in self.mod.mod_alias:
                target_mod = self.reg.modules.get(self.mod.mod_alias[recv.id])
                if target_mod is not None:
                    return target_mod.functions.get(f.attr)
            return None
        if (
            isinstance(recv, ast.Attribute)
            and isinstance(recv.value, ast.Name)
            and recv.value.id == "self"
            and fu.cls is not None
        ):
            tname = fu.cls.attr_types.get(recv.attr)
            if tname is not None:
                tcls = self.reg.classes.get(tname)
                if tcls is not None:
                    return tcls.methods.get(f.attr)
        return None

    def _recv_type(self, fu: FuncUnit, recv: ast.expr) -> Optional[str]:
        """Coarse receiver classification: thread | queue | event | cond."""
        if isinstance(recv, ast.Name):
            scope: Optional[FuncUnit] = fu
            while scope is not None:
                if recv.id in scope.local_threads:
                    return "thread"
                if recv.id in scope.local_bound:
                    return None
                scope = scope.parent
            return None
        if not (
            isinstance(recv, ast.Attribute)
            and isinstance(recv.value, ast.Name)
            and recv.value.id == "self"
            and fu.cls is not None
        ):
            return None
        cls = fu.cls
        if recv.attr in cls.thread_attrs:
            return "thread"
        if recv.attr in cls.cond_of or (
            recv.attr in cls.locks and "cond" in recv.attr.lower()
        ):
            return "cond"
        if recv.attr in cls.safe_attrs:
            return "safe"
        return None

    # ---------------------------------------------------------------- walk
    def _walk_body(
        self,
        fu: FuncUnit,
        stmts: Sequence[ast.stmt],
        held: Tuple[str, ...],
        loop_depth: int,
    ) -> Tuple[str, ...]:
        for stmt in stmts:
            held = self._walk_stmt(fu, stmt, held, loop_depth)
        return held

    def _acquire(
        self, fu: FuncUnit, lock_id: str, kind: str,
        held: Tuple[str, ...], line: int,
    ) -> Tuple[str, ...]:
        if lock_id in held:
            if kind == "lock":
                fu.acquires.append((f"{lock_id}!self", frozenset(held), line))
            return held
        if kind != "opaque":
            fu.acquires.append((lock_id, frozenset(held), line))
        return held + (lock_id,)

    def _walk_stmt(
        self,
        fu: FuncUnit,
        stmt: ast.stmt,
        held: Tuple[str, ...],
        loop_depth: int,
    ) -> Tuple[str, ...]:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return held  # nested functions are walked via walk_module
        if isinstance(stmt, ast.ClassDef):
            return held
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            inner = held
            for item in stmt.items:
                lk = self._resolve_lock(fu, item.context_expr)
                if lk is not None:
                    inner = self._acquire(
                        fu, lk[0], lk[1], inner, stmt.lineno
                    )
                self._scan_expr(fu, item.context_expr, held, loop_depth)
            self._walk_body(fu, stmt.body, inner, loop_depth)
            return held
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            if isinstance(stmt, ast.While):
                self._scan_expr(fu, stmt.test, held, loop_depth)
            else:
                self._scan_expr(fu, stmt.iter, held, loop_depth)
            self._walk_body(fu, stmt.body, held, loop_depth + 1)
            self._walk_body(fu, stmt.orelse, held, loop_depth)
            return held
        if isinstance(stmt, ast.If):
            self._scan_expr(fu, stmt.test, held, loop_depth)
            self._walk_body(fu, stmt.body, held, loop_depth)
            self._walk_body(fu, stmt.orelse, held, loop_depth)
            return held
        if isinstance(stmt, ast.Try):
            self._walk_body(fu, stmt.body, held, loop_depth)
            for h in stmt.handlers:
                self._walk_body(fu, h.body, held, loop_depth)
            self._walk_body(fu, stmt.orelse, held, loop_depth)
            self._walk_body(fu, stmt.finalbody, held, loop_depth)
            return held
        # leaf statements: acquire()/release(), mutations, expression scan
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            call = stmt.value
            if isinstance(call.func, ast.Attribute) and not call.args:
                lk = self._resolve_lock(fu, call.func.value)
                if lk is not None and call.func.attr == "release":
                    self._scan_expr(fu, call, held, loop_depth)
                    return tuple(h for h in held if h != lk[0])
            if (
                isinstance(call.func, ast.Attribute)
                and call.func.attr == "acquire"
            ):
                lk = self._resolve_lock(fu, call.func.value)
                if lk is not None:
                    self._scan_expr(fu, call, held, loop_depth)
                    return self._acquire(fu, lk[0], lk[1], held, stmt.lineno)
        self._record_mutations(fu, stmt, held)
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._scan_expr(fu, child, held, loop_depth)
        return held

    # ----------------------------------------------------------- mutations
    def _note_site(
        self, fu: FuncUnit, kind: str, key: str, line: int,
        held: Tuple[str, ...], access: str = "write",
    ) -> None:
        site = Site(fn=fu, line=line, guards=frozenset(held), access=access)
        if kind == "attr" and fu.cls is not None and not fu.is_init:
            fu.cls.mutations.setdefault(key, []).append(site)
        elif kind == "global":
            self.mod.global_sites.setdefault(key, []).append(site)
        elif kind == "closure":
            owner = fu.parent
            while owner is not None:
                if key in owner.local_containers or key in owner.local_bound:
                    break
                owner = owner.parent
            if owner is not None and key in owner.local_containers:
                self.mod.closure_vars.setdefault(
                    (owner.qual, key), []
                ).append(site)

    def _mutation_target(
        self, fu: FuncUnit, expr: ast.expr
    ) -> Optional[Tuple[str, str]]:
        """("attr"|"global"|"closure", key) for a mutated expression root."""
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and fu.cls is not None
        ):
            a = expr.attr
            if (
                a in fu.cls.locks or a in fu.cls.cond_of
                or a in fu.cls.safe_attrs
            ):
                return None
            return ("attr", a)
        if isinstance(expr, ast.Name):
            if expr.id in fu.global_decls:
                return ("global", expr.id)
            if expr.id not in fu.local_bound and fu.parent is not None:
                return ("closure", expr.id)
            if (
                expr.id not in fu.local_bound
                and fu.parent is None
                and expr.id in self.mod.global_candidates
            ):
                return ("global", expr.id)
        return None

    def _record_mutations(
        self, fu: FuncUnit, stmt: ast.stmt, held: Tuple[str, ...]
    ) -> None:
        targets: List[ast.expr] = []
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            targets = [stmt.target]
        for t in targets:
            base: Optional[ast.expr] = None
            if isinstance(t, ast.Subscript):
                base = t.value
            elif isinstance(t, (ast.Attribute, ast.Name)):
                base = t
            if base is None:
                continue
            tgt = self._mutation_target(fu, base)
            if tgt is not None:
                self._note_site(fu, tgt[0], tgt[1], stmt.lineno, held)

    # --------------------------------------------------------- expressions
    def _scan_expr(
        self, fu: FuncUnit, expr: ast.expr, held: Tuple[str, ...],
        loop_depth: int,
    ) -> None:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self._scan_call(fu, node, held, loop_depth)
            elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                if (
                    node.id in self.mod.global_candidates
                    and node.id not in self._bound_anywhere(fu, node.id)
                ):
                    self._note_site(
                        fu, "global", node.id, node.lineno, held, access="read"
                    )

    def _bound_anywhere(self, fu: FuncUnit, name: str) -> Set[str]:
        scope: Optional[FuncUnit] = fu
        while scope is not None:
            if name in scope.local_bound and name not in scope.global_decls:
                return {name}
            scope = scope.parent
        return set()

    def _blocking_kind(
        self, fu: FuncUnit, call: ast.Call
    ) -> Optional[str]:
        f = call.func
        if isinstance(f, ast.Name):
            spec = self.mod.from_names.get(f.id)
            if spec in ("os.fsync", "time.sleep"):
                return spec.split(".")[1]
            return None
        if not isinstance(f, ast.Attribute):
            return None
        if isinstance(f.value, ast.Name):
            root = self.mod.alias.get(f.value.id)
            if root == "os" and f.attr == "fsync":
                return "fsync"
            if root == "time" and f.attr == "sleep":
                return "sleep"
        rtype = self._recv_type(fu, f.value)
        if rtype == "thread" and f.attr == "join":
            if not _has_timeout(call):
                return "join"
            return None
        if rtype == "safe" and f.attr in ("get", "put"):
            if _is_blocking_queue_call(call, f.attr):
                return f"queue.{f.attr}"
            return None
        if rtype == "safe" and f.attr == "wait" and not _has_timeout(call):
            # Event.wait() without a timeout (Condition attrs are "cond")
            return "event.wait"
        return None

    def _scan_call(
        self, fu: FuncUnit, call: ast.Call, held: Tuple[str, ...],
        loop_depth: int,
    ) -> None:
        f = call.func
        # thread roots
        ctor = _thread_target(call, self.mod)
        if ctor is not None:
            root_fu = self._resolve_target_fn(fu, ctor)
            if root_fu is not None:
                root_fu.is_thread_root = True
        # condition wait-not-in-loop
        if isinstance(f, ast.Attribute) and f.attr in ("wait", "wait_for"):
            lk = self._resolve_lock(fu, f.value)
            is_cond = (
                isinstance(f.value, ast.Attribute)
                and isinstance(f.value.value, ast.Name)
                and f.value.value.id == "self"
                and fu.cls is not None
                and f.value.attr in fu.cls.cond_of
            ) or (
                isinstance(f.value, ast.Name)
                and lk is not None and "cond" in f.value.id.lower()
            )
            if is_cond and f.attr == "wait":
                fu.condwaits.append(
                    (lk[0] if lk else "?", call.lineno, loop_depth > 0)
                )
        # blocking primitives
        bk = self._blocking_kind(fu, call)
        if bk is not None:
            fu.blocking.append(
                BlockRecord(op=bk, held=frozenset(held), line=call.lineno)
            )
        # resolvable calls -> call graph
        callee = self._resolve_callee(fu, call)
        if callee is not None:
            fu.calls.append(
                CallRecord(callee=callee, held=frozenset(held),
                           line=call.lineno)
            )
        # mutating method calls on tracked receivers
        if isinstance(f, ast.Attribute) and f.attr in _MUTATING_METHODS:
            tgt = self._mutation_target(fu, f.value)
            if tgt is not None:
                self._note_site(fu, tgt[0], tgt[1], call.lineno, held)

    def _resolve_target_fn(
        self, fu: FuncUnit, target: ast.expr
    ) -> Optional[FuncUnit]:
        if isinstance(target, ast.Name):
            scope: Optional[FuncUnit] = fu
            while scope is not None:
                if target.id in scope.nested:
                    return scope.nested[target.id]
                scope = scope.parent
            return self.mod.functions.get(target.id)
        if (
            isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"
            and fu.cls is not None
        ):
            return fu.cls.methods.get(target.attr)
        return None


def _has_timeout(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "timeout" and not (
            isinstance(kw.value, ast.Constant) and kw.value.value is None
        ):
            return True
    return len(call.args) > 0  # join(5.0) / wait(5.0)


def _is_blocking_queue_call(call: ast.Call, op: str) -> bool:
    """q.get()/q.put(item) with block=True (default) and no timeout."""
    pos_limit = 1 if op == "get" else 2  # beyond: block/timeout positionals
    if op == "get" and len(call.args) >= 1:
        return False
    if op == "put" and len(call.args) >= pos_limit:
        return False
    for kw in call.keywords:
        if kw.arg == "timeout" and not (
            isinstance(kw.value, ast.Constant) and kw.value.value is None
        ):
            return False
        if kw.arg == "block" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is False:
            return False
    return True


def _thread_target(call: ast.Call, mod: ModuleInfo) -> Optional[ast.expr]:
    f = call.func
    is_thread = (
        isinstance(f, ast.Attribute)
        and isinstance(f.value, ast.Name)
        and mod.alias.get(f.value.id) == "threading"
        and f.attr == "Thread"
    ) or (
        isinstance(f, ast.Name)
        and mod.from_names.get(f.id) == "threading.Thread"
    )
    if not is_thread:
        return None
    for kw in call.keywords:
        if kw.arg == "target":
            return kw.value
    return None


def _all_funcs(mod: ModuleInfo) -> List[FuncUnit]:
    out: List[FuncUnit] = []

    def rec(fu: FuncUnit) -> None:
        out.append(fu)
        for n in fu.nested.values():
            rec(n)

    for f in mod.functions.values():
        rec(f)
    for c in mod.classes.values():
        for m in c.methods.values():
            rec(m)
    return out


# --------------------------------------------------------------------------
# linking + fixed points + diagnostics
# --------------------------------------------------------------------------


@dataclass
class _Registry:
    modules: Dict[str, ModuleInfo] = field(default_factory=dict)
    classes: Dict[str, ClassInfo] = field(default_factory=dict)


def _fixed_points(funcs: List[FuncUnit]) -> None:
    # may_acquire / may_block: transitive closure over resolved calls
    for fu in funcs:
        fu.may_acquire = {
            lid.split("!", 1)[0] for lid, _h, _l in fu.acquires
        }
        fu.may_block = (
            set() if fu.sanction else {b.op for b in fu.blocking}
        )
    changed = True
    while changed:
        changed = False
        for fu in funcs:
            for cr in fu.calls:
                add_a = cr.callee.may_acquire - fu.may_acquire
                if add_a:
                    fu.may_acquire |= add_a
                    changed = True
                if not fu.sanction and not cr.callee.sanction:
                    add_b = cr.callee.may_block - fu.may_block
                    if add_b:
                        fu.may_block |= add_b
                        changed = True
    # ctx_guards: meet (intersection) over in-tree call sites; __init__
    # callers are pre-publication and do not constrain the meet.
    callers: Dict[int, List[Tuple[FuncUnit, CallRecord]]] = {}
    for fu in funcs:
        for cr in fu.calls:
            callers.setdefault(id(cr.callee), []).append((fu, cr))
    for fu in funcs:
        fu.ctx_guards = _TOP
    changed = True
    while changed:
        changed = False
        for fu in funcs:
            sites = callers.get(id(fu), [])
            if fu.is_thread_root:
                new: FrozenSet[str] = frozenset()
            elif not sites:
                # no in-tree callers: external entry point, except __init__
                # which by definition runs pre-publication
                new = _TOP if fu.is_init else frozenset()
            else:
                acc: Optional[FrozenSet[str]] = None
                for caller, cr in sites:
                    if caller.is_init:
                        continue  # pre-publication: no constraint
                    if caller.ctx_guards == _TOP:
                        contrib = cr.held  # prepub chain: held only
                    else:
                        contrib = cr.held | caller.ctx_guards
                    acc = contrib if acc is None else (acc & contrib)
                new = _TOP if acc is None else acc
            if new != fu.ctx_guards:
                fu.ctx_guards = new
                changed = True


def _known(guards: Iterable[str]) -> Set[str]:
    return {g for g in guards if not g.startswith("~opaque:")}


def _reachable_from_roots(funcs: List[FuncUnit]) -> Set[int]:
    frontier = [f for f in funcs if f.is_thread_root]
    seen: Set[int] = {id(f) for f in frontier}
    while frontier:
        fu = frontier.pop()
        for cr in fu.calls:
            if id(cr.callee) not in seen:
                seen.add(id(cr.callee))
                frontier.append(cr.callee)
    return seen


def _site_guards(site: Site) -> FrozenSet[str]:
    return site.fn.effective(site.guards)


def _emit_shared_state(
    report: AnalysisReport,
    mod: ModuleInfo,
    what: str,
    key: str,
    sites: List[Site],
    root_reachable: Set[int],
) -> None:
    live = [
        s for s in sites
        if not (s.fn.ctx_guards == _TOP and not s.guards)  # prepub-only
    ]
    writes = [s for s in live if s.access == "write"]
    if not writes:
        return
    guarded = [s for s in live if _site_guards(s)]
    unguarded = [s for s in live if not _site_guards(s)]
    flag: List[Site] = []
    why = ""
    if guarded and unguarded:
        flag = unguarded
        why = "mutated without the guard used elsewhere"
    elif guarded and not unguarded:
        common: Set[str] = set(_site_guards(guarded[0]))
        for s in guarded[1:]:
            common &= set(_site_guards(s))
        if not common:
            flag = guarded
            why = "sites are guarded by different locks (no common guard)"
    else:
        fns = {s.fn.qual for s in writes}
        rooted = [s for s in writes if id(s.fn) in root_reachable]
        if len(fns) >= 2 and rooted:
            flag = writes
            why = "lock-free mutation reachable from a thread root"
    guard_names = sorted(
        {g for s in guarded for g in _site_guards(s)}
    ) if guarded else []
    for s in flag:
        sanction = mod.sanction_at(s.line) or s.fn.sanction
        sev = "info" if sanction else "error"
        prefix = f"[sanctioned: {sanction}] " if sanction else ""
        report.add(make(
            "SAT-C002", sev,
            f"{prefix}shared {what} {key!r} {s.access} in {s.fn.qual} "
            f"without a common guard: {why}",
            counterexample={
                "name": key, "access": s.access,
                "guards_here": sorted(s.guards),
                "guards_elsewhere": guard_names,
            },
            location=f"{mod.path}:{s.line}",
            category="concurrency",
        ))


def run(
    paths: Sequence[str], *, package_root: Optional[str] = None
) -> ConcurrencyResult:
    """Analyze ``paths`` (files and/or directories) as one program."""
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for base, _dirs, names in sorted(os.walk(p)):
                for n in sorted(names):
                    if n.endswith(".py"):
                        files.append(os.path.join(base, n))
        elif p.endswith(".py"):
            files.append(p)
        else:
            raise OSError(f"not a python file or directory: {p!r}")
    report = AnalysisReport(subject=f"concurrency:{','.join(paths)}")
    reg = _Registry()
    for path in files:
        with open(path, encoding="utf-8") as fh:
            src = fh.read()
        try:
            col = _Collector(path, src)
        except SyntaxError as e:
            report.add(make(
                "SAT-C000", "error", f"cannot parse {path}: {e}",
                category="concurrency",
            ))
            continue
        mod = col.collect()
        # module-name collisions (e.g. two __init__.py): suffix to keep both
        key = mod.name
        n = 1
        while key in reg.modules:
            n += 1
            key = f"{mod.name}#{n}"
        reg.modules[key] = mod
        for cname, cinfo in mod.classes.items():
            reg.classes.setdefault(cname, cinfo)
    for mod in reg.modules.values():
        _Walker(mod, reg).walk_module()
    funcs: List[FuncUnit] = []
    for mod in reg.modules.values():
        funcs.extend(_all_funcs(mod))
    _fixed_points(funcs)
    root_reachable = _reachable_from_roots(funcs)

    # ---------------------------------------------------- SAT-C001: ordering
    edges: Dict[Tuple[str, str], str] = {}
    all_locks: Dict[str, LockDef] = {}
    for mod in reg.modules.values():
        for d in mod.locks.values():
            all_locks[d.lock_id] = d
        for c in mod.classes.values():
            for d in c.locks.values():
                all_locks[d.lock_id] = d
        for fu in _all_funcs(mod):
            for d in fu.local_locks.values():
                all_locks[d.lock_id] = d
    for fu in funcs:
        # direct acquisitions under effective held context
        for lid, held, line in fu.acquires:
            if lid.endswith("!self"):
                base = lid[:-5]
                d = all_locks.get(base)
                if d is not None and d.kind == "lock":
                    sanction = fu.module.sanction_at(line) or fu.sanction
                    sev = "info" if sanction else "error"
                    prefix = f"[sanctioned: {sanction}] " if sanction else ""
                    report.add(make(
                        "SAT-C001", sev,
                        f"{prefix}re-acquiring non-reentrant lock "
                        f"{base!r} already held (self-deadlock)",
                        counterexample={"cycle": [base, base]},
                        location=f"{fu.module.path}:{line}",
                        category="concurrency",
                    ))
                continue
            eff = _known(fu.effective(held))
            for h in eff:
                if h != lid:
                    edges.setdefault((h, lid), f"{fu.module.path}:{line}")
            # cross-call self-reacquire: every in-tree caller holds this
            # non-reentrant lock when we acquire it again (the syntactic
            # same-function case is the "!self" branch above)
            d = all_locks.get(lid)
            if (d is not None and d.kind == "lock"
                    and lid not in held and lid in fu.ctx_guards):
                sanction = fu.module.sanction_at(line) or fu.sanction
                sev = "info" if sanction else "error"
                prefix = f"[sanctioned: {sanction}] " if sanction else ""
                report.add(make(
                    "SAT-C001", sev,
                    f"{prefix}re-acquiring non-reentrant lock {lid!r} "
                    f"held by every caller of {fu.qual} (self-deadlock)",
                    counterexample={"cycle": [lid, lid]},
                    location=f"{fu.module.path}:{line}",
                    category="concurrency",
                ))
        # call-site expansion: held here -> locks the callee may acquire
        for cr in fu.calls:
            eff = _known(fu.effective(cr.held))
            if not eff:
                continue
            for lid in cr.callee.may_acquire:
                if lid in eff or lid.startswith("~opaque:"):
                    continue
                for h in eff:
                    if h != lid:
                        edges.setdefault(
                            (h, lid), f"{fu.module.path}:{cr.line}"
                        )
    for cyc in find_cycles(set(edges)):
        pairs = list(zip(cyc, cyc[1:]))
        report.add(make(
            "SAT-C001", "error",
            "lock-order inversion (potential deadlock): "
            + " -> ".join(cyc),
            counterexample={
                "cycle": cyc,
                "edges": [
                    {"from": a, "to": b, "where": edges.get((a, b), "?")}
                    for a, b in pairs
                ],
            },
            location=edges.get(pairs[0], None) if pairs else None,
            category="concurrency",
        ))

    # ------------------------------------------------- SAT-C002: shared state
    for mod in reg.modules.values():
        for cls in mod.classes.values():
            for attr, sites in sorted(cls.mutations.items()):
                _emit_shared_state(
                    report, mod, f"attribute self.{attr} of {cls.name}",
                    attr, sites, root_reachable,
                )
        for (owner, var), sites in sorted(mod.closure_vars.items()):
            _emit_shared_state(
                report, mod, f"closure variable of {owner}", var, sites,
                root_reachable,
            )
        managed = {
            g for g, sites in mod.global_sites.items()
            if any(s.access == "write" and _site_guards(s) for s in sites)
        }
        for g in sorted(managed):
            _emit_shared_state(
                report, mod, f"module global of {mod.name}", g,
                mod.global_sites[g], root_reachable,
            )

    # ---------------------------------------------- SAT-C003: blocking calls
    for fu in funcs:
        for br in fu.blocking:
            eff = fu.effective(br.held)
            if not eff:
                continue
            sanction = fu.module.sanction_at(br.line) or fu.sanction
            sev = "info" if sanction else "error"
            prefix = f"[sanctioned: {sanction}] " if sanction else ""
            report.add(make(
                "SAT-C003", sev,
                f"{prefix}blocking call ({br.op}) while holding "
                f"{sorted(eff)} in {fu.qual}",
                counterexample={"op": br.op, "held": sorted(eff)},
                location=f"{fu.module.path}:{br.line}",
                category="concurrency",
            ))
        for cr in fu.calls:
            if not cr.callee.may_block or cr.callee.sanction:
                continue
            eff = fu.effective(cr.held)
            if not eff:
                continue
            sanction = fu.module.sanction_at(cr.line) or fu.sanction
            sev = "info" if sanction else "error"
            prefix = f"[sanctioned: {sanction}] " if sanction else ""
            report.add(make(
                "SAT-C003", sev,
                f"{prefix}call to {cr.callee.qual} (may block: "
                f"{sorted(cr.callee.may_block)}) while holding "
                f"{sorted(eff)} in {fu.qual}",
                counterexample={
                    "op": sorted(cr.callee.may_block),
                    "held": sorted(eff),
                    "callee": cr.callee.qual,
                },
                location=f"{fu.module.path}:{cr.line}",
                category="concurrency",
            ))

    # ------------------------------------------ SAT-C004: wait without loop
    for fu in funcs:
        for cond_id, line, in_loop in fu.condwaits:
            if in_loop:
                continue
            sanction = fu.module.sanction_at(line) or fu.sanction
            sev = "info" if sanction else "error"
            prefix = f"[sanctioned: {sanction}] " if sanction else ""
            report.add(make(
                "SAT-C004", sev,
                f"{prefix}Condition.wait() outside a retest loop in "
                f"{fu.qual} (lost/spurious wakeup hazard)",
                counterexample={"condition": cond_id},
                location=f"{fu.module.path}:{line}",
                category="concurrency",
            ))

    report.diagnostics.sort(
        key=lambda d: (d.code, d.location or "", d.message)
    )
    return ConcurrencyResult(report=report, edges=edges, locks=all_locks)


def analyze_paths(
    paths: Sequence[str], *, package_root: Optional[str] = None
) -> AnalysisReport:
    return run(paths, package_root=package_root).report


#: The thread-mesh surfaces the repo gates on (tools/lint.py, tests).
AUDITED_PATHS: Tuple[str, ...] = (
    "saturn_tpu/executor",
    "saturn_tpu/service",
    "saturn_tpu/durability",
    "saturn_tpu/data",
    "saturn_tpu/health",
    "saturn_tpu/tenancy",
    "saturn_tpu/resilience",
    "saturn_tpu/utils/metrics.py",
)


def default_paths(repo_root: Optional[str] = None) -> List[str]:
    """The audited package list, resolved against ``repo_root`` (cwd)."""
    root = repo_root or os.getcwd()
    out = []
    for rel in AUDITED_PATHS:
        cand = os.path.join(root, rel)
        if os.path.exists(cand):
            out.append(cand)
    return out
