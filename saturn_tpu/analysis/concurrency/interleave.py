"""Seeded deterministic interleaving scheduler for concurrency tests.

The crash harness (``resilience/crash.py``) made crash-recovery testing
deterministic by naming the interesting instants (kill points) and
letting a seeded schedule decide which one fires.  This module applies
the same idea to thread interleavings: product hot paths are annotated
with named **preemption points** (:func:`sched_point`), and a test
installs an :class:`InterleaveScheduler` that serializes its *managed*
threads, choosing at every decision which parked thread runs next with
``random.Random(seed)`` — same seed, same interleaving, same verdict,
bit-identically, run after run.

Mechanics
---------
- Exactly one managed thread executes at a time; everyone else is
  parked at a preemption point waiting for a grant.  The coordinator
  (the thread that calls :meth:`InterleaveScheduler.run`) waits until
  every managed thread is parked or finished, then grants one parked
  thread chosen by the seeded RNG.
- A managed thread only *parks* when it holds no traced locks
  (:func:`sanitizer.held_locks` is empty) — parking while holding a real
  lock could deadlock the very threads we are trying to schedule.  At a
  point reached with locks held the thread records a trace entry and
  continues; serialization still holds because nobody else is running.
- Unmanaged threads (anything not started via :meth:`spawn`) pass
  through :func:`sched_point` untouched, so production code is never
  affected by a scheduler some test forgot to uninstall.
- Installing the scheduler also activates the sanitizer
  (:func:`sanitizer.set_active`), so locks created *inside* the ``with``
  block come up traced and the held-lock test above works.

When no scheduler is installed, :func:`sched_point` is a single global
read — cheap enough for the engine/service/journal hot paths it sits in.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from saturn_tpu.analysis.concurrency import sanitizer

__all__ = ["sched_point", "InterleaveScheduler", "SchedulerAborted"]

_SCHED: Optional["InterleaveScheduler"] = None

_TLS = threading.local()


def sched_point(name: str) -> None:
    """Named preemption point; no-op unless an interleaving scheduler is on."""
    s = _SCHED
    if s is not None:
        s.point(name)


class SchedulerAborted(BaseException):
    """Raised inside managed threads when the coordinator gives up.

    Derives from BaseException (like the crash harness's SimulatedKill)
    so product ``except Exception`` blocks don't swallow the abort.
    """


class InterleaveScheduler:
    """Seeded serialization of managed threads at named preemption points.

    Usage::

        with InterleaveScheduler(seed=7) as sched:
            q = SubmissionQueue(...)          # locks come up traced
            sched.spawn(lambda: q.submit(j), name="producer")
            sched.spawn(lambda: drain_loop(q), name="service")
            trace = sched.run()
    """

    def __init__(self, seed: int, *, timeout: float = 30.0) -> None:
        self.seed = seed
        self.timeout = timeout
        self._rng = random.Random(seed)
        self._mu = threading.Lock()  # raw on purpose: invisible to tracing
        self._cv = threading.Condition(self._mu)
        self._states: Dict[str, str] = {}  # name -> running|parked|done
        self._threads: List[threading.Thread] = []
        self._errors: Dict[str, BaseException] = {}
        self._trace: List[str] = []
        self._abort = False
        self._prev_active = False

    # -- install / uninstall -------------------------------------------------

    def __enter__(self) -> "InterleaveScheduler":
        global _SCHED
        if _SCHED is not None:
            raise RuntimeError("an InterleaveScheduler is already installed")
        self._prev_active = sanitizer.enabled()
        sanitizer.set_active(True)
        _SCHED = self
        return self

    def __exit__(self, *exc: Any) -> None:
        global _SCHED
        _SCHED = None
        sanitizer.set_active(self._prev_active)
        with self._cv:
            self._abort = True
            self._cv.notify_all()

    # -- thread management ---------------------------------------------------

    def spawn(self, fn: Callable[[], Any], *, name: str) -> threading.Thread:
        """Start ``fn`` on a managed daemon thread parked at an implicit
        start point (unrecorded, so registration order can't skew the
        trace)."""
        if name in self._states:
            raise ValueError(f"duplicate managed thread name {name!r}")

        def runner() -> None:
            _TLS.name = name
            try:
                self._park(name, point=None)
                fn()
            except SchedulerAborted:
                pass
            except BaseException as e:  # noqa: BLE001 - surfaced via .errors
                with self._cv:
                    self._errors[name] = e
            finally:
                with self._cv:
                    self._states[name] = "done"
                    self._cv.notify_all()

        with self._cv:
            self._states[name] = "running"
        t = threading.Thread(target=runner, name=f"ilv-{name}", daemon=True)
        self._threads.append(t)
        t.start()
        return t

    # -- preemption points ---------------------------------------------------

    def point(self, point_name: str) -> None:
        name = getattr(_TLS, "name", None)
        if name is None:
            return  # unmanaged thread: pass through
        if sanitizer.held_locks():
            # Never park holding a real lock.  Append-only trace write is
            # safe: only one managed thread runs at any moment.
            with self._cv:
                self._trace.append(f"{name}@{point_name}+locked")
            return
        self._park(name, point=point_name)

    def _park(self, name: str, point: Optional[str]) -> None:
        deadline = time.monotonic() + self.timeout
        with self._cv:
            if point is not None:
                self._trace.append(f"{name}@{point}")
            self._states[name] = "parked"
            self._cv.notify_all()
            while self._states.get(name) == "parked":
                if self._abort:
                    raise SchedulerAborted(name)
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._cv.wait(remaining):
                    raise SchedulerAborted(f"{name}: no grant in {self.timeout}s")

    # -- coordination --------------------------------------------------------

    def run(self, *, join_timeout: float = 10.0) -> List[str]:
        """Drive managed threads to completion; return the decision trace.

        Raises the first managed-thread exception (deterministic: thread
        completion order is scheduler-controlled), or RuntimeError on a
        stuck mesh (a managed thread neither parks nor finishes).
        """
        deadline = time.monotonic() + self.timeout
        with self._cv:
            while True:
                while any(s == "running" for s in self._states.values()):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not self._cv.wait(remaining):
                        self._abort = True
                        self._cv.notify_all()
                        raise RuntimeError(
                            "interleave scheduler stuck; thread states: "
                            f"{dict(self._states)}"
                        )
                parked = sorted(
                    n for n, s in self._states.items() if s == "parked"
                )
                if not parked:
                    break  # everyone done
                pick = parked[self._rng.randrange(len(parked))]
                self._states[pick] = "running"
                self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=join_timeout)
        if self._errors:
            first = sorted(self._errors)[0]
            raise self._errors[first]
        return list(self._trace)

    @property
    def trace(self) -> List[str]:
        return list(self._trace)

    @property
    def errors(self) -> Dict[str, BaseException]:
        with self._cv:
            return dict(self._errors)
